"""Streaming all-device engine: raw byte windows in, bounded rows kept.

The one-shot all-device engine (ops/device_tokenizer.py) needs the
whole corpus byte tensor and its token-capacity arrays in HBM at once.
Here the corpus arrives in doc-aligned byte windows and the device
carries only the **unique (word, doc) rows seen so far**, each row the
``ceil(width/12)`` 30-bit (hi, lo) 5-bit-group code pairs that
``ops/device_tokenizer.tokenize_groups`` emits directly, plus the doc
id — bounded by the output's unique-pair count, not the stream length.
(``pack_groups`` survives only as the property-test reference for this
code layout; the hot path never materializes byte columns.)  The same
blockwise-accumulator discipline as the integer-pair streaming engine
(ops/streaming.py), lifted from packed ints to word rows, so the
"device scan" column of the engine matrix gets the same
larger-than-HBM story the host-scan engines have:

    per window:  rows  <- tokenize_groups ► sort ► dedup
                 acc   <- unique(merge_sort(acc, rows))

as fused XLA programs with static shapes and NO device->host sync in
the stream loop: the host bounds unique rows by the fed token count
(host_token_stats, already computed per window for tok_cap), growing
the accumulator by host-side doubling BEFORE a window that could
overflow it.  Group passes whose chars the stream has not seen yet are
skipped (the host's running max cleaned length is exact).

Exactness: rows are the actual cleaned bytes under an injective code
map — no hashing anywhere; a window whose max cleaned token exceeds
``width`` raises WidthOverflow BEFORE that window is fed and the model
restarts on the host path, so output stays byte-identical always
(main.c:105-111 / main.c:227-234 semantics, like every other engine).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import faults
from ..utils.rounding import round_up
from .device_tokenizer import (
    INT32_MAX,
    groups_sort_perm,
    live_groups_for,
    num_groups_for,
    tokenize_groups,
)
from .segment import first_occurrence_mask, set_bit_positions


def _row_first_mask(rows):
    """first-occurrence mask over sorted (group pairs…, doc) rows;
    rows[0] (group-0 hi) carries INT32_MAX on padding."""
    neq = first_occurrence_mask(rows[0])
    for r in rows[1:]:
        neq = neq | first_occurrence_mask(r)
    return neq & (rows[0] != INT32_MAX)


def _compact_rows(rows, mask, out_cap: int):
    """Set-bit-sort/gather compaction of row tuples (no scatters —
    ops/segment.py discipline); dropped slots become padding rows
    (INT32_MAX in every column, so later sorts still push them last)."""
    n = rows[0].shape[0]
    kept = set_bit_positions(mask, out_cap)
    live = kept != INT32_MAX
    pos = jnp.clip(kept, 0, n - 1)
    return tuple(jnp.where(live, r[pos], INT32_MAX) for r in rows)


@functools.partial(
    jax.jit,
    static_argnames=("width", "tok_cap", "num_docs", "sort_cols",
                     "num_groups", "out_cap"),
)
def window_rows(data, doc_ends, doc_id_values, *, width: int, tok_cap: int,
                num_docs: int, sort_cols: int, num_groups: int,
                out_cap: int):
    """One byte window -> its deduped (group rows…, doc) pairs.

    Returns ``(rows, counts)``: ``rows`` is ``2 * num_groups + 1``
    int32 arrays of length ``out_cap`` (compressed unique pairs first,
    INT32_MAX padding after), ``counts = [num_pairs, max_word_len,
    num_tokens]`` for the caller's divergence asserts (fetched lazily,
    never inside the stream loop).
    """
    groups, doc_col, max_word_len, num_tokens = tokenize_groups(
        data, doc_ends, doc_id_values, width=width, tok_cap=tok_cap,
        num_docs=num_docs, sort_cols=sort_cols)
    live = live_groups_for(sort_cols, width)
    perm = groups_sort_perm(groups[:live], doc_col, tok_cap)
    zero = jnp.zeros(tok_cap, jnp.int32)
    s_rows = tuple(
        g[perm] for pair in groups[:live] for g in pair
    ) + tuple([zero] * (2 * (num_groups - live))) + (doc_col[perm],)
    first = _row_first_mask(s_rows)
    rows = _compact_rows(s_rows, first, out_cap)
    counts = jnp.stack([first.sum(dtype=jnp.int32), max_word_len,
                        num_tokens])
    return rows, counts


@functools.partial(jax.jit, static_argnames=("cap", "live_groups"),
                   donate_argnums=(0,))
def _merge_unique_rows(acc, window, *, cap: int, live_groups: int):
    """Fold a window's row tuple into the sorted-unique accumulator;
    also returns the accumulator's true unique-row count (the host
    reads it two merges LATE, keeping two merges in flight).  "True"
    is exact, not an upper bound: _row_first_mask masks all-INT32_MAX
    padding rows, so no padding row counts as a first occurrence
    (pinned by tests/test_device_streaming.py::
    test_merge_count_is_exact_not_upper_bound).

    ``live_groups``: groups the stream has produced a nonzero char for
    so far (host-exact running max) — later groups are all zero in both
    operands except on padding rows, where every column is INT32_MAX,
    equal too; their sort passes are skipped, their dedup compares
    kept (cheap elementwise, robustness)."""
    cat = tuple(jnp.concatenate([a, w]) for a, w in zip(acc, window))
    doc = cat[-1]
    groups = [(cat[2 * g], cat[2 * g + 1]) for g in range(live_groups)]
    perm = groups_sort_perm(groups, doc, doc.shape[0])
    s_rows = tuple(r[perm] for r in cat)
    first = _row_first_mask(s_rows)
    return _compact_rows(s_rows, first, cap), first.sum(dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("cap",))
def _regrow_rows(acc, *, cap: int):
    """Copy row arrays into larger INT32_MAX-padded buffers."""
    def one(a):
        out = jnp.full((cap,), INT32_MAX, jnp.int32)
        return lax.dynamic_update_slice(out, a, (0,))
    return tuple(one(a) for a in acc)


@functools.partial(jax.jit, static_argnames=("pad",))
def _head_rows(acc, *, pad: int):
    """Static-size prefix of every accumulator column — the snapshot
    fetch moves only this instead of the full capacity (the cap can
    sit at ~2x the live count right after a doubling; at 1M-doc scale
    that slack is >100 MB over the tunnel).  ``pad`` is granule-
    rounded by the caller so the program count stays O(high-water /
    granule), not one per distinct live count."""
    return tuple(lax.slice(a, (0,), (pad,)) for a in acc)


def finalize_rows_body(acc, *, num_groups: int):
    """Traceable core of :func:`_finalize_rows` — also runs per shard
    inside the mesh streaming engine's ``shard_map`` finalize
    (parallel/dist_device_streaming.py), where each owner's
    accumulator is one independent row set.

    Every valid row is one unique (word, doc) pair and the rows are
    already in emit-ready lexicographic order, so: postings are the doc
    column's valid prefix verbatim; df falls out of the word-run edges;
    unique word rows return AS the 5-bit group pairs gathered at each
    run's first row — the host decodes them at vocab scale
    (ops/device_tokenizer.decode_word_groups), matching the one-shot
    engine's contract.
    """
    cap = acc[0].shape[0]
    doc = acc[-1]
    valid = acc[0] != INT32_MAX
    word_cols = acc[:-1]
    neq = first_occurrence_mask(word_cols[0])
    for r in word_cols[1:]:
        neq = neq | first_occurrence_mask(r)
    first_word = neq & valid
    num_words = first_word.sum(dtype=jnp.int32)
    num_pairs = valid.sum(dtype=jnp.int32)

    slots = jnp.arange(cap, dtype=jnp.int32)
    # word-start positions via the shared set-bit sort (segment.py);
    # W[cap] == cap keeps the df difference below always in range
    W = jnp.concatenate([
        jnp.minimum(set_bit_positions(first_word, cap), cap),
        jnp.full(1, cap, jnp.int32)])
    word_live = slots < num_words
    Wg = jnp.clip(W[:-1], 0, cap - 1).astype(jnp.int32)
    df = jnp.where(word_live, jnp.minimum(W[1:], num_pairs) - W[:-1], 0)
    postings = jnp.where(slots < num_pairs, doc, 0)

    groups = [(jnp.where(word_live, acc[2 * g][Wg], 0),
               jnp.where(word_live, acc[2 * g + 1][Wg], 0))
              for g in range(num_groups)]
    # >12-char word count so the sparse tail-group fetch can size its
    # transfer (device_tokenizer.fetch_pack contract)
    num_long = ((word_live & (groups[1][0] != 0)).sum(dtype=jnp.int32)
                if num_groups > 1 else jnp.int32(0))
    return {
        "counts": jnp.stack([num_words, num_pairs, num_long]),
        "df": df,
        "postings": postings,
        "unique_groups": tuple(groups),
    }


_finalize_rows = functools.partial(
    jax.jit, static_argnames=("num_groups",))(finalize_rows_body)


class DeviceStreamEngine:
    """Bounded-memory all-device reduction over a raw byte-window
    stream.  ``width`` fixes the row shape for the whole stream; the
    caller guards WidthOverflow per window BEFORE feeding (host-exact
    max cleaned length), so the accumulator never holds a truncated
    row.  ``window_pad`` rounds per-window token capacities so window
    programs reuse across similar windows.
    """

    def __init__(self, *, width: int, window_pad: int = 1 << 14,
                 initial_capacity: int = 1 << 16):
        self._width = width
        self._num_groups = num_groups_for(width)
        self._window_pad = window_pad
        self._cap = initial_capacity
        self._acc = None
        self._unique_bound = 0     # host bound on unique rows in acc
        # in-flight merges' (true-count handle, tokens folded) pairs,
        # oldest first; depth 2 keeps one merge always dispatchable
        # while the previous still runs (see feed)
        self._pending = []
        self._max_inflight = 2
        self._live_groups = 1      # running ceil(ceil(maxlen/4)/3)
        self.windows_fed = 0
        self.max_word_len = 0
        self._window_checks = []   # (counts_dev, tok_cap, host_max_len)
        # snapshot prefix-fetch rounding: bounds the number of distinct
        # _head_rows programs at high-water/granule while keeping the
        # over-fetch under one granule of rows per column
        self._snapshot_granule = 1 << 16
        # resolved unique-row counts in resolution order — the
        # accumulator GROWTH curve (trails windows_fed by the in-flight
        # merges; snapshot drains those, finalize leaves them): free
        # observability for scale artifacts, mirroring the host-stream
        # engines' vocab_curve
        self.rows_curve: list[int] = []

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def snapshot_nbytes(self) -> int:
        """Bytes a :meth:`snapshot` would fetch over the link right
        now: a granule-padded valid-prefix of every int32 column (the
        host bound on unique rows stands in for the drained count).
        Callers use this to project the snapshot tax before paying it
        — at 1M-doc scale an accumulator snapshot is hundreds of MB
        over a ~8 MB/s tunnel (VERDICT r4 weak #3)."""
        if self._acc is None:
            return 0
        # snapshot() drains the in-flight merges BEFORE fetching, so
        # project from the last resolved true count, not the pending-
        # inflated capacity bound: _unique_bound carries every pending
        # window's whole token count (worst case all-unique), which at
        # streaming scale overstates the fetch by windows' worth of
        # tokens and makes the budget loop skip affordable snapshots.
        drained_bound = self._unique_bound - sum(
            tc for _, tc in self._pending)
        pad = min(round_up(max(drained_bound, 1),
                           self._snapshot_granule), self._cap)
        return (2 * self._num_groups + 1) * pad * 4

    def _ensure_capacity(self, extra: int) -> None:
        self._unique_bound += extra
        while self._unique_bound > self._cap:
            self._cap *= 2
            if self._acc is not None:
                self._acc = _regrow_rows(self._acc, cap=self._cap)

    def feed(self, buf: np.ndarray, ends: np.ndarray, ids: np.ndarray,
             *, tok_count: int, max_len: int, stage_hook=None) -> None:
        """Tokenize one padded byte window on device and fold its
        unique rows into the accumulator.  ``tok_count`` / ``max_len``
        are the window's host-exact stats (host_token_stats) — the
        caller has already rejected ``max_len > width``.

        ``stage_hook(name, device_value)``, when given, is called after
        each stage (``upload``, ``window_rows``, ``merge``) with a
        device value the hook can fetch-barrier on — so stage
        attribution tooling (tools/profile_stream_stages.py) times the
        PRODUCTION path instead of a re-implementation that drifts
        (advisor r4).  A hooked feed also resolves every in-flight
        merge count at the end (serialized semantics: the 2-deep
        pipeline is exactly what the hook's barriers suppress), keeping
        the capacity-growth path identical to a resolved-count run.
        Production callers pass nothing and pay nothing."""
        if tok_count == 0:
            return
        self.max_word_len = max(self.max_word_len, max_len)
        sort_cols = -(-max(self.max_word_len, 1) // 4)
        self._live_groups = max(self._live_groups,
                                live_groups_for(sort_cols, self._width))
        tok_cap = round_up(tok_count + 1, self._window_pad)
        out_cap = round_up(min(tok_count, tok_cap), self._window_pad)
        d_buf = jax.device_put(buf)
        d_ends = jax.device_put(ends)
        d_ids = jax.device_put(ids)
        if stage_hook is not None:
            # all three uploads: barriering d_buf alone lets the ends /
            # ids transfers leak into the next stage's measured time
            stage_hook("upload", (d_buf, d_ends, d_ids))
        rows, counts = window_rows(
            d_buf, d_ends, d_ids,
            width=self._width, tok_cap=tok_cap, num_docs=ends.shape[0],
            sort_cols=sort_cols, num_groups=self._num_groups,
            out_cap=out_cap)
        counts.copy_to_host_async()
        self._window_checks.append((counts, tok_cap, max_len))
        if stage_hook is not None:
            stage_hook("window_rows", counts)
        # tighten the host bound against resolved merge counts, read
        # TWO merges late: resolving merge i-2 before dispatching
        # merge i keeps two merges in flight (the previous count sync
        # serialized the stream — each window paid a full link RTT
        # with the device idle during the host scan).  The bound stays
        # provably safe: true count of the last RESOLVED merge plus
        # every token folded by the still-unresolved ones — unique
        # rows + two windows' tokens, never the stream length (the
        # module's bounded-memory claim).
        while len(self._pending) >= self._max_inflight:
            handle, _ = self._pending.pop(0)
            resolved = int(np.asarray(handle))
            self.rows_curve.append(resolved)
            self._unique_bound = (resolved
                                  + sum(tc for _, tc in self._pending))
        self._ensure_capacity(tok_count)
        if self._acc is None:
            pad = np.full(self._cap, INT32_MAX, np.int32)
            self._acc = tuple(
                jax.device_put(pad) for _ in range(2 * self._num_groups + 1))
        self._acc, pending_count = _merge_unique_rows(
            self._acc, rows, cap=self._cap, live_groups=self._live_groups)
        pending_count.copy_to_host_async()
        self._pending.append((pending_count, tok_count))
        self.windows_fed += 1
        # fault hook (faults.py stream-crash:window=K): raise AFTER this
        # window's merge is dispatched but before any later checkpoint —
        # the worst-case crash position for the resume contract
        inj = faults.active()
        if inj is not None:
            inj.on_stream_window(self.windows_fed)
        if stage_hook is not None:
            stage_hook("merge", pending_count)
            while self._pending:
                handle, _ = self._pending.pop(0)
                self._unique_bound = int(np.asarray(handle))
                self.rows_curve.append(self._unique_bound)

    def _verify_window_checks(self) -> None:
        """Fetch + verify the accumulated per-window device stats
        against the host classifier (shared by finalize and snapshot —
        a snapshot must not persist an unverified prefix)."""
        for counts_dev, tok_cap, host_max_len in self._window_checks:
            _pairs, dev_max_len, dev_tokens = (
                int(v) for v in np.asarray(counts_dev))
            if dev_tokens + 1 > tok_cap:
                raise AssertionError(
                    f"device token count {dev_tokens} exceeded tok_cap "
                    f"{tok_cap}: host mask count diverged from the "
                    "device classifier (bug)")
            if dev_max_len != host_max_len:
                raise AssertionError(
                    f"device max word len {dev_max_len} != host "
                    f"{host_max_len}: classifier divergence (bug)")
        self._window_checks = []

    def snapshot(self) -> dict | None:
        """Verified host snapshot of the stream state — the durable
        form of the reference's spill files (main.c:332-341, which
        persist after the run and make the reduce phase re-runnable;
        SURVEY.md §5 checkpoint row).

        Drains the in-flight merges (paying the pipeline depth once),
        verifies every window fed so far, then fetches the accumulator
        and keeps only the valid row prefix.  Returns ``None`` when
        nothing has been fed.  The engine stays live — streaming
        continues after a snapshot.
        """
        if self._acc is None:
            return None
        while self._pending:
            handle, _ = self._pending.pop(0)
            self._unique_bound = int(np.asarray(handle))
            self.rows_curve.append(self._unique_bound)
        self._verify_window_checks()
        count = self._unique_bound
        # fetch only a granule-padded prefix: every valid row sits in
        # acc[:count] (merges compact valid rows first), and the cap
        # can be ~2x count right after a doubling — slack worth >100 MB
        # at 1M-doc scale over the tunnel
        pad = min(round_up(max(count, 1), self._snapshot_granule),
                  self._cap)
        heads = (_head_rows(self._acc, pad=pad) if pad < self._cap
                 else self._acc)
        cols = jax.device_get(heads)
        return {
            "width": self._width,
            # bytes this fetch actually moved — the budget loop
            # calibrates its link rate from this, NOT from the pre-
            # drain snapshot_nbytes projection (whose pending-inflated
            # bound can overstate the transfer and inflate the rate)
            "fetched_nbytes": (2 * self._num_groups + 1) * pad * 4,
            "count": count,
            "cap": self._cap,
            "live_groups": self._live_groups,
            "max_word_len": self.max_word_len,
            "windows_fed": self.windows_fed,
            "rows_curve": list(self.rows_curve),
            "columns": [np.asarray(c[:count]) for c in cols],
        }

    def restore(self, state: dict) -> None:
        """Rebuild the device accumulator from :meth:`snapshot` output.
        The engine must be freshly constructed with the same ``width``."""
        if self._acc is not None or self.windows_fed:
            raise ValueError("restore() requires a fresh engine")
        if state["width"] != self._width:
            raise ValueError(
                f"checkpoint width {state['width']} != engine width "
                f"{self._width}")
        ncols = 2 * self._num_groups + 1
        if len(state["columns"]) != ncols:
            raise ValueError(
                f"checkpoint has {len(state['columns'])} row columns, "
                f"engine width {self._width} needs {ncols}")
        count = int(state["count"])
        cap = int(state["cap"])
        if count > cap:
            raise ValueError(
                f"checkpoint count {count} exceeds its capacity {cap}: "
                "truncated or corrupt stream checkpoint")
        for i, c in enumerate(state["columns"]):
            if len(c) != count:
                raise ValueError(
                    f"checkpoint column {i} holds {len(c)} rows, header "
                    f"says {count}: truncated or corrupt stream checkpoint")
        self._cap = cap
        cols = []
        for c in state["columns"]:
            buf = np.full(self._cap, INT32_MAX, np.int32)
            buf[:count] = c
            cols.append(jax.device_put(buf))
        self._acc = tuple(cols)
        self._unique_bound = count
        self._live_groups = int(state["live_groups"])
        self.max_word_len = int(state["max_word_len"])
        self.windows_fed = int(state["windows_fed"])
        # pre-crash growth history, so a resumed run's reported curve
        # covers the WHOLE stream (absent in checkpoints written
        # before the key existed)
        self.rows_curve = [int(v) for v in state.get("rows_curve", [])]
        self._pending = []
        self._window_checks = []

    def finalize(self):
        """Device dict with the one-shot engine's output contract
        (counts / df / postings / unique_groups valid prefixes).

        Re-checks every window's device-computed stats against the
        host classifier here — ONE lazy fetch per window, all outside
        the stream loop — so host/device divergence fails as loudly as
        the one-shot engine's asserts instead of silently truncating.
        """
        if self._acc is None:
            raise ValueError("no windows fed")
        self._verify_window_checks()
        out = _finalize_rows(self._acc, num_groups=self._num_groups)
        self._acc = None
        self._pending = []
        return out
