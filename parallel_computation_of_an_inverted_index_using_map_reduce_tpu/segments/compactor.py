"""Background compaction: k-way merge of the smallest adjacent segments.

Compaction bounds segment fan-out (every query costs one probe per
segment) and is the point where tombstoned documents finally leave the
index.  It picks the ADJACENT run of segments with the smallest total
artifact bytes — adjacency keeps the global doc-id order equal to the
manifest's concatenation order, the invariant the multi-segment merge
relies on — decodes their postings, drops tombstoned docs, and packs
ONE replacement segment via the same ``serve.artifact`` packer every
builder uses.  Global doc ids are preserved: the merged segment keeps
the first input's ``doc_base`` and re-bases locals without renumbering,
so compaction is invisible to queries (byte-identical answers before
and after, minus nothing — deletes were already filtered).

The multi-round k-way merge discipline follows the MapReduce shuffle
model of "Sorting, Searching, and Simulation in the MapReduce
Framework" (PAPERS.md): each round folds a bounded number of sorted
runs, and repeated rounds converge the segment count under
``MRI_SEGMENT_MAX_SEGMENTS``.

Crash safety is the manifest discipline: the replacement segment is
fully built and checksummed before the generation swap; a crash at any
earlier point (including the injected ``compact-crash`` fault) leaves
the old generation serving and at worst an orphan directory no
manifest references.  Inputs are retired from the manifest but their
directories are kept on disk — concurrent readers of an older
generation may still be mapping them; ``prune_retired`` removes
anything the current generation no longer names.
"""

from __future__ import annotations

import logging
import shutil
import time

import numpy as np

from . import tombstones as tomb_mod
from . import wal as wal_mod
from .manifest import (SegmentEntry, SegmentError, SegmentManifest,
                       load_manifest, mutation_lock, save_manifest,
                       segment_dir, segments_root)
from .. import faults
from ..obs import metrics as obs_metrics
from ..serve import artifact as artifact_mod
from ..utils import envknobs

log = logging.getLogger("mri_tpu.segments")

TRIGGER_ENV = "MRI_SEGMENT_COMPACT_TRIGGER"
MAX_SEGMENTS_ENV = "MRI_SEGMENT_MAX_SEGMENTS"


def should_compact(man: SegmentManifest) -> bool:
    """The auto-compaction trigger: at or past the knob's segment
    count (``MRI_SEGMENT_COMPACT_TRIGGER``)."""
    return len(man.entries) >= envknobs.get(TRIGGER_ENV)


def _pick_run(entries: tuple[SegmentEntry, ...]) -> tuple[int, int]:
    """``(start, stop)`` of the adjacent run to merge: the cheapest
    window of ``min(trigger, len)`` consecutive segments by total
    artifact bytes (the "smallest segments" rule, kept adjacent)."""
    k = min(max(envknobs.get(TRIGGER_ENV), 2), len(entries))
    sizes = [e.bytes for e in entries]
    best, best_at = None, 0
    for i in range(len(entries) - k + 1):
        w = sum(sizes[i:i + k])
        if best is None or w < best:
            best, best_at = w, i
    return best_at, best_at + k


def _merge_segments(root, picked: list[SegmentEntry], *, name: str
                    ) -> tuple[str, int, int, int]:
    """Decode the picked segments, drop tombstones, pack the merged
    replacement.  Returns ``(adler32, bytes, docs_span, dropped)``."""
    new_base = picked[0].doc_base
    span = picked[-1].doc_base + picked[-1].docs - new_base
    doc_lens = np.zeros(span + 1, dtype=np.int64)
    terms: dict[bytes, list] = {}
    dropped = 0
    for e in picked:
        seg = segment_dir(root, e.name)
        off = e.doc_base - new_base
        bits = None
        if e.tombstones is not None and e.tomb_count:
            bits = tomb_mod.load(seg / e.tombstones, ndocs=e.docs)
            dropped += int(bits.sum())
        with artifact_mod.load_artifact(seg) as art:
            dl = artifact_mod.bm25_corpus(art)[0].astype(np.int64)
            # skip the local pad slot dl[0]: global index ``off`` is the
            # previous segment's last doc, not this segment's
            n = min(len(dl), e.docs + 1)
            doc_lens[off + 1:off + n] = dl[1:n]
            if bits is not None:
                doc_lens[off + np.nonzero(bits)[0] + 1] = 0
            for t in range(art.vocab):
                docs = art.decode_postings(t).astype(np.int64)
                if bits is not None:
                    live = ~bits[docs - 1]
                    if not live.all():
                        tf = art.decode_tf(t).astype(np.int64)[live]
                        docs = docs[live]
                    else:
                        tf = art.decode_tf(t).astype(np.int64)
                else:
                    tf = art.decode_tf(t).astype(np.int64)
                if len(docs):
                    terms.setdefault(art.term(t), []).append(
                        (docs + off, tf))
    words = sorted(terms)
    blob = b"".join(words)
    term_offsets = np.zeros(len(words) + 1, dtype=np.int64)
    np.cumsum([len(w) for w in words], out=term_offsets[1:])
    df = np.zeros(len(words), dtype=np.int64)
    doc_parts: list[np.ndarray] = []
    tf_parts: list[np.ndarray] = []
    for i, w in enumerate(words):
        runs = terms[w]
        # inputs are doc_base-ordered and locally ascending, so plain
        # concatenation is already globally sorted per term
        doc_parts.extend(r[0] for r in runs)
        tf_parts.extend(r[1] for r in runs)
        df[i] = sum(len(r[0]) for r in runs)
    post_offsets = np.zeros(len(words) + 1, dtype=np.int64)
    np.cumsum(df, out=post_offsets[1:])
    postings = (np.concatenate(doc_parts) if doc_parts
                else np.zeros(0, dtype=np.int64))
    tf = (np.concatenate(tf_parts) if tf_parts
          else np.zeros(0, dtype=np.int64))
    letters = (np.frombuffer(blob, dtype=np.uint8)[term_offsets[:-1]]
               if words else np.zeros(0, dtype=np.uint8))
    # emit order: letter asc, df desc, word asc (lexsort is stable, so
    # equal (letter, df) keys keep ascending lex-index == word order)
    df_order = np.lexsort((-df, letters)).astype(np.int32)
    seg = segment_dir(root, name)
    seg.mkdir(parents=True, exist_ok=True)
    dst = seg / artifact_mod.ARTIFACT_NAME
    artifact_mod.pack(
        dst, term_blob=np.frombuffer(blob, dtype=np.uint8),
        term_offsets=term_offsets, df=df, post_offsets=post_offsets,
        postings=postings, df_order=df_order, max_doc_id=span,
        tf=tf, doc_lens=doc_lens)
    crc, size = artifact_mod.checksum(dst)
    return crc, size, span, dropped


def compact(root, *, force: bool = False, registry=None,
            wal_seq=None) -> dict:
    """One compaction round; publishes the next generation.

    Below the ``MRI_SEGMENT_COMPACT_TRIGGER`` segment count this is a
    counted no-op unless ``force`` — background callers can invoke it
    unconditionally and let the trigger decide.  ``wal_seq`` marks the
    recovery re-application of an already logged record.
    """
    t0 = time.perf_counter()
    with mutation_lock(root):
        man = load_manifest(root)
        if man is None or len(man.entries) < 2:
            return {"compacted": False,
                    "reason": "fewer than two segments",
                    "generation": 0 if man is None else man.generation,
                    "segments": 0 if man is None else len(man.entries)}
        if not force and not should_compact(man):
            return {"compacted": False,
                    "reason": f"below trigger "
                              f"({envknobs.get(TRIGGER_ENV)} segments)",
                    "generation": man.generation,
                    "segments": len(man.entries)}
        seq = wal_seq
        if seq is None and wal_mod.wal_enabled():
            # logged before the merge: a SIGKILL anywhere inside the
            # merge window replays the whole round on recovery
            seq = wal_mod.log_mutation(root, "compact",
                                       {"force": bool(force)},
                                       base_seq=man.wal_seq,
                                       registry=registry)
        start, stop = _pick_run(man.entries)
        picked = list(man.entries[start:stop])
        gen = man.generation + 1
        name = f"seg_{gen}_{man.next_seg}"
        try:
            crc, size, span, dropped = _merge_segments(
                root, picked, name=name)
            inj = faults.active()
            if inj is not None:
                # the injected mid-compaction crash: replacement built
                # but never published — old generation keeps serving,
                # the orphan directory is exactly what a real crash
                # leaves
                inj.on_compact()
            merged = SegmentEntry(name=name, doc_base=picked[0].doc_base,
                                  docs=span, adler32=crc, bytes=size)
            new = SegmentManifest(
                generation=gen, next_seg=man.next_seg + 1,
                entries=man.entries[:start] + (merged,)
                + man.entries[stop:],
                wal_seq=man.wal_seq if seq is None else seq)
            save_manifest(root, new, op="compact")
        except (SegmentError, faults.InjectedCompactCrash):
            # rejected to the caller: replay must not redo this round
            if seq is not None and wal_seq is None:
                wal_mod.discard(root, seq)
            raise
        if seq is not None:
            wal_mod.truncate_published(root)
    dt = time.perf_counter() - t0
    reg = registry if registry is not None \
        else obs_metrics.default_registry()
    reg.counter("mri_compactions_total").inc()
    reg.gauge("mri_generation").set(new.generation)
    reg.gauge("mri_segments_active").set(len(new.entries))
    reg.gauge("mri_tombstoned_docs").set(
        sum(e.tomb_count for e in new.entries))
    log.info("compacted %d segments into %s (%d tombstones dropped, "
             "%.1f ms)", len(picked), name, dropped, dt * 1e3)
    return {"compacted": True, "generation": new.generation,
            "segment": name, "inputs": [e.name for e in picked],
            "tombstones_dropped": dropped,
            "segments": len(new.entries), "bytes": size,
            "compact_ms": round(dt * 1e3, 3)}


def compact_to_limit(root, *, registry=None) -> list[dict]:
    """Repeat single rounds until the segment count is at or under
    ``MRI_SEGMENT_MAX_SEGMENTS`` (the append path's backstop)."""
    limit = envknobs.get(MAX_SEGMENTS_ENV)
    out: list[dict] = []
    while True:
        man = load_manifest(root)
        if man is None or len(man.entries) <= max(limit, 1):
            return out
        res = compact(root, force=True, registry=registry)
        out.append(res)
        if not res.get("compacted"):
            return out


def prune_retired(root) -> list[str]:
    """Remove segment directories the CURRENT manifest no longer
    references (retired compaction inputs, orphaned staging).  Safe
    only when no reader is still serving an older generation — an
    explicit operator action, never automatic."""
    with mutation_lock(root):
        man = load_manifest(root)
        if man is None:
            return []
        keep = {e.name for e in man.entries}
        removed = []
        base = segments_root(root)
        if base.is_dir():
            for child in sorted(base.iterdir()):
                if child.is_dir() and child.name not in keep:
                    shutil.rmtree(child, ignore_errors=True)
                    removed.append(child.name)
    return removed
