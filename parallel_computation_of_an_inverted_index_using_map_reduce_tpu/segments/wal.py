"""Mutation write-ahead log: acked means durable, crash means replay.

The manifest swap makes each *published* generation atomic, but two
windows could still lose an **acknowledged** mutation before this
module existed: the daemon's buffered deletes (acked on the wire,
flushed to a manifest only every ``MRI_SEGMENT_TOMBSTONE_FLUSH`` ops)
and any crash between a mutation's side effects and its publish.  The
WAL closes both: every append / delete / compact is recorded here —
fsync'd — *before* the ``segments.manifest.json`` swap, and a mutation
is only acknowledged on the wire after its record is durable.  On
daemon start (and via ``mri recover DIR``), :func:`replay` rolls the
directory forward to the exact last-acknowledged state.

Container discipline follows ``build/spill.py``'s ``MRISPILL`` rule —
magic, length-framed sections, per-section adler32, quarantine on
damage — adapted to an append-only record stream::

    header   8s    b"MRIWAL01"
    record   4s    b"WREC"
             u32   payload length (little-endian)
             ...   canonical-JSON payload
             8s    adler32 hex of the payload (utils.checksum spelling)

Unlike spill files the WAL **fsyncs every append**: its whole point is
surviving SIGKILL, so durability is the product, not overhead (the
``--wal-ab`` bench prices it).

Sequencing model: every record carries a monotonic ``seq``; every
manifest publish stamps ``wal_seq`` with the seq it covers.  Replay
applies records with ``seq > manifest.wal_seq`` in order;
:func:`truncate_published` drops records at or below the stamp.  The
invariant mutators must keep: a record is only logged when every
lower-seq record has already been applied (the daemon flushes buffered
deletes before appends/compacts for exactly this reason).  Mixing CLI
mutations with a live daemon holding *buffered* deletes remains
unsupported — the same pre-existing hazard the flush knob documents.

A torn tail (crash or the ``wal-torn-record`` fault mid-append) is
quarantined to ``segments.wal.corrupt`` and the log truncated back to
the last whole record — a torn record was by definition never acked,
so dropping it loses nothing.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import struct
from pathlib import Path

from .manifest import (SegmentError, SegmentManifest, load_manifest,
                       mutation_lock, save_manifest, segments_root)
from .. import faults
from ..obs import metrics as obs_metrics
from ..utils import envknobs
from ..utils.checksum import adler32_hex

log = logging.getLogger("mri_tpu.segments")

WAL_NAME = "segments.wal"
WAL_MAGIC = b"MRIWAL01"
REC_MAGIC = b"WREC"
_REC_FIXED = len(REC_MAGIC) + 4   # record magic + u32 payload length
_CRC_BYTES = 8                    # adler32 hex digits

WAL_ENV = "MRI_SEGMENT_WAL"


class WalError(SegmentError):
    """The WAL itself is unusable (distinct from a quarantined tail,
    which is repaired in place and only reported)."""


def wal_path(root) -> Path:
    return Path(root) / WAL_NAME


def corrupt_path(root) -> Path:
    return Path(root) / (WAL_NAME + ".corrupt")


def wal_enabled() -> bool:
    """``MRI_SEGMENT_WAL`` (default on).  Off restores the pre-WAL
    publish-only durability — the A/B the bench prices."""
    return bool(envknobs.get(WAL_ENV))


def _encode_record(rec: dict) -> bytes:
    payload = json.dumps(rec, sort_keys=True,
                         separators=(",", ":")).encode("utf-8")
    return (REC_MAGIC + struct.pack("<I", len(payload)) + payload
            + adler32_hex(payload).encode("ascii"))


def _parse(data: bytes) -> tuple[list[dict], int, str | None]:
    """``(records, clean_offset, damage)``: parse until the first torn
    or corrupt record; ``clean_offset`` is where the undamaged prefix
    ends (0 when even the header is wrong)."""
    if not data:
        return [], 0, None
    if len(data) < len(WAL_MAGIC) or data[:len(WAL_MAGIC)] != WAL_MAGIC:
        return [], 0, "bad wal magic"
    off = len(WAL_MAGIC)
    records: list[dict] = []
    damage = None
    while off < len(data):
        if len(data) - off < _REC_FIXED:
            damage = "torn record frame"
            break
        if data[off:off + len(REC_MAGIC)] != REC_MAGIC:
            damage = "bad record magic"
            break
        (n,) = struct.unpack_from("<I", data, off + len(REC_MAGIC))
        end = off + _REC_FIXED + n + _CRC_BYTES
        if end > len(data):
            damage = "torn record payload"
            break
        payload = data[off + _REC_FIXED:off + _REC_FIXED + n]
        want = data[end - _CRC_BYTES:end].decode("ascii", "replace")
        if adler32_hex(payload) != want:
            damage = "record checksum mismatch"
            break
        try:
            rec = json.loads(payload)
            seq = int(rec["seq"])
            op = str(rec["op"])
        except (ValueError, KeyError, TypeError):
            damage = "malformed record payload"
            break
        if op not in ("append", "delete", "compact"):
            damage = f"unknown record op {op!r}"
            break
        if records and seq <= int(records[-1]["seq"]):
            damage = "non-monotonic record seq"
            break
        records.append(rec)
        off = end
    return records, off, damage


def _rewrite(root, records: list[dict]) -> None:
    """Atomically rewrite the log to exactly ``records`` (fsync'd); an
    empty record set removes the file entirely."""
    path = wal_path(root)
    if not records:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        return
    tmp = path.with_name(path.name + ".tmp")
    # mrilint: allow(fault-boundary) atomic tmp+fsync+rename rewrite; damage on read surfaces via quarantine in read_records
    with open(tmp, "wb") as f:
        f.write(WAL_MAGIC)
        for rec in records:
            f.write(_encode_record(rec))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_records(root) -> tuple[list[dict], dict]:
    """Parse the log, repairing damage in place: the torn tail is
    quarantined to ``segments.wal.corrupt`` and the log truncated back
    to its last whole record.  Returns ``(records, info)`` where
    ``info`` reports any quarantine.  Caller holds the mutation lock
    (or is single-owner, e.g. recovery)."""
    path = wal_path(root)
    try:
        # mrilint: allow(fault-boundary) WAL read is the integrity boundary itself; tears are quarantined right here
        data = path.read_bytes()
    except FileNotFoundError:
        return [], {}
    except OSError as e:
        raise WalError(f"{path}: cannot read wal ({e})") from e
    records, clean, damage = _parse(data)
    if damage is None:
        return records, {}
    tail = data[clean:]
    cpath = corrupt_path(root)
    # mrilint: allow(fault-boundary) quarantine sidecar write, append so repeated tears all stay inspectable
    with open(cpath, "ab") as f:
        f.write(tail)
    _rewrite(root, records)
    log.warning("wal %s: %s at offset %d — %d byte(s) quarantined to %s",
                path, damage, clean, len(tail), cpath.name)
    return records, {"damage": damage, "quarantined_bytes": len(tail),
                     "quarantine": str(cpath)}


def log_mutation(root, op: str, payload: dict, *, base_seq: int | None = None,
                 registry=None) -> int:
    """Durably record one mutation BEFORE its manifest swap; returns
    the record's seq.  Caller holds the mutation lock.  The record is
    fsync'd before this returns — the ack-ordering contract ("acked
    means durable") rests on exactly that fsync.

    The ``wal-torn-record`` fault tears the just-written record and
    raises before the fsync: the mutation then fails un-acked, and the
    next :func:`read_records` quarantines the torn tail.
    """
    records, _info = read_records(root)
    if base_seq is None:
        man = load_manifest(root)
        base_seq = 0 if man is None else man.wal_seq
    last = int(records[-1]["seq"]) if records else 0
    seq = max(int(base_seq), last) + 1
    rec = {"seq": seq, "op": op, **payload}
    path = wal_path(root)
    # mrilint: allow(fault-boundary) append+fsync of the durability record; the faults hook below owns the injected tear
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        if os.fstat(fd).st_size == 0:
            os.write(fd, WAL_MAGIC)
        os.write(fd, _encode_record(rec))
        inj = faults.active()
        if inj is not None:
            inj.on_wal_append(str(path))
        os.fsync(fd)
    except faults.InjectedWalTorn as e:
        # surface as the usual SegmentError family: the mutation fails
        # un-acked and the torn tail is quarantined on the next read
        raise WalError(str(e)) from e
    finally:
        os.close(fd)
    reg = registry if registry is not None \
        else obs_metrics.default_registry()
    reg.counter("mri_wal_records_total").inc()
    return seq


def tail(root, after_seq: int) -> list[dict]:
    """Records with ``seq > after_seq`` — the replica catch-up feed
    (acked-but-unpublished mutations the manifest swap hasn't covered)."""
    records, _info = read_records(root)
    return [r for r in records if int(r["seq"]) > int(after_seq)]


def append_tail(root, records: list[dict]) -> int:
    """Adopt a primary's WAL tail on a replica: append every record
    with a seq above both the local stamp and the local log's last
    record.  Returns the number adopted.  Caller holds no lock (the
    replica is single-owner during catch-up)."""
    local, _info = read_records(root)
    man = load_manifest(root)
    floor = max(0 if man is None else man.wal_seq,
                int(local[-1]["seq"]) if local else 0)
    fresh = [r for r in sorted(records, key=lambda r: int(r["seq"]))
             if int(r["seq"]) > floor]
    if fresh:
        _rewrite(root, local + fresh)
    return len(fresh)


def discard(root, seq: int) -> None:
    """Drop one record after its mutation was *explicitly rejected*
    (e.g. a torn publish): the caller reports failure to the client,
    so replaying the record later would resurrect a mutation the
    client was told did not happen.  A genuine crash (no rejection
    reported, no ack either) keeps its record — at-least-once replay
    of an un-acked mutation is the standard WAL trade."""
    records, _info = read_records(root)
    keep = [r for r in records if int(r["seq"]) != int(seq)]
    if len(keep) != len(records):
        _rewrite(root, keep)


def truncate_published(root) -> int:
    """Drop records the current manifest already covers (``seq <=
    wal_seq``); returns how many were dropped.  Runs after every
    publish so the log only ever holds the unpublished suffix."""
    man = load_manifest(root)
    if man is None:
        return 0
    records, _info = read_records(root)
    keep = [r for r in records if int(r["seq"]) > man.wal_seq]
    if len(keep) != len(records):
        _rewrite(root, keep)
    return len(records) - len(keep)


def _sweep_scratch(root, man: SegmentManifest | None) -> list[str]:
    """Remove build/fetch staging and unreferenced segment dirs —
    recovery runs with no live readers, so a crashed mutation's
    orphans (including a replayed append's half-built twin) go."""
    removed: list[str] = []
    base = segments_root(root)
    if not base.is_dir():
        return removed
    keep = set() if man is None else {e.name for e in man.entries}
    for child in sorted(base.iterdir()):
        if not child.is_dir():
            continue
        if child.name.startswith((".build_", ".fetch_")) \
                or child.name not in keep:
            shutil.rmtree(child, ignore_errors=True)
            removed.append(child.name)
    return removed


def _stamp(root, seq: int) -> None:
    """Advance ``wal_seq`` on the live manifest without any other
    change — covers replayed records whose re-application was a no-op
    (an idempotent delete, a compact that found nothing to merge)."""
    with mutation_lock(root):
        man = load_manifest(root)
        if man is None:
            man = SegmentManifest(generation=0, next_seg=0, entries=())
        if man.wal_seq >= seq:
            return
        save_manifest(root, dataclasses.replace(man, wal_seq=seq),
                      op="recover")


def replay(root, *, registry=None) -> dict:
    """Roll the directory forward to the last acked mutation.

    Quarantines any torn tail, sweeps crashed-mutation scratch, then
    re-applies every record above the manifest's ``wal_seq`` stamp in
    seq order: appends re-run the segment build from the recorded
    source paths, deletes re-set tombstone bits (idempotent), compacts
    re-merge.  Each replayed record's publish stamps the manifest, and
    the log is truncated back to the unpublished suffix at the end —
    replay of an already-consistent directory is a no-op.
    """
    from . import compactor as compactor_mod
    from . import writer as writer_mod

    records, info = read_records(root)
    man = load_manifest(root)
    swept = _sweep_scratch(root, man)
    covered = 0 if man is None else man.wal_seq
    replayed = skipped = 0
    reg = registry if registry is not None \
        else obs_metrics.default_registry()
    for rec in sorted(records, key=lambda r: int(r["seq"])):
        seq = int(rec["seq"])
        if seq <= covered:
            skipped += 1
            continue
        op = rec["op"]
        if op == "append":
            writer_mod.append_files(root, rec["files"],
                                    registry=registry, wal_seq=seq)
        elif op == "delete":
            writer_mod.delete_docs(root, rec["docs"],
                                   registry=registry, wal_seq=seq)
        else:
            compactor_mod.compact(root, force=bool(rec.get("force", True)),
                                  registry=registry, wal_seq=seq)
        man = load_manifest(root)
        if man is None or man.wal_seq < seq:
            _stamp(root, seq)
        covered = seq
        replayed += 1
        reg.counter("mri_wal_replayed_total").inc()
    dropped = truncate_published(root)
    man = load_manifest(root)
    out = {
        "generation": 0 if man is None else man.generation,
        "wal_seq": 0 if man is None else man.wal_seq,
        "replayed": replayed,
        "skipped": skipped,
        "truncated": dropped,
        "swept": swept,
    }
    if info:
        out["quarantined_bytes"] = info.get("quarantined_bytes", 0)
        out["damage"] = info.get("damage")
    if replayed or swept or info:
        log.info("wal recovery: %s", out)
    return out


def recover(root, *, registry=None) -> dict:
    """``mri recover DIR`` / daemon-start entry point: :func:`replay`
    when the directory is (or may become) segment-managed; a directory
    with neither manifest nor WAL is reported untouched."""
    if load_manifest(root) is None and not wal_path(root).exists():
        return {"generation": 0, "wal_seq": 0, "replayed": 0,
                "skipped": 0, "truncated": 0, "swept": [],
                "segmented": False}
    out = replay(root, registry=registry)
    out["segmented"] = True
    return out
