"""``segments.manifest.json`` — the generation-numbered segment set.

The manifest is the single source of truth for a live (incrementally
updated) index directory: which immutable segment artifacts are
serving, at which document-id bases, and which tombstone files mask
deleted documents.  Every mutation (append / delete / compact) writes a
NEW manifest under ``generation + 1`` and publishes it with the same
stage-then-rename discipline the artifact writer and the daemon's hot
reload already use — readers either see the complete old set or the
complete new set, never a torn mix.

Integrity is checked at three layers:

* the manifest body carries its own adler32 (``checksum`` field over
  the canonical JSON payload), so a torn/bit-rotted manifest file is
  rejected at load;
* every entry records the adler32 + byte size of its ``index.mri`` and
  tombstone file, so ``mri --verify DIR`` can re-hash the whole
  generation without opening an engine;
* each ``index.mri`` keeps its own header/payload checksums, verified
  again when an engine maps it.

Document-id model: segment-local ids are 1-based; the global id of a
segment document is ``doc_base + local_id``.  ``docs`` is the local id
span (max local id), so segments own the disjoint global ranges
``(doc_base, doc_base + docs]``.  Compaction preserves global ids (the
merged segment keeps the first input's ``doc_base`` and re-bases
locals without renumbering survivors), so ids handed to clients stay
valid for the lifetime of the directory — the id space just becomes
sparse where deletes landed.

Cross-process mutators serialize on ``segments.lock`` (flock), so a
CLI append racing a daemon compaction cannot lose an update; in-daemon
mutations additionally serialize under the reload lock.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
from pathlib import Path

from .. import faults
from ..utils.checksum import adler32_hex

MANIFEST_NAME = "segments.manifest.json"
SEGMENTS_DIR = "segments"
LOCK_NAME = "segments.lock"
MAGIC = "MRISEGMENTS1"


class SegmentError(RuntimeError):
    """The segment set is missing, torn, or internally inconsistent."""


@dataclasses.dataclass(frozen=True)
class SegmentEntry:
    """One immutable segment of the live index."""

    name: str                 # directory name under segments/
    doc_base: int             # global id = doc_base + local id
    docs: int                 # local id span (max local id)
    adler32: str              # of the segment's index.mri
    bytes: int                # size of the segment's index.mri
    tombstones: str | None = None      # file name inside the segment dir
    tomb_adler32: str | None = None
    tomb_bytes: int | None = None
    tomb_count: int = 0       # set bits (deleted docs) in the bitmap

    def to_json(self) -> dict:
        d = {"name": self.name, "doc_base": self.doc_base,
             "docs": self.docs, "adler32": self.adler32,
             "bytes": self.bytes}
        if self.tombstones is not None:
            d["tombstones"] = {
                "name": self.tombstones, "adler32": self.tomb_adler32,
                "bytes": self.tomb_bytes, "count": self.tomb_count}
        return d

    @classmethod
    def from_json(cls, d: dict) -> "SegmentEntry":
        try:
            t = d.get("tombstones")
            return cls(
                name=str(d["name"]), doc_base=int(d["doc_base"]),
                docs=int(d["docs"]), adler32=str(d["adler32"]),
                bytes=int(d["bytes"]),
                tombstones=str(t["name"]) if t else None,
                tomb_adler32=str(t["adler32"]) if t else None,
                tomb_bytes=int(t["bytes"]) if t else None,
                tomb_count=int(t["count"]) if t else 0)
        except (KeyError, TypeError, ValueError) as e:
            raise SegmentError(f"malformed segment entry {d!r}: {e}") \
                from e


@dataclasses.dataclass(frozen=True)
class SegmentManifest:
    """One generation of the segment set (immutable once published)."""

    generation: int
    next_seg: int             # monotonic segment ordinal allocator
    entries: tuple[SegmentEntry, ...]
    wal_seq: int = 0          # highest WAL record seq this set covers

    @property
    def doc_span(self) -> int:
        """One past the highest global doc id any entry can hold."""
        return max((e.doc_base + e.docs for e in self.entries),
                   default=0)

    @property
    def live_docs_max(self) -> int:
        """Upper bound on live documents (span minus tombstones)."""
        return sum(e.docs - e.tomb_count for e in self.entries)

    def to_json(self) -> dict:
        return {"magic": MAGIC, "generation": self.generation,
                "next_seg": self.next_seg, "wal_seq": self.wal_seq,
                "entries": [e.to_json() for e in self.entries]}


def _body_checksum(body: dict) -> str:
    blob = json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return adler32_hex(blob)


def manifest_path(root) -> Path:
    return Path(root) / MANIFEST_NAME


def segments_root(root) -> Path:
    return Path(root) / SEGMENTS_DIR


def segment_dir(root, name: str) -> Path:
    return segments_root(root) / name


@contextlib.contextmanager
def mutation_lock(root):
    """Cross-process mutation lock for one index directory (flock on
    ``segments.lock``) — append/delete/compact hold it across their
    whole read-modify-publish cycle, so concurrent mutators from the
    chaos soak serialize instead of losing generations."""
    import fcntl
    Path(root).mkdir(parents=True, exist_ok=True)
    path = Path(root) / LOCK_NAME
    # mrilint: allow(fault-boundary) lock acquisition, not data I/O; fault hooks fire inside the guarded mutation
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def load_manifest(root) -> SegmentManifest | None:
    """Parse + checksum-verify the current manifest; None when the
    directory has never been segment-managed.  Every structural or
    checksum violation raises :class:`SegmentError` — a torn set is
    rejected whole, never half-served."""
    path = manifest_path(root)
    try:
        # mrilint: allow(fault-boundary) manifest read is the integrity boundary itself; tears surface as SegmentError
        text = path.read_text(encoding="utf-8")
    except FileNotFoundError:
        return None
    except OSError as e:
        raise SegmentError(f"{path}: cannot read manifest ({e})") from e
    try:
        doc = json.loads(text)
    except ValueError as e:
        raise SegmentError(f"{path}: torn manifest (bad JSON: {e})") \
            from e
    if not isinstance(doc, dict) or doc.get("magic") != MAGIC:
        raise SegmentError(f"{path}: not a segment manifest")
    want = doc.pop("checksum", None)
    got = _body_checksum(doc)
    if want != got:
        raise SegmentError(
            f"{path}: manifest checksum mismatch "
            f"(stored {want!r}, computed {got!r})")
    try:
        man = SegmentManifest(
            generation=int(doc["generation"]),
            next_seg=int(doc["next_seg"]),
            entries=tuple(SegmentEntry.from_json(e)
                          for e in doc["entries"]),
            # pre-WAL manifests carry no wal_seq: they cover seq 0
            wal_seq=int(doc.get("wal_seq", 0)))
    except (KeyError, TypeError, ValueError) as e:
        raise SegmentError(f"{path}: malformed manifest: {e}") from e
    bases = [(e.doc_base, e.doc_base + e.docs) for e in man.entries]
    if bases != sorted(bases) or any(
            bases[i][1] > bases[i + 1][0] for i in range(len(bases) - 1)):
        raise SegmentError(
            f"{path}: segment doc ranges overlap or are unsorted")
    return man


def save_manifest(root, man: SegmentManifest, *, op: str) -> Path:
    """Publish a new generation atomically (stage + rename).

    ``op`` names the mutation (append/delete/compact/seed) for the
    fault-injection hook: ``append-torn-manifest`` tears the STAGED
    file and aborts before the rename, so the previous generation keeps
    serving — the crash-mid-publish the discipline exists to survive.
    """
    path = manifest_path(root)
    body = man.to_json()
    body["checksum"] = _body_checksum(body)
    blob = json.dumps(body, indent=1, sort_keys=True) + "\n"
    tmp = path.with_name(path.name + ".tmp")
    # mrilint: allow(fault-boundary) atomic stage+rename publish; the faults hook below owns the injected tear
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    inj = faults.active()
    if inj is not None:
        try:
            inj.on_segment_publish(op, str(tmp))
        except faults.InjectedPublishTear as e:
            # a crash mid-publish: the torn staged file never replaces
            # the live manifest, so the old generation keeps serving
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise SegmentError(f"{path}: publish failed ({e})") from e
    os.replace(tmp, path)
    return path


def is_segmented(root) -> bool:
    return manifest_path(root).exists()
