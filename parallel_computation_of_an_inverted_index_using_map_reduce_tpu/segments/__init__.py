"""Incremental indexing: segment manifests, tombstones, compaction.

The batch pipeline stays the segment builder; this package adds the
live layer on top — generation-numbered manifests of immutable
segments (:mod:`.manifest`), append/delete mutations (:mod:`.writer`),
per-segment tombstone bitmaps (:mod:`.tombstones`), and background
compaction (:mod:`.compactor`).  The query-side merge lives in
``serve.multi_engine`` so serving has no hard dependency on the build
stack.
"""

from .compactor import (compact, compact_to_limit, prune_retired,
                        should_compact)
from .manifest import (LOCK_NAME, MANIFEST_NAME, SEGMENTS_DIR,
                       SegmentEntry, SegmentError, SegmentManifest,
                       is_segmented, load_manifest, manifest_path,
                       mutation_lock, save_manifest, segment_dir,
                       segments_root)
from .replica import (LeaseError, ReplicaError, read_lease,
                      release_lease, renew_lease, replicate)
from .tombstones import empty_bitmap, tombstone_name
from .wal import WAL_NAME, WalError, recover, replay, wal_path
from .writer import append_files, delete_docs

__all__ = [
    "LOCK_NAME", "MANIFEST_NAME", "SEGMENTS_DIR", "WAL_NAME",
    "LeaseError", "ReplicaError", "SegmentEntry", "SegmentError",
    "SegmentManifest", "WalError",
    "append_files", "compact", "compact_to_limit", "delete_docs",
    "empty_bitmap", "is_segmented", "load_manifest", "manifest_path",
    "mutation_lock", "prune_retired", "read_lease", "recover",
    "release_lease", "renew_lease", "replay", "replicate",
    "save_manifest", "segment_dir", "segments_root", "should_compact",
    "tombstone_name", "wal_path",
]
