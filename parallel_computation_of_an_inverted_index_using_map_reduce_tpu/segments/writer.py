"""Segment building: live append and delete for an index directory.

``append_files`` turns one batch of corpus files into a small immutable
segment — built by the SAME native ``--artifact`` export every batch
build uses (real term frequencies and document lengths, so BM25 over
segments stays bit-identical to a from-scratch build) — and publishes
it under a new manifest generation.  ``delete_docs`` flips tombstone
bits in generation-tagged sidecar bitmaps and publishes the result the
same way.  Neither ever modifies a published file in place; the
manifest rename is the only visible state change, and a crash at any
point leaves the previous generation fully intact (at worst plus an
orphan staging directory no manifest references).
"""

from __future__ import annotations

import logging
import os
import shutil

import numpy as np

from . import tombstones as tomb_mod
from . import wal as wal_mod
from .manifest import (SegmentEntry, SegmentError, SegmentManifest,
                       load_manifest, manifest_path, mutation_lock,
                       save_manifest, segment_dir, segments_root)
from ..obs import metrics as obs_metrics
from ..serve import artifact as artifact_mod

log = logging.getLogger("mri_tpu.segments")


def _load_or_seed(root) -> SegmentManifest:
    """The current manifest; first mutation of a directory seeds one.

    A directory holding a batch-built ``index.mri`` becomes generation
    1 with that artifact copied in as segment 0 (``doc_base`` 0, so
    every existing doc id is unchanged); a fresh directory starts
    empty at generation 0.  Caller holds the mutation lock.
    """
    man = load_manifest(root)
    if man is not None:
        return man
    src = artifact_mod.artifact_path(root)
    if not src.exists():
        return SegmentManifest(generation=0, next_seg=0, entries=())
    with artifact_mod.load_artifact(src) as art:
        docs = int(art.max_doc_id)
    name = "seg_1_0"
    seg = segment_dir(root, name)
    seg.mkdir(parents=True, exist_ok=True)
    dst = seg / artifact_mod.ARTIFACT_NAME
    tmp = dst.with_name(dst.name + ".tmp")
    shutil.copyfile(src, tmp)
    os.replace(tmp, dst)
    crc, size = artifact_mod.checksum(dst)
    man = SegmentManifest(
        generation=1, next_seg=1,
        entries=(SegmentEntry(name=name, doc_base=0, docs=docs,
                              adler32=crc, bytes=size),))
    save_manifest(root, man, op="seed")
    log.info("seeded segment manifest from existing artifact "
             "(%d docs, generation 1)", docs)
    return man


def _build_segment_artifact(root, files: list[str], *, name: str) -> tuple:
    """Run the existing ``--artifact`` batch build over ``files`` into
    a staging dir and move the packed ``index.mri`` into the segment
    directory.  Returns ``(adler32, bytes, docs)``."""
    from ..config import IndexConfig
    from ..corpus.manifest import Manifest, _stat_sizes
    from ..models.inverted_index import InvertedIndexModel

    paths = tuple(str(p) for p in files)
    corpus = Manifest(paths=paths, sizes=_stat_sizes(paths))
    stage = segments_root(root) / f".build_{name}"
    if stage.exists():
        shutil.rmtree(stage)
    stage.mkdir(parents=True)
    try:
        cfg = IndexConfig(backend="cpu", output_dir=str(stage),
                          artifact=True)
        InvertedIndexModel(cfg).run(corpus)
        built = artifact_mod.artifact_path(stage)
        seg = segment_dir(root, name)
        seg.mkdir(parents=True, exist_ok=True)
        dst = seg / artifact_mod.ARTIFACT_NAME
        os.replace(built, dst)
    finally:
        shutil.rmtree(stage, ignore_errors=True)
    crc, size = artifact_mod.checksum(dst)
    return crc, size, len(paths)


def append_files(root, files, *, registry=None, wal_seq=None) -> dict:
    """Append a batch of corpus files as one new immutable segment and
    publish the next manifest generation.  Global doc ids continue
    densely from the current span; returns the assignment.

    ``wal_seq`` is the recovery path only: replay re-runs an already
    logged record, so no new record is written and the manifest is
    stamped with the replayed seq.
    """
    files = [str(f) for f in files]
    if not files:
        raise SegmentError("append needs at least one file")
    missing = [f for f in files if not os.path.isfile(f)]
    if missing:
        raise SegmentError(f"append: no such file(s): {missing}")
    with mutation_lock(root):
        man = _load_or_seed(root)
        seq = wal_seq
        if seq is None and wal_mod.wal_enabled():
            # the durability point: the record is fsync'd before any
            # segment bytes exist, so a crash anywhere past here
            # replays instead of losing the acked mutation
            seq = wal_mod.log_mutation(root, "append", {"files": files},
                                       base_seq=man.wal_seq,
                                       registry=registry)
        gen = man.generation + 1
        name = f"seg_{gen}_{man.next_seg}"
        doc_base = man.doc_span
        crc, size, docs = _build_segment_artifact(root, files, name=name)
        entry = SegmentEntry(name=name, doc_base=doc_base, docs=docs,
                             adler32=crc, bytes=size)
        new = SegmentManifest(generation=gen, next_seg=man.next_seg + 1,
                              entries=man.entries + (entry,),
                              wal_seq=man.wal_seq if seq is None else seq)
        try:
            save_manifest(root, new, op="append")
        except SegmentError:
            # injected/real publish failure: retire the orphan segment
            # so --verify of the surviving generation stays clean, and
            # drop the WAL record — this mutation is REJECTED to the
            # caller, so replay must never resurrect it
            shutil.rmtree(segment_dir(root, name), ignore_errors=True)
            if seq is not None and wal_seq is None:
                wal_mod.discard(root, seq)
            raise
        if seq is not None:
            wal_mod.truncate_published(root)
    reg = registry if registry is not None \
        else obs_metrics.default_registry()
    reg.gauge("mri_generation").set(new.generation)
    reg.gauge("mri_segments_active").set(len(new.entries))
    return {"generation": new.generation, "segment": name,
            "doc_base": doc_base, "docs": docs,
            "doc_ids": [doc_base + i for i in range(1, docs + 1)],
            "segments": len(new.entries)}


def _entry_for(man: SegmentManifest, gid: int) -> SegmentEntry:
    for e in man.entries:
        if e.doc_base < gid <= e.doc_base + e.docs:
            return e
    raise SegmentError(
        f"doc id {gid} is outside every segment "
        f"(live span is 1..{man.doc_span})")


def delete_docs(root, doc_ids, *, registry=None, wal_seq=None) -> dict:
    """Tombstone global doc ids and publish the next generation.

    Idempotent per id (re-deleting is a no-op bit set); an id outside
    every segment's range is an error.  The artifact files are never
    touched — only new generation-tagged bitmap sidecars appear.
    ``wal_seq`` marks the recovery re-application of an already logged
    record (no new record, manifest stamped with the replayed seq).
    """
    ids = sorted({int(d) for d in doc_ids})
    if not ids:
        raise SegmentError("delete needs at least one doc id")
    with mutation_lock(root):
        man = _load_or_seed(root)
        if not man.entries:
            raise SegmentError(
                f"{manifest_path(root)}: nothing indexed yet")
        seq = wal_seq
        if seq is None and wal_mod.wal_enabled():
            seq = wal_mod.log_mutation(root, "delete", {"docs": ids},
                                       base_seq=man.wal_seq,
                                       registry=registry)
        gen = man.generation + 1
        try:
            per: dict[str, list[int]] = {}
            by_name = {e.name: e for e in man.entries}
            for gid in ids:
                e = _entry_for(man, gid)
                per.setdefault(e.name, []).append(gid - e.doc_base)
            entries = []
            newly = 0
            for e in man.entries:
                locals_ = per.get(e.name)
                if not locals_:
                    entries.append(e)
                    continue
                seg = segment_dir(root, e.name)
                if e.tombstones is not None:
                    bits = tomb_mod.load(seg / e.tombstones, ndocs=e.docs)
                else:
                    bits = tomb_mod.empty_bitmap(e.docs)
                before = int(bits.sum())
                bits[np.asarray(locals_, dtype=np.int64) - 1] = True
                count = int(bits.sum())
                newly += count - before
                tname = tomb_mod.tombstone_name(gen)
                crc, size = tomb_mod.save(seg / tname, bits)
                entries.append(SegmentEntry(
                    name=e.name, doc_base=e.doc_base, docs=e.docs,
                    adler32=e.adler32, bytes=e.bytes, tombstones=tname,
                    tomb_adler32=crc, tomb_bytes=size, tomb_count=count))
            new = SegmentManifest(generation=gen, next_seg=man.next_seg,
                                  entries=tuple(entries),
                                  wal_seq=man.wal_seq if seq is None
                                  else seq)
            save_manifest(root, new, op="delete")
        except SegmentError:
            # rejected to the caller (bad id, torn bitmap, torn
            # publish): replay must never resurrect this record
            if seq is not None and wal_seq is None:
                wal_mod.discard(root, seq)
            raise
        if seq is not None:
            wal_mod.truncate_published(root)
    total = sum(e.tomb_count for e in new.entries)
    reg = registry if registry is not None \
        else obs_metrics.default_registry()
    reg.gauge("mri_generation").set(new.generation)
    reg.gauge("mri_tombstoned_docs").set(total)
    return {"generation": new.generation, "deleted": ids,
            "newly_tombstoned": newly, "tombstoned_total": total,
            "segments": len(new.entries)}
