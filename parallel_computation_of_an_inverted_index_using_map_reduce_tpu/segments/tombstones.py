"""Per-segment tombstone bitmaps — delete without rewrite.

A delete never touches the immutable segment artifact: it flips a bit
in a tiny sidecar bitmap that the multi-segment engine filters at
query time and compaction finally drops.  Files are generation-tagged
(``tombstones_<gen>.bin``) and referenced from the manifest entry, so
the manifest swap stays the single atomicity point: the OLD generation
keeps pointing at the OLD bitmap, and a crash mid-delete leaves at
worst an orphan file no manifest references.

Wire format, little-endian::

    magic    8s   b"MRITOMB1"
    ndocs    u32  local id span (bit i covers local id i + 1)
    bitmap   u8[ceil(ndocs / 8)]  LSB-first (numpy packbits order)
    adler32  u32  over everything above

Loads verify magic, size, and checksum — a corrupted bitmap raises
:class:`~.manifest.SegmentError` instead of silently resurrecting or
deleting documents.
"""

from __future__ import annotations

import os
import struct
import zlib
from pathlib import Path

import numpy as np

from .manifest import SegmentError
from .. import faults
from ..utils.checksum import adler32_hex

TOMB_MAGIC = b"MRITOMB1"


def tombstone_name(gen: int) -> str:
    return f"tombstones_{gen}.bin"


def empty_bitmap(ndocs: int) -> np.ndarray:
    """All-live bitmap: bool[ndocs], index ``local_id - 1``."""
    return np.zeros(int(ndocs), dtype=bool)


def encode(bits: np.ndarray) -> bytes:
    bits = np.asarray(bits, dtype=bool)
    packed = np.packbits(bits, bitorder="little").tobytes()
    body = TOMB_MAGIC + struct.pack("<I", len(bits)) + packed
    return body + struct.pack("<I", zlib.adler32(body))


def decode(data: bytes, *, ndocs: int, path: str = "") -> np.ndarray:
    """Parse + verify one bitmap file's bytes; ``ndocs`` is the span
    the manifest entry promises (a mismatch is corruption too)."""
    where = path or "<tombstones>"
    if len(data) < 16 or data[:8] != TOMB_MAGIC:
        raise SegmentError(f"{where}: not a tombstone bitmap")
    (n,) = struct.unpack_from("<I", data, 8)
    want = 12 + ((n + 7) // 8) + 4
    if len(data) != want:
        raise SegmentError(
            f"{where}: truncated tombstone bitmap "
            f"({len(data)} bytes, expected {want})")
    (crc,) = struct.unpack_from("<I", data, len(data) - 4)
    if zlib.adler32(data[:-4]) != crc:
        raise SegmentError(f"{where}: tombstone checksum mismatch")
    if n != int(ndocs):
        raise SegmentError(
            f"{where}: bitmap covers {n} docs, manifest entry "
            f"promises {ndocs}")
    bits = np.unpackbits(
        np.frombuffer(data, dtype=np.uint8, count=(n + 7) // 8,
                      offset=12), bitorder="little")[:n]
    return bits.astype(bool)


def load(path, *, ndocs: int) -> np.ndarray:
    try:
        # mrilint: allow(fault-boundary) sidecar read is checksum-verified below; tears surface as SegmentError
        data = Path(path).read_bytes()
    except OSError as e:
        raise SegmentError(f"{path}: cannot read tombstones ({e})") \
            from e
    return decode(data, ndocs=ndocs, path=str(path))


def save(path, bits: np.ndarray) -> tuple[str, int]:
    """Stage, fault-check, re-verify, then rename — returns the
    published file's ``(adler32_hex, size)`` for the manifest entry.

    The ``tombstone-corrupt`` fault kind flips bytes in the STAGED
    file; the re-verify then rejects the write before anything is
    published, proving the old generation keeps serving.
    """
    path = Path(path)
    data = encode(bits)
    tmp = path.with_name(path.name + ".tmp")
    # mrilint: allow(fault-boundary) atomic stage+rename publish; the faults hook below owns the injected corruption
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    inj = faults.active()
    if inj is not None:
        inj.on_tombstone_write(str(tmp))
    try:
        # mrilint: allow(fault-boundary) read-back verification of the staged bytes (the corruption gate)
        staged = tmp.read_bytes()
        decode(staged, ndocs=len(bits), path=str(tmp))
    except SegmentError:
        os.unlink(tmp)
        raise
    os.replace(tmp, path)
    return adler32_hex(staged), len(staged)
