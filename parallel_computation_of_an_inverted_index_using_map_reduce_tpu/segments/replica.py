"""Segment shipping + primary-election leases for live index dirs.

A replica never re-indexes: it catches up by asking the primary's
daemon for a manifest ``snapshot``, fetching exactly the segment
artifacts and tombstone bitmaps it is missing (content-addressed by the
manifest's per-file adler32, verified on arrival, staged under
``segments/.fetch_*`` and published by the same atomic manifest swap
every mutation uses), then adopting the primary's WAL tail — the
acked-but-unpublished suffix — so an acknowledged mutation survives
even a primary that never gets to publish it.  :func:`replicate` is
one catch-up round; the daemon's ``--replica-of`` poll loop and the
``mri replicate`` CLI both call it.

Primary election is a TTL'd lease stored INSIDE ``segments.lock`` (the
flock target every mutator already serializes on; :func:`~.manifest.
mutation_lock` opens it without truncation precisely so the lease JSON
survives).  With ``MRI_SEGMENT_LEASE_TTL_S`` > 0 every mutation first
:func:`renew_lease`; a live foreign owner raises :class:`LeaseError`
("lease_lost") and the mutation is rejected while reads keep serving
the old generation.  TTL 0 (the default) disables leasing for
single-writer deployments.

Failure shapes proven by the fault kinds: ``fetch-partial`` truncates
one shipped payload (the per-file verification must reject + retry,
never swap a torn segment in) and ``lease-steal`` rewrites the lease
to a foreign owner mid-run (the next renew must reject).
"""

from __future__ import annotations

import base64
import contextlib
import json
import logging
import os
import re
import shutil
import socket
import time
from pathlib import Path

from . import wal as wal_mod
from .manifest import (LOCK_NAME, SegmentEntry, SegmentError,
                       SegmentManifest, load_manifest, mutation_lock,
                       save_manifest, segment_dir, segments_root)
from .. import faults
from ..serve import artifact as artifact_mod
from ..utils import envknobs
from ..utils.checksum import adler32_hex

log = logging.getLogger("mri_tpu.segments")

LEASE_TTL_ENV = "MRI_SEGMENT_LEASE_TTL_S"
POLL_ENV = "MRI_REPLICA_POLL_MS"

#: Owner name the ``lease-steal`` fault writes — a value no real
#: daemon ever uses, so trial logs attribute the rejection correctly.
THIEF_OWNER = "lease-thief"

_SEGMENT_NAME = re.compile(r"^seg_\d+_\d+$")
_TOMB_NAME = re.compile(r"^tombstones_\d+\.bin$")


class ReplicaError(SegmentError):
    """A catch-up round failed (unreachable primary, refused op, or a
    shipped file that failed verification twice)."""


class LeaseError(SegmentError):
    """The mutation lease is held by a live foreign owner.  The
    message starts with ``lease_lost`` — the wire detail clients key
    rejection handling on."""


def parse_addr(target: str) -> tuple[str, int]:
    host, _, port_s = str(target).rpartition(":")
    try:
        port = int(port_s)
        if not host or not (0 < port <= 65535):
            raise ValueError
    except ValueError:
        raise ReplicaError(
            f"replica source must be HOST:PORT, got {target!r}") from None
    return host, port


# -- lease (TTL'd primary election inside segments.lock) ---------------

def lease_ttl() -> float:
    return float(envknobs.get(LEASE_TTL_ENV))


@contextlib.contextmanager
def _locked_lease_fd(root):
    """flock'd fd over ``segments.lock`` — the SAME lock every mutator
    takes, so a lease decision can never interleave with a mutation.
    Never call while already holding :func:`~.manifest.mutation_lock`:
    flock on a second fd in the same process self-deadlocks."""
    import fcntl
    Path(root).mkdir(parents=True, exist_ok=True)
    path = Path(root) / LOCK_NAME
    # mrilint: allow(fault-boundary) lease storage inside the lock file; the faults lease hook fires on the caller
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield fd
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def _read_lease_fd(fd) -> dict | None:
    data = os.pread(fd, 4096, 0)
    if not data.strip():
        return None
    try:
        lease = json.loads(data)
        return {"owner": str(lease["owner"]),
                "expires": float(lease["expires"])}
    except (ValueError, KeyError, TypeError):
        return None  # pre-lease lock file content: no holder


def _write_lease_fd(fd, lease: dict | None) -> None:
    os.ftruncate(fd, 0)
    if lease is not None:
        os.pwrite(fd, json.dumps(lease, sort_keys=True).encode("utf-8"), 0)


def read_lease(root) -> dict | None:
    """The current lease (diagnostics; no freshness judgement)."""
    with _locked_lease_fd(root) as fd:
        return _read_lease_fd(fd)


def renew_lease(root, owner: str, *, ttl: float | None = None) -> dict | None:
    """Validate-and-renew the mutation lease for ``owner``; None when
    leasing is disabled (TTL 0).  A live foreign holder raises
    :class:`LeaseError`; an expired or absent lease is taken over.
    Callers run this BEFORE taking the mutation lock (same flock)."""
    ttl = lease_ttl() if ttl is None else float(ttl)
    if ttl <= 0:
        return None
    with _locked_lease_fd(root) as fd:
        inj = faults.active()
        if inj is not None and inj.on_lease_check():
            # the injected steal: a foreign owner grabbed a live lease
            # between our mutations — written here so the normal check
            # below is the thing that rejects it
            _write_lease_fd(fd, {"owner": THIEF_OWNER,
                                 "expires": time.time() + ttl})
        lease = _read_lease_fd(fd)
        now = time.time()
        if lease is not None and lease["owner"] != owner \
                and lease["expires"] > now:
            raise LeaseError(
                f"lease_lost: held by {lease['owner']!r} for another "
                f"{lease['expires'] - now:.1f}s")
        fresh = {"owner": owner, "expires": now + ttl}
        _write_lease_fd(fd, fresh)
        return fresh


def release_lease(root, owner: str) -> bool:
    """Drop the lease iff ``owner`` still holds it (clean shutdown —
    the successor takes over without waiting out the TTL)."""
    if lease_ttl() <= 0:
        return False
    with _locked_lease_fd(root) as fd:
        lease = _read_lease_fd(fd)
        if lease is None or lease["owner"] != owner:
            return False
        _write_lease_fd(fd, None)
        return True


# -- primary-side payload builders (daemon admin ops) ------------------

def snapshot_payload(root) -> dict:
    """The ``snapshot`` admin-op body: the manifest a replica diffs
    against (generation, wal_seq, entries with their checksums)."""
    man = load_manifest(root)
    if man is None:
        raise ReplicaError(
            f"{root}: not segment-managed (nothing to replicate)")
    return man.to_json()


def segment_file_payload(root, segment: str, file: str) -> dict:
    """The ``fetch_segment`` admin-op body: one segment file, base64'd,
    with the adler32 + size of the TRUE content (computed before the
    ``fetch-partial`` fault may truncate the shipped copy, so a torn
    ship is detectable by the replica)."""
    if not _SEGMENT_NAME.match(segment or ""):
        raise ReplicaError(f"bad segment name {segment!r}")
    if file != artifact_mod.ARTIFACT_NAME and not _TOMB_NAME.match(file or ""):
        raise ReplicaError(f"bad segment file name {file!r}")
    path = segment_dir(root, segment) / file
    try:
        # mrilint: allow(fault-boundary) immutable published segment bytes; the fetch-partial faults hook fires below
        raw = path.read_bytes()
    except OSError as e:
        raise ReplicaError(f"{path}: cannot ship segment file ({e})") \
            from e
    crc, size = adler32_hex(raw), len(raw)
    inj = faults.active()
    if inj is not None:
        raw = inj.on_fetch_payload(f"{segment}/{file}", raw)
    return {"segment": segment, "file": file, "adler32": crc,
            "bytes": size,
            "data": base64.b64encode(raw).decode("ascii")}


def wal_tail_payload(root, after_seq: int) -> list[dict]:
    """The ``wal_tail`` admin-op body: records above ``after_seq``.
    Takes the mutation lock — the tail read repairs damage in place and
    must never interleave with a writer's append."""
    with mutation_lock(root):
        return wal_mod.tail(root, int(after_seq))


# -- replica-side catch-up ---------------------------------------------

class _Client:
    """Minimal JSON-lines RPC client over the daemon protocol."""

    def __init__(self, addr: tuple[str, int], timeout: float = 30.0):
        try:
            # mrilint: allow(fault-boundary) replication RPC; failures surface as ReplicaError and the poll loop retries
            self._sock = socket.create_connection(addr, timeout=timeout)
            # mrilint: allow(fault-boundary) buffered read view of the same replication socket
            self._f = self._sock.makefile("rb")
        except OSError as e:
            raise ReplicaError(
                f"cannot reach primary at {addr[0]}:{addr[1]}: {e}") \
                from e
        self._id = 0

    def rpc(self, op: str, **fields) -> dict:
        self._id += 1
        req = {"id": self._id, "op": op, **fields}
        try:
            self._sock.sendall(
                (json.dumps(req, separators=(",", ":")) + "\n").encode())
            line = self._f.readline()
        except OSError as e:
            raise ReplicaError(f"primary connection lost: {e}") from e
        if not line:
            raise ReplicaError("primary closed the connection")
        try:
            resp = json.loads(line)
        except ValueError as e:
            raise ReplicaError(f"primary sent a torn response: {e}") \
                from e
        if not resp.get("ok"):
            raise ReplicaError(
                f"primary refused {op}: {resp.get('error')} "
                f"({resp.get('detail', '')})")
        return resp

    def close(self) -> None:
        with contextlib.suppress(OSError):
            self._f.close()
        with contextlib.suppress(OSError):
            self._sock.close()


def _manifest_from_snapshot(doc: dict) -> SegmentManifest:
    try:
        return SegmentManifest(
            generation=int(doc["generation"]),
            next_seg=int(doc["next_seg"]),
            entries=tuple(SegmentEntry.from_json(e)
                          for e in doc["entries"]),
            wal_seq=int(doc.get("wal_seq", 0)))
    except (KeyError, TypeError, ValueError) as e:
        raise ReplicaError(f"malformed snapshot: {e}") from e


def _missing_files(local: SegmentManifest | None,
                   remote: SegmentManifest) -> list[tuple[str, str, str, int]]:
    """``(segment, file, adler32, bytes)`` for every remote file the
    local set lacks or holds under a different checksum."""
    have = {} if local is None else {e.name: e for e in local.entries}
    out: list[tuple[str, str, str, int]] = []
    for e in remote.entries:
        mine = have.get(e.name)
        if mine is None or mine.adler32 != e.adler32:
            out.append((e.name, artifact_mod.ARTIFACT_NAME,
                        e.adler32, e.bytes))
        if e.tombstones is not None and (
                mine is None or mine.tombstones != e.tombstones
                or mine.tomb_adler32 != e.tomb_adler32):
            out.append((e.name, e.tombstones,
                        e.tomb_adler32 or "", e.tomb_bytes or 0))
    return out


def _fetch_one(client: _Client, stage: Path, segment: str, file: str,
               want_crc: str, want_bytes: int) -> None:
    """Fetch one file into the staging dir, verifying the manifest's
    checksum; one retry on a short/torn ship (the ``fetch-partial``
    proof), then :class:`ReplicaError`."""
    last = ""
    for attempt in (1, 2):
        resp = client.rpc("fetch_segment", segment=segment, file=file)
        try:
            data = base64.b64decode(resp.get("data", ""), validate=True)
        except (ValueError, TypeError):
            data = b""
        if len(data) == want_bytes and adler32_hex(data) == want_crc:
            tmp = stage / f"{file}.tmp"
            # mrilint: allow(fault-boundary) verified bytes into the staging dir; the swap only happens after every file lands
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, stage / file)
            return
        last = (f"{segment}/{file}: shipped {len(data)} byte(s) "
                f"(adler32 {adler32_hex(data)}), manifest promises "
                f"{want_bytes} ({want_crc}) — attempt {attempt}")
        log.warning("replicate: %s", last)
    raise ReplicaError(f"segment ship failed verification twice: {last}")


def replicate(root, addr: tuple[str, int], *, registry=None,
              timeout: float = 30.0) -> dict:
    """One catch-up round against a primary daemon at ``addr``.

    Snapshot → diff by (name, adler32) → fetch missing files into
    ``segments/.fetch_<name>`` staging (verified per file) → move into
    the live segment dirs → atomically adopt the primary's manifest →
    adopt its WAL tail → drop published records.  Idempotent: a replica
    already at the primary's generation fetches nothing.  Never
    re-indexes and never touches files the old generation still serves.
    """
    t0 = time.perf_counter()
    client = _Client(addr, timeout=timeout)
    try:
        remote = _manifest_from_snapshot(client.rpc("snapshot")["snapshot"])
        local = load_manifest(root)
        behind = remote.generation - (0 if local is None
                                      else local.generation)
        if behind < 0:
            # refuse BEFORE any fetch: a same-named segment with a
            # different checksum would otherwise overwrite newer local
            # bytes on its way to the (doomed) manifest adoption
            raise ReplicaError(
                f"local generation {local.generation} is ahead of the "
                f"primary's {remote.generation} — refusing to roll "
                "back (two primaries?)")
        missing = _missing_files(local, remote)
        fetched: list[str] = []
        bytes_fetched = 0
        for segment, file, crc, size in missing:
            stage = segments_root(root) / f".fetch_{segment}"
            stage.mkdir(parents=True, exist_ok=True)
            _fetch_one(client, stage, segment, file, crc, size)
            seg = segment_dir(root, segment)
            seg.mkdir(parents=True, exist_ok=True)
            os.replace(stage / file, seg / file)
            shutil.rmtree(stage, ignore_errors=True)
            fetched.append(f"{segment}/{file}")
            bytes_fetched += size
        changed = local is None or remote.generation != local.generation \
            or remote.wal_seq != local.wal_seq
        if changed:
            with mutation_lock(root):
                # re-check under the lock: a local mutator advancing the
                # directory past the snapshot must not be rolled back
                current = load_manifest(root)
                if current is not None \
                        and current.generation > remote.generation:
                    raise ReplicaError(
                        f"local generation {current.generation} is ahead "
                        f"of the primary's {remote.generation} — refusing "
                        "to roll back (two primaries?)")
                save_manifest(root, remote, op="replicate")
        tail = client.rpc("wal_tail",
                          after_seq=remote.wal_seq).get("records", [])
        adopted = wal_mod.append_tail(root, tail)
        wal_mod.truncate_published(root)
    finally:
        client.close()
    dt = time.perf_counter() - t0
    if fetched or adopted:
        log.info("replicate: generation %d (%d behind), %d file(s) / "
                 "%d byte(s) shipped, %d wal record(s) adopted "
                 "(%.1f ms)", remote.generation, behind, len(fetched),
                 bytes_fetched, adopted, dt * 1e3)
    return {"generation": remote.generation, "wal_seq": remote.wal_seq,
            "behind": behind, "changed": changed, "fetched": fetched,
            "bytes_fetched": bytes_fetched, "adopted_records": adopted,
            "seconds": round(dt, 6)}
