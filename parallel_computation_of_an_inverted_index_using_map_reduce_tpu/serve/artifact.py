"""``index.mri`` — the compact, memory-mappable serving artifact.

The letter files are the conformance surface (byte-exact against the
reference); this is the *serving* surface: one columnar file the query
engine mmaps and reads with zero-copy numpy views, so a process serving
lookups never re-parses text.

Format v1, little-endian throughout:

    header (96 bytes)
      magic            8s   b"MRIIDX01"
      version          u32  1
      width            u32  fixed term-row width (max term length)
      vocab            i64  V — number of terms
      num_postings     i64  P — total (term, doc) pairs
      max_doc_id       i64
      term_blob_bytes  i64
      payload_bytes    i64  everything after the header
      payload_adler32  u32  over the payload bytes
      reserved         32 zero bytes
      header_adler32   u32  over header bytes [0, 92)

    payload — fixed section order, each section 16-byte aligned:
      letter_dir    i64[27]   lex term-index bounds per first letter
      term_offsets  i64[V+1]  exclusive prefix into term_blob
      term_blob     u8[...]   term bytes, lex order, no separators
      df            i32[V]    document frequency per term
      post_offsets  i64[V+1]  exclusive prefix into postings
      postings      i32[P]    per-term runs, delta-encoded: first doc id
                              absolute, the rest diffs (>= 1 — ids are
                              strictly ascending within a term)
      df_order      i32[V]    emit-order permutation over lex indices
                              (letter asc, df desc, word asc); its
                              letter bounds are letter_dir too, since
                              both orders are letter-contiguous

Terms are in lexicographic order — the engine's binary-search key — and
``df_order`` gives O(k) top-k-by-df per letter.  Writes are atomic
(tmp + rename), loads verify both checksums before any answer is
served: a torn artifact raises :class:`ArtifactError`, never garbage.
"""

from __future__ import annotations

import mmap
import os
import struct
import zlib
from pathlib import Path

import numpy as np

#: Written next to a.txt..z.txt by ``--artifact`` runs.
ARTIFACT_NAME = "index.mri"

MAGIC = b"MRIIDX01"
VERSION = 1
HEADER_BYTES = 96
_ALIGN = 16
_HEADER_FMT = "<8sIIqqqqqI"  # ... + 32 reserved + u32 header_adler32


class ArtifactError(RuntimeError):
    """The artifact is missing, torn, or not an artifact at all."""


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _layout(vocab: int, num_postings: int, blob_bytes: int):
    """Section name -> (file offset, byte length), plus total file size.

    Deterministic from the three header scalars, so the loader never
    stores per-section offsets in the file.
    """
    sections = [
        ("letter_dir", 27 * 8),
        ("term_offsets", (vocab + 1) * 8),
        ("term_blob", blob_bytes),
        ("df", vocab * 4),
        ("post_offsets", (vocab + 1) * 8),
        ("postings", num_postings * 4),
        ("df_order", vocab * 4),
    ]
    out: dict[str, tuple[int, int]] = {}
    cur = HEADER_BYTES
    for name, nbytes in sections:
        cur = _align(cur)
        out[name] = (cur, nbytes)
        cur += nbytes
    return out, _align(cur)


def artifact_path(index_dir: str | Path) -> Path:
    return Path(index_dir) / ARTIFACT_NAME


def pack(path, *, term_blob: np.ndarray, term_offsets: np.ndarray,
         df: np.ndarray, post_offsets: np.ndarray, postings: np.ndarray,
         df_order: np.ndarray, max_doc_id: int, width: int | None = None
         ) -> int:
    """Write the artifact from lex-order arrays; returns bytes written.

    ``postings`` arrives ABSOLUTE (ascending per term) — the delta
    encoding happens here, vectorized: one subtraction pass plus a
    scatter restoring each term's first id.
    """
    path = Path(path)
    term_offsets = np.ascontiguousarray(term_offsets, dtype=np.int64)
    post_offsets = np.ascontiguousarray(post_offsets, dtype=np.int64)
    term_blob = np.ascontiguousarray(term_blob, dtype=np.uint8)
    df = np.ascontiguousarray(df, dtype=np.int32)
    df_order = np.ascontiguousarray(df_order, dtype=np.int32)
    postings = np.asarray(postings, dtype=np.int32)
    vocab = len(df)
    num_postings = int(post_offsets[-1]) if len(post_offsets) else 0
    blob_bytes = int(term_offsets[-1]) if len(term_offsets) else 0
    if width is None:
        lens = np.diff(term_offsets)
        width = int(lens.max()) if vocab else 1

    deltas = postings.copy()
    if num_postings:
        deltas[1:] -= postings[:-1]
        starts = post_offsets[:-1][np.diff(post_offsets) > 0]
        deltas[starts] = postings[starts]

    layout, total = _layout(vocab, num_postings, blob_bytes)
    buf = np.zeros(total, dtype=np.uint8)

    def put(name: str, arr: np.ndarray) -> None:
        off, nbytes = layout[name]
        buf[off:off + nbytes] = np.frombuffer(arr.tobytes(), dtype=np.uint8)

    first_bytes = term_blob[term_offsets[:-1]] if vocab else term_blob[:0]
    letter_dir = np.searchsorted(
        first_bytes, np.arange(ord("a"), ord("a") + 27)).astype(np.int64)
    put("letter_dir", letter_dir)
    put("term_offsets", term_offsets)
    put("term_blob", term_blob)
    put("df", df)
    put("post_offsets", post_offsets)
    put("postings", deltas)
    put("df_order", df_order)

    return _write(path, buf, width=width, vocab=vocab,
                  num_postings=num_postings, max_doc_id=max_doc_id,
                  blob_bytes=blob_bytes)


def _header(*, width: int, vocab: int, num_postings: int, max_doc_id: int,
            blob_bytes: int, payload_len: int, payload_crc: int) -> bytes:
    header = struct.pack(
        _HEADER_FMT, MAGIC, VERSION, int(max(width, 1)), vocab,
        num_postings, int(max_doc_id), blob_bytes, payload_len,
        payload_crc)
    header = header + b"\0" * (HEADER_BYTES - 4 - len(header))
    return header + struct.pack("<I", zlib.adler32(header))


def _write(path, buf: np.ndarray, *, width: int, vocab: int,
           num_postings: int, max_doc_id: int, blob_bytes: int) -> int:
    """Checksum + header a filled file buffer, write atomically."""
    path = Path(path)
    payload = buf[HEADER_BYTES:]
    header = _header(width=width, vocab=vocab, num_postings=num_postings,
                     max_doc_id=max_doc_id, blob_bytes=blob_bytes,
                     payload_len=len(payload),
                     payload_crc=zlib.adler32(payload))
    buf[:HEADER_BYTES] = np.frombuffer(header, dtype=np.uint8)

    tmp = path.with_name(path.name + ".tmp")
    # mrilint: allow(fault-boundary) atomic tmp+rename publish; a crash leaves only the .tmp
    with open(tmp, "wb") as f:
        f.write(memoryview(buf))
    os.replace(tmp, path)
    return len(buf)


class Artifact:
    """Zero-copy numpy views over a verified, mmapped ``index.mri``."""

    def __init__(self, path: Path, mm: mmap.mmap, meta: dict,
                 views: dict[str, np.ndarray]):
        self.path = path
        self._mm = mm
        self.vocab = meta["vocab"]
        self.num_postings = meta["num_postings"]
        self.max_doc_id = meta["max_doc_id"]
        self.width = meta["width"]
        self.nbytes = meta["nbytes"]
        self.letter_dir = views["letter_dir"]
        self.term_offsets = views["term_offsets"]
        self.term_blob = views["term_blob"]
        self.df = views["df"]
        self.post_offsets = views["post_offsets"]
        self.postings = views["postings"]  # delta-encoded
        self.df_order = views["df_order"]

    def term(self, idx: int) -> bytes:
        lo, hi = self.term_offsets[idx], self.term_offsets[idx + 1]
        return self.term_blob[lo:hi].tobytes()

    def decode_postings(self, idx: int) -> np.ndarray:
        """One term's absolute ascending doc ids (a fresh array)."""
        lo, hi = self.post_offsets[idx], self.post_offsets[idx + 1]
        return np.cumsum(self.postings[lo:hi], dtype=np.int64).astype(
            np.int32)

    def close(self) -> None:
        # drop the views before the mmap: numpy holds buffer references
        for name in ("letter_dir", "term_offsets", "term_blob", "df",
                     "post_offsets", "postings", "df_order"):
            setattr(self, name, None)
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # a caller still holds a view (e.g. an engine's df
                # column): the map frees when the last view dies
                pass
            self._mm = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_artifact(path: str | Path) -> Artifact:
    """mmap + verify an artifact (a directory means its ``index.mri``).

    Every structural and checksum violation raises :class:`ArtifactError`
    with a one-line reason — the contract the CLI maps to exit 2.
    """
    path = Path(path)
    if path.is_dir():
        path = path / ARTIFACT_NAME
    try:
        f = open(path, "rb")
    except OSError as e:
        msg = f"{path}: cannot open artifact ({e})"
        # A letter-file index next to a missing index.mri means the
        # build ran without --artifact: name the remediation instead of
        # leaving the operator to diff the two output formats.
        if path.name == ARTIFACT_NAME and not path.exists() \
                and (path.parent / "a.txt").exists():
            msg += ("; directory holds a letter-file index built "
                    "without --artifact — rebuild with --artifact "
                    "to pack index.mri")
        raise ArtifactError(msg) from e
    with f:
        try:
            size = os.fstat(f.fileno()).st_size
            if size < HEADER_BYTES:
                raise ArtifactError(
                    f"{path}: {size} bytes is smaller than the "
                    f"{HEADER_BYTES}-byte header")
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError) as e:
            raise ArtifactError(f"{path}: cannot map artifact ({e})") from e
    try:
        head = bytes(mm[:HEADER_BYTES])
        (want_crc,) = struct.unpack_from("<I", head, HEADER_BYTES - 4)
        if zlib.adler32(head[:HEADER_BYTES - 4]) != want_crc:
            raise ArtifactError(f"{path}: header checksum mismatch")
        (magic, version, width, vocab, num_postings, max_doc_id,
         blob_bytes, payload_bytes, payload_crc) = struct.unpack_from(
            _HEADER_FMT, head)
        if magic != MAGIC:
            raise ArtifactError(
                f"{path}: bad magic {magic!r} (not an index.mri)")
        if version != VERSION:
            raise ArtifactError(
                f"{path}: unsupported artifact version {version} "
                f"(this reader knows version {VERSION})")
        layout, total = _layout(vocab, num_postings, blob_bytes)
        if total != size or payload_bytes != size - HEADER_BYTES:
            raise ArtifactError(
                f"{path}: truncated artifact — header promises "
                f"{total} bytes, file has {size}")
        if zlib.adler32(mm[HEADER_BYTES:]) != payload_crc:
            raise ArtifactError(f"{path}: payload checksum mismatch")

        raw = np.frombuffer(mm, dtype=np.uint8)
        dtypes = {"letter_dir": np.int64, "term_offsets": np.int64,
                  "term_blob": np.uint8, "df": np.int32,
                  "post_offsets": np.int64, "postings": np.int32,
                  "df_order": np.int32}
        views = {name: raw[off:off + nbytes].view(dtypes[name])
                 for name, (off, nbytes) in layout.items()}
        meta = {"vocab": vocab, "num_postings": num_postings,
                "max_doc_id": max_doc_id, "width": width, "nbytes": size}
        return Artifact(path, mm, meta, views)
    except ArtifactError:
        mm.close()
        raise
    except Exception:
        mm.close()
        raise


def term_table(art: Artifact) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Materialize the engines' term-resolution columns from the blob.

    Returns ``(rows, terms, key8)``:

    - ``rows``   (max(V,1), width) uint8 — NUL-padded fixed-width term
      rows, scattered from the compact blob in two vectorized ops
    - ``terms``  (V,) ``S{width}`` view of those rows (exact-match gathers)
    - ``key8``   (V, 8) uint8 — each term's NUL-padded 8-byte prefix;
      viewed big-endian, numeric order == lexicographic term order, so
      it is THE binary-search key column (host: one ``>u8`` view;
      device: a (hi, lo) ``u32`` pair, x64-free)
    """
    V, width = art.vocab, max(art.width, 1)
    lens = np.diff(art.term_offsets)
    rows = np.zeros((max(V, 1), width), dtype=np.uint8)
    if V:
        rows[np.arange(width) < lens[:, None]] = art.term_blob
    terms = rows.view(f"S{width}").ravel()[:V]
    pad = rows if width >= 8 else np.pad(rows, ((0, 0), (0, 8 - width)))
    key8 = np.ascontiguousarray(pad[:, :8])[:V]
    return rows, terms, key8


def device_columns(art: Artifact) -> dict:
    """Host-side staging of every column the device engine uploads.

    All integer columns are narrowed to 32-bit (jax default, x64 off):
    the 8-byte prefix key becomes a big-endian ``(key_hi, key_lo)``
    uint32 pair whose pairwise lexicographic order equals the u64
    numeric order, and ``post_offsets`` drops to int32 — guarded, since
    an artifact with >= 2**31 postings can't be addressed that way.
    ``max_prefix_group`` is the largest set of vocabulary terms sharing
    one 8-byte prefix: the static trip count of the device lookup's
    collision-fixup loop.
    """
    if art.num_postings >= 2 ** 31 or art.vocab >= 2 ** 31:
        raise ArtifactError(
            f"{art.path}: {art.num_postings} postings / {art.vocab} terms "
            f"exceed the device engine's int32 addressing")
    rows, _, key8 = term_table(art)
    V = art.vocab
    if V:
        key_hi = np.ascontiguousarray(key8[:, :4]).view(">u4").ravel()
        key_lo = np.ascontiguousarray(key8[:, 4:]).view(">u4").ravel()
        groups = np.unique(key8.view(">u8").ravel(), return_counts=True)[1]
        max_group = int(groups.max())
    else:
        key_hi = key_lo = np.zeros(0, dtype=np.uint32)
        max_group = 1
    return {
        "rows": rows[:V],
        "key_hi": key_hi.astype(np.uint32),
        "key_lo": key_lo.astype(np.uint32),
        "df": np.ascontiguousarray(art.df, dtype=np.int32),
        "post_offsets": np.ascontiguousarray(
            art.post_offsets, dtype=np.int32),
        "postings": np.ascontiguousarray(art.postings, dtype=np.int32),
        "df_order": np.ascontiguousarray(art.df_order, dtype=np.int32),
        "letter_dir": np.ascontiguousarray(art.letter_dir, dtype=np.int32),
        "max_prefix_group": max_group,
        "vocab": V,
        "width": max(art.width, 1),
    }


def checksum(path: str | Path) -> tuple[str, int]:
    """``(adler32_hex, size)`` of the artifact file — the audit
    manifest's fingerprint, same scheme as the letter files."""
    data = Path(path).read_bytes()
    return f"{zlib.adler32(data):08x}", len(data)


# -- builders: lex arrays from each engine family's native shapes --------


def build_from_merge(path, merge) -> int:
    """Pack straight off a live :class:`native.HostIndexMerge`: one C++
    pass fills every payload section of the final file buffer at the
    layout's offsets — compact blob, delta-encoded postings and all —
    leaving only checksums, the header, and the atomic write here.  The
    cpu backend's fast path: the two-step ``export_arrays`` +
    :func:`build_from_export` route costs ~2x more on the pack-time
    budget (<= 10 % of the unaudited e2e)."""
    vocab, width, num_pairs, blob_bytes, max_doc_id = merge.export_info()
    layout, total = _layout(vocab, num_pairs, blob_bytes)
    buf = np.zeros(total, dtype=np.uint8)
    merge.export_payload(buf, {n: off for n, (off, _) in layout.items()})
    return _write(path, buf, width=width, vocab=vocab,
                  num_postings=num_pairs, max_doc_id=max_doc_id,
                  blob_bytes=blob_bytes)


def build_from_export(path, export: dict) -> int:
    """Pack from :meth:`native.HostIndexMerge.export_arrays` output —
    the cpu backend's no-text-round-trip path."""
    vocab_packed = export["vocab_packed"]
    word_lens = np.asarray(export["word_lens"], dtype=np.int64)
    term_offsets = np.zeros(len(word_lens) + 1, dtype=np.int64)
    np.cumsum(word_lens, out=term_offsets[1:])
    if len(word_lens):
        # trim the NUL padding out of the fixed-width rows, vectorized:
        # keep column j of row i when j < word_lens[i]
        width = vocab_packed.shape[1]
        mask = np.arange(width) < word_lens[:, None]
        term_blob = vocab_packed[mask]
    else:
        term_blob = np.zeros(0, dtype=np.uint8)
    return pack(
        path, term_blob=term_blob, term_offsets=term_offsets,
        df=export["df"], post_offsets=export["offsets"],
        postings=export["postings"], df_order=export["df_order"],
        max_doc_id=export["max_doc_id"], width=export["width"])


def build_from_emit_arrays(path, *, vocab: np.ndarray, order: np.ndarray,
                           df: np.ndarray, offsets: np.ndarray,
                           postings: np.ndarray, max_doc_id: int) -> int:
    """Pack from ``formatter.emit_index``'s argument shapes (the device
    engines' host-side arrays): 'S' terms in ANY order (re-sorted to
    the lex invariant here), ``order`` the emit permutation over those
    indices, ``offsets``/``df`` addressing absolute postings in a
    possibly oversized buffer (gaps re-compacted here)."""
    vocab = np.asarray(vocab)
    df = np.asarray(df, dtype=np.int64)
    order = np.asarray(order, dtype=np.int64)
    offsets = np.asarray(offsets, dtype=np.int64)
    postings = np.asarray(postings, dtype=np.int32)
    V = len(vocab)
    # original index -> lex rank (identity when vocab arrives sorted,
    # e.g. from the one-shot device engine's sorted-unique output)
    perm = np.argsort(vocab, kind="stable")
    inv = np.empty(V, dtype=np.int64)
    inv[perm] = np.arange(V)
    vocab = vocab[perm]
    df_lex = df[perm]
    starts_lex = offsets[perm]
    lens = np.char.str_len(vocab).astype(np.int64) if V else \
        np.zeros(0, dtype=np.int64)
    term_offsets = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(lens, out=term_offsets[1:])
    if V:
        width = vocab.dtype.itemsize
        rows = np.ascontiguousarray(vocab).view(np.uint8).reshape(V, width)
        term_blob = rows[np.arange(width) < lens[:, None]]
    else:
        term_blob = np.zeros(0, dtype=np.uint8)
    post_offsets = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(df_lex, out=post_offsets[1:])
    P = int(post_offsets[-1])
    flat = np.zeros(0, dtype=np.int32)
    if P:
        src = (np.repeat(starts_lex, df_lex)
               + (np.arange(P) - np.repeat(post_offsets[:-1], df_lex)))
        flat = postings[src]
    return pack(
        path, term_blob=term_blob, term_offsets=term_offsets, df=df_lex,
        post_offsets=post_offsets, postings=flat,
        df_order=inv[order], max_doc_id=int(max_doc_id))


def build_from_grouped(path, per_letter: dict) -> int:
    """Pack from the oracle/empty-path grouped form: per-letter lists of
    ``(word_bytes, ids)`` already in emit order."""
    words: list[bytes] = []
    ids: list[list[int]] = []
    for letter in sorted(per_letter):
        for word, docs in per_letter[letter]:
            words.append(word)
            ids.append(list(docs))
    emit_to_lex = np.argsort(np.array(words, dtype="S") if words
                             else np.zeros(0, dtype="S1"), kind="stable")
    lex_words = [words[i] for i in emit_to_lex]
    # df_order[emit position] = lex index: the argsort's inverse
    df_order = np.empty(len(words), dtype=np.int64)
    df_order[emit_to_lex] = np.arange(len(words))
    term_blob = np.frombuffer(b"".join(lex_words), dtype=np.uint8)
    term_offsets = np.zeros(len(words) + 1, dtype=np.int64)
    np.cumsum([len(w) for w in lex_words], out=term_offsets[1:])
    df = np.array([len(ids[i]) for i in emit_to_lex], dtype=np.int64)
    post_offsets = np.zeros(len(words) + 1, dtype=np.int64)
    np.cumsum(df, out=post_offsets[1:])
    flat = (np.concatenate([np.asarray(ids[i], dtype=np.int32)
                            for i in emit_to_lex])
            if words else np.zeros(0, dtype=np.int32))
    max_doc_id = int(flat.max()) if len(flat) else 0
    return pack(
        path, term_blob=term_blob, term_offsets=term_offsets, df=df,
        post_offsets=post_offsets, postings=flat, df_order=df_order,
        max_doc_id=max_doc_id)
