"""``index.mri`` — the compact, memory-mappable serving artifact.

The letter files are the conformance surface (byte-exact against the
reference); this is the *serving* surface: one columnar file the query
engine mmaps and reads with zero-copy numpy views, so a process serving
lookups never re-parses text.

Format v1, little-endian throughout:

    header (96 bytes)
      magic            8s   b"MRIIDX01"
      version          u32  1
      width            u32  fixed term-row width (max term length)
      vocab            i64  V — number of terms
      num_postings     i64  P — total (term, doc) pairs
      max_doc_id       i64
      term_blob_bytes  i64
      payload_bytes    i64  everything after the header
      payload_adler32  u32  over the payload bytes
      reserved         32 zero bytes
      header_adler32   u32  over header bytes [0, 92)

    payload — fixed section order, each section 16-byte aligned:
      letter_dir    i64[27]   lex term-index bounds per first letter
      term_offsets  i64[V+1]  exclusive prefix into term_blob
      term_blob     u8[...]   term bytes, lex order, no separators
      df            i32[V]    document frequency per term
      post_offsets  i64[V+1]  exclusive prefix into postings
      postings      i32[P]    per-term runs, delta-encoded: first doc id
                              absolute, the rest diffs (>= 1 — ids are
                              strictly ascending within a term)
      df_order      i32[V]    emit-order permutation over lex indices
                              (letter asc, df desc, word asc); its
                              letter bounds are letter_dir too, since
                              both orders are letter-contiguous

Terms are in lexicographic order — the engine's binary-search key — and
``df_order`` gives O(k) top-k-by-df per letter.  Writes are atomic
(tmp + rename), loads verify both checksums before any answer is
served: a torn artifact raises :class:`ArtifactError`, never garbage.

Format v2 (``$MRI_SERVE_FORMAT``, the default) keeps the header
discipline and the term sections but stores postings as fixed-size
blocks of ``block_size`` doc ids (``$MRI_SERVE_BLOCK_SIZE``, default
128, power of two).  The reserved header bytes gain, at offset 60:

      block_size       u32
      reserved0        u32
      num_blocks       i64  NB — total blocks over all terms
      post_data_bytes  i64
      tf_data_bytes    i64

and the payload becomes (same alignment discipline):

      letter_dir    i64[27]   as v1
      term_offsets  i64[V+1]  as v1
      term_blob     u8[...]   as v1
      df            i32[V]    as v1
      blk_max       i32[NB]   skip table: last doc id per block
      blk_first     i32[NB]   first doc id per block (absolute, so any
                              block decodes without its predecessors)
      blk_width     u8[NB]    bit width of the block's packed deltas
      blk_tf_width  u8[NB]    bit width of the block's packed tf
      post_data     u8[...]   per block: (count-1) values of
                              (delta - 1) at blk_width bits, LSB-first
                              little-endian, zero-padded to a 4-byte
                              boundary per block (width 0 => 0 bytes)
      tf_data       u8[...]   per block: count values of (tf - 1) at
                              blk_tf_width bits, same packing
      doc_lens      i32[max_doc_id + 1]  tokens per document (BM25
                              length norm; 0 = absent doc)
      df_order      i32[V]    as v1

Nothing else is stored: block counts per term derive from ``df``, and
each block's byte offset derives from the width/count columns — the
loader reconstructs both prefix sums vectorized at load time.

Format v2.1 (version 3, the default) is v2 plus two per-block
max-score columns for dynamic pruning (Block-Max WAND / MaxScore):

      blk_max_tf    u8/u16[NB]  max tf in the block, saturated at
                                2**score_bits - 1 (saturation means
                                "assume the tf->inf BM25 limit")
      blk_min_dl    u8/u16[NB]  min doc length in the block, saturated
                                the same way (a saturated/short length
                                only loosens the bound, never unsafe)

inserted between ``blk_tf_width`` and ``post_data``.  ``score_bits``
(8 or 16, ``$MRI_SERVE_SCORE_BITS``) lives in the v2 header's
reserved0 slot, which v2 writers always zeroed.  Integer columns —
not quantized floats — keep the native C++ exporter and the
pure-Python packer bit-identical; the engines derive the float BM25
upper bound ``idf * (k1+1) * mtf / (mtf + k1*(1-b+b*mdl/avgdl))``
from them at query time.  v1 and v2 stay readable forever; engines
fall back to exhaustive scoring when the columns are absent.
"""

from __future__ import annotations

import mmap
import os
import struct
import zlib
from pathlib import Path

import numpy as np

from ..utils import envknobs
from ..utils.checksum import file_checksum

#: Written next to a.txt..z.txt by ``--artifact`` runs.
ARTIFACT_NAME = "index.mri"

MAGIC = b"MRIIDX01"
VERSION = 1
VERSION_V2 = 2
VERSION_V21 = 3
HEADER_BYTES = 96
_ALIGN = 16
_HEADER_FMT = "<8sIIqqqqqI"  # ... + 32 reserved + u32 header_adler32
_HEADER_V2_FMT = "<IIqqq"    # v2+: packed into the 32 reserved bytes
_HEADER_V2_OFF = struct.calcsize(_HEADER_FMT)  # 60

#: Artifact format written by the builders (1, 2 or 3; older versions
#: stay readable forever), the v2+ postings block size (power of two
#: >= 2), and the v2.1 max-score column width (8 or 16 bits).
FORMAT_ENV = "MRI_SERVE_FORMAT"
BLOCK_ENV = "MRI_SERVE_BLOCK_SIZE"
SCORE_BITS_ENV = "MRI_SERVE_SCORE_BITS"

DEFAULT_BLOCK_SIZE = 128
DEFAULT_SCORE_BITS = 8


class ArtifactError(RuntimeError):
    """The artifact is missing, torn, or not an artifact at all."""


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _layout(vocab: int, num_postings: int, blob_bytes: int):
    """Section name -> (file offset, byte length), plus total file size.

    Deterministic from the three header scalars, so the loader never
    stores per-section offsets in the file.
    """
    sections = [
        ("letter_dir", 27 * 8),
        ("term_offsets", (vocab + 1) * 8),
        ("term_blob", blob_bytes),
        ("df", vocab * 4),
        ("post_offsets", (vocab + 1) * 8),
        ("postings", num_postings * 4),
        ("df_order", vocab * 4),
    ]
    out: dict[str, tuple[int, int]] = {}
    cur = HEADER_BYTES
    for name, nbytes in sections:
        cur = _align(cur)
        out[name] = (cur, nbytes)
        cur += nbytes
    return out, _align(cur)


def _layout_v2(vocab: int, blob_bytes: int, num_blocks: int,
               post_data_bytes: int, tf_data_bytes: int, max_doc_id: int,
               score_bits: int = 0):
    """v2/v2.1 section name -> (file offset, byte length), plus total
    size — deterministic from the header scalars, like :func:`_layout`.
    ``score_bits`` 0 is plain v2; 8/16 inserts the v2.1 max-score
    columns (every section offset before them is unchanged)."""
    sections = [
        ("letter_dir", 27 * 8),
        ("term_offsets", (vocab + 1) * 8),
        ("term_blob", blob_bytes),
        ("df", vocab * 4),
        ("blk_max", num_blocks * 4),
        ("blk_first", num_blocks * 4),
        ("blk_width", num_blocks),
        ("blk_tf_width", num_blocks),
        ("post_data", post_data_bytes),
        ("tf_data", tf_data_bytes),
        ("doc_lens", (max_doc_id + 1) * 4),
        ("df_order", vocab * 4),
    ]
    if score_bits:
        sections[8:8] = [
            ("blk_max_tf", num_blocks * (score_bits // 8)),
            ("blk_min_dl", num_blocks * (score_bits // 8)),
        ]
    out: dict[str, tuple[int, int]] = {}
    cur = HEADER_BYTES
    for name, nbytes in sections:
        cur = _align(cur)
        out[name] = (cur, nbytes)
        cur += nbytes
    return out, _align(cur)


def resolve_format(fmt: int | None = None) -> int:
    """The artifact version the builders should write: the explicit
    argument, else ``$MRI_SERVE_FORMAT`` (default 3)."""
    fmt = int(envknobs.get(FORMAT_ENV) if fmt is None else fmt)
    if fmt not in (VERSION, VERSION_V2, VERSION_V21):
        raise ValueError(f"unsupported artifact format {fmt}")
    return fmt


def resolve_score_bits(bits: int | None = None) -> int:
    """The v2.1 max-score column width: the explicit argument, else
    ``$MRI_SERVE_SCORE_BITS``.  Must be 8 or 16."""
    b = int(envknobs.get(SCORE_BITS_ENV) if bits is None else bits)
    if b not in (8, 16):
        raise ValueError(f"{SCORE_BITS_ENV}={b} is not 8 or 16")
    return b


def resolve_block_size(block_size: int | None = None) -> int:
    """The v2 postings block size: the explicit argument, else
    ``$MRI_SERVE_BLOCK_SIZE``.  Must be a power of two >= 2."""
    b = int(envknobs.get(BLOCK_ENV) if block_size is None else block_size)
    if b < 2 or b > (1 << 20) or b & (b - 1):
        raise ValueError(
            f"{BLOCK_ENV}={b} is not a power of two in [2, 2**20]")
    return b


def artifact_path(index_dir: str | Path) -> Path:
    return Path(index_dir) / ARTIFACT_NAME


#: Present in a directory that the incremental-indexing layer manages
#: (mirrors segments.manifest.MANIFEST_NAME; duplicated here so the
#: serve stack can detect segmented directories without importing the
#: build-side segments package).
SEGMENTS_MANIFEST_NAME = "segments.manifest.json"


def is_segment_managed(path) -> bool:
    """Whether ``path`` is a directory whose live truth is a segment
    manifest rather than its (possibly stale) root ``index.mri``.
    Engines refuse to open such a directory as a single artifact.  A
    path to the root ``index.mri`` file itself is equally stale, so it
    is judged by its parent directory (segment artifacts live one level
    down, under ``segments/``, and stay openable)."""
    p = Path(path)
    if p.is_dir():
        return (p / SEGMENTS_MANIFEST_NAME).exists()
    return (p.name == ARTIFACT_NAME
            and (p.parent / SEGMENTS_MANIFEST_NAME).exists())


def pack(path, *, term_blob: np.ndarray, term_offsets: np.ndarray,
         df: np.ndarray, post_offsets: np.ndarray, postings: np.ndarray,
         df_order: np.ndarray, max_doc_id: int, width: int | None = None,
         fmt: int | None = None, tf: np.ndarray | None = None,
         doc_lens: np.ndarray | None = None, block_size: int | None = None
         ) -> int:
    """Write the artifact from lex-order arrays; returns bytes written.

    ``postings`` arrives ABSOLUTE (ascending per term) — the wire
    encoding (v1 deltas or v2 bitpacked blocks, per ``fmt`` /
    ``$MRI_SERVE_FORMAT``) happens here.  ``tf``/``doc_lens`` only
    matter for v2; absent, every tf is 1 and doc lengths fall back to
    the per-doc posting count — self-consistent BM25 stats for builders
    that never saw token-level frequencies.
    """
    fmt = resolve_format(fmt)
    if fmt != VERSION:
        return pack_v2(
            path, term_blob=term_blob, term_offsets=term_offsets, df=df,
            post_offsets=post_offsets, postings=postings, df_order=df_order,
            max_doc_id=max_doc_id, width=width, tf=tf, doc_lens=doc_lens,
            block_size=block_size, fmt=fmt)
    path = Path(path)
    term_offsets = np.ascontiguousarray(term_offsets, dtype=np.int64)
    post_offsets = np.ascontiguousarray(post_offsets, dtype=np.int64)
    term_blob = np.ascontiguousarray(term_blob, dtype=np.uint8)
    df = np.ascontiguousarray(df, dtype=np.int32)
    df_order = np.ascontiguousarray(df_order, dtype=np.int32)
    postings = np.asarray(postings, dtype=np.int32)
    vocab = len(df)
    num_postings = int(post_offsets[-1]) if len(post_offsets) else 0
    blob_bytes = int(term_offsets[-1]) if len(term_offsets) else 0
    if width is None:
        lens = np.diff(term_offsets)
        width = int(lens.max()) if vocab else 1

    deltas = postings.copy()
    if num_postings:
        deltas[1:] -= postings[:-1]
        starts = post_offsets[:-1][np.diff(post_offsets) > 0]
        deltas[starts] = postings[starts]

    layout, total = _layout(vocab, num_postings, blob_bytes)
    buf = np.zeros(total, dtype=np.uint8)

    def put(name: str, arr: np.ndarray) -> None:
        off, nbytes = layout[name]
        buf[off:off + nbytes] = np.frombuffer(arr.tobytes(), dtype=np.uint8)

    first_bytes = term_blob[term_offsets[:-1]] if vocab else term_blob[:0]
    letter_dir = np.searchsorted(
        first_bytes, np.arange(ord("a"), ord("a") + 27)).astype(np.int64)
    put("letter_dir", letter_dir)
    put("term_offsets", term_offsets)
    put("term_blob", term_blob)
    put("df", df)
    put("post_offsets", post_offsets)
    put("postings", deltas)
    put("df_order", df_order)

    return _write(path, buf, width=width, vocab=vocab,
                  num_postings=num_postings, max_doc_id=max_doc_id,
                  blob_bytes=blob_bytes)


def _header(*, width: int, vocab: int, num_postings: int, max_doc_id: int,
            blob_bytes: int, payload_len: int, payload_crc: int,
            version: int = VERSION, v2: dict | None = None) -> bytes:
    header = struct.pack(
        _HEADER_FMT, MAGIC, version, int(max(width, 1)), vocab,
        num_postings, int(max_doc_id), blob_bytes, payload_len,
        payload_crc)
    if v2 is not None:
        header += struct.pack(
            _HEADER_V2_FMT, v2["block_size"], v2.get("score_bits", 0),
            v2["num_blocks"], v2["post_data_bytes"], v2["tf_data_bytes"])
    header = header + b"\0" * (HEADER_BYTES - 4 - len(header))
    return header + struct.pack("<I", zlib.adler32(header))


def _write(path, buf: np.ndarray, *, width: int, vocab: int,
           num_postings: int, max_doc_id: int, blob_bytes: int,
           version: int = VERSION, v2: dict | None = None) -> int:
    """Checksum + header a filled file buffer, write atomically."""
    path = Path(path)
    payload = buf[HEADER_BYTES:]
    header = _header(width=width, vocab=vocab, num_postings=num_postings,
                     max_doc_id=max_doc_id, blob_bytes=blob_bytes,
                     payload_len=len(payload),
                     payload_crc=zlib.adler32(payload),
                     version=version, v2=v2)
    buf[:HEADER_BYTES] = np.frombuffer(header, dtype=np.uint8)

    tmp = path.with_name(path.name + ".tmp")
    # mrilint: allow(fault-boundary) atomic tmp+rename publish; a crash leaves only the .tmp
    with open(tmp, "wb") as f:
        f.write(memoryview(buf))
    os.replace(tmp, path)
    return len(buf)


def _pack_bits(vals: np.ndarray, w: int) -> np.ndarray:
    """Pack ``vals`` (< 2**w each) at ``w`` bits LSB-first into a
    word-aligned little-endian uint8 array (the C++ BitPacker's wire
    form; width 0 packs to nothing)."""
    if w == 0 or not len(vals):
        return np.zeros(0, dtype=np.uint8)
    bits = np.unpackbits(
        np.ascontiguousarray(vals, dtype="<u4").view(np.uint8).reshape(-1, 4),
        axis=1, bitorder="little")[:, :w].ravel()
    pad = (-len(bits)) % 32
    if pad:
        bits = np.concatenate([bits, np.zeros(pad, dtype=np.uint8)])
    return np.packbits(bits, bitorder="little")


def pack_v2(path, *, term_blob: np.ndarray, term_offsets: np.ndarray,
            df: np.ndarray, post_offsets: np.ndarray, postings: np.ndarray,
            df_order: np.ndarray, max_doc_id: int, width: int | None = None,
            tf: np.ndarray | None = None,
            doc_lens: np.ndarray | None = None,
            block_size: int | None = None, fmt: int | None = None,
            score_bits: int | None = None) -> int:
    """Write a format-v2/v2.1 artifact from lex-order ABSOLUTE postings
    (the pure-Python packer — the cpu backend's merge handle has a
    one-pass native equivalent in :func:`build_from_merge`).

    ``tf`` aligns with ``postings`` (defaults to all-ones); ``doc_lens``
    defaults to each doc's tf sum, so scoring stays self-consistent for
    builders without token-level data.  ``fmt`` 3 (the default) adds
    the per-block saturated max-tf / min-doc-length columns.
    """
    path = Path(path)
    fmt = resolve_format(fmt)
    if fmt == VERSION:
        raise ValueError("pack_v2 writes formats 2 and 3, not 1")
    bits = resolve_score_bits(score_bits) if fmt == VERSION_V21 else 0
    B = resolve_block_size(block_size)
    term_offsets = np.ascontiguousarray(term_offsets, dtype=np.int64)
    post_offsets = np.ascontiguousarray(post_offsets, dtype=np.int64)
    term_blob = np.ascontiguousarray(term_blob, dtype=np.uint8)
    df = np.ascontiguousarray(df, dtype=np.int32)
    df_order = np.ascontiguousarray(df_order, dtype=np.int32)
    postings = np.asarray(postings, dtype=np.int32)
    vocab = len(df)
    num_postings = int(post_offsets[-1]) if len(post_offsets) else 0
    blob_bytes = int(term_offsets[-1]) if len(term_offsets) else 0
    if width is None:
        lens = np.diff(term_offsets)
        width = int(lens.max()) if vocab else 1
    if tf is None:
        tf = np.ones(num_postings, dtype=np.int32)
    tf = np.ascontiguousarray(tf, dtype=np.int32)
    if doc_lens is None:
        doc_lens = np.bincount(
            postings, weights=tf,
            minlength=max_doc_id + 1).astype(np.int32)
    doc_lens = np.ascontiguousarray(doc_lens, dtype=np.int32)
    if len(doc_lens) != max_doc_id + 1:
        out = np.zeros(max_doc_id + 1, dtype=np.int32)
        out[:len(doc_lens)] = doc_lens[:max_doc_id + 1]
        doc_lens = out

    blk_max: list[int] = []
    blk_first: list[int] = []
    blk_width: list[int] = []
    blk_tf_width: list[int] = []
    blk_max_tf: list[int] = []
    blk_min_dl: list[int] = []
    post_parts: list[np.ndarray] = []
    tf_parts: list[np.ndarray] = []
    cap = (1 << bits) - 1 if bits else 0
    for t in range(vocab):
        lo, hi = int(post_offsets[t]), int(post_offsets[t + 1])
        for b0 in range(lo, hi, B):
            b1 = min(b0 + B, hi)
            docs = postings[b0:b1].astype(np.int64)
            tfs = tf[b0:b1].astype(np.int64)
            blk_first.append(int(docs[0]))
            blk_max.append(int(docs[-1]))
            deltas = np.diff(docs) - 1
            w = int(deltas.max()).bit_length() if len(deltas) and \
                deltas.max() > 0 else 0
            tw = int(tfs.max() - 1).bit_length() if tfs.max() > 1 else 0
            blk_width.append(w)
            blk_tf_width.append(tw)
            post_parts.append(_pack_bits(deltas, w))
            tf_parts.append(_pack_bits(tfs - 1, tw))
            if bits:
                # saturated integer columns (never floats: the native
                # exporter must reproduce these bytes exactly)
                blk_max_tf.append(min(int(tfs.max()), cap))
                blk_min_dl.append(min(int(doc_lens[docs].min()), cap))
    post_data = (np.concatenate(post_parts) if post_parts
                 else np.zeros(0, dtype=np.uint8))
    tf_data = (np.concatenate(tf_parts) if tf_parts
               else np.zeros(0, dtype=np.uint8))
    num_blocks = len(blk_max)

    layout, total = _layout_v2(vocab, blob_bytes, num_blocks,
                               len(post_data), len(tf_data), max_doc_id,
                               score_bits=bits)
    buf = np.zeros(total, dtype=np.uint8)

    def put(name: str, arr: np.ndarray) -> None:
        off, nbytes = layout[name]
        buf[off:off + nbytes] = np.frombuffer(arr.tobytes(), dtype=np.uint8)

    first_bytes = term_blob[term_offsets[:-1]] if vocab else term_blob[:0]
    letter_dir = np.searchsorted(
        first_bytes, np.arange(ord("a"), ord("a") + 27)).astype(np.int64)
    put("letter_dir", letter_dir)
    put("term_offsets", term_offsets)
    put("term_blob", term_blob)
    put("df", df)
    put("blk_max", np.asarray(blk_max, dtype=np.int32))
    put("blk_first", np.asarray(blk_first, dtype=np.int32))
    put("blk_width", np.asarray(blk_width, dtype=np.uint8))
    put("blk_tf_width", np.asarray(blk_tf_width, dtype=np.uint8))
    if bits:
        sdt = "<u1" if bits == 8 else "<u2"
        put("blk_max_tf", np.asarray(blk_max_tf, dtype=sdt))
        put("blk_min_dl", np.asarray(blk_min_dl, dtype=sdt))
    put("post_data", post_data)
    put("tf_data", tf_data)
    put("doc_lens", doc_lens)
    put("df_order", df_order)

    return _write(path, buf, width=width, vocab=vocab,
                  num_postings=num_postings, max_doc_id=max_doc_id,
                  blob_bytes=blob_bytes, version=fmt,
                  v2={"block_size": B, "num_blocks": num_blocks,
                      "score_bits": bits,
                      "post_data_bytes": len(post_data),
                      "tf_data_bytes": len(tf_data)})


class Artifact:
    """Zero-copy numpy views over a verified, mmapped ``index.mri``.

    Both format versions present the same decode API; v2 additionally
    exposes the block skip table (``blk_max``/``blk_first``/widths), the
    derived block geometry (``term_block_off``, ``blk_cnt``, word-offset
    prefix sums) and the BM25 columns (``decode_tf``, ``doc_lens``).
    """

    _VIEW_NAMES = ("letter_dir", "term_offsets", "term_blob", "df",
                   "post_offsets", "postings", "df_order",
                   "blk_max", "blk_first", "blk_width", "blk_tf_width",
                   "blk_max_tf", "blk_min_dl",
                   "post_words", "tf_words", "doc_lens")

    def __init__(self, path: Path, mm: mmap.mmap, meta: dict,
                 views: dict[str, np.ndarray]):
        self.path = path
        self._mm = mm
        self.version = meta.get("version", VERSION)
        self.vocab = meta["vocab"]
        self.num_postings = meta["num_postings"]
        self.max_doc_id = meta["max_doc_id"]
        self.width = meta["width"]
        self.nbytes = meta["nbytes"]
        for name in self._VIEW_NAMES:
            setattr(self, name, views.get(name))
        # v2 derived block geometry (computed by the loader, vectorized)
        self.block_size = meta.get("block_size", 0)
        self.num_blocks = meta.get("num_blocks", 0)
        self.score_bits = meta.get("score_bits", 0)
        self.term_block_off = meta.get("term_block_off")
        self.blk_cnt = meta.get("blk_cnt")
        self.blk_woff = meta.get("blk_woff")
        self.blk_tf_woff = meta.get("blk_tf_woff")

    @property
    def has_block_scores(self) -> bool:
        """True when the v2.1 per-block max-score columns are present
        (the planner's precondition for Block-Max WAND / MaxScore)."""
        return self.blk_max_tf is not None

    def term(self, idx: int) -> bytes:
        lo, hi = self.term_offsets[idx], self.term_offsets[idx + 1]
        return self.term_blob[lo:hi].tobytes()

    def _gather_packed(self, sel: np.ndarray, words: np.ndarray,
                       woff: np.ndarray, widths: np.ndarray,
                       nvals: np.ndarray) -> np.ndarray:
        """Decode variable-width packed values for the selected blocks.

        ``sel`` indexes blocks; block i holds ``nvals[i]`` values at
        ``widths[i]`` bits starting at word ``woff[i]`` of ``words``.
        Returns an (len(sel), max(nvals)) int64 matrix; entries past a
        block's count are 0.  Fully vectorized: one word gather, one
        unpackbits, one broadcast bit-gather, one matmul.
        """
        n = len(sel)
        J = int(nvals.max()) if n else 0
        out = np.zeros((n, max(J, 1)), dtype=np.int64)
        if not n or not J:
            return out
        W = int(widths.max())
        wlen = (nvals * widths + 31) >> 5
        total = int(wlen.sum())
        if not W or not total:
            return out
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(wlen[:-1], out=starts[1:])
        word_src = np.repeat(woff - starts, wlen) + np.arange(total)
        bits = np.unpackbits(
            np.ascontiguousarray(words[word_src]).view(np.uint8),
            bitorder="little")
        j = np.arange(J)
        k = np.arange(W)
        bitpos = (starts * 32)[:, None] + j[None, :] * widths[:, None]
        idx3 = bitpos[:, :, None] + k[None, None, :]
        np.clip(idx3, 0, bits.size - 1, out=idx3)
        valid = (j[None, :, None] < nvals[:, None, None]) & \
                (k[None, None, :] < widths[:, None, None])
        g = np.where(valid, bits[idx3], 0)
        out[:, :J] = g @ (np.int64(1) << k)
        return out

    def decode_blocks(self, sel: np.ndarray) -> tuple[np.ndarray,
                                                      np.ndarray]:
        """v2: absolute doc ids of the selected (global) block indices.

        Returns ``(ids, cnt)`` — an (len(sel), block_size) int32 matrix
        (entries past ``cnt[i]`` are garbage; mask with
        ``arange(block_size) < cnt[:, None]``) and the per-block counts.
        """
        sel = np.asarray(sel, dtype=np.int64)
        cnt = self.blk_cnt[sel].astype(np.int64)
        w = self.blk_width[sel].astype(np.int64)
        deltas = self._gather_packed(sel, self.post_words,
                                     self.blk_woff[sel], w, cnt - 1)
        B = self.block_size
        out = np.zeros((len(sel), B), dtype=np.int64)
        out[:, 0] = self.blk_first[sel]
        out[:, 1:deltas.shape[1] + 1] = np.where(
            np.arange(deltas.shape[1])[None, :] < (cnt - 1)[:, None],
            deltas + 1, 0)
        np.cumsum(out, axis=1, out=out)
        return out.astype(np.int32), cnt

    def decode_tf_blocks(self, sel: np.ndarray) -> tuple[np.ndarray,
                                                         np.ndarray]:
        """v2: per-doc term frequencies of the selected (global) block
        indices, aligned row-for-row with :meth:`decode_blocks` — an
        (len(sel), block_size) int64 matrix plus the per-block counts
        (entries past ``cnt[i]`` are meaningless; mask like
        ``decode_blocks``)."""
        sel = np.asarray(sel, dtype=np.int64)
        cnt = self.blk_cnt[sel].astype(np.int64)
        tw = self.blk_tf_width[sel].astype(np.int64)
        vals = self._gather_packed(sel, self.tf_words,
                                   self.blk_tf_woff[sel], tw, cnt)
        B = self.block_size
        tfm = (vals + 1)[:, :B]
        if tfm.shape[1] < B:
            tfm = np.pad(tfm, ((0, 0), (0, B - tfm.shape[1])))
        return tfm, cnt

    def decode_postings(self, idx: int) -> np.ndarray:
        """One term's absolute ascending doc ids (a fresh array)."""
        if self.version == VERSION:
            lo, hi = self.post_offsets[idx], self.post_offsets[idx + 1]
            return np.cumsum(self.postings[lo:hi], dtype=np.int64).astype(
                np.int32)
        b0, b1 = self.term_block_off[idx], self.term_block_off[idx + 1]
        if b0 == b1:
            return np.zeros(0, dtype=np.int32)
        ids, cnt = self.decode_blocks(np.arange(b0, b1))
        return ids[np.arange(self.block_size)[None, :] < cnt[:, None]]

    def decode_tf(self, idx: int) -> np.ndarray:
        """One term's per-document term frequencies, aligned with
        :meth:`decode_postings` (v1 artifacts carry no tf: all ones)."""
        if self.version == VERSION:
            df = int(self.post_offsets[idx + 1] - self.post_offsets[idx])
            return np.ones(df, dtype=np.int32)
        b0, b1 = self.term_block_off[idx], self.term_block_off[idx + 1]
        if b0 == b1:
            return np.zeros(0, dtype=np.int32)
        sel = np.arange(b0, b1)
        cnt = self.blk_cnt[sel].astype(np.int64)
        tw = self.blk_tf_width[sel].astype(np.int64)
        vals = self._gather_packed(sel, self.tf_words,
                                   self.blk_tf_woff[sel], tw, cnt)
        tfm = (vals + 1)[:, :self.block_size]
        if tfm.shape[1] < self.block_size and len(sel) > 1:
            tfm = np.pad(tfm, ((0, 0),
                               (0, self.block_size - tfm.shape[1])))
        return tfm[np.arange(tfm.shape[1])[None, :]
                   < cnt[:, None]].astype(np.int32)

    def close(self) -> None:
        # drop the views before the mmap: numpy holds buffer references
        for name in self._VIEW_NAMES:
            setattr(self, name, None)
        self.term_block_off = self.blk_cnt = None
        self.blk_woff = self.blk_tf_woff = None
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # a caller still holds a view (e.g. an engine's df
                # column): the map frees when the last view dies
                pass
            self._mm = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_artifact(path: str | Path) -> Artifact:
    """mmap + verify an artifact (a directory means its ``index.mri``).

    Every structural and checksum violation raises :class:`ArtifactError`
    with a one-line reason — the contract the CLI maps to exit 2.
    """
    path = Path(path)
    if path.is_dir():
        path = path / ARTIFACT_NAME
    try:
        f = open(path, "rb")
    except OSError as e:
        msg = f"{path}: cannot open artifact ({e})"
        # A letter-file index next to a missing index.mri means the
        # build ran without --artifact: name the remediation instead of
        # leaving the operator to diff the two output formats.
        if path.name == ARTIFACT_NAME and not path.exists() \
                and (path.parent / "a.txt").exists():
            msg += ("; directory holds a letter-file index built "
                    "without --artifact — rebuild with --artifact "
                    "to pack index.mri")
        raise ArtifactError(msg) from e
    with f:
        try:
            size = os.fstat(f.fileno()).st_size
            if size < HEADER_BYTES:
                raise ArtifactError(
                    f"{path}: {size} bytes is smaller than the "
                    f"{HEADER_BYTES}-byte header")
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError) as e:
            raise ArtifactError(f"{path}: cannot map artifact ({e})") from e
    try:
        head = bytes(mm[:HEADER_BYTES])
        (want_crc,) = struct.unpack_from("<I", head, HEADER_BYTES - 4)
        if zlib.adler32(head[:HEADER_BYTES - 4]) != want_crc:
            raise ArtifactError(f"{path}: header checksum mismatch")
        (magic, version, width, vocab, num_postings, max_doc_id,
         blob_bytes, payload_bytes, payload_crc) = struct.unpack_from(
            _HEADER_FMT, head)
        if magic != MAGIC:
            raise ArtifactError(
                f"{path}: bad magic {magic!r} (not an index.mri)")
        if version not in (VERSION, VERSION_V2, VERSION_V21):
            raise ArtifactError(
                f"{path}: unsupported artifact version {version} "
                f"(this reader knows versions {VERSION}-{VERSION_V21})")
        v2 = None
        score_bits = 0
        if version >= VERSION_V2:
            (block_size, score_bits, num_blocks, post_data_bytes,
             tf_data_bytes) = struct.unpack_from(
                _HEADER_V2_FMT, head, _HEADER_V2_OFF)
            if block_size < 2 or block_size & (block_size - 1):
                raise ArtifactError(
                    f"{path}: invalid v2 block size {block_size}")
            if version == VERSION_V2:
                score_bits = 0  # v2 writers zeroed this slot
            elif score_bits not in (8, 16):
                raise ArtifactError(
                    f"{path}: invalid v2.1 score_bits {score_bits}")
            v2 = (block_size, num_blocks, post_data_bytes, tf_data_bytes)
            layout, total = _layout_v2(
                vocab, blob_bytes, num_blocks, post_data_bytes,
                tf_data_bytes, max_doc_id, score_bits=score_bits)
        else:
            layout, total = _layout(vocab, num_postings, blob_bytes)
        if total != size or payload_bytes != size - HEADER_BYTES:
            raise ArtifactError(
                f"{path}: truncated artifact — header promises "
                f"{total} bytes, file has {size}")
        if zlib.adler32(mm[HEADER_BYTES:]) != payload_crc:
            raise ArtifactError(f"{path}: payload checksum mismatch")

        raw = np.frombuffer(mm, dtype=np.uint8)
        dtypes = {"letter_dir": np.int64, "term_offsets": np.int64,
                  "term_blob": np.uint8, "df": np.int32,
                  "post_offsets": np.int64, "postings": np.int32,
                  "df_order": np.int32,
                  "blk_max": np.int32, "blk_first": np.int32,
                  "blk_width": np.uint8, "blk_tf_width": np.uint8,
                  "blk_max_tf": "<u1" if score_bits == 8 else "<u2",
                  "blk_min_dl": "<u1" if score_bits == 8 else "<u2",
                  "post_words": np.uint32, "tf_words": np.uint32,
                  "doc_lens": np.int32}
        names = {"post_data": "post_words", "tf_data": "tf_words"}
        views = {}
        for name, (off, nbytes) in layout.items():
            name = names.get(name, name)
            views[name] = raw[off:off + nbytes].view(dtypes[name])
        meta = {"version": version, "vocab": vocab,
                "num_postings": num_postings,
                "max_doc_id": max_doc_id, "width": width, "nbytes": size}
        if v2 is not None:
            block_size, num_blocks, post_data_bytes, tf_data_bytes = v2
            df = views["df"].astype(np.int64)
            bpt = -(-df // block_size)  # ceil(df / B); 0 for df == 0
            term_block_off = np.zeros(vocab + 1, dtype=np.int64)
            np.cumsum(bpt, out=term_block_off[1:])
            if term_block_off[-1] != num_blocks:
                raise ArtifactError(
                    f"{path}: v2 geometry mismatch — df implies "
                    f"{int(term_block_off[-1])} blocks, header says "
                    f"{num_blocks}")
            blk_cnt = np.full(num_blocks, block_size, dtype=np.int32)
            last = term_block_off[1:][bpt > 0] - 1
            blk_cnt[last] = (df[bpt > 0]
                             - (bpt[bpt > 0] - 1) * block_size)
            cnt64 = blk_cnt.astype(np.int64)
            pw = (np.maximum(cnt64 - 1, 0)
                  * views["blk_width"].astype(np.int64) + 31) >> 5
            tw = (cnt64 * views["blk_tf_width"].astype(np.int64)
                  + 31) >> 5
            blk_woff = np.zeros(num_blocks + 1, dtype=np.int64)
            np.cumsum(pw, out=blk_woff[1:])
            blk_tf_woff = np.zeros(num_blocks + 1, dtype=np.int64)
            np.cumsum(tw, out=blk_tf_woff[1:])
            if blk_woff[-1] * 4 != post_data_bytes \
                    or blk_tf_woff[-1] * 4 != tf_data_bytes:
                raise ArtifactError(
                    f"{path}: v2 geometry mismatch — widths imply "
                    f"{int(blk_woff[-1]) * 4}/{int(blk_tf_woff[-1]) * 4} "
                    f"packed bytes, header says "
                    f"{post_data_bytes}/{tf_data_bytes}")
            meta.update(block_size=block_size, num_blocks=num_blocks,
                        score_bits=score_bits,
                        term_block_off=term_block_off, blk_cnt=blk_cnt,
                        blk_woff=blk_woff, blk_tf_woff=blk_tf_woff)
        return Artifact(path, mm, meta, views)
    except ArtifactError:
        mm.close()
        raise
    except Exception:
        mm.close()
        raise


def term_table(art: Artifact) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Materialize the engines' term-resolution columns from the blob.

    Returns ``(rows, terms, key8)``:

    - ``rows``   (max(V,1), width) uint8 — NUL-padded fixed-width term
      rows, scattered from the compact blob in two vectorized ops
    - ``terms``  (V,) ``S{width}`` view of those rows (exact-match gathers)
    - ``key8``   (V, 8) uint8 — each term's NUL-padded 8-byte prefix;
      viewed big-endian, numeric order == lexicographic term order, so
      it is THE binary-search key column (host: one ``>u8`` view;
      device: a (hi, lo) ``u32`` pair, x64-free)
    """
    V, width = art.vocab, max(art.width, 1)
    lens = np.diff(art.term_offsets)
    rows = np.zeros((max(V, 1), width), dtype=np.uint8)
    if V:
        rows[np.arange(width) < lens[:, None]] = art.term_blob
    terms = rows.view(f"S{width}").ravel()[:V]
    pad = rows if width >= 8 else np.pad(rows, ((0, 0), (0, 8 - width)))
    key8 = np.ascontiguousarray(pad[:, :8])[:V]
    return rows, terms, key8


def device_columns(art: Artifact) -> dict:
    """Host-side staging of every column the device engine uploads.

    All integer columns are narrowed to 32-bit (jax default, x64 off):
    the 8-byte prefix key becomes a big-endian ``(key_hi, key_lo)``
    uint32 pair whose pairwise lexicographic order equals the u64
    numeric order, and ``post_offsets`` drops to int32 — guarded, since
    an artifact with >= 2**31 postings can't be addressed that way.
    ``max_prefix_group`` is the largest set of vocabulary terms sharing
    one 8-byte prefix: the static trip count of the device lookup's
    collision-fixup loop.
    """
    if art.num_postings >= 2 ** 31 or art.vocab >= 2 ** 31:
        raise ArtifactError(
            f"{art.path}: {art.num_postings} postings / {art.vocab} terms "
            f"exceed the device engine's int32 addressing")
    rows, _, key8 = term_table(art)
    V = art.vocab
    if V:
        key_hi = np.ascontiguousarray(key8[:, :4]).view(">u4").ravel()
        key_lo = np.ascontiguousarray(key8[:, 4:]).view(">u4").ravel()
        groups = np.unique(key8.view(">u8").ravel(), return_counts=True)[1]
        max_group = int(groups.max())
    else:
        key_hi = key_lo = np.zeros(0, dtype=np.uint32)
        max_group = 1
    cols = {
        "format": art.version,
        "rows": rows[:V],
        "key_hi": key_hi.astype(np.uint32),
        "key_lo": key_lo.astype(np.uint32),
        "df": np.ascontiguousarray(art.df, dtype=np.int32),
        "df_order": np.ascontiguousarray(art.df_order, dtype=np.int32),
        "letter_dir": np.ascontiguousarray(art.letter_dir, dtype=np.int32),
        "max_prefix_group": max_group,
        "vocab": V,
        "width": max(art.width, 1),
        "max_doc_id": art.max_doc_id,
    }
    if art.version == VERSION:
        cols["post_offsets"] = np.ascontiguousarray(
            art.post_offsets, dtype=np.int32)
        cols["postings"] = np.ascontiguousarray(
            art.postings, dtype=np.int32)
        return cols
    # v2: blocked layout.  All word offsets must fit int32 addressing;
    # one zero pad word past each packed stream lets the unaligned
    # two-word bit-window gather read words[i + 1] unconditionally.
    if art.blk_woff[-1] >= 2 ** 31 - 1 \
            or art.blk_tf_woff[-1] >= 2 ** 31 - 1:
        raise ArtifactError(
            f"{art.path}: packed postings exceed the device engine's "
            f"int32 word addressing")
    pad = np.zeros(1, dtype=np.uint32)
    cols.update({
        "block_size": art.block_size,
        "term_block_off": np.ascontiguousarray(
            art.term_block_off, dtype=np.int32),
        "blk_first": np.ascontiguousarray(art.blk_first, dtype=np.int32),
        "blk_width": np.ascontiguousarray(art.blk_width, dtype=np.int32),
        "blk_tf_width": np.ascontiguousarray(
            art.blk_tf_width, dtype=np.int32),
        "blk_woff": np.ascontiguousarray(art.blk_woff, dtype=np.int32),
        "blk_tf_woff": np.ascontiguousarray(
            art.blk_tf_woff, dtype=np.int32),
        "post_words": np.concatenate([art.post_words, pad]),
        "tf_words": np.concatenate([art.tf_words, pad]),
        "doc_lens": np.ascontiguousarray(art.doc_lens, dtype=np.int32),
    })
    return cols


def serve_columns(art: Artifact) -> dict:
    """Zero-copy column views handed to the native serve kernels.

    Unlike ``device_columns`` this makes NO padded copies: every entry
    is a view straight into the artifact mmap (or a derived geometry
    array the loader already materialized), so the dict is valid only
    while the artifact stays open.  The native unpack kernel reads one
    u32 word past each block payload unconditionally; that over-read is
    always in-file because the v2 layout places ``tf_data`` /
    ``doc_lens`` / ``df_order`` after ``post_data`` and ``doc_lens`` /
    ``df_order`` after ``tf_data`` (both 16-byte aligned), so no pad
    word is appended here.  ``blk_max_tf`` / ``blk_min_dl`` are exposed
    as raw bytes (``None`` on plain v2): C picks u8 vs u16-LE off
    ``score_bits`` itself.
    """
    if art.version < VERSION_V2:
        raise ArtifactError(
            f"{art.path}: native serve kernels need a v2+ artifact "
            f"(got version {art.version})")
    has_scores = art.score_bits != 0
    return {
        "blk_max": art.blk_max,
        "blk_first": art.blk_first,
        "blk_width": art.blk_width,
        "blk_tf_width": art.blk_tf_width,
        "blk_max_tf": art.blk_max_tf.view(np.uint8) if has_scores
        else None,
        "blk_min_dl": art.blk_min_dl.view(np.uint8) if has_scores
        else None,
        "post_words": art.post_words,
        "tf_words": art.tf_words,
        "term_block_off": art.term_block_off,
        "blk_cnt": art.blk_cnt,
        "blk_woff": art.blk_woff,
        "blk_tf_woff": art.blk_tf_woff,
        "vocab": art.vocab,
        "num_blocks": art.num_blocks,
        "block_size": art.block_size,
        "score_bits": art.score_bits,
    }


def bm25_corpus(art: Artifact) -> tuple[np.ndarray, int, float]:
    """``(doc_lens float64, ndocs, avgdl)`` for BM25 scoring.

    v2 reads the packed doc-length column; v1 carries no lengths, so
    they are reconstructed from the postings themselves (each stored
    pair counts 1 — the same tf=1 fallback the scorer uses).  Shared by
    both engines so their corpus statistics agree exactly.
    """
    if art.version >= VERSION_V2:
        doc_lens = art.doc_lens.astype(np.float64)
    elif art.num_postings:
        flat = art.postings.astype(np.int64)
        starts = art.post_offsets[:-1]
        csum = np.cumsum(flat)
        # undo the per-term delta encoding in one pass: subtract each
        # term's cumulative base, re-anchor at its first absolute id
        base = np.repeat(
            csum[starts] - flat[starts], np.diff(art.post_offsets))
        doc_lens = np.bincount(
            (csum - base).astype(np.int64),
            minlength=art.max_doc_id + 1).astype(np.float64)
    else:
        doc_lens = np.zeros(art.max_doc_id + 1, dtype=np.float64)
    ndocs = int(np.count_nonzero(doc_lens))
    avgdl = float(doc_lens[doc_lens > 0].mean()) if ndocs else 1.0
    return doc_lens, ndocs, avgdl


def checksum(path: str | Path) -> tuple[str, int]:
    """``(adler32_hex, size)`` of the artifact file — the audit
    manifest's fingerprint, same scheme as the letter files.  Shim
    over :func:`..utils.checksum.file_checksum`."""
    return file_checksum(path)


# -- builders: lex arrays from each engine family's native shapes --------


def build_from_merge(path, merge, *, fmt: int | None = None,
                     block_size: int | None = None) -> int:
    """Pack straight off a live :class:`native.HostIndexMerge`: one C++
    pass fills every payload section of the final file buffer at the
    layout's offsets — compact blob, delta-encoded postings and all —
    leaving only checksums, the header, and the atomic write here.  The
    cpu backend's fast path: the two-step ``export_arrays`` +
    :func:`build_from_export` route costs ~2x more on the pack-time
    budget (<= 10 % of the unaudited e2e).

    ``fmt``/``block_size`` default to the ``MRI_SERVE_FORMAT`` /
    ``MRI_SERVE_BLOCK_SIZE`` knobs; format 2 runs the native two-call
    v2 export (prepare sizes the packed streams, payload fills them).
    """
    vocab, width, num_pairs, blob_bytes, max_doc_id = merge.export_info()
    fmt = resolve_format(fmt)
    if fmt >= VERSION_V2:
        block_size = resolve_block_size(block_size)
        bits = resolve_score_bits() if fmt == VERSION_V21 else 0
        num_blocks, post_bytes, tf_bytes = \
            merge.export_v2_prepare(block_size, bits)
        layout, total = _layout_v2(vocab, blob_bytes, num_blocks,
                                   post_bytes, tf_bytes, max_doc_id,
                                   score_bits=bits)
        buf = np.zeros(total, dtype=np.uint8)
        merge.export_v2_payload(
            buf, {n: off for n, (off, _) in layout.items()})
        return _write(path, buf, width=width, vocab=vocab,
                      num_postings=num_pairs, max_doc_id=max_doc_id,
                      blob_bytes=blob_bytes, version=fmt,
                      v2={"block_size": block_size,
                          "num_blocks": num_blocks,
                          "score_bits": bits,
                          "post_data_bytes": post_bytes,
                          "tf_data_bytes": tf_bytes})
    layout, total = _layout(vocab, num_pairs, blob_bytes)
    buf = np.zeros(total, dtype=np.uint8)
    merge.export_payload(buf, {n: off for n, (off, _) in layout.items()})
    return _write(path, buf, width=width, vocab=vocab,
                  num_postings=num_pairs, max_doc_id=max_doc_id,
                  blob_bytes=blob_bytes)


def build_from_export(path, export: dict) -> int:
    """Pack from :meth:`native.HostIndexMerge.export_arrays` output —
    the cpu backend's no-text-round-trip path."""
    vocab_packed = export["vocab_packed"]
    word_lens = np.asarray(export["word_lens"], dtype=np.int64)
    term_offsets = np.zeros(len(word_lens) + 1, dtype=np.int64)
    np.cumsum(word_lens, out=term_offsets[1:])
    if len(word_lens):
        # trim the NUL padding out of the fixed-width rows, vectorized:
        # keep column j of row i when j < word_lens[i]
        width = vocab_packed.shape[1]
        mask = np.arange(width) < word_lens[:, None]
        term_blob = vocab_packed[mask]
    else:
        term_blob = np.zeros(0, dtype=np.uint8)
    return pack(
        path, term_blob=term_blob, term_offsets=term_offsets,
        df=export["df"], post_offsets=export["offsets"],
        postings=export["postings"], df_order=export["df_order"],
        max_doc_id=export["max_doc_id"], width=export["width"])


def build_from_emit_arrays(path, *, vocab: np.ndarray, order: np.ndarray,
                           df: np.ndarray, offsets: np.ndarray,
                           postings: np.ndarray, max_doc_id: int) -> int:
    """Pack from ``formatter.emit_index``'s argument shapes (the device
    engines' host-side arrays): 'S' terms in ANY order (re-sorted to
    the lex invariant here), ``order`` the emit permutation over those
    indices, ``offsets``/``df`` addressing absolute postings in a
    possibly oversized buffer (gaps re-compacted here)."""
    vocab = np.asarray(vocab)
    df = np.asarray(df, dtype=np.int64)
    order = np.asarray(order, dtype=np.int64)
    offsets = np.asarray(offsets, dtype=np.int64)
    postings = np.asarray(postings, dtype=np.int32)
    V = len(vocab)
    # original index -> lex rank (identity when vocab arrives sorted,
    # e.g. from the one-shot device engine's sorted-unique output)
    perm = np.argsort(vocab, kind="stable")
    inv = np.empty(V, dtype=np.int64)
    inv[perm] = np.arange(V)
    vocab = vocab[perm]
    df_lex = df[perm]
    starts_lex = offsets[perm]
    lens = np.char.str_len(vocab).astype(np.int64) if V else \
        np.zeros(0, dtype=np.int64)
    term_offsets = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(lens, out=term_offsets[1:])
    if V:
        width = vocab.dtype.itemsize
        rows = np.ascontiguousarray(vocab).view(np.uint8).reshape(V, width)
        term_blob = rows[np.arange(width) < lens[:, None]]
    else:
        term_blob = np.zeros(0, dtype=np.uint8)
    post_offsets = np.zeros(V + 1, dtype=np.int64)
    np.cumsum(df_lex, out=post_offsets[1:])
    P = int(post_offsets[-1])
    flat = np.zeros(0, dtype=np.int32)
    if P:
        src = (np.repeat(starts_lex, df_lex)
               + (np.arange(P) - np.repeat(post_offsets[:-1], df_lex)))
        flat = postings[src]
    return pack(
        path, term_blob=term_blob, term_offsets=term_offsets, df=df_lex,
        post_offsets=post_offsets, postings=flat,
        df_order=inv[order], max_doc_id=int(max_doc_id))


def build_from_grouped(path, per_letter: dict) -> int:
    """Pack from the oracle/empty-path grouped form: per-letter lists of
    ``(word_bytes, ids)`` already in emit order."""
    words: list[bytes] = []
    ids: list[list[int]] = []
    for letter in sorted(per_letter):
        for word, docs in per_letter[letter]:
            words.append(word)
            ids.append(list(docs))
    emit_to_lex = np.argsort(np.array(words, dtype="S") if words
                             else np.zeros(0, dtype="S1"), kind="stable")
    lex_words = [words[i] for i in emit_to_lex]
    # df_order[emit position] = lex index: the argsort's inverse
    df_order = np.empty(len(words), dtype=np.int64)
    df_order[emit_to_lex] = np.arange(len(words))
    term_blob = np.frombuffer(b"".join(lex_words), dtype=np.uint8)
    term_offsets = np.zeros(len(words) + 1, dtype=np.int64)
    np.cumsum([len(w) for w in lex_words], out=term_offsets[1:])
    df = np.array([len(ids[i]) for i in emit_to_lex], dtype=np.int64)
    post_offsets = np.zeros(len(words) + 1, dtype=np.int64)
    np.cumsum(df, out=post_offsets[1:])
    flat = (np.concatenate([np.asarray(ids[i], dtype=np.int32)
                            for i in emit_to_lex])
            if words else np.zeros(0, dtype=np.int32))
    max_doc_id = int(flat.max()) if len(flat) else 0
    return pack(
        path, term_blob=term_blob, term_offsets=term_offsets, df=df,
        post_offsets=post_offsets, postings=flat, df_order=df_order,
        max_doc_id=max_doc_id)
