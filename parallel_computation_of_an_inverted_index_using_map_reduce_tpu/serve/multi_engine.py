"""Scatter-gather query engine over a live segment set.

One :class:`MultiSegmentEngine` serves a directory managed by the
incremental-indexing layer (``segments.manifest.json``).  Every query
fans out over per-segment :class:`~.engine.Engine` instances — each
running the unchanged single-artifact code paths, BMW/MaxScore pruning
included — and the per-segment answers are merged exactly (DrJAX's
broadcast/reduce framing, PAPERS.md: broadcast the batch, reduce the
per-segment partials).  Segments own disjoint global doc-id ranges
``(doc_base, doc_base + docs]``, so boolean/postings merges are plain
offset-shifted concatenations and ranked merges are a heap over
per-segment candidate lists.

Byte-identity with a from-scratch single-artifact build of the same
live corpus state is a design invariant, not an approximation:

* global ``ndocs``/``avgdl`` are computed from the concatenated
  per-segment doc-length columns (tombstoned slots zeroed), which is
  elementwise the same float64 sequence the from-scratch artifact
  yields — same ``np.count_nonzero``, same ``mean()``;
* each segment engine gets those globals plus a global live-df
  callable through :meth:`~.engine.Engine.set_corpus_override`, so
  every per-(term, doc) BM25 contribution is computed by the same
  expression over the same operands;
* per-segment top-k asks for ``k + tomb_count`` candidates (a
  tombstoned doc can displace at most one live one), filters
  tombstones, and the global merge picks k by ``(-score, doc_id)`` —
  the single-engine tie order.

Deletes are visible immediately: tombstone bitmaps load with the
manifest generation and every query path filters through them.  The
engine is immutable per generation — mutations publish a new manifest
and the daemon swaps in a freshly opened engine, exactly like a hot
reload.
"""

from __future__ import annotations

import heapq

import numpy as np

from . import artifact as artifact_mod
from . import engine as engine_mod
from ..obs import attribution as obs_attrib
from ..obs import metrics as obs_metrics
from ..segments import manifest as seg_manifest
from ..segments import tombstones as tomb_mod


def merge_ranked(per_part, k: int) -> list[tuple[int, float]]:
    """Gather a ranked answer from per-part candidate lists.

    Each part is a list of ``(-score, global_doc_id)`` pairs already
    sorted ascending — i.e. best-first by ``(-score, gid)``, the
    single-engine tie order.  A D-way :func:`heapq.merge` pops exactly
    ``k`` winners without materializing the rest; parts may be empty.
    Shared by :class:`MultiSegmentEngine` (parts = segments) and the
    cluster router (parts = doc-shards answering over TCP).
    """
    out: list[tuple[int, float]] = []
    if k <= 0:
        return out
    for neg, gid in heapq.merge(*per_part):
        out.append((gid, -neg))
        if len(out) == k:
            break
    return out


def merge_doc_ids(parts) -> np.ndarray:
    """Gather one globally ascending int32 doc-id array from per-part
    ascending arrays over disjoint id sets.  Parts covering ascending
    disjoint *ranges* (segments in doc_base order) concatenate as-is;
    interleaved id sets (round-robin doc shards) take the sort arm —
    either way the output is what a monolithic engine would return.
    """
    parts = [np.asarray(p, dtype=np.int64) for p in parts if len(p)]
    if not parts:
        return np.zeros(0, dtype=np.int32)
    out = parts[0] if len(parts) == 1 else np.concatenate(parts)
    if len(out) > 1 and not (np.diff(out) > 0).all():
        out = np.sort(out, kind="mergesort")
    return out.astype(np.int32)


class ShardRestrictedOracle:
    """The monolith's answers restricted to a covered subset of doc
    shards — the byte-parity contract a *partial* cluster answer is
    held to.

    When the router degrades under ``partial_policy: allow`` (a shard's
    replica set exhausted, its leg timed out), the gathered answer must
    equal what a monolithic engine over the full corpus would return
    with the missing shards' documents erased: doc shards are disjoint
    and every shard scores with the injected GLOBAL BM25 stats, so
    dropping a leg drops exactly that shard's docs from the merge and
    nothing else.  This wrapper computes that reference answer from an
    ordinary full-corpus :class:`~.engine.Engine` plus the covered gid
    set, mirroring the router's merge rules:

    * ``df`` — live df restricted to covered docs (the sum the router
      takes over answered shards' local dfs);
    * ``postings`` — covered docs only, ``None`` when no covered doc
      holds the term (the router emits ``None`` when every answered
      part is ``None``);
    * ``query_and`` / ``query_or`` — covered docs only;
    * ``top_k_scored`` — the full ranking filtered to covered docs,
      then cut to k (scores are the monolith's floats untouched);
    * ``top_k`` (letter) — terms re-ranked by restricted df,
      zero-coverage terms dropped, ``(-df, term)`` order.

    Test/chaos harness infrastructure: exactness over completeness —
    everything is recomputed per call from the base engine.
    """

    def __init__(self, engine, covered_gids):
        self._eng = engine
        self._covered = frozenset(int(g) for g in covered_gids)

    @classmethod
    def round_robin(cls, engine, shards: int, covered,
                    total_docs: int | None = None):
        """Covered set for the partition tool's default assignment
        (gid ``g`` lives on shard ``(g - 1) % shards``)."""
        if total_docs is None:
            total_docs = int(engine.artifact.max_doc_id)
        cov = frozenset(int(s) for s in covered)
        gids = [g for g in range(1, total_docs + 1)
                if (g - 1) % shards in cov]
        return cls(engine, gids)

    def _mask(self, docs: np.ndarray) -> np.ndarray:
        if not len(docs):
            return np.asarray(docs, dtype=np.int32)
        keep = np.array([int(d) in self._covered for d in docs])
        return np.asarray(docs, dtype=np.int32)[keep]

    def df(self, batch) -> np.ndarray:
        out = np.zeros(len(batch), dtype=np.int64)
        for j, col in enumerate(self._eng.postings(batch)):
            if col is not None:
                out[j] = len(self._mask(col))
        return out

    def postings(self, batch) -> list[np.ndarray | None]:
        cols = []
        for col in self._eng.postings(batch):
            col = self._mask(col) if col is not None else col
            cols.append(col if col is not None and len(col) else None)
        return cols

    def query_and(self, batch) -> np.ndarray:
        return self._mask(self._eng.query_and(batch))

    def query_or(self, batch) -> np.ndarray:
        return self._mask(self._eng.query_or(batch))

    def top_k_scored(self, batch, k: int) -> list[tuple[int, float]]:
        if k <= 0:
            return []
        # the monolith's COMPLETE ranking (every OR candidate), then
        # filter: a covered doc's rank among covered docs is its
        # monolith rank with misses deleted — same (-score, id) order
        full = self._eng.top_k_scored(
            batch, int(len(self._eng.query_or(batch))))
        return [(d, s) for d, s in full if d in self._covered][:k]

    def top_k(self, letter, k: int) -> list[tuple[bytes, int]]:
        every = self._eng.top_k(letter, self._eng.vocab_size)
        if not every:
            return []
        terms = [t for t, _ in every]
        dfs = self.df(self._eng.encode_batch(terms))
        tally = [(t, int(d)) for t, d in zip(terms, dfs) if d > 0]
        tally.sort(key=lambda kv: (-kv[1], kv[0]))
        return tally[:max(k, 0)]

    def encode_batch(self, terms) -> np.ndarray:
        return self._eng.encode_batch(terms)


class _Segment:
    """One opened segment: entry metadata, its Engine, its tombstones."""

    __slots__ = ("entry", "engine", "bits", "live_df_memo")

    def __init__(self, entry, engine, bits):
        self.entry = entry
        self.engine = engine
        self.bits = bits          # bool[docs] or None; True = deleted
        self.live_df_memo: dict[int, int] = {}

    @property
    def doc_base(self) -> int:
        return self.entry.doc_base

    def live_df(self, idx: int) -> int:
        """This segment's live (non-tombstoned) df for lex index
        ``idx``; equals the raw df when nothing here is deleted."""
        if self.bits is None:
            return int(self.engine._df[idx])
        hit = self.live_df_memo.get(idx)
        if hit is None:
            docs = self.engine.postings_by_index(idx)
            hit = int((~self.bits[docs - 1]).sum())
            self.live_df_memo[idx] = hit
        return hit

    def live_locals(self, docs: np.ndarray) -> np.ndarray:
        """Filter segment-local doc ids through the tombstone bitmap."""
        if self.bits is None or not len(docs):
            return docs
        return docs[~self.bits[np.asarray(docs, dtype=np.int64) - 1]]


class MultiSegmentEngine:
    """Batched query API over every live segment of one directory.

    Answers the same surface as :class:`~.engine.Engine` (df, postings,
    AND/OR, letter top-k, BM25 top-k, describe/close) with global doc
    ids; the daemon and CLI route here automatically when the directory
    carries a segment manifest.
    """

    engine_name = "multi"

    def __init__(self, path, cache_terms: int = 4096):
        self.root = path
        man = seg_manifest.load_manifest(path)
        if man is None:
            raise artifact_mod.ArtifactError(
                f"{path}: no segment manifest (not a live index dir)")
        self.manifest = man
        self.generation = man.generation
        self._segs: list[_Segment] = []
        try:
            for e in man.entries:
                seg_dir = seg_manifest.segment_dir(path, e.name)
                eng = engine_mod.Engine(seg_dir, cache_terms=cache_terms)
                bits = None
                if e.tombstones is not None and e.tomb_count:
                    bits = tomb_mod.load(seg_dir / e.tombstones,
                                         ndocs=e.docs)
                self._segs.append(_Segment(e, eng, bits))
        except BaseException:
            for s in self._segs:
                s.engine.close()
            raise
        self._width = max((s.engine._width for s in self._segs),
                          default=1)
        self._sdtype = f"S{self._width}"
        # global corpus stats: concatenate the per-segment doc-length
        # columns in doc_base order (zeros at tombstones and at any
        # inter-segment gap compaction left behind).  The nonzero
        # subsequence is elementwise identical to the from-scratch
        # artifact's, so ndocs and avgdl match it bit for bit.
        span = man.doc_span
        doc_lens = np.zeros(span + 1, dtype=np.float64)
        for s in self._segs:
            dl = s.engine._bm25_corpus()[0]
            e = s.entry
            n = min(len(dl), e.docs + 1)
            doc_lens[e.doc_base + 1:e.doc_base + n] = dl[1:n]
            if s.bits is not None:
                doc_lens[e.doc_base + np.nonzero(s.bits)[0] + 1] = 0.0
        self._doc_lens = doc_lens
        self._ndocs = int(np.count_nonzero(doc_lens))
        live = doc_lens[doc_lens > 0]
        # all-tombstoned corpus: avgdl 1.0 keeps the per-segment BM25
        # denominator finite (every score is filtered out anyway)
        self._avgdl = float(live.mean()) if len(live) else 1.0
        self._tomb_total = sum(e.tomb_count for e in man.entries)
        # per-term global live df, keyed by term bytes (lex indices
        # differ per segment); safe to memoize — the engine is
        # per-generation immutable
        self._global_df_memo: dict[bytes, int] = {}
        for s in self._segs:
            s.engine.set_corpus_override(
                self._ndocs, self._avgdl,
                self._df_fn_for(s))
        self.metrics = obs_metrics.Registry()
        self.metrics.gauge("mri_segments_active").set(len(self._segs))
        self.metrics.gauge("mri_generation").set(self.generation)
        self.metrics.gauge("mri_tombstoned_docs").set(self._tomb_total)
        self.metrics.gauge("mri_engine_vocab_terms").set(self.vocab_size)
        self.metrics.gauge("mri_engine_artifact_bytes").set(
            sum(e.bytes for e in man.entries))
        self._ops = engine_mod.OpTimer(registry=self.metrics)
        self._h_topk = self._ops.histogram("top_k_scored")

    # -- global stats -----------------------------------------------------

    def _df_fn_for(self, seg: _Segment):
        def df_fn(idx: int, _seg=seg) -> int:
            return self._global_live_df(_seg.engine.artifact.term(idx))
        return df_fn

    def _global_live_df(self, term: bytes) -> int:
        hit = self._global_df_memo.get(term)
        if hit is None:
            hit = 0
            for s in self._segs:
                if len(term) > s.engine._width:
                    continue
                idx, found = s.engine.lookup(
                    np.array([term], dtype=s.engine._sdtype))
                if found[0]:
                    hit += s.live_df(int(idx[0]))
            if len(self._global_df_memo) > (1 << 16):
                self._global_df_memo.clear()
            self._global_df_memo[term] = hit
        return hit

    def _seg_batch(self, seg: _Segment, batch: np.ndarray) -> np.ndarray:
        """Re-encode the global batch for one segment's width.  Terms
        longer than the segment's width are blanked BEFORE the S-dtype
        cast — a plain cast would truncate them into false matches."""
        w = seg.engine._width
        if w >= self._width:
            return batch.astype(seg.engine._sdtype)
        q = batch.astype(seg.engine._sdtype)
        long = np.array([len(t) > w for t in batch.tolist()])
        if long.any():
            q = q.copy()
            q[long] = b""
        return q

    @staticmethod
    def _seg_attrib(coll, seg: _Segment):
        """Install a per-segment child collector around one segment-
        engine call (``None`` when attribution is off); the caller
        uninstalls the returned token in a ``finally``.  The segment
        engine's own feed sites then land in the child, giving the
        explain report its per-segment breakdown."""
        if coll is None:
            return None
        return obs_attrib.install(coll.child(seg.entry.name))

    # -- term resolution --------------------------------------------------

    @property
    def vocab_size(self) -> int:
        """Distinct live terms across the segment set (terms whose
        postings are fully tombstoned still count until compaction —
        matching what a segment's vocabulary physically stores)."""
        if not self._segs:
            return 0
        if len(self._segs) == 1:
            return self._segs[0].engine.vocab_size
        cols = [s.engine._terms.astype(self._sdtype)
                for s in self._segs]
        return int(len(np.unique(np.concatenate(cols))))

    def encode_batch(self, terms) -> np.ndarray:
        return engine_mod.encode_terms(terms, self._width)

    # -- single-term answers ----------------------------------------------

    def df(self, batch) -> np.ndarray:
        """Global live document frequency per query term."""
        with self._ops.time("df"):
            q = np.asarray(batch, dtype=self._sdtype)
            out = np.zeros(len(q), dtype=np.int64)
            coll = obs_attrib.active()
            for s in self._segs:
                token = self._seg_attrib(coll, s)
                try:
                    sq = self._seg_batch(s, q)
                    idx, found = s.engine.lookup(sq)
                    if s.bits is None:
                        out += np.where(found, s.engine._df[idx], 0)
                    else:
                        for j in np.nonzero(found)[0]:
                            out[j] += s.live_df(int(idx[j]))
                finally:
                    if token is not None:
                        obs_attrib.uninstall(token)
            return out

    def postings(self, batch) -> list[np.ndarray | None]:
        """Global live postings per query term; None where the term has
        no live posting anywhere (same as a from-scratch build, where
        such a term simply would not exist)."""
        with self._ops.time("postings"):
            q = np.asarray(batch, dtype=self._sdtype)
            parts: list[list[np.ndarray]] = [[] for _ in q]
            coll = obs_attrib.active()
            for s in self._segs:
                token = self._seg_attrib(coll, s)
                try:
                    sq = self._seg_batch(s, q)
                    idx, found = s.engine.lookup(sq)
                    for j in np.nonzero(found)[0]:
                        docs = s.live_locals(
                            s.engine.postings_by_index(int(idx[j])))
                        if len(docs):
                            parts[j].append(
                                docs.astype(np.int64) + s.doc_base)
                finally:
                    if token is not None:
                        obs_attrib.uninstall(token)
            return [merge_doc_ids(p) if p else None for p in parts]

    # -- compound queries -------------------------------------------------

    def query_and(self, batch) -> np.ndarray:
        """Docs containing EVERY term.  Segments are independent AND
        problems (doc ranges are disjoint): each segment's own engine
        intersects with its planner/skip machinery, tombstones filter
        the result, and the shifted survivors concatenate in doc_base
        order — already globally ascending."""
        with self._ops.time("and"):
            q = np.asarray(batch, dtype=self._sdtype)
            outs = []
            coll = obs_attrib.active()
            for s in self._segs:
                token = self._seg_attrib(coll, s)
                try:
                    res = s.engine.query_and(self._seg_batch(s, q))
                finally:
                    if token is not None:
                        obs_attrib.uninstall(token)
                res = s.live_locals(res)
                if len(res):
                    outs.append(res.astype(np.int64) + s.doc_base)
            return merge_doc_ids(outs)

    def query_or(self, batch) -> np.ndarray:
        """Docs containing ANY term (disjoint ranges: concat merge)."""
        with self._ops.time("or"):
            q = np.asarray(batch, dtype=self._sdtype)
            outs = []
            coll = obs_attrib.active()
            for s in self._segs:
                token = self._seg_attrib(coll, s)
                try:
                    res = s.engine.query_or(self._seg_batch(s, q))
                finally:
                    if token is not None:
                        obs_attrib.uninstall(token)
                res = s.live_locals(res)
                if len(res):
                    outs.append(res.astype(np.int64) + s.doc_base)
            return merge_doc_ids(outs)

    def top_k(self, letter, k: int) -> list[tuple[bytes, int]]:
        """The letter's k highest-live-df terms across segments,
        ordered (df desc, term asc).  Note: within equal df a single
        artifact's emit order is also ascending-term, so this matches
        the single-engine answer wherever dfs are distinct or the
        artifact was produced by a packer (seed/compaction)."""
        letter = engine_mod.letter_index(letter)
        lo_b = bytes([ord("a") + letter])
        hi_b = bytes([ord("a") + letter + 1])
        with self._ops.time("top_k"):
            tally: dict[bytes, int] = {}
            coll = obs_attrib.active()
            for s in self._segs:
                token = self._seg_attrib(coll, s)
                try:
                    terms = s.engine._terms
                    lo = int(np.searchsorted(terms, np.bytes_(lo_b)))
                    hi = int(np.searchsorted(terms, np.bytes_(hi_b)))
                    for i in range(lo, hi):
                        d = s.live_df(i)
                        if d:
                            t = s.engine.artifact.term(i)
                            tally[t] = tally.get(t, 0) + d
                finally:
                    if token is not None:
                        obs_attrib.uninstall(token)
            order = sorted(tally.items(), key=lambda kv: (-kv[1], kv[0]))
            return [(t, d) for t, d in order[:max(k, 0)]]

    # -- ranked retrieval -------------------------------------------------

    def top_k_scored(self, batch, k: int) -> list[tuple[int, float]]:
        """Global BM25 top-k: each segment answers ``k + tomb_count``
        from its unchanged pruned evaluators (scoring with the injected
        global stats), tombstones filter, and a heap merge picks k by
        ``(-score, doc_id)``.  Exact: a live doc in the global top k is
        outranked within its segment by at most ``k - 1`` live docs
        plus every tombstoned one."""
        import time as _time
        t0 = _time.perf_counter()
        try:
            q = np.asarray(batch, dtype=self._sdtype)
            if k <= 0:
                return []
            per_seg: list[list[tuple[float, int]]] = []
            coll = obs_attrib.active()
            for s in self._segs:
                k2 = k + s.entry.tomb_count
                token = self._seg_attrib(coll, s)
                try:
                    res = s.engine.top_k_scored(
                        self._seg_batch(s, q), k2)
                finally:
                    if token is not None:
                        obs_attrib.uninstall(token)
                if s.bits is not None:
                    res = [(d, sc) for d, sc in res
                           if not s.bits[d - 1]][:k]
                per_seg.append(
                    [(-sc, d + s.doc_base) for d, sc in res])
            # D-way heap merge on (-score, global id): per-segment
            # lists are already sorted that way (merge_ranked never
            # materializes past the k winners)
            return merge_ranked(per_seg, k)
        finally:
            self._h_topk.observe(_time.perf_counter() - t0)

    # -- bookkeeping ------------------------------------------------------

    def bm25_stats(self) -> tuple[int, float]:
        """Global ``(ndocs, avgdl)`` the segment engines score with."""
        return self._ndocs, self._avgdl

    def describe(self) -> dict:
        segs = [{
            "name": s.entry.name,
            "doc_base": s.entry.doc_base,
            "docs": s.entry.docs,
            "tombstoned": s.entry.tomb_count,
            "vocab": s.engine.vocab_size,
            "bytes": s.entry.bytes,
        } for s in self._segs]
        return {
            "engine": self.engine_name,
            "generation": self.generation,
            "segments": segs,
            "vocab": self.vocab_size,
            "ndocs": self._ndocs,
            "avgdl": self._avgdl,
            "tombstoned_docs": self._tomb_total,
            "artifact_bytes": sum(s["bytes"] for s in segs),
            "ops": self._ops.stats(),
        }

    def op_stats(self) -> dict:
        return self._ops.stats()

    def close(self) -> None:
        for s in self._segs:
            s.engine.close()
        self._segs = []
        self._global_df_memo.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
