"""Resident serving daemon over one loaded ``index.mri`` artifact.

``mri query`` pays the artifact open + engine warmup on every
invocation, which caps it near the batch-1 closed-loop floor (~27K
lookups/s) no matter how fast the engine is at batch 1024 (653K/s,
BENCH_SERVE_r05.json).  :class:`ServeDaemon` closes that gap: load
once, accept concurrent connections speaking a JSON-lines protocol,
and coalesce whatever is pending into micro-batches for the existing
vectorized batch path — the multi-round batching discipline of
"Sorting, Searching, and Simulation in the MapReduce Framework"
applied to read traffic, with the engine kept as the stateless core
(DrJAX's split between algorithm and orchestration).

The headline is the robustness envelope, not raw QPS:

admission control
    The pending queue is bounded (``MRI_SERVE_QUEUE_DEPTH``).  A full
    queue sheds the request with a counted ``{"error":"overloaded"}``
    response — never a silent drop, never an unbounded queue.
deadlines
    Requests may carry ``deadline_ms``; work whose deadline passed is
    dropped *before* dispatch and answered ``deadline_expired``
    (counted) — stale work never occupies the engine.
graceful drain
    :meth:`drain` (the CLI's SIGTERM/SIGINT) stops accepting, finishes
    in-flight work within ``MRI_SERVE_DRAIN_S``, flushes stragglers as
    counted ``draining`` errors, joins every thread, flushes stats,
    and returns for a clean exit 0.  A second signal forces exit 1.
crash-safe hot reload
    :meth:`reload` (the CLI's SIGHUP, or the ``reload`` protocol
    command) opens and checksum-verifies the replacement artifact off
    the dispatcher, then swaps engines atomically under the dispatch
    lock.  Verification failure keeps the old artifact serving and
    counts ``reload_rejected`` — the tmp+rename/``ArtifactError``
    discipline extended to live traffic.

Threading model: one accept thread, one dispatcher (the only thread
that touches the engine's batch path), and a reader/writer pair per
connection.  Writers own their socket exclusively (responses are
single ``sendall`` lines — never torn) and are fed through a bounded
outbound queue, so a stalled peer can only ever cost its own
connection (counted ``slow_client_closes``), never the dispatcher.

Protocol — one JSON object per line, one response line per request::

    {"id": 1, "op": "df",       "terms": ["the", "magic"]}
    {"id": 2, "op": "postings", "terms": ["magic"], "deadline_ms": 50}
    {"id": 3, "op": "and",      "terms": ["big", "cat"]}
    {"id": 4, "op": "or",       "terms": ["big", "cat"]}
    {"id": 5, "op": "top_k",    "letter": "a", "k": 3}
    {"id": 5, "op": "top_k",    "score": "bm25", "k": 3,
                                "terms": ["big", "cat"]}
    {"id": 6, "op": "stats"}        # admin: answered inline
    {"id": 7, "op": "healthz"}      # admin: answered inline
    {"id": 8, "op": "reload"}       # admin: swap to the new index.mri
    {"id": 9, "op": "metrics"}      # admin: Prometheus text exposition
    {"id": 10, "op": "trace", "n": 8}   # admin: recent request traces
    {"id": 11, "op": "append", "files": ["d.txt"]}   # admin: live append
    {"id": 12, "op": "delete", "docs": [7, 9]}       # admin: tombstone
    {"id": 13, "op": "compact"}     # admin: merge a segment run
    {"id": 14, "op": "flightdump"}  # admin: flight-recorder contents
    {"id": 15, "op": "top_k", "score": "bm25", "k": 3,
               "terms": ["big", "cat"], "explain": true}  # cost report
    {"id": 16, "op": "snapshot"}    # admin: manifest for replication
    {"id": 17, "op": "fetch_segment", "segment": "seg_2_1",
               "file": "index.mri"}  # admin: ship one segment file
    {"id": 18, "op": "wal_tail", "after_seq": 12}  # admin: WAL tail
    {"id": 19, "op": "df", "terms": ["cat"],
               "min_generation": 7}  # read-your-writes fence
    {"id": 20, "op": "df", "terms": ["cat"],
               "tenant": "search-ui"}  # multi-tenant QoS lane

Live mutations (the ``append``/``delete``/``compact`` ops) run on the
reader thread under the reload lock — never the dispatcher — publish a
new segment-manifest generation on disk, open a fresh engine over it,
and swap under the dispatch lock exactly like a hot reload.  Any
failure keeps the OLD generation serving and counts
``mutation_rejected``.  Deletes batch per
``MRI_SEGMENT_TOMBSTONE_FLUSH`` (a generation is published every N
delete ops; a ``compact`` or drain flushes the remainder).

Durability: with ``MRI_SEGMENT_WAL`` on (default), every mutation's
checksummed WAL record is fsync'd BEFORE its manifest swap and before
the ack leaves the wire — buffered delete ops included, so a SIGKILL
between an acknowledged delete and its batched tombstone flush is
replayed by the startup recovery (``segments.recover``) that runs
before the first engine opens.  Replication: ``snapshot`` /
``fetch_segment`` / ``wal_tail`` serve a replica's catch-up round
(``--replica-of`` or ``mri replicate``); a replica is read-only,
reports ``replica_lagging`` in healthz until a round succeeds, and
adopts shipped generations with a quiet engine swap.  Read-your-writes
across failover: mutation acks echo a ``generation`` token, and any
request may carry ``min_generation`` — a node still behind that
generation answers ``stale_generation`` instead of serving stale
state.  With ``MRI_SEGMENT_LEASE_TTL_S`` > 0 mutations renew a TTL'd
primary lease inside ``segments.lock`` first; a live foreign holder
rejects the mutation with a ``lease_lost`` detail.

Result cache: repeat data queries are answered from a
generation-keyed whole-payload cache (:mod:`.result_cache`,
``MRI_SERVE_RESULT_CACHE``) on the reader thread — a hit never touches
the dispatch queue or the engine, and the answer is byte-identical to
the engine's because the cache key carries the published manifest
generation: a mutation's generation bump invalidates exactly (a hot
reload, which may change content at an unchanged generation, purges
outright).  ``explain`` requests always run the engine.

Multi-tenant QoS: requests may carry a ``tenant`` name.  Each tenant
gets its own bounded dispatch lane (weighted-fair dequeue per
``MRI_SERVE_TENANT_WEIGHTS``), an optional token-bucket admission rate
(``MRI_SERVE_TENANT_RATE``), its own CoDel gate (the PR 19 delay
machinery composes per tenant), per-tenant counters/latency histogram
on the registry (rolled into the PR 14 windows + SLO burn, surfaced in
``stats()["tenants"]``), and a ``tenant``-filtered ``flightdump``
slice.  Untagged requests ride the ``default`` tenant and behave
exactly like the pre-tenant daemon.

Success: ``{"id":1,"ok":true,"df":[5241,3]}``.  Failure:
``{"id":2,"error":"<kind>","detail":"..."}`` with kind one of
``overloaded`` / ``deadline_expired`` / ``draining`` /
``bad_request`` / ``internal`` / ``reload_rejected`` — every one
counted in ``stats``.

Observability: every tally is an ``obs.metrics`` counter on the
daemon's registry; ``stats()["counters"]`` is a byte-compatible view
over it and the ``metrics`` op (or ``--listen-metrics PORT``) renders
the same numbers as ``# TYPE``-annotated Prometheus text.  Requests
may carry a ``trace_id`` (auto-generated under ``MRI_OBS_ENABLE``)
which is echoed on the response; each finished request records
contiguous queue-wait → coalesce → engine spans into a bounded ring
(the ``trace`` op) and requests slower than ``MRI_OBS_SLOW_MS`` emit
one structured JSON line on the ``mri_tpu.obs`` logger.

Cost attribution: a data request carrying ``"explain": true`` runs
SOLO (outside the coalesced df/postings groups, so its costs are its
own) under an :mod:`..obs.attribution` collector, and the response
carries an ``explain`` object — per-term resolution, planner decision
with its θ progression, blocks scored/skipped, bytes decoded, cache
hits, per-stage µs.  Every completed request (explain or not) also
lands in the :class:`..obs.attribution.FlightRecorder` — a bounded
ring (``MRI_OBS_FLIGHT_RING``) dumped as one JSON file on dispatcher
crash, abnormal drain, the CLI's SIGQUIT, or on demand through the
``flightdump`` admin op.  Latency histograms attach OpenMetrics
exemplars (``MRI_OBS_EXEMPLARS``) so a scrape's slow bucket links back
to a concrete trace_id in the ring.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import queue
import re
import socket
import threading
import time
from collections import deque

from .. import faults
from ..obs import attribution as obs_attrib
from ..obs import logging as obs_logging
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import tracing as obs_tracing
from ..obs import watchdog as obs_watchdog
from ..obs import windows as obs_windows
from ..utils import envknobs
from . import result_cache as result_cache_mod
from .artifact import ArtifactError
from .engine import create_engine

log = logging.getLogger("mri_tpu.serve.daemon")

COALESCE_ENV = "MRI_SERVE_COALESCE_US"
QUEUE_ENV = "MRI_SERVE_QUEUE_DEPTH"
BATCH_ENV = "MRI_SERVE_MAX_BATCH"
DRAIN_ENV = "MRI_SERVE_DRAIN_S"
CODEL_TARGET_ENV = "MRI_SERVE_CODEL_TARGET_MS"
CODEL_INTERVAL_ENV = "MRI_SERVE_CODEL_INTERVAL_MS"

#: Per-connection outbound response queue bound: past this, the peer
#: is not reading and the connection is closed (counted) rather than
#: letting responses pile up or the dispatcher block.
OUTBOUND_DEPTH = 1024

DATA_OPS = ("df", "postings", "and", "or", "top_k")
ADMIN_OPS = ("stats", "healthz", "reload", "metrics", "trace",
             "append", "delete", "compact", "flightdump", "slo",
             "snapshot", "fetch_segment", "wal_tail")

OVERLOAD_ENV = "MRI_OBS_OVERLOAD_SHED_RATE"

_SENTINEL = object()

#: legacy ``counters`` key -> Prometheus metric name, in the
#: historical insertion order (``stats()["counters"]`` preserves it)
_COUNTER_NAMES = (
    ("requests", "mri_serve_requests_total"),
    ("responses", "mri_serve_responses_total"),
    ("shed", "mri_serve_shed_total"),
    ("deadline_expired", "mri_serve_deadline_expired_total"),
    ("draining_rejected", "mri_serve_draining_rejected_total"),
    ("bad_request", "mri_serve_bad_request_total"),
    ("internal_errors", "mri_serve_internal_errors_total"),
    ("client_disconnects", "mri_serve_client_disconnects_total"),
    ("slow_client_closes", "mri_serve_slow_client_closes_total"),
    ("reload_ok", "mri_serve_reload_ok_total"),
    ("reload_rejected", "mri_serve_reload_rejected_total"),
    ("batches", "mri_serve_batches_total"),
    ("batched_requests", "mri_serve_batched_requests_total"),
    ("connections", "mri_serve_connections_total"),
    ("mutations", "mri_serve_mutations_total"),
    ("mutation_rejected", "mri_serve_mutation_rejected_total"),
    ("stale_generation", "mri_serve_stale_generation_total"),
    ("codel_sheds", "mri_serve_codel_sheds_total"),
)


class _CoDelGate:
    """Controlled-delay admission: shed on sustained queue DELAY, not
    queue depth.

    The fixed bounded queue sheds only when it is completely full — by
    then every queued request has already paid the worst-case wait,
    and under sustained overload the daemon times out work it already
    queued ("late and expensive").  This gate adapts CoDel (RFC 8289,
    in its server-admission variant) to the dispatcher: the dispatcher
    reports every popped request's queue delay via :meth:`on_delay`;
    once the delay has stayed above ``target_s`` for a full
    ``interval_s`` the gate enters the *dropping* state, where

    * reader threads shed new arrivals at the control-law rate
      (:meth:`should_shed`, next shed at ``interval/sqrt(count)`` —
      pressure grows the longer the overload lasts), and
    * the dispatcher sheds ALREADY-QUEUED requests whose delay
      exceeds the target (:meth:`late_shed`) — cheap, pre-execution —
      so the requests that DO execute carry bounded queueing.

    The first on_delay below target exits dropping.  ``target_s`` 0
    disables the gate entirely (fixed-queue behavior)."""

    def __init__(self, target_s: float, interval_s: float,
                 gauge=None, clock=time.monotonic):
        self.target_s = target_s
        self.interval_s = interval_s
        self._gauge = gauge  # mri_serve_codel_state: 1 while dropping
        self._clock = clock
        self._lock = threading.Lock()
        self._first_above: float | None = None
        self._dropping = False
        self._drop_next = 0.0
        self._count = 0

    @property
    def enabled(self) -> bool:
        return self.target_s > 0

    @property
    def dropping(self) -> bool:
        return self._dropping

    def on_delay(self, delay_s: float) -> None:
        """Dispatcher feed: the queue delay of a just-popped request."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            if delay_s < self.target_s:
                self._first_above = None
                if self._dropping:
                    self._dropping = False
                    if self._gauge is not None:
                        self._gauge.set(0)
            elif self._first_above is None:
                self._first_above = now
            elif not self._dropping \
                    and now - self._first_above >= self.interval_s:
                self._dropping = True
                # CoDel restart heuristic: a recent dropping episode
                # resumes near its old rate instead of from scratch
                self._count = self._count - 2 if self._count > 2 else 1
                self._drop_next = now
                if self._gauge is not None:
                    self._gauge.set(1)

    def should_shed(self) -> bool:
        """Reader-thread admission check: shed this arrival?"""
        if not self.enabled:
            return False
        with self._lock:
            if not self._dropping:
                return False
            now = self._clock()
            if now < self._drop_next:
                return False
            self._count += 1
            self._drop_next = now + \
                self.interval_s / (self._count ** 0.5)
            return True

    def late_shed(self, delay_s: float) -> bool:
        """Dispatcher dequeue check: while dropping, a request that
        already waited past the target is shed before execution."""
        if not self.enabled:
            return False
        with self._lock:
            return self._dropping and delay_s > self.target_s

    def state(self) -> dict:
        with self._lock:
            return {"dropping": self._dropping, "count": self._count}


#: tenant names on the wire: short, metric-safe-ish, no whitespace
_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

#: overflow lane once MRI_SERVE_TENANT_MAX distinct names are tracked
OTHER_TENANT = "other"


def _sanitize_tenant(name: str) -> str:
    """Metric-name-safe label for a tenant (dots/dashes to underscores;
    two names that sanitize identically share metric series — the
    admission lanes stay distinct)."""
    return re.sub(r"[^A-Za-z0-9_]", "_", name)


def _parse_tenant_weights(spec: str) -> dict:
    """``MRI_SERVE_TENANT_WEIGHTS`` grammar: ``name=w,name=w,*=w``
    (integer weights >= 1; ``*`` is the default for unlisted names)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w = part.partition("=")
        if not sep or not name.strip():
            raise ValueError(
                f"tenant weight {part!r} is not name=weight")
        try:
            wi = int(w)
        except ValueError:
            raise ValueError(f"tenant weight {part!r}: weight must be "
                             "an integer") from None
        if wi < 1:
            raise ValueError(
                f"tenant weight {part!r}: weight must be >= 1")
        out[name.strip()] = wi
    return out


def _parse_tenant_rates(spec: str) -> dict:
    """``MRI_SERVE_TENANT_RATE`` grammar: ``name=rps[:burst],...``
    (floats; burst defaults to one second of rps, floor 1)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, rate = part.partition("=")
        if not sep or not name.strip():
            raise ValueError(
                f"tenant rate {part!r} is not name=rps[:burst]")
        rps_s, _, burst_s = rate.partition(":")
        try:
            rps = float(rps_s)
            burst = float(burst_s) if burst_s else max(1.0, rps)
        except ValueError:
            raise ValueError(f"tenant rate {part!r}: rps/burst must "
                             "be numbers") from None
        if rps <= 0 or burst < 1:
            raise ValueError(f"tenant rate {part!r}: rps must be > 0 "
                             "and burst >= 1")
        out[name.strip()] = (rps, burst)
    return out


class _TokenBucket:
    """Classic token bucket: ``rps`` refill, ``burst`` cap, one token
    per admitted request.  Thread-safe (reader threads race)."""

    def __init__(self, rps: float, burst: float, clock=time.monotonic):
        self.rps = float(rps)
        self.burst = float(burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst  # guarded by: self._lock
        self._t = clock()          # guarded by: self._lock

    def allow(self) -> bool:
        now = self._clock()
        with self._lock:
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rps)
            self._t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class _TenantState:
    """One tenant's QoS lane: weight, optional admission bucket, its
    own CoDel gate, per-tenant counters/histogram (tracked by the
    rolling windows) and an SLO tracker over them."""

    __slots__ = ("name", "label", "weight", "bucket", "codel",
                 "c_requests", "c_shed", "c_deadline", "c_errors",
                 "c_cache_hits", "h_request", "hist_name", "slo")

    def __init__(self, name: str, *, registry, rolling, weight: int,
                 rate, codel):
        self.name = name
        self.label = _sanitize_tenant(name)
        base = f"mri_serve_tenant_{self.label}"
        self.c_requests = registry.counter(f"{base}_requests_total")
        self.c_shed = registry.counter(f"{base}_shed_total")
        self.c_deadline = registry.counter(
            f"{base}_deadline_expired_total")
        self.c_errors = registry.counter(f"{base}_errors_total")
        self.c_cache_hits = registry.counter(
            f"{base}_result_cache_hits_total")
        self.hist_name = f"{base}_request_seconds"
        self.h_request = registry.histogram(self.hist_name)
        rolling.track(
            counters=(f"{base}_requests_total", f"{base}_shed_total",
                      f"{base}_deadline_expired_total",
                      f"{base}_errors_total"),
            histograms=(self.hist_name,))
        self.weight = max(1, int(weight))
        self.bucket = None if rate is None else _TokenBucket(*rate)
        self.codel = codel
        # per-tenant burn: same math as the daemon-wide tracker over
        # this lane's series; the lane's requests counter already
        # counts its sheds (incremented at arrival), so no extra_total
        self.slo = obs_slo.SLOTracker(
            rolling,
            total=f"{base}_requests_total",
            bad=(f"{base}_errors_total", f"{base}_shed_total",
                 f"{base}_deadline_expired_total"),
            extra_total=(),
            latency_hist=self.hist_name)


class _FairQueue:
    """Weighted-fair dispatch queue, drop-in for the old bounded
    ``queue.Queue``: ``put_nowait`` / ``get`` / ``get_nowait`` /
    ``qsize`` keep their signatures (``queue.Full`` / ``queue.Empty``
    included) so the dispatcher and drain paths are unchanged.  One
    bounded FIFO lane per tenant; ``get`` serves lanes round-robin
    with each lane taking up to ``weight`` consecutive items at the
    head before rotating to the back.  A full lane sheds only its own
    tenant.  With a single tenant this degenerates to exactly the old
    single FIFO."""

    def __init__(self, depth: int):
        self.depth = depth
        self._cv = threading.Condition()
        self._lanes: dict = {}    # tstate -> deque  # guarded by: self._cv
        self._active: deque = deque()  # lanes with items, RR order  # guarded by: self._cv
        self._queued: set = set()  # tstates present in _active  # guarded by: self._cv
        self._credit = 0  # head lane's remaining turn  # guarded by: self._cv
        self._size = 0    # guarded by: self._cv

    def put_nowait(self, item) -> None:
        ts = item.tstate
        with self._cv:
            lane = self._lanes.get(ts)
            if lane is None:
                lane = self._lanes[ts] = deque()
            if len(lane) >= self.depth:
                raise queue.Full
            lane.append(item)
            self._size += 1
            if ts not in self._queued:
                self._active.append(ts)
                self._queued.add(ts)
                if len(self._active) == 1:
                    self._credit = ts.weight
            self._cv.notify()

    # mrilint: holds(self._cv)
    def _pop_locked(self):
        ts = self._active[0]
        lane = self._lanes[ts]
        item = lane.popleft()
        self._size -= 1
        self._credit -= 1
        if not lane:
            self._active.popleft()
            self._queued.discard(ts)
            if self._active:
                self._credit = self._active[0].weight
        elif self._credit <= 0:
            self._active.rotate(-1)
            self._credit = self._active[0].weight
        return item

    def get(self, timeout: float | None = None):
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cv:
            while self._size == 0:
                rem = None if deadline is None \
                    else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise queue.Empty
                self._cv.wait(rem)
            return self._pop_locked()

    def get_nowait(self):
        with self._cv:
            if self._size == 0:
                raise queue.Empty
            return self._pop_locked()

    def qsize(self) -> int:
        with self._cv:
            return self._size

    def lane_depth(self, ts) -> int:
        with self._cv:
            lane = self._lanes.get(ts)
            return len(lane) if lane else 0


class _Request:
    """One admitted data request, from queue admission to its single
    ``finish`` (exactly one response per request — ok or counted
    error — enforced by the ``done`` flag)."""

    __slots__ = ("conn", "rid", "op", "terms", "letter", "k", "score",
                 "seq", "expires_at", "done", "trace_id", "t_admit",
                 "t_pop", "t_exec", "planner", "explain", "attrib",
                 "tenant", "tstate", "cached", "ckey", "cgen")

    def __init__(self, conn, rid, op, terms, letter, k, score, seq,
                 expires_at, trace_id=None, t_admit=0.0, explain=False,
                 tenant=None, tstate=None):
        self.conn = conn
        self.rid = rid
        self.op = op
        self.terms = terms
        self.letter = letter
        self.k = k
        self.score = score
        self.seq = seq
        self.expires_at = expires_at
        self.done = False
        self.trace_id = trace_id
        self.t_admit = t_admit  # monotonic admission timestamp
        self.t_pop = None  # dispatcher popped it off the queue
        self.t_exec = None  # batch reached the engine lock
        self.planner = None  # ranked queries: the planner's decision
        self.explain = explain  # run solo under a cost collector
        self.attrib = None  # the collector, once the request executed
        self.tenant = tenant  # wire tenant name ("default" if untagged)
        self.tstate = tstate  # its _TenantState (QoS lane)
        self.cached = False  # answered from the result cache
        self.ckey = None  # epoch-free result-cache key (None: uncacheable)
        self.cgen = None  # generation snapshot taken with the engine


class _Conn:
    """One accepted connection: reader thread (parse + admit), writer
    thread (sole socket writer), bounded outbound queue between the
    daemon and the writer."""

    _ids = iter(range(1, 1 << 62))

    def __init__(self, daemon: "ServeDaemon", sock: socket.socket, addr):
        self.daemon = daemon
        self.sock = sock
        self.addr = addr
        self.outbound: queue.Queue = queue.Queue(maxsize=OUTBOUND_DEPTH)
        self.lock = threading.Lock()
        self.pending = 0  # admitted, not yet enqueued  # guarded by: self.lock
        self.read_eof = False
        self.dead = False
        self.reader_done = False
        self.writer_done = False
        cid = next(self._ids)
        self.reader = threading.Thread(
            target=daemon._reader_loop, args=(self,),
            name=f"mri-serve-read-{cid}", daemon=True)
        self.writer = threading.Thread(
            target=daemon._writer_loop, args=(self,),
            name=f"mri-serve-write-{cid}", daemon=True)

    def start(self) -> None:
        self.reader.start()
        self.writer.start()

    def enqueue(self, seq: int, payload: dict) -> bool:
        """Queue one response line for the writer.  False (and the
        connection is condemned) when the peer is too slow to drain
        OUTBOUND_DEPTH responses."""
        data = (json.dumps(payload, separators=(",", ":")) + "\n").encode()
        try:
            self.outbound.put_nowait((seq, data))
            return True
        except queue.Full:
            if not self.dead:
                self.daemon._count("slow_client_closes")
            self.kill()
            return False

    def enqueue_sentinel(self) -> None:
        try:
            self.outbound.put_nowait(_SENTINEL)
        except queue.Full:
            self.kill()  # writer exits on the closed socket instead

    def kill(self) -> None:
        """Force-close the socket: both loops unblock and exit."""
        self.dead = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    @property
    def finished(self) -> bool:
        return self.reader_done and self.writer_done


class ServeDaemon:
    """The resident server.  ``start()`` binds and spawns threads;
    ``drain()`` is the graceful single-exit path (idempotent);
    ``reload()`` hot-swaps the artifact.  See the module docstring for
    the protocol and robustness contract."""

    def __init__(self, path, host: str = "127.0.0.1", port: int = 0, *,
                 engine: str | None = None, cache_terms: int = 4096,
                 shards: int | None = None,
                 coalesce_us: int | None = None,
                 queue_depth: int | None = None,
                 max_batch: int | None = None,
                 drain_s: float | None = None,
                 metrics_port: int | None = None,
                 replica_of: str | None = None):
        self._path = path
        self._replica_of = replica_of
        if replica_of is None:
            # startup recovery BEFORE the first engine opens: WAL
            # records acknowledged by a crashed predecessor are part of
            # the index, not debris — roll the directory forward to the
            # exact last-acked generation.  Replicas skip this: their
            # adopted tail may reference source files that only exist
            # on the primary, so they converge by segment shipping.
            from .. import segments
            rep = segments.recover(path)
            if rep.get("replayed"):
                log.info("startup recovery: %s", json.dumps(rep))
        else:
            # bootstrap catch-up so a replica born on an empty dir has
            # a generation to open; an unreachable primary only warns —
            # an existing local generation serves stale while the poll
            # loop heals (a dir with nothing to serve still fails the
            # engine open below)
            from .. import segments
            from ..segments import replica as segrep
            addr = segrep.parse_addr(replica_of)
            try:
                segrep.replicate(path, addr)
            except (segments.SegmentError, OSError) as e:
                log.warning("initial replica catch-up from %s failed: "
                            "%s", replica_of, e)
        self._engine_choice = engine
        self._cache_terms = cache_terms
        self._shards = shards
        self.coalesce_us = coalesce_us if coalesce_us is not None \
            else envknobs.get(COALESCE_ENV)
        self.queue_depth = queue_depth if queue_depth is not None \
            else envknobs.get(QUEUE_ENV)
        self.max_batch = max_batch if max_batch is not None \
            else envknobs.get(BATCH_ENV)
        self.drain_s = drain_s if drain_s is not None \
            else envknobs.get(DRAIN_ENV)
        self.codel_target_ms = envknobs.get(CODEL_TARGET_ENV)
        self.codel_interval_ms = envknobs.get(CODEL_INTERVAL_ENV)

        self._engine_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._engine = create_engine(path, engine, cache_terms=cache_terms,
                                     shards=shards)  # guarded by: self._engine_lock
        td = envknobs.get("MRI_SERVE_TENANT_QUEUE_DEPTH")
        self._tenant_depth = td if td > 0 else self.queue_depth
        self._queue = _FairQueue(self._tenant_depth)
        self._inflight = 0  # admitted minus finished  # guarded by: self._count_lock
        self._seq = 0  # data-request ordinal (faults)  # guarded by: self._count_lock
        # every tally is an obs counter on this per-daemon registry;
        # _counts maps the legacy stats key to its counter object (the
        # mapping itself is immutable after construction)
        self.registry = obs_metrics.Registry()
        self._counts = {key: self.registry.counter(name)
                        for key, name in _COUNTER_NAMES}
        self._g_queue_depth = self.registry.gauge("mri_serve_queue_depth")
        self._g_inflight = self.registry.gauge("mri_serve_inflight")
        self._g_draining = self.registry.gauge("mri_serve_draining")
        self._codel = _CoDelGate(
            self.codel_target_ms / 1e3, self.codel_interval_ms / 1e3,
            gauge=self.registry.gauge("mri_serve_codel_state"))
        self._h_request = \
            self.registry.histogram("mri_serve_request_seconds")
        self._h_queue_wait = \
            self.registry.histogram("mri_serve_queue_wait_seconds")
        self._count_lock = threading.Lock()
        self._obs_enabled = obs_tracing.enabled()
        self._slow_ms = obs_tracing.slow_ms()
        self._trace_ring = obs_tracing.TraceRing()
        self._exemplars = obs_attrib.exemplars_enabled()
        self._flight = obs_attrib.FlightRecorder(
            slow_threshold_ms=self._slow_ms)
        # operational health: rolling SLIs sampled off this registry,
        # SLO math over them, and the stall watchdog.  The sampler
        # diffs cumulative state — zero new hot-path feed sites.
        self._rolling = obs_windows.RollingWindows(
            self.registry,
            counters=[name for _key, name in _COUNTER_NAMES],
            histograms=("mri_serve_request_seconds",))
        self._slo = obs_slo.SLOTracker(self._rolling)
        # generation-keyed whole-payload cache, probed on reader
        # threads and filled by the dispatcher under the engine lock
        self._result_cache = result_cache_mod.ResultCache(
            registry=self.registry)
        # multi-tenant QoS: lanes materialize on a tenant's first
        # request; untagged traffic rides "default", whose CoDel gate
        # IS the daemon-wide gate (pre-tenant behavior preserved)
        self._tenant_lock = threading.Lock()
        self._tenants: dict[str, _TenantState] = {}  # guarded by: self._tenant_lock
        self._tenant_weights = _parse_tenant_weights(
            envknobs.get("MRI_SERVE_TENANT_WEIGHTS"))
        self._tenant_rates = _parse_tenant_rates(
            envknobs.get("MRI_SERVE_TENANT_RATE"))
        self._tenant_max = envknobs.get("MRI_SERVE_TENANT_MAX")
        self._tenant("default")
        self._watchdog = obs_watchdog.Watchdog(
            on_stall=self._on_stall, on_recover=self._on_recover,
            registry=self.registry)
        self._overload_shed_rate = envknobs.get(OVERLOAD_ENV)
        self._reloading = False
        self._conns: set[_Conn] = set()  # guarded by: self._conn_lock
        self._conn_lock = threading.Lock()
        self._draining = False
        self._drain_started = False  # guarded by: self._drain_guard
        self._drain_guard = threading.Lock()
        self._drained = threading.Event()
        self._dispatch_stop = threading.Event()
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._dispatcher: threading.Thread | None = None
        self._metrics_port = metrics_port
        self._metrics_listener: socket.socket | None = None
        self._metrics_thread: threading.Thread | None = None
        # live-mutation state (segment-managed dirs); buffered delete
        # ops flush every MRI_SEGMENT_TOMBSTONE_FLUSH ops (guarded by:
        # self._reload_lock, like every mutation)
        self._pending_deletes: list[int] = []
        self._delete_ops = 0
        self._tomb_flush = envknobs.get("MRI_SEGMENT_TOMBSTONE_FLUSH")
        # a failed delete flush leaves acked WAL records above the
        # manifest's wal_seq; the next mutation replays them first so
        # truncation can never pass an unapplied acked record
        self._stale_wal = False  # guarded by: self._reload_lock
        self._lease_owner = f"pid{os.getpid()}"  # rebound on start()
        # last published generation — the read-your-writes token echoed
        # on mutation acks and checked against ``min_generation``
        from .. import segments
        try:
            man = segments.load_manifest(path)
        except segments.SegmentError:
            man = None
        self._generation = 0 if man is None else man.generation
        self._replica_stop = threading.Event()
        self._replica_thread: threading.Thread | None = None
        # a replica is born lagging: not ready until one catch-up
        # round against the primary has succeeded
        self._replica_lagging = replica_of is not None
        self._g_replica_lag = \
            self.registry.gauge("mri_replica_lag_generations")
        self._host = host
        self._port = port
        self.final_stats: dict | None = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        # mrilint: allow(fault-boundary) serving plane; faults.py hooks cover the index build path
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self._port))
        ls.listen(128)
        ls.settimeout(0.2)
        self._listener = ls
        self._host, self._port = ls.getsockname()[:2]
        self._lease_owner = f"{self._host}:{self._port}#{os.getpid()}"
        self._watchdog.register("dispatcher")
        self._watchdog.register("accept")
        self._rolling.start()
        self._watchdog.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="mri-serve-dispatch",
            daemon=True)
        self._dispatcher.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="mri-serve-accept", daemon=True)
        self._accept_thread.start()
        if self._metrics_port is not None:
            ms = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ms.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ms.bind(("127.0.0.1", self._metrics_port))
            ms.listen(8)
            ms.settimeout(0.2)
            self._metrics_listener = ms
            self._metrics_port = ms.getsockname()[1]
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, name="mri-serve-metrics",
                daemon=True)
            self._metrics_thread.start()
        if self._replica_of is not None:
            self._replica_thread = threading.Thread(
                target=self._replica_loop, name="mri-serve-replica",
                daemon=True)
            self._replica_thread.start()
        # mrilint: allow(guarded-by) no reload can race start()
        log.info("serving %s on %s:%d (engine=%s coalesce_us=%d "
                 "queue_depth=%d max_batch=%d)", self._path, self._host,
                 self._port, self._engine.engine_name, self.coalesce_us,
                 self.queue_depth, self.max_batch)

    @property
    def address(self) -> tuple[str, int]:
        return self._host, self._port

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def metrics_address(self) -> tuple[str, int] | None:
        """(host, port) of the HTTP scrape listener, when enabled."""
        if self._metrics_listener is None:
            return None
        return "127.0.0.1", self._metrics_port

    def _count(self, key: str, n: int = 1) -> None:
        self._counts[key].inc(n)

    # -- multi-tenant QoS ----------------------------------------------

    def _tenant(self, name: str) -> _TenantState:
        """The tenant's lane, created on first sight.  Past
        ``MRI_SERVE_TENANT_MAX`` distinct names, new ones fold into the
        shared ``other`` lane (bounded metric cardinality)."""
        with self._tenant_lock:
            ts = self._tenants.get(name)
            if ts is not None:
                return ts
            if len(self._tenants) >= self._tenant_max:
                name = OTHER_TENANT
                ts = self._tenants.get(name)
                if ts is not None:
                    return ts
            gate = self._codel if name == "default" else _CoDelGate(
                self.codel_target_ms / 1e3,
                self.codel_interval_ms / 1e3)
            ts = _TenantState(
                name, registry=self.registry, rolling=self._rolling,
                weight=self._tenant_weights.get(
                    name, self._tenant_weights.get("*", 1)),
                rate=self._tenant_rates.get(
                    name, self._tenant_rates.get("*")),
                codel=gate)
            self._tenants[name] = ts
            return ts

    def _tenant_list(self) -> list:
        with self._tenant_lock:
            return list(self._tenants.values())

    # -- operational health --------------------------------------------

    def _ready_reasons(self) -> list:
        """Why the daemon is NOT ready to serve right now ([] = ready).
        Ordered: the first reason becomes the legacy ``status``."""
        reasons = []
        if self._draining:
            reasons.append("draining")
        if self._reloading:
            reasons.append("reloading")
        if self._watchdog.stalled():
            reasons.append("stalled")
        if self._replica_of is not None and self._replica_lagging:
            reasons.append("replica_lagging")
        limit = self._overload_shed_rate
        if limit > 0:
            counts = self._rolling.counts(10.0)
            shed = counts.get("mri_serve_shed_total", 0)
            attempts = shed + counts.get("mri_serve_requests_total", 0)
            if attempts > 0 and shed / attempts >= limit:
                reasons.append("overloaded")
        return reasons

    def _on_stall(self, name: str, age_ms: float) -> None:
        """Watchdog callback (monitor thread), once per stall episode:
        one structured event + a flight-recorder dump to autopsy."""
        obs_logging.emit(log, "stall", level=logging.WARNING,
                         thread=name, age_ms=round(age_ms, 1),
                         stall_ms=self._watchdog.stall_ms)
        self.dump_flight("stall")

    def _on_recover(self, name: str) -> None:
        obs_logging.emit(log, "stall_recovered", thread=name)

    # -- accept / per-connection threads -------------------------------

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._draining:
            self._watchdog.beat("accept")
            try:
                sock, addr = self._listener.accept()
            except socket.timeout:
                self._prune_conns()
                continue
            except OSError:
                break  # listener closed by drain()
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(self, sock, addr)
            with self._conn_lock:
                self._conns.add(conn)
            self._count("connections")
            conn.start()

    def _prune_conns(self) -> None:
        with self._conn_lock:
            done = [c for c in self._conns if c.finished]
            self._conns.difference_update(done)

    def _reader_loop(self, conn: _Conn) -> None:
        f = None
        try:
            # mrilint: allow(fault-boundary) serving plane; client disconnects are handled right here
            f = conn.sock.makefile("rb")
            for raw in f:
                self._handle_line(conn, raw)
                if conn.dead:
                    break
        except (OSError, ValueError):
            pass
        finally:
            # The makefile wrapper holds an _io_refs reference on the
            # socket: until it is closed, socket.close() only marks the
            # object closed and the OS fd stays open (a leak the conftest
            # guard would flag).  Close it here, deterministically.
            if f is not None:
                with contextlib.suppress(OSError):
                    f.close()
            with conn.lock:
                conn.read_eof = True
                idle = conn.pending == 0
            if idle:
                conn.enqueue_sentinel()
            conn.reader_done = True

    def _writer_loop(self, conn: _Conn) -> None:
        inj = faults.active()
        try:
            while True:
                item = conn.outbound.get()
                if item is _SENTINEL:
                    break
                seq, data = item
                if inj and seq and inj.on_serve_response(seq):
                    self._count("client_disconnects")
                    break
                try:
                    conn.sock.sendall(data)
                except OSError:
                    self._count("client_disconnects")
                    break
                self._count("responses")
        finally:
            conn.kill()
            conn.writer_done = True

    # -- request admission ---------------------------------------------

    def _handle_line(self, conn: _Conn, raw: bytes) -> None:
        line = raw.strip()
        if not line:
            return
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as e:
            self._count("bad_request")
            conn.enqueue(0, {"error": "bad_request", "detail": str(e)})
            return
        rid = req.get("id")
        op = req.get("op")
        tid = req.get("trace_id")
        if tid is not None and not isinstance(tid, str):
            tid = str(tid)
        if op in ADMIN_OPS:
            self._handle_admin(conn, rid, op, req)
            return
        err = self._validate(req, op)
        if err:
            self._count("bad_request")
            payload = {"error": "bad_request", "detail": err}
            if rid is not None:
                payload["id"] = rid
            if tid is not None:
                payload["trace_id"] = tid
            conn.enqueue(0, payload)
            return
        if self._draining:
            self._count("draining_rejected")
            payload = {"error": "draining",
                       "detail": "daemon is shutting down"}
            if rid is not None:
                payload["id"] = rid
            if tid is not None:
                payload["trace_id"] = tid
            conn.enqueue(0, payload)
            return
        mg = req.get("min_generation")
        if mg is not None and self._generation < mg:
            # read-your-writes: the client holds a generation token from
            # a mutation ack this node (a lagging replica) has not yet
            # caught up to — refusing is correct, serving stale is not
            self._count("stale_generation")
            payload = {"error": "stale_generation",
                       "detail": f"serving generation "
                                 f"{self._generation}, client requires "
                                 f">= {mg}",
                       "generation": self._generation}
            if rid is not None:
                payload["id"] = rid
            if tid is not None:
                payload["trace_id"] = tid
            conn.enqueue(0, payload)
            return
        if tid is None and self._obs_enabled:
            tid = obs_tracing.gen_trace_id()
        t_admit = time.monotonic()
        self._counts["requests"].inc()
        tname = req.get("tenant") or "default"
        tstate = self._tenant(tname)
        tstate.c_requests.inc()
        with self._count_lock:
            self._seq += 1
            seq = self._seq
        deadline_ms = req.get("deadline_ms")
        expires_at = t_admit + deadline_ms / 1e3 \
            if deadline_ms is not None else None
        item = _Request(conn, rid, op, req.get("terms"),
                        req.get("letter"), int(req.get("k") or 0),
                        req.get("score") or "df", seq, expires_at,
                        trace_id=tid, t_admit=t_admit,
                        explain=bool(req.get("explain", False)),
                        tenant=tname, tstate=tstate)
        with conn.lock:
            conn.pending += 1
        inj = faults.active()
        if inj is not None and inj.on_serve_admit(seq):
            # injected overload storm: this daemon pretends it cannot
            # absorb the request — the typed refusal the router's
            # breaker/budget machinery is soaked against.  Faults fire
            # before the result cache so chaos scenarios keep biting
            # even when the probed query is hot.
            self._count("shed")
            self._finish(item, {"error": "overloaded",
                                "detail": "injected overload storm "
                                          "(fault spec)"},
                         admitted=False)
            return
        if not item.explain:
            item.ckey = result_cache_mod.key_for(
                op, item.terms, item.letter, item.k, item.score)
        hit = self._result_cache.lookup(item.ckey, self._generation)
        if hit is not None:
            if inj is not None:
                # request-targeted faults fire whether the answer
                # comes from the engine or the cache: a hit is still
                # request handling, and chaos specs key on seq
                try:
                    inj.on_serve_request(seq)
                except faults.HandlerCrash as e:
                    self._count("internal_errors")
                    self._finish(item, {"error": "internal",
                                        "detail": str(e)},
                                 admitted=False)
                    return
            # answered from the reader thread: a hot query never
            # touches the dispatch queue, token bucket or CoDel gate —
            # it costs no engine time, so it spends no admission budget
            item.cached = True
            tstate.c_cache_hits.inc()
            self._finish(item, hit, admitted=False)
            return
        if tstate.bucket is not None and not tstate.bucket.allow():
            self._count("shed")
            self._finish(item, {"error": "overloaded",
                                "detail": f"tenant {tname!r} over its "
                                          "admission rate"},
                         admitted=False)
            return
        if tstate.codel.should_shed():
            # adaptive admission: the queue's DELAY (not depth) says
            # the daemon is past saturation — shed now, cheaply, while
            # the request has cost nothing
            self._count("shed")
            self._count("codel_sheds")
            self._finish(item, {"error": "overloaded",
                                "detail": "queue delay over CoDel "
                                          "target "
                                          f"{self.codel_target_ms}ms"},
                         admitted=False)
            return
        try:
            self._queue.put_nowait(item)
            with self._count_lock:
                self._inflight += 1
        except queue.Full:
            self._count("shed")
            self._finish(item, {"error": "overloaded",
                                "detail": f"pending queue at depth "
                                          f"{self._tenant_depth}"},
                         admitted=False)

    @staticmethod
    def _validate(req: dict, op) -> str | None:
        """One-line reason when the request is malformed, else None."""
        if op not in DATA_OPS:
            return (f"unknown op {op!r} "
                    f"(choices: {DATA_OPS + ADMIN_OPS})")
        dl = req.get("deadline_ms")
        if dl is not None and (not isinstance(dl, (int, float))
                               or isinstance(dl, bool) or dl <= 0):
            return f"deadline_ms must be a positive number, got {dl!r}"
        tn = req.get("tenant")
        if tn is not None and (not isinstance(tn, str)
                               or not _TENANT_RE.match(tn)):
            return ("tenant must be 1-64 chars of [A-Za-z0-9._-], "
                    f"got {tn!r}")
        ex = req.get("explain")
        if ex is not None and not isinstance(ex, bool):
            return f"explain must be a boolean, got {ex!r}"
        mg = req.get("min_generation")
        if mg is not None and (not isinstance(mg, int)
                               or isinstance(mg, bool) or mg < 0):
            return (f"min_generation must be a non-negative integer, "
                    f"got {mg!r}")
        if op == "top_k":
            score = req.get("score") or "df"
            if score not in ("df", "bm25"):
                return f"top_k score must be df or bm25, got {score!r}"
            k = req.get("k")
            if not isinstance(k, int) or isinstance(k, bool) or k < 0:
                return f"top_k needs integer k >= 0, got {k!r}"
            if score == "bm25":
                terms = req.get("terms")
                if not isinstance(terms, list) or not terms \
                        or not all(isinstance(t, str) for t in terms):
                    return ("top_k score=bm25 needs terms=[str, ...], "
                            f"got {terms!r}")
                return None
            letter = req.get("letter")
            if not (isinstance(letter, str) and len(letter) == 1
                    and "a" <= letter <= "z"):
                return f"top_k needs letter=a..z, got {letter!r}"
            return None
        terms = req.get("terms")
        if not isinstance(terms, list) \
                or not all(isinstance(t, str) for t in terms):
            return f"{op} needs terms=[str, ...], got {terms!r}"
        return None

    def _handle_admin(self, conn: _Conn, rid, op: str, req: dict) -> None:
        """Admin ops answer inline from the reader thread — they must
        work while the dispatcher is wedged in a batch."""
        # mrilint: allow(trace) stats healthz slo metrics trace flightdump
        # snapshot fetch_segment wal_tail — read-only introspection and
        # replication-source ops: answered inline from published state,
        # no engine or generation change
        if op == "healthz":
            # liveness vs readiness: ``ok`` stays unconditionally True
            # for old clients (the process answered — it is alive);
            # ``ready``/``reasons`` carry the serving verdict
            reasons = self._ready_reasons()
            payload = {"ok": True,
                       "live": True,
                       "ready": not reasons,
                       "reasons": reasons,
                       "status": reasons[0] if reasons else "ok",
                       "queue_depth": self._queue.qsize(),
                       # additive: the router's health prober learns
                       # each shard's serving generation from here and
                       # keys its result cache on the full vector
                       "generation": self._generation}
        elif op == "slo":
            payload = {"ok": True, "slo": self._slo.report()}
        elif op == "stats":
            payload = {"ok": True, "stats": self.stats()}
        elif op == "metrics":
            payload = {"ok": True, "text": self.render_metrics()}
        elif op == "trace":
            n = req.get("n")
            n = n if isinstance(n, int) and not isinstance(n, bool) \
                and n > 0 else 32
            payload = {"ok": True,
                       "traces": self._trace_ring.snapshot(n)}
        elif op == "flightdump":
            flight = self._flight.dump("admin")
            tn = req.get("tenant")
            if isinstance(tn, str) and tn and isinstance(flight, dict):
                # per-tenant slice: keep only this lane's requests in
                # both lists (headline fields stay daemon-wide)
                for lst in ("requests", "slow"):
                    flight[lst] = [
                        e for e in flight.get(lst, ())
                        if e.get("trace", {}).get("tenant") == tn]
                flight["tenant"] = tn
            payload = {"ok": True, "flight": flight}
            where = req.get("write_to")
            if isinstance(where, str) and where:
                payload["path"] = self._flight.dump_to_file(where, "admin")
        elif op in ("append", "delete", "compact"):
            err = None
            if op == "append":
                files = req.get("files")
                if not isinstance(files, list) or not files or \
                        not all(isinstance(f, str) for f in files):
                    err = f"append needs files=[str, ...], got {files!r}"
            elif op == "delete":
                docs = req.get("docs")
                if not isinstance(docs, list) or not docs or \
                        not all(isinstance(d, int)
                                and not isinstance(d, bool)
                                for d in docs):
                    err = f"delete needs docs=[int, ...], got {docs!r}"
            if err is not None:
                self._count("bad_request")
                payload = {"error": "bad_request", "detail": err}
            else:
                ok, out = self.mutate(op, files=req.get("files"),
                                      docs=req.get("docs"),
                                      force=bool(req.get("force", True)))
                if ok:
                    payload = {"ok": True, "result": out}
                else:
                    payload = {"error": "mutation_rejected", "detail": out}
        elif op in ("snapshot", "fetch_segment", "wal_tail"):
            # mrilint: allow(trace) snapshot fetch_segment wal_tail — read-only
            # replication source ops: read-only views over PUBLISHED
            # state (manifest, immutable segment files, the WAL tail) —
            # a replica's catch-up round is snapshot → fetch_segment per
            # missing file → wal_tail
            from .. import segments
            from ..segments import replica as segrep
            try:
                if op == "snapshot":
                    payload = {"ok": True,
                               "snapshot":
                                   segrep.snapshot_payload(self._path),
                               "lease": segments.read_lease(self._path)}
                elif op == "fetch_segment":
                    payload = {"ok": True,
                               **segrep.segment_file_payload(
                                   self._path,
                                   str(req.get("segment") or ""),
                                   str(req.get("file") or ""))}
                else:  # wal_tail
                    after = req.get("after_seq", 0)
                    if not isinstance(after, int) \
                            or isinstance(after, bool) or after < 0:
                        raise segments.ReplicaError(
                            f"after_seq must be a non-negative "
                            f"integer, got {after!r}")
                    payload = {"ok": True,
                               "records": segrep.wal_tail_payload(
                                   self._path, after)}
            except segments.SegmentError as e:
                self._count("bad_request")
                payload = {"error": "bad_request", "detail": str(e)}
        else:  # reload
            t0 = time.monotonic()
            ok, detail = self.reload()
            self._admin_trace("reload", t0,
                              status="ok" if ok else "reload_rejected")
            if ok:
                payload = {"ok": True, "reloaded": True}
            else:
                payload = {"error": "reload_rejected", "detail": detail}
        if rid is not None:
            payload["id"] = rid
        tid = req.get("trace_id")
        if tid is not None:
            payload["trace_id"] = tid if isinstance(tid, str) else str(tid)
        conn.enqueue(0, payload)

    # -- dispatch ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        """Crash boundary for the dispatcher thread: an exception
        escaping the batch loop takes the serving plane down, so the
        flight recorder is dumped first — the black box survives."""
        try:
            self._dispatch_inner()
        except BaseException:
            self.dump_flight("crash")
            raise

    def _dispatch_inner(self) -> None:
        while True:
            # heartbeat every iteration INCLUDING the idle path: an
            # empty queue is quiet, not stalled
            self._watchdog.beat("dispatcher")
            try:
                first = self._queue.get(timeout=0.02)
            except queue.Empty:
                if self._dispatch_stop.is_set():
                    return
                # an empty queue IS a zero-delay observation: without
                # it a drained-but-still-dropping gate would keep
                # admission-shedding a modest retry stream forever —
                # only dequeues exit dropping, and sheds never dequeue.
                # Every tenant's gate gets the observation: an idle
                # queue is idle for all lanes at once.
                for ts in self._tenant_list():
                    ts.codel.on_delay(0.0)
                continue
            inj = faults.active()
            if inj is not None:
                inj.on_dispatch_batch()
            first.t_pop = time.monotonic()
            batch = [first]
            if self.coalesce_us > 0 and self.max_batch > 1 \
                    and not self._draining:
                until = first.t_pop + self.coalesce_us / 1e6
                while len(batch) < self.max_batch:
                    rem = until - time.monotonic()
                    if rem <= 0:
                        break
                    try:
                        rider = self._queue.get(timeout=rem)
                    except queue.Empty:
                        break
                    rider.t_pop = time.monotonic()
                    batch.append(rider)
            while len(batch) < self.max_batch:  # free riders
                try:
                    rider = self._queue.get_nowait()
                except queue.Empty:
                    break
                rider.t_pop = time.monotonic()
                batch.append(rider)
            if self._codel.enabled:
                # CoDel dequeue side: feed each request's queue delay
                # to ITS TENANT's gate (the default lane's gate is the
                # daemon-wide one), and while dropping shed the ones
                # that already waited past target BEFORE they reach
                # the engine — executed requests then carry bounded
                # queueing even under sustained overload, and one
                # tenant's self-inflicted queue delay closes only its
                # own admission gate
                kept = []
                for it in batch:
                    delay = it.t_pop - it.t_admit
                    gate = it.tstate.codel if it.tstate is not None \
                        else self._codel
                    gate.on_delay(delay)
                    if gate.late_shed(delay):
                        self._count("shed")
                        self._count("codel_sheds")
                        self._finish(
                            it, {"error": "overloaded",
                                 "detail": "queued past CoDel target "
                                           f"{self.codel_target_ms}"
                                           "ms"})
                        continue
                    kept.append(it)
                if not kept:
                    continue
                batch = kept
            self._execute(batch)

    def _finish(self, item: _Request, payload: dict, *,
                admitted: bool = True) -> None:
        """The one response for an admitted request (ok or error)."""
        if item.done:
            return
        item.done = True
        if item.tstate is not None:
            err = payload.get("error")
            if err == "overloaded":
                item.tstate.c_shed.inc()
            elif err == "deadline_expired":
                item.tstate.c_deadline.inc()
            elif err == "internal":
                item.tstate.c_errors.inc()
        if not item.cached and item.ckey is not None \
                and item.cgen is not None and payload.get("ok"):
            # fill before id/trace_id stamping: the cached payload must
            # stay request-agnostic so a later hit for a different
            # request id returns byte-identical *data* fields
            self._result_cache.fill(item.ckey, item.cgen, payload)
        if item.rid is not None:
            payload.setdefault("id", item.rid)
        if item.trace_id is not None:
            payload.setdefault("trace_id", item.trace_id)
        item.conn.enqueue(item.seq, payload)
        with item.conn.lock:
            item.conn.pending -= 1
            idle = item.conn.read_eof and item.conn.pending == 0
        if idle:
            item.conn.enqueue_sentinel()
        if admitted:
            with self._count_lock:
                self._inflight -= 1
        self._record_trace(item, payload)

    def _admin_trace(self, op: str, t0: float, *, status: str = "ok",
                     generation=None) -> None:
        """One trace-ring span for an admin op that changed daemon
        state.  Mutation ops (append/delete/compact) stamp the manifest
        ``generation`` they produced on the record AND its span, so a
        ring snapshot shows which generation each query span ran
        against.  Never raises."""
        if not self._obs_enabled:
            return
        dur_ms = round((time.monotonic() - t0) * 1e3, 3)
        span = {"name": op, "start_ms": 0.0, "dur_ms": dur_ms}
        trace = {
            "trace_id": obs_tracing.gen_trace_id(),
            "id": None, "op": op, "seq": 0,
            "status": status, "dur_ms": dur_ms,
            "spans": [span],
        }
        if generation is not None:
            trace["generation"] = int(generation)
            span["generation"] = int(generation)
        self._trace_ring.push(trace)

    def _record_trace(self, item: _Request, payload: dict) -> None:
        """Latency histograms + one trace record per finished request.
        Off the response path's critical invariants — never raises."""
        t_done = time.monotonic()
        t0 = item.t_admit
        self._h_request.observe(
            t_done - t0,
            exemplar=item.trace_id if self._exemplars else None)
        if item.tstate is not None:
            item.tstate.h_request.observe(t_done - t0)
        if item.t_pop is not None:
            self._h_queue_wait.observe(item.t_pop - t0)
        want_trace = self._obs_enabled and item.trace_id is not None
        if not (want_trace or self._flight.enabled):
            return
        spans = []

        def add(name, a, b):
            spans.append({"name": name,
                          "start_ms": round((a - t0) * 1e3, 3),
                          "dur_ms": round((b - a) * 1e3, 3)})

        if item.t_pop is None:  # cache hit, admission shed, drain flush
            add("result_cache" if item.cached else "admission",
                t0, t_done)
        elif item.t_exec is None:  # popped, never reached the engine
            add("queue_wait", t0, item.t_pop)
            add("dispatch", item.t_pop, t_done)
        else:
            add("queue_wait", t0, item.t_pop)
            add("coalesce", item.t_pop, item.t_exec)
            add("engine", item.t_exec, t_done)
            if item.planner is not None:
                # label the engine span with the ranked plan so slow
                # BM25 queries are attributable to their strategy
                spans[-1]["planner"] = item.planner
        dur_ms = (t_done - t0) * 1e3
        trace = {
            "trace_id": item.trace_id,
            "id": item.rid,
            "op": item.op,
            "seq": item.seq,
            "status": "ok" if payload.get("ok")
                      else payload.get("error", "error"),
            "dur_ms": round(dur_ms, 3),
            "spans": spans,
        }
        if item.tenant is not None:
            trace["tenant"] = item.tenant
        if want_trace:
            self._trace_ring.push(trace)
            if 0 < self._slow_ms <= dur_ms:
                obs_tracing.emit_slow(trace)
        if self._flight.enabled:
            self._flight.record(
                trace, item.attrib.report()
                if item.attrib is not None else None)

    def _execute(self, items: list[_Request]) -> None:
        inj = faults.active()
        with self._engine_lock:
            # expiry is judged NOW — after any wait for the engine, at
            # the last instant before dispatch — so stale work never
            # reaches the batch path no matter where the queue stalled
            now = time.monotonic()
            # snapshot the cache epoch under the same lock that pins
            # the engine: mutations swap the engine BEFORE bumping
            # self._generation, so the only possible mismatch pairs
            # NEW bytes with the OLD generation key — an entry the next
            # probe (at the new generation) can never return
            gen = self._generation
            for it in items:
                it.t_exec = now
                it.cgen = gen
            live = []
            for it in items:
                if it.expires_at is not None and now > it.expires_at:
                    self._count("deadline_expired")
                    self._finish(it, {"error": "deadline_expired",
                                      "detail": "deadline passed "
                                                "before dispatch"})
                else:
                    live.append(it)
            if not live:
                return
            self._count("batches")
            self._count("batched_requests", len(live))
            eng = self._engine
            ready = []
            for it in live:
                if inj is not None:
                    try:
                        inj.on_serve_request(it.seq)
                    except faults.HandlerCrash as e:
                        self._count("internal_errors")
                        self._finish(it, {"error": "internal",
                                          "detail": str(e)})
                        continue
                ready.append(it)
            # coalesced groups: one vectorized engine call answers every
            # df (resp. postings) request in the batch.  Explain
            # requests are excluded — they run solo below, so the cost
            # report charges them for their own work only.
            for op in ("df", "postings"):
                group = [it for it in ready
                         if it.op == op and not it.explain]
                if not group:
                    continue
                try:
                    terms = [t for it in group for t in it.terms]
                    batch = eng.encode_batch(terms)
                    if op == "df":
                        out = eng.df(batch)
                        pos = 0
                        for it in group:
                            n = len(it.terms)
                            self._finish(it, {
                                "ok": True,
                                "df": out[pos:pos + n].tolist()})
                            pos += n
                    else:
                        runs = eng.postings(batch)
                        pos = 0
                        for it in group:
                            n = len(it.terms)
                            part = runs[pos:pos + n]
                            self._finish(it, {
                                "ok": True,
                                "postings": [r.tolist() if r is not None
                                             else None for r in part]})
                            pos += n
                except Exception as e:  # group failed: every unanswered
                    for it in group:    # member gets a counted internal
                        if not it.done:
                            self._count("internal_errors")
                            self._finish(it, {"error": "internal",
                                              "detail": str(e)})
            # ranked groups: a router fanning one client's pipelined
            # BM25 queries across shards lands same-k bursts here — one
            # top_k_scored_batch call crosses into the native kernel
            # once for the whole group.  Solo requests keep the
            # per-query path (planner trace detail rides it), and
            # explain requests always run solo for honest attribution.
            ranked = [it for it in ready
                      if not it.done and not it.explain
                      and it.op == "top_k" and it.score == "bm25"]
            batcher = getattr(eng, "top_k_scored_batch", None)
            if len(ranked) > 1 and batcher is not None:
                by_k: dict[int, list] = {}
                for it in ranked:
                    by_k.setdefault(it.k, []).append(it)
                for k, group in by_k.items():
                    if len(group) < 2:
                        continue
                    try:
                        tops = batcher(
                            [eng.encode_batch(it.terms)
                             for it in group], k)
                        for it, top in zip(group, tops):
                            self._finish(it, {
                                "ok": True,
                                "docs": [[d, s] for d, s in top]})
                    except Exception as e:
                        for it in group:
                            if not it.done:
                                self._count("internal_errors")
                                self._finish(it, {"error": "internal",
                                                  "detail": str(e)})
            for it in ready:
                if it.done:
                    continue
                try:
                    if it.explain:
                        with obs_attrib.collect(it.op) as coll:
                            t_eng = time.monotonic()
                            payload = self._exec_one(eng, it)
                        coll.stage("queue",
                                   (it.t_pop - it.t_admit) * 1e6)
                        coll.stage("coalesce",
                                   (it.t_exec - it.t_pop) * 1e6)
                        coll.stage("engine",
                                   (time.monotonic() - t_eng) * 1e6)
                        it.attrib = coll
                        payload["explain"] = coll.report()
                    else:
                        payload = self._exec_one(eng, it)
                    self._finish(it, payload)
                except Exception as e:
                    self._count("internal_errors")
                    self._finish(it, {"error": "internal",
                                      "detail": str(e)})

    def _exec_one(self, eng, it: _Request) -> dict:
        """One data request against the engine; returns the ok payload.
        df/postings normally ride the coalesced group path — they land
        here solo when the request asked for an explain report."""
        if it.op == "df":
            out = eng.df(eng.encode_batch(it.terms))
            return {"ok": True, "df": out.tolist()}
        if it.op == "postings":
            runs = eng.postings(eng.encode_batch(it.terms))
            return {"ok": True,
                    "postings": [r.tolist() if r is not None else None
                                 for r in runs]}
        if it.op == "and":
            docs = eng.query_and(eng.encode_batch(it.terms))
            return {"ok": True, "docs": docs.tolist()}
        if it.op == "or":
            docs = eng.query_or(eng.encode_batch(it.terms))
            return {"ok": True, "docs": docs.tolist()}
        if it.op == "top_k" and it.score == "bm25":
            top = eng.top_k_scored(eng.encode_batch(it.terms), it.k)
            planner = getattr(eng, "planner", None)
            if planner is not None:
                # decision + pruning counters ride the trace record so
                # slow ranked queries are attributable to their strategy
                it.planner = planner.last_ranked
            return {"ok": True, "docs": [[d, s] for d, s in top]}
        top = eng.top_k(it.letter, it.k)  # top_k by df
        return {"ok": True,
                "top": [[t.decode("ascii", "replace"), int(d)]
                        for t, d in top]}

    # -- live mutations (segment-managed dirs) -------------------------

    def _flush_deletes_locked(self):  # mrilint: holds(self._reload_lock)
        """Publish every buffered delete op as ONE tombstone generation.
        Caller holds ``_reload_lock``.  Returns the mutation result, or
        None when the buffer was empty.  On failure the buffer is
        dropped (the caller reports the rejection) so a poisoned flush
        can never wedge later compactions."""
        if not self._pending_deletes:
            return None
        from .. import segments
        from ..segments import wal as wal_mod
        ids = sorted(set(self._pending_deletes))
        self._pending_deletes = []
        self._delete_ops = 0
        try:
            return segments.delete_docs(self._path, ids,
                                        registry=self.registry)
        except Exception:
            # the buffer is gone but its acked per-op WAL records are
            # not: the next mutation must replay them before logging
            # anything newer, or truncation would pass them unapplied
            self._stale_wal = wal_mod.wal_enabled()
            raise

    def mutate(self, op: str, *, files=None, docs=None,
               force: bool = True) -> tuple[bool, dict | str]:
        """Run one live-index mutation (``append`` / ``delete`` /
        ``compact``) and swap in an engine over the new generation.

        Runs on the caller's thread (a connection reader), serialized
        with hot reloads under ``_reload_lock`` — never the dispatcher.
        The mutation publishes its manifest generation atomically on
        disk first; only then is a fresh engine opened and swapped under
        the dispatch lock.  On ANY failure the old generation keeps
        serving and the attempt is counted ``mutation_rejected``.

        Durability (acknowledgement) ordering: every mutation's WAL
        record is fsync'd BEFORE its manifest swap — for buffered
        deletes the record is fsync'd here, before the ack, even though
        the tombstone generation publishes ops later.  With leasing
        enabled (``MRI_SEGMENT_LEASE_TTL_S`` > 0) the lease is renewed
        first; a live foreign holder rejects the mutation with
        ``lease_lost`` while reads keep serving."""
        from .. import segments
        from ..segments import wal as wal_mod
        if self._replica_of is not None:
            self._count("mutation_rejected")
            return False, ("replica is read-only: mutations go to the "
                           f"primary at {self._replica_of}")
        with self._reload_lock:
            t0 = time.monotonic()
            published = True
            try:
                segments.renew_lease(self._path, self._lease_owner)
                if self._stale_wal:
                    # a failed delete flush left acked records above
                    # the manifest's wal_seq — apply them before this
                    # mutation logs (and later truncates past) a
                    # higher seq
                    segments.replay(self._path, registry=self.registry)
                    self._stale_wal = False
                if op == "append":
                    # buffered deletes flush first: WAL seq order must
                    # match apply order, and the append's published
                    # wal_seq must never cover an unapplied delete
                    self._flush_deletes_locked()
                    res = segments.append_files(self._path, files,
                                                registry=self.registry)
                    auto = segments.compact_to_limit(
                        self._path, registry=self.registry)
                    if auto:
                        res = dict(res, auto_compactions=len(auto),
                                   segments=auto[-1]["segments"],
                                   generation=auto[-1]["generation"])
                elif op == "delete":
                    man = segments.load_manifest(self._path)
                    if man is None:
                        raise segments.SegmentError(
                            f"{self._path}: not segment-managed "
                            "(append first)")
                    bad = [d for d in docs if not any(
                        e.doc_base < d <= e.doc_base + e.docs
                        for e in man.entries)]
                    if bad:
                        raise segments.SegmentError(
                            f"doc ids {bad} are outside every segment "
                            f"(live span is 1..{man.doc_span})")
                    wal_seq = None
                    if self._delete_ops + 1 < self._tomb_flush \
                            and wal_mod.wal_enabled():
                        # durability point for a buffered ack: the
                        # tombstone generation publishes later, but
                        # this fsync'd record survives a crash now
                        # (replayed by recover; made idempotent by
                        # bitmap-OR semantics)
                        with segments.mutation_lock(self._path):
                            wal_seq = wal_mod.log_mutation(
                                self._path, "delete",
                                {"docs": sorted(set(docs))},
                                registry=self.registry)
                    self._pending_deletes.extend(docs)
                    self._delete_ops += 1
                    if self._delete_ops >= self._tomb_flush:
                        res = self._flush_deletes_locked()
                    else:
                        published = False
                        res = {"buffered": True,
                               "pending_docs":
                                   len(set(self._pending_deletes)),
                               "pending_ops": self._delete_ops,
                               "wal_seq": wal_seq,
                               "generation": self._generation}
                else:  # compact (flushes buffered deletes first, so the
                    #    merge sees every tombstone it should drop)
                    self._flush_deletes_locked()
                    res = segments.compact(self._path, force=force,
                                           registry=self.registry)
                if published:
                    new_engine = create_engine(
                        self._path, self._engine_choice,
                        cache_terms=self._cache_terms,
                        shards=self._shards)
            except (segments.SegmentError, ArtifactError, ValueError,
                    OSError, faults.InjectedCompactCrash) as e:
                self._count("mutation_rejected")
                self._admin_trace(op, t0, status="mutation_rejected")
                log.warning("%s rejected, old generation keeps "
                            "serving: %s", op, e)
                return False, str(e)
            if published:
                with self._engine_lock:
                    old, self._engine = self._engine, new_engine
                old.close()
                if isinstance(res, dict) \
                        and res.get("generation") is not None:
                    self._generation = int(res["generation"])
                # generation bumped (or content republished): entries
                # keyed under the old generation are dead — drop them
                self._result_cache.on_epoch(self._generation)
            self._count("mutations")
            dur_ms = round((time.monotonic() - t0) * 1e3, 3)
            # mrilint: allow(trace) append delete compact — every
            # mutation op lands here; the span carries the generation it
            # produced.  A buffered delete publishes nothing — its ack
            # echoes the CURRENT generation as a read-your-writes token,
            # which must not masquerade as a produced one here.
            gen = res.get("generation") \
                if isinstance(res, dict) and published else None
            self._admin_trace(op, t0, generation=gen)
            log.info("%s: %s (%.1f ms)", op, json.dumps(res), dur_ms)
            return True, res

    # -- hot reload ----------------------------------------------------

    def reload(self) -> tuple[bool, str]:
        """Open + checksum-verify the artifact again and atomically swap
        engines.  On ANY failure the old engine keeps serving and the
        attempt is counted ``reload_rejected`` — a bad push can reject,
        never kill, the daemon.  Runs on the caller's thread (reader or
        the CLI's SIGHUP thread), off the dispatcher; only the O(1)
        swap itself holds the dispatch lock."""
        with self._reload_lock:
            self._reloading = True  # healthz readiness: "reloading"
            try:
                inj = faults.active()
                new_engine = None
                try:
                    new_engine = create_engine(
                        self._path, self._engine_choice,
                        cache_terms=self._cache_terms,
                        shards=self._shards)
                    if inj is not None:
                        inj.on_reload()
                except (ArtifactError, ValueError, OSError,
                        faults.InjectedReloadCorrupt) as e:
                    if new_engine is not None:
                        new_engine.close()
                    self._count("reload_rejected")
                    log.warning("hot reload rejected, keeping current "
                                "artifact: %s", e)
                    return False, str(e)
                with self._engine_lock:
                    old, self._engine = self._engine, new_engine
                old.close()
                # a reload can change artifact content at an UNCHANGED
                # generation (an out-of-band artifact push) — the
                # epoch key cannot see that, so drop everything
                self._result_cache.purge()
                self._count("reload_ok")
                log.info("hot reload: swapped in %s", self._path)
                return True, ""
            finally:
                self._reloading = False

    # -- replica catch-up ----------------------------------------------

    def _replica_loop(self) -> None:
        """Poll the primary every ``MRI_REPLICA_POLL_MS``: one
        :func:`~..segments.replica.replicate` round per tick, adopting
        the shipped generation when it changed.  Failures mark the
        replica lagging (healthz ``replica_lagging``) and keep
        polling — a partition heals by itself."""
        from .. import segments
        from ..segments import replica as segrep
        try:
            addr = segrep.parse_addr(self._replica_of)
        except segments.SegmentError as e:
            log.error("replica mode dead on arrival: %s", e)
            return
        period = max(0.001, envknobs.get(segrep.POLL_ENV) / 1e3)
        while True:
            try:
                res = segrep.replicate(self._path, addr,
                                       registry=self.registry)
                self._g_replica_lag.set(max(0, res["behind"]))
                if res["changed"] or self._replica_lagging:
                    self._adopt_generation(res["generation"])
                self._replica_lagging = False
                self._g_replica_lag.set(0)
            except (segments.SegmentError, ArtifactError, ValueError,
                    OSError) as e:
                self._replica_lagging = True
                log.warning("replica catch-up from %s failed: %s",
                            self._replica_of, e)
            if self._replica_stop.wait(period):
                return

    def _adopt_generation(self, generation: int) -> None:
        """Swap in an engine over a freshly shipped generation.  Quiet
        on purpose: adoption is not a reload — no ``reload_ok`` count,
        no ``reloading`` readiness blip — readers never notice."""
        with self._reload_lock:
            new_engine = create_engine(
                self._path, self._engine_choice,
                cache_terms=self._cache_terms, shards=self._shards)
            with self._engine_lock:
                old, self._engine = self._engine, new_engine
            old.close()
            self._generation = generation
            self._result_cache.on_epoch(generation)

    # -- stats ---------------------------------------------------------

    def stats(self) -> dict:
        counters = {key: c.value for key, c in self._counts.items()}
        with self._count_lock:
            inflight = self._inflight
        # serialized against reload's swap+close via _reload_lock, NOT
        # the dispatch lock: stats must answer even while the
        # dispatcher is wedged inside a batch
        engine = {}
        if not self._drained.is_set():
            with self._reload_lock:
                try:
                    # mrilint: allow(guarded-by) serialized by _reload_lock
                    engine = self._engine.describe()
                except Exception:  # racing a drain's engine close
                    engine = {}
        with self._conn_lock:
            connections = len(self._conns)
        return {
            "queue_depth": self._queue.qsize(),
            "inflight": inflight,
            "draining": self._draining,
            "connections": connections,
            "counters": counters,
            "engine": engine,
            "rolling": self._rolling_stats(),
            "slo": self._slo.report(),
            "config": {
                "coalesce_us": self.coalesce_us,
                "queue_depth": self.queue_depth,
                "max_batch": self.max_batch,
                "drain_s": self.drain_s,
                "codel_target_ms": self.codel_target_ms,
                "codel_interval_ms": self.codel_interval_ms,
            },
            "codel": self._codel.state(),
            "result_cache": self._result_cache.stats(),
            "tenants": self._tenant_stats(),
        }

    def _tenant_stats(self) -> dict:
        """Per-tenant QoS slice for ``stats()``: cumulative counters,
        live lane depth, 1m p95 and 1m SLO burn — one poll answers
        ``mri top``'s whole tenants table."""
        out = {}
        for ts in self._tenant_list():
            p95 = self._rolling.quantile(ts.hist_name, 60.0, 95.0)
            burn = {
                name: entry["windows"]["1m"]["burn"]
                for name, entry in ts.slo.report().items()}
            out[ts.name] = {
                "weight": ts.weight,
                "rate_rps": None if ts.bucket is None
                            else ts.bucket.rps,
                "requests": ts.c_requests.value,
                "shed": ts.c_shed.value,
                "deadline_expired": ts.c_deadline.value,
                "errors": ts.c_errors.value,
                "cache_hits": ts.c_cache_hits.value,
                "queue_depth": self._queue.lane_depth(ts),
                "p95_ms": None if p95 is None
                          else round(p95 * 1e3, 3),
                "burn_1m": burn,
            }
        return out

    def _rolling_stats(self) -> dict:
        """Per-window rates + latency quantiles for ``stats()``."""
        out = {}
        roll = self._rolling
        for label, span in obs_windows.WINDOWS:
            p50 = roll.quantile("mri_serve_request_seconds", span, 50.0)
            p99 = roll.quantile("mri_serve_request_seconds", span, 99.0)
            out[label] = {
                "qps": round(
                    roll.rate("mri_serve_requests_total", span), 3),
                "shed_per_s": round(
                    roll.rate("mri_serve_shed_total", span), 3),
                "deadline_per_s": round(roll.rate(
                    "mri_serve_deadline_expired_total", span), 3),
                "error_per_s": round(roll.rate(
                    "mri_serve_internal_errors_total", span), 3),
                "p50_ms": round(p50 * 1e3, 3) if p50 is not None
                          else None,
                "p99_ms": round(p99 * 1e3, 3) if p99 is not None
                          else None,
            }
        return out

    # -- flight recorder -----------------------------------------------

    @property
    def flight(self) -> obs_attrib.FlightRecorder:
        return self._flight

    def dump_flight(self, reason: str) -> str | None:
        """Write the flight recorder next to the served artifact as
        ``flight-<pid>-<reason>.json``; returns the path or ``None``.
        Crash-path safe — never raises."""
        return self._flight.dump_to_file(str(self._path), reason)

    # -- metrics exposition --------------------------------------------

    def render_metrics(self) -> str:
        """Prometheus text exposition: the daemon's registry, the
        current engine's registry, and the process-global registry
        (fault firings), merged with first-occurrence-wins dedup —
        live mutations put segment gauges on the daemon registry that a
        multi-segment engine also carries."""
        with self._count_lock:
            self._g_inflight.set(self._inflight)
        self._g_queue_depth.set(self._queue.qsize())
        self._g_draining.set(1 if self._draining else 0)
        self._slo.set_gauges(self.registry)
        self.registry.gauge("mri_watchdog_heartbeat_age_seconds").set(
            round(self._watchdog.max_age_s(), 6))
        parts = [self.registry.render_text(exemplars=self._exemplars)]
        if not self._drained.is_set():
            with self._reload_lock:
                try:
                    # mrilint: allow(guarded-by) serialized by _reload_lock
                    parts.append(self._engine.metrics.render_text())
                except Exception:  # racing a drain's engine close
                    pass
        parts.append(obs_metrics.default_registry().render_text())
        return obs_metrics.merge_expositions(parts)

    def _metrics_loop(self) -> None:
        """Minimal HTTP/1.0 scrape endpoint on the loopback listener:
        read (and ignore) the request, answer one 200 with the text
        exposition, close.  Serial on purpose — scrapes are rare."""
        assert self._metrics_listener is not None
        while not self._draining:
            try:
                sock, _ = self._metrics_listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed by drain()
            try:
                sock.settimeout(1.0)
                with contextlib.suppress(OSError):
                    sock.recv(65536)  # request head, ignored
                body = self.render_metrics().encode()
                head = (b"HTTP/1.0 200 OK\r\n"
                        b"Content-Type: text/plain; version=0.0.4; "
                        b"charset=utf-8\r\n"
                        b"Content-Length: " + str(len(body)).encode()
                        + b"\r\n\r\n")
                with contextlib.suppress(OSError):
                    sock.sendall(head + body)
            finally:
                with contextlib.suppress(OSError):
                    sock.close()

    # -- drain ---------------------------------------------------------

    def drain(self) -> int:
        """Graceful shutdown; returns the process exit code (0).
        Idempotent — the second call just waits for the first."""
        with self._drain_guard:
            if self._drain_started:
                racing = True
            else:
                self._drain_started = True
                racing = False
        if racing:
            self._drained.wait()
            return 0
        self._draining = True
        # health machinery goes first: a drain wedging a loop must not
        # fire spurious stall dumps, and the leak guard wants these
        # threads gone with the rest
        self._watchdog.stop()
        self._rolling.stop()
        self._replica_stop.set()
        if self._replica_thread is not None:
            self._replica_thread.join(timeout=5.0)
        deadline = time.monotonic() + self.drain_s
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self._metrics_listener is not None:
            try:
                self._metrics_listener.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        if self._metrics_thread is not None:
            self._metrics_thread.join(timeout=2.0)
        # finish in-flight work within the drain budget
        while time.monotonic() < deadline:
            with self._count_lock:
                idle = self._inflight == 0
            if idle:
                break
            time.sleep(0.005)
        self._dispatch_stop.set()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=max(2.0, self.drain_s))
        # budget expired with work still queued: flush it as counted,
        # well-formed errors — drain never silently drops a request
        flushed = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            self._count("draining_rejected")
            self._finish(item, {"error": "draining",
                                "detail": "daemon drained before "
                                          "dispatch"})
            flushed += 1
        if flushed:
            # abnormal drain — the budget expired with work queued;
            # dump the flight recorder so the backlog is diagnosable
            self.dump_flight("drain-flush")
        # unblock every reader (idle keep-alive clients never EOF on
        # their own), let writers flush, then force-close stragglers
        with self._conn_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.sock.shutdown(socket.SHUT_RD)
            except OSError:
                pass
        grace = max(0.0, deadline - time.monotonic()) + 1.0
        for conn in conns:
            conn.reader.join(timeout=grace)
            conn.enqueue_sentinel()
        for conn in conns:
            conn.writer.join(timeout=grace)
            if conn.writer.is_alive():
                conn.kill()
                conn.writer.join(timeout=1.0)
        with self._conn_lock:
            self._conns.clear()
        # buffered deletes must not die with the process
        with self._reload_lock:
            try:
                self._flush_deletes_locked()
            except Exception as e:
                log.warning("drain: buffered delete flush failed: %s", e)
        # a clean exit hands the lease to the successor immediately
        # instead of making it wait out the TTL
        with contextlib.suppress(Exception):
            from .. import segments
            segments.release_lease(self._path, self._lease_owner)
        self.final_stats = self.stats()
        with self._engine_lock:
            self._engine.close()
        self._drained.set()
        log.info("drained: %s", json.dumps(self.final_stats["counters"]))
        return 0

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.drain()
