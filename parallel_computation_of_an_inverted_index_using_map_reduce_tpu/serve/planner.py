"""Query planner: picks the evaluation strategy per query.

Ranked (BM25 top-k) queries choose between exhaustive scoring and the
two classic dynamic-pruning disciplines the v2.1 per-block max-score
columns enable — **MaxScore** (terms whose summed upper bounds cannot
reach the heap threshold stop admitting new candidates) and **Block-Max
WAND** (whole posting blocks whose quantized upper bound cannot reach
the threshold are never decoded).  AND queries choose between the
galloping ``searchsorted`` probe and a linear sorted-set merge.  Every
decision and the resulting block economy is counted on the engine's obs
registry so ``describe()``/``mri query --stats``/the daemon ``stats``
op expose what the planner actually did.
"""

from __future__ import annotations

import os

import numpy as np

from ..obs import attribution as obs_attrib
from ..utils import envknobs

PLANNER_ENV = "MRI_SERVE_PLANNER"
PLANNER_CHOICES = ("auto", "exhaustive", "bmw", "maxscore")

#: Relative slack applied to every theta comparison on the host path.
#: The pruned evaluators accumulate the same per-term float64
#: contributions as the exhaustive scorer but in bound-sorted order;
#: one part in 1e9 absorbs the worst-case associativity drift so a
#: candidate sitting exactly on the threshold is never wrongly pruned.
THETA_MARGIN = 1.0 - 1e-9

#: Wider slack for the device path, whose scores are float32.
DEVICE_MARGIN = 1.0 - 1e-5


def resolve_planner(mode: str | None = None) -> str:
    """Explicit mode, else ``$MRI_SERVE_PLANNER`` (default auto)."""
    mode = mode or envknobs.get(PLANNER_ENV)
    if mode not in PLANNER_CHOICES:
        raise ValueError(
            f"unknown planner {mode!r} (choices: {PLANNER_CHOICES})")
    return mode


def block_upper_bounds(art, idx: int, idf: float, avgdl: float,
                       k1: float, b: float) -> np.ndarray:
    """Per-block BM25 upper bounds for term ``idx`` (float64).

    Derived from the stored quantized columns: ``blk_max_tf`` (max tf
    in the block, saturating) and ``blk_min_dl`` (min doc length in the
    block, saturating).  BM25's per-doc contribution is increasing in
    tf and decreasing in doc length, so evaluating it at (max tf,
    min dl) bounds every doc in the block from above.  A saturated
    max-tf cell is taken to the tf→∞ limit ``idf*(k1+1)``; a saturated
    min-dl cell only underestimates the length, which keeps the bound
    on the safe (over-estimating) side.
    """
    b0 = int(art.term_block_off[idx])
    b1 = int(art.term_block_off[idx + 1])
    cap = (1 << art.score_bits) - 1
    mtf = art.blk_max_tf[b0:b1].astype(np.float64)
    mdl = art.blk_min_dl[b0:b1].astype(np.float64)
    denom = mtf + k1 * (1.0 - b + b * mdl / avgdl)
    ub = idf * mtf * (k1 + 1.0) / denom
    return np.where(mtf >= cap, idf * (k1 + 1.0), ub)


class Planner:
    """Per-engine strategy picker + decision/economy counters.

    All tallies live on the engine's obs registry (the repo-wide
    no-hand-rolled-counters contract); ``last_ranked`` keeps the most
    recent ranked decision for trace attribution.
    """

    def __init__(self, registry):
        self._c_ranked = {
            m: registry.counter(f"mri_planner_ranked_{m}_total")
            for m in ("exhaustive", "bmw", "maxscore")}
        self._c_and = {
            m: registry.counter(f"mri_planner_and_{m}_total")
            for m in ("gallop", "merge", "native")}
        self._c_scored = registry.counter(
            "mri_planner_blocks_scored_total")
        self._c_skipped = registry.counter(
            "mri_planner_blocks_skipped_total")
        self.last_ranked: dict | None = None
        self._raw_mode: object = -1
        self._resolved_mode = "auto"

    def resolve_cached(self) -> str:
        """:func:`resolve_planner` with the parsed value cached against
        the raw environment string — the ranked hot path re-resolves
        only when ``$MRI_SERVE_PLANNER`` actually changes."""
        # mrilint: allow(env-knobs) raw-string cache key only; the
        # parse still goes through the declared knob on change
        raw = os.environ.get(PLANNER_ENV)
        if raw != self._raw_mode:
            self._resolved_mode = resolve_planner(None)
            self._raw_mode = raw
        return self._resolved_mode

    def plan_ranked(self, art, dfs, k: int, mode: str | None = None) -> str:
        """Pick the ranked strategy for a query with term dfs ``dfs``
        and cutoff ``k``.  Pruning needs the v2.1 max-score columns and
        a cutoff that can actually drop something; ``auto`` prefers
        MaxScore on short posting lists (block skipping can't pay below
        a handful of blocks per term) and Block-Max WAND on long ones.
        """
        mode = self.resolve_cached() if mode is None \
            else resolve_planner(mode)
        if not art.has_block_scores or k <= 0 or not dfs \
                or k >= sum(dfs):
            return "exhaustive"
        if mode == "auto":
            mode = "bmw" if max(dfs) > 4 * art.block_size else "maxscore"
        return mode

    def plan_and(self, n_acc: int, df: int, native: bool = False) -> str:
        """Gallop (probe the partner run at surviving candidates only)
        vs merge (linear sorted-set intersection) for one AND step.
        Galloping wins when the partner dwarfs the accumulator; a
        linear merge is cache-friendlier when the runs are comparable.
        With ``native`` the C kernel (which fuses blk_max skip routing
        with in-block galloping) takes the gallop arm's territory; the
        comparable-runs merge stays numpy, where a linear pass over an
        already-decoded cached array beats re-decoding blocks.
        """
        mode = "merge" if df <= 2 * n_acc else "gallop"
        if native and mode == "gallop":
            mode = "native"
        self._c_and[mode].inc()
        coll = obs_attrib.active()
        if coll is not None:
            coll.and_arm(mode)
        return mode

    def note_ranked(self, mode: str, scored: int, skipped: int,
                    candidates: int, backend: str = "numpy") -> None:
        """Record one ranked query's decision + block economy.
        ``backend`` labels who executed the chosen plan (numpy or
        native) so the trace span and ``--stats`` stay auditable."""
        self._c_ranked[mode].inc()
        if scored:
            self._c_scored.inc(scored)
        if skipped:
            self._c_skipped.inc(skipped)
        coll = obs_attrib.active()
        if coll is not None:
            coll.ranked(f"{mode}/native" if backend == "native"
                        else mode, scored, skipped, candidates)
        self.last_ranked = {
            "mode": mode,
            "backend": backend,
            "blocks_scored": scored,
            "blocks_skipped": skipped,
            "candidates": candidates,
        }

    def note_ranked_batch(self, counts: dict, last_mode: str,
                          scored: int, skipped: int, candidates: int,
                          backend: str = "native") -> None:
        """Accounting for one coalesced ranked batch: per-mode ranked
        counters advance by each query (``counts`` maps mode → how
        many of the batch ran it, so ``describe()`` totals match the
        per-query path exactly) while the block-economy totals —
        already summed across the batch by the native kernel — land in
        one locked increment each.  ``last_ranked`` records the final
        query's mode with the batch's summed block economy."""
        for m, c in counts.items():
            self._c_ranked[m].inc(c)
        if scored:
            self._c_scored.inc(scored)
        if skipped:
            self._c_skipped.inc(skipped)
        self.last_ranked = {
            "mode": last_mode,
            "backend": backend,
            "blocks_scored": scored,
            "blocks_skipped": skipped,
            "candidates": candidates,
        }

    def describe(self) -> dict:
        """Planner block for ``Engine.describe()``/daemon ``stats``."""
        return {
            "mode": envknobs.get(PLANNER_ENV),
            "ranked": {m: c.value for m, c in self._c_ranked.items()},
            "and": {m: c.value for m, c in self._c_and.items()},
            "blocks_scored": self._c_scored.value,
            "blocks_skipped": self._c_skipped.value,
            "last_ranked": self.last_ranked,
        }
