"""Generation-keyed query-result cache.

The serving layer recomputes every answer from postings on every
request; under a Zipf workload most requests are repeats of a small hot
set.  This cache stores whole response payloads keyed on
``(op, normalized terms, k, score, <epoch>)`` where the epoch is the
published segment-manifest generation (PR 12) — a live append, delete
or compact bumps the generation, so invalidation is exact and free: a
stale entry's key simply can never be probed again.  No TTLs, no
staleness window on the daemon.

Normalization is chosen so two requests share an entry *only* when the
engine provably returns byte-identical payloads for both:

- ``and`` / ``or``: results are ascending doc-id merges, independent of
  term order and duplicates — key is ``sorted(set(terms))``.
- ``top_k`` (ranked): BM25 sums per-term contributions and breaks ties
  on ``(-score, gid)``, so term *order* is irrelevant but duplicates
  are not (a repeated term scores twice) — key is ``sorted(terms)``.
- ``df`` / ``postings``: replies are positional per input term — key is
  the term tuple verbatim.
- letter ``top_k``: keyed on the letter (no terms).

Callers keep ``explain`` requests out of the cache (their payloads
carry per-request cost reports) and snapshot the generation under the
same lock that guards the engine they read, so a fill can never pair
old bytes with a new generation (see ``ServeDaemon._execute``).
"""

from __future__ import annotations

import json
import threading

from .cache import LRUCache
from ..obs import metrics as obs_metrics
from ..utils import envknobs

#: ops whose answers are cacheable (admin + mutation ops never are)
CACHEABLE_OPS = ("df", "postings", "and", "or", "top_k")


def key_for(op: str, terms, letter, k, score) -> tuple | None:
    """Epoch-free cache key for a request, or ``None`` when the request
    shape is not cacheable.  The caller appends the generation/epoch at
    probe and fill time."""
    if op not in CACHEABLE_OPS:
        return None
    if letter is not None:
        if op != "top_k":
            return None
        return ("top_k_letter", str(letter), int(k or 0), str(score or ""))
    if not terms:
        return None
    tt = tuple(str(t) for t in terms)
    if op in ("and", "or"):
        norm = tuple(sorted(set(tt)))
    elif op == "top_k":
        norm = tuple(sorted(tt))
    else:  # df / postings: positional replies
        norm = tt
    return (op, norm, int(k or 0), str(score or ""))


class ResultCache:
    """LRU of full response payloads, bounded by entries and bytes.

    Thread-safe: probed on reader threads (daemon) / conn threads
    (router) while fills arrive from the dispatcher — the underlying
    :class:`LRUCache` lock covers both.  Stored and returned payloads
    are shallow copies, because ``_finish`` mutates its payload
    (``setdefault`` of id/trace_id) after the fact.
    """

    def __init__(self, *, registry: obs_metrics.Registry,
                 enabled: bool | None = None,
                 entries: int | None = None,
                 max_bytes: int | None = None,
                 prefix: str = "mri_serve_result_cache"):
        if enabled is None:
            enabled = bool(envknobs.get("MRI_SERVE_RESULT_CACHE"))
        if entries is None:
            entries = envknobs.get("MRI_SERVE_RESULT_CACHE_ENTRIES")
        if max_bytes is None:
            max_bytes = envknobs.get("MRI_SERVE_RESULT_CACHE_BYTES")
        self.enabled = bool(enabled)
        self._lru = LRUCache(int(entries) if self.enabled else 0,
                             registry=registry, prefix=prefix,
                             max_bytes=int(max_bytes))
        self._invalidations = registry.counter(f"{prefix}_invalidations_total")
        self._lock = threading.Lock()
        self._epoch = None  # last adopted epoch, guarded by: self._lock

    def lookup(self, key: tuple, epoch) -> dict | None:
        """Payload copy for ``key`` at ``epoch``, or ``None`` on miss."""
        if not self.enabled or key is None or epoch is None:
            return None
        hit = self._lru.get((key, epoch))
        return dict(hit) if hit is not None else None

    def fill(self, key: tuple, epoch, payload: dict) -> None:
        """Store a copy of ``payload`` under ``(key, epoch)``, sized by
        its JSON encoding (the bytes a hit saves re-serializing are the
        bytes it occupies)."""
        if not self.enabled or key is None or epoch is None:
            return
        try:
            nbytes = len(json.dumps(payload, separators=(",", ":")))
        except (TypeError, ValueError):
            return  # non-JSON payload: never cacheable on this protocol
        self._lru.put((key, epoch), dict(payload), nbytes=nbytes)

    def on_epoch(self, epoch) -> None:
        """Adopt a new epoch (generation bump or shard-vector change):
        entries keyed under older epochs can never be probed again, so
        drop them eagerly to free the byte budget."""
        if not self.enabled:
            return
        with self._lock:
            changed = epoch != self._epoch
            self._epoch = epoch
        if changed:
            self._invalidations.inc()
            self._lru.purge()

    def purge(self) -> None:
        """Drop everything without an epoch change — the reload path,
        where artifact content may change at an *unchanged* generation."""
        if not self.enabled:
            return
        self._invalidations.inc()
        self._lru.purge()

    def stats(self) -> dict:
        out = self._lru.stats()
        out["enabled"] = self.enabled
        out["invalidations"] = self._invalidations.value
        return out
