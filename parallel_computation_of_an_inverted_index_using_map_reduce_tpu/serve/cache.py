"""LRU hot-term cache for decoded posting runs.

The artifact stores postings delta-encoded; decoding is one cumsum per
term.  Under a Zipf workload a few hundred hot terms cover most lookups,
so the engine keeps their decoded arrays here — bounded by entry count
(hot terms are the frequent ones, so bounding by count bounds bytes by
roughly ``capacity * mean_hot_df * 4``).
"""

from __future__ import annotations

from collections import OrderedDict

_MISSING = object()


class LRUCache:
    """Plain ordered-dict LRU with hit/miss counters (single-thread:
    one Engine per serving thread, like one cursor per connection)."""

    def __init__(self, capacity: int):
        if capacity < 0:
            raise ValueError(f"cache capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self._data: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key, default=None):
        value = self._data.get(key, _MISSING)
        if value is _MISSING:
            self.misses += 1
            return default
        self._data.move_to_end(key)
        self.hits += 1
        return value

    def put(self, key, value) -> None:
        if self.capacity == 0:
            return
        if key in self._data:
            self._data.move_to_end(key)
        self._data[key] = value
        if len(self._data) > self.capacity:
            self._data.popitem(last=False)
            self.evictions += 1

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:  # no counter side effects
        return key in self._data

    def clear(self) -> None:
        self._data.clear()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "capacity": self.capacity,
            "entries": len(self._data),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
        }
