"""LRU hot-term cache for decoded posting runs.

The artifact stores postings delta-encoded; decoding is one cumsum per
term.  Under a Zipf workload a few hundred hot terms cover most lookups,
so the engine keeps their decoded arrays here — bounded by entry count
(hot terms are the frequent ones, so bounding by count bounds bytes by
roughly ``capacity * mean_hot_df * 4``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..obs import attribution as obs_attrib
from ..obs import metrics as obs_metrics

_MISSING = object()


class LRUCache:
    """Ordered-dict LRU with hit/miss counters.

    Thread-safe: the serve daemon shares one Engine (and therefore one
    cache) across every connection, so ``get``/``put`` race between the
    dispatcher and admin-stat readers.  A plain lock around the tiny
    OrderedDict ops costs ~100ns — noise next to the postings cumsum
    the cache exists to skip.
    """

    def __init__(self, capacity: int, *,
                 registry: obs_metrics.Registry | None = None,
                 prefix: str = "mri_cache", max_bytes: int = 0):
        if capacity < 0:
            raise ValueError(f"cache capacity must be >= 0, got {capacity}")
        if max_bytes < 0:
            raise ValueError(f"cache max_bytes must be >= 0, got {max_bytes}")
        self.capacity = capacity
        self.max_bytes = max_bytes  # 0 = unbounded by bytes
        self._data: OrderedDict = OrderedDict()  # guarded by: self._lock
        self._sizes: dict = {}        # key -> nbytes, guarded by: self._lock
        self._bytes = 0               # sum(self._sizes), guarded by: self._lock
        self._lock = threading.Lock()
        # hit/miss/eviction tallies are obs counters (each with its own
        # lock) so the engine's registry exposes them in the Prometheus
        # text; ``registry=None`` keeps them private to this cache.
        reg = registry if registry is not None else obs_metrics.Registry()
        self._prefix = prefix
        self._hits = reg.counter(f"{prefix}_hits_total")
        self._misses = reg.counter(f"{prefix}_misses_total")
        self._evictions = reg.counter(f"{prefix}_evictions_total")

    @property
    def hits(self) -> int:
        return self._hits.value

    @property
    def misses(self) -> int:
        return self._misses.value

    @property
    def evictions(self) -> int:
        return self._evictions.value

    def get(self, key, default=None):
        # the attribution feed sits beside the counter inc it mirrors:
        # the per-request cache tally can never drift from the registry
        coll = obs_attrib.active()
        with self._lock:
            value = self._data.get(key, _MISSING)
            if value is _MISSING:
                self._misses.inc()
                if coll is not None:
                    coll.cache_event(key, False, self._prefix)
                return default
            self._data.move_to_end(key)
            self._hits.inc()
        if coll is not None:
            coll.cache_event(key, True, self._prefix)
        return value

    def put(self, key, value, *, nbytes: int = 0) -> None:
        """Insert ``key``; ``nbytes`` is the caller-declared payload size
        counted against ``max_bytes`` (0 = entry-count bound only).  An
        entry larger than the whole byte budget is refused outright so
        one oversized payload cannot flush the working set."""
        with self._lock:
            if self.capacity == 0:
                return
            if self.max_bytes and nbytes > self.max_bytes:
                return
            if key in self._data:
                self._bytes -= self._sizes.get(key, 0)
                self._data.move_to_end(key)
            self._data[key] = value
            self._sizes[key] = nbytes
            self._bytes += nbytes
            while (len(self._data) > self.capacity
                   or (self.max_bytes and self._bytes > self.max_bytes)):
                old_key, _old = self._data.popitem(last=False)
                self._bytes -= self._sizes.pop(old_key, 0)
                self._evictions.inc()

    def peek(self, key, default=None):
        """``get`` without recency promotion or hit/miss accounting —
        for callers that only want to know whether paying the decode
        can be avoided (e.g. the v2 skip-AND arm)."""
        with self._lock:
            value = self._data.get(key, _MISSING)
            return default if value is _MISSING else value

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key) -> bool:  # no counter side effects
        with self._lock:
            return key in self._data

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def purge(self) -> int:
        """Drop every entry but keep the cumulative hit/miss/eviction
        tallies — the invalidation path, where history must survive the
        flush.  Returns the number of entries dropped."""
        with self._lock:
            n = len(self._data)
            self._data.clear()
            self._sizes.clear()
            self._bytes = 0
        return n

    def clear(self) -> None:
        self.purge()
        self._hits.reset()
        self._misses.reset()
        self._evictions.reset()

    def stats(self) -> dict:
        hits, misses = self._hits.value, self._misses.value
        total = hits + misses
        with self._lock:
            entries = len(self._data)
            nbytes = self._bytes
        return {
            "capacity": self.capacity,
            "entries": entries,
            "bytes": nbytes,
            "max_bytes": self.max_bytes,
            "hits": hits,
            "misses": misses,
            "evictions": self._evictions.value,
            "hit_rate": round(hits / total, 4) if total else 0.0,
        }
