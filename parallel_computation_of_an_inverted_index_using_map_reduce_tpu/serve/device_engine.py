"""Device-resident batched query engine over ``index.mri``.

The host engine (:mod:`.engine`) answers batches with numpy over mmap
views; this engine uploads the artifact's columns to device memory ONCE
and answers large batches as jitted XLA programs, the batch dimension
sharded across devices with ``shard_map`` through the
``parallel/compat.py`` shim (DrJAX's broadcast/map/reduce shape, arxiv
2403.07128: columns replicated, queries mapped, results concatenated).

Per batch the pipeline is

  1. term resolution — a fixed-step vectorized bisect over the 8-byte
     big-endian term-prefix key column.  jax runs x64-free here, so the
     u64 key is carried as a big-endian ``(hi, lo)`` uint32 pair whose
     pairwise lexicographic order equals the u64 numeric order; the
     bisect is ``ceil(log2 V)`` masked ``jnp.where`` steps (the shape
     ``jnp.searchsorted`` lowers to, spelled out for the pair dtype).
     Shared-prefix collisions resolve in a static ``max_prefix_group``-
     step gather-compare over the full fixed-width term rows, fused
     with the df gather.
  2. postings decode — segment-gather of each hit's delta run into a
     fixed-width tier (powers of 4, statically bucketed so steady-state
     serving never recompiles) and one int32 row-cumsum; invalid lanes
     carry ``_SENTINEL``.
  3. compound ops — AND/OR as sorted-set intersection/union over the
     sentinel-padded posting windows (membership via vectorized
     ``jnp.searchsorted`` probes; union via sort + neighbor-compare
     dedup), and top-k as a ``df_order`` gather.

Every answer is byte-identical to the host engine — the parity suite
(tests/test_serve_device.py) fuzzes both engines against each other at
batches {1, 32, 1024, 8192} under ``JAX_PLATFORMS=cpu``.

Shape discipline: batches pad to power-of-two buckets (multiples of the
shard count), posting tiers are powers of 4, and compound ops pad their
term count to powers of two — so the jit cache stays O(log) in every
dimension and ``compile_stats()`` can assert a zero-recompile steady
state after warmup.
"""

from __future__ import annotations

import os

import numpy as np

from . import artifact as artifact_mod
from . import planner as planner_mod
from .cache import LRUCache
from .engine import BM25_B, BM25_K1, OpTimer, encode_terms, letter_index

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..obs import attribution as obs_attrib
from ..obs import metrics as obs_metrics
from ..parallel.compat import shard_map
from ..parallel.mesh import SHARD_AXIS, make_mesh
from ..utils import envknobs

#: pad value in posting windows: larger than any doc id (guarded at
#: load), so sentinel lanes sort after every real doc.
_SENTINEL = np.int32(2 ** 31 - 1)

SHARDS_ENV = "MRI_SERVE_SHARDS"
#: soft cap on decode-window elements per call (B * W); oversize
#: batches loop in bucket-sized chunks instead of materializing one
#: giant (B, W) window.
DECODE_BUDGET_ENV = "MRI_SERVE_DEVICE_DECODE_BUDGET"  # default: envknobs

#: smallest per-shard batch bucket: tiny batches all share one compile.
_MIN_LANES = 8


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n >= 1 else 1


def _make_lookup(mesh, nsteps: int, group: int):
    """Jitted fused resolve: (idx, found, df) per query lane."""

    def body(key_hi, key_lo, rows, df, q_hi, q_lo, q_rows):
        V = key_hi.shape[0]

        def bisect(right: bool):
            lo = jnp.zeros(q_hi.shape, jnp.int32)
            hi = jnp.full(q_hi.shape, V, jnp.int32)
            for _ in range(nsteps):
                active = lo < hi
                mid = (lo + hi) >> 1
                m = jnp.minimum(mid, V - 1)
                kh, kl = key_hi[m], key_lo[m]
                go = (kh < q_hi) | ((kh == q_hi)
                                    & ((kl <= q_lo) if right
                                       else (kl < q_lo)))
                lo = jnp.where(active & go, mid + 1, lo)
                hi = jnp.where(active & ~go, mid, hi)
            return lo

        lo_i, hi_i = bisect(right=False), bisect(right=True)
        at = jnp.minimum(lo_i, V - 1)
        found = jnp.zeros(q_hi.shape, bool)
        # Shared-prefix fixup: up to `group` vocabulary terms share one
        # 8-byte key; compare full fixed-width rows at each candidate.
        for j in range(group):
            cand = jnp.minimum(lo_i + j, V - 1)
            ok = ((lo_i + j) < hi_i) & jnp.all(
                rows[cand] == q_rows, axis=1)
            at = jnp.where(ok & ~found, cand, at)
            found = found | ok
        found = found & ((q_hi | q_lo) != 0)
        dfv = jnp.where(found, df[at], 0)
        return at.astype(jnp.int32), found, dfv

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), P(),
                  P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False))


def _decode_window(post_offsets, postings, idx, n, *, width: int):
    """(len(idx), width) sentinel-padded absolute doc ids: segment
    gather of the delta runs + one row cumsum."""
    Ptot = postings.shape[0]
    start = post_offsets[idx]
    lane = jnp.arange(width, dtype=jnp.int32)
    pos = start[:, None] + lane[None, :]
    valid = lane[None, :] < n[:, None]
    d = jnp.where(valid, postings[jnp.clip(pos, 0, max(Ptot - 1, 0))], 0)
    docs = jnp.cumsum(d, axis=1, dtype=jnp.int32)
    return jnp.where(valid, docs, _SENTINEL)


def _make_decode(mesh, width: int):
    def body(post_offsets, postings, idx, n):
        return _decode_window(post_offsets, postings, idx, n, width=width)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(SHARD_AXIS), check_vma=False))


def _bit_window(words, word_ix, off, nbits):
    """Per-lane unaligned read of a ``nbits``-bit little-endian value
    starting ``off`` bits into word ``word_ix``: two word gathers + a
    fixed shift-or (``words`` carries one zero pad word so ``+ 1`` never
    reads past the stream)."""
    r = (off & 31).astype(jnp.uint32)
    w0 = words[word_ix]
    w1 = words[word_ix + 1]
    val = (w0 >> r) | jnp.where(
        r == 0, jnp.uint32(0), w1 << ((jnp.uint32(32) - r) & 31))
    nb = nbits.astype(jnp.uint32)
    mask = jnp.where(nb == 0, jnp.uint32(0), (jnp.uint32(1) << nb)
                     - jnp.uint32(1))
    return (val & mask).astype(jnp.int32)


def _decode_window_v2(term_block_off, blk_first, blk_width, blk_woff,
                      post_words, idx, n, *, width: int,
                      block_size: int):
    """v2 mirror of :func:`_decode_window`: (len(idx), width) sentinel-
    padded absolute doc ids straight from the blocked bitpacked layout.

    Lane j of a term maps statically to block ``j // block_size`` slot
    ``j % block_size``; slot 0 reads the skip table's absolute
    ``blk_first``, every other slot bit-extracts its (delta - 1).  The
    cumsum then runs PER BLOCK (blocks re-anchor absolutely), so a
    partially-filled block's trailing garbage never contaminates the
    next block — and invalid lanes are sentinel-masked exactly as v1.
    """
    lane = jnp.arange(width, dtype=jnp.int32)
    s = lane & (block_size - 1)
    qb = lane >> (block_size.bit_length() - 1)
    bl = term_block_off[idx][:, None] + qb[None, :]
    w = blk_width[bl]
    off = jnp.maximum(s - 1, 0)[None, :] * w
    delta = _bit_window(post_words, blk_woff[bl] + (off >> 5),
                        off, w) + 1
    vals = jnp.where(s[None, :] == 0, blk_first[bl], delta)
    if width <= block_size:
        docs = jnp.cumsum(vals, axis=1, dtype=jnp.int32)
    else:
        T = vals.shape[0]
        docs = jnp.cumsum(
            vals.reshape(T, width // block_size, block_size),
            axis=2, dtype=jnp.int32).reshape(T, width)
    valid = lane[None, :] < n[:, None]
    return jnp.where(valid, docs, _SENTINEL)


def _tf_window_v2(term_block_off, blk_tf_width, blk_tf_woff, tf_words,
                  idx, n, *, width: int, block_size: int):
    """(len(idx), width) term frequencies aligned with
    :func:`_decode_window_v2` (slot s reads packed value s; no cumsum —
    tf entries are independent).  Invalid lanes carry 0."""
    lane = jnp.arange(width, dtype=jnp.int32)
    s = lane & (block_size - 1)
    qb = lane >> (block_size.bit_length() - 1)
    bl = term_block_off[idx][:, None] + qb[None, :]
    tw = blk_tf_width[bl]
    off = s[None, :] * tw
    tf = _bit_window(tf_words, blk_tf_woff[bl] + (off >> 5),
                     off, tw) + 1
    valid = lane[None, :] < n[:, None]
    return jnp.where(valid, tf, 0)


def _make_decode_v2(mesh, width: int, block_size: int):
    def body(term_block_off, blk_first, blk_width, blk_woff, post_words,
             idx, n):
        return _decode_window_v2(
            term_block_off, blk_first, blk_width, blk_woff, post_words,
            idx, n, width=width, block_size=block_size)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(),
                  P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(SHARD_AXIS), check_vma=False))


def _make_bool(op: str, width: int):
    """Jitted T-term AND/OR over sentinel-padded posting windows.

    One query, T terms (T static, padded to a power of two): decode all
    runs to (T, width), then intersect (membership probes via
    ``jnp.searchsorted`` on each other run) or union (flat sort +
    neighbor-compare dedup).  Returns the sorted result pushed to the
    front plus its count — the host slices."""

    def body(post_offsets, postings, idx, n):
        docs = _decode_window(post_offsets, postings, idx, n, width=width)
        return _bool_tail(op, docs, n, width)

    return jax.jit(body)


def _bool_tail(op: str, docs, n, width: int):
    """Shared AND/OR combine over a (T, width) sentinel-padded window."""
    T = docs.shape[0]
    if op == "and":
        vals = docs[0]
        alive = jnp.arange(width) < n[0]
        for t in range(1, T):
            j = jnp.searchsorted(docs[t], vals)
            alive = alive & (j < width) & (
                docs[t][jnp.minimum(j, width - 1)] == vals)
        out = jnp.sort(jnp.where(alive, vals, _SENTINEL))
        return out, alive.sum()
    flat = jnp.sort(docs.ravel())
    first = jnp.concatenate(
        [jnp.ones((1,), bool), flat[1:] != flat[:-1]])
    keep = first & (flat != _SENTINEL)
    out = jnp.sort(jnp.where(keep, flat, _SENTINEL))
    return out, keep.sum()


def _make_bool_v2(op: str, width: int, block_size: int):
    def body(term_block_off, blk_first, blk_width, blk_woff, post_words,
             idx, n):
        docs = _decode_window_v2(
            term_block_off, blk_first, blk_width, blk_woff, post_words,
            idx, n, width=width, block_size=block_size)
        return _bool_tail(op, docs, n, width)

    return jax.jit(body)


def _bm25_tail(docs, tfs, n, found, doc_lens, ndocs, avgdl, width: int,
               k: int):
    """Scatter-add BM25 contributions into a dense doc-score column and
    ``lax.top_k`` it: ties prefer the lower doc id (top_k is stable)."""
    lane_ok = (jnp.arange(width)[None, :] < n[:, None]) \
        & found[:, None] & (docs != _SENTINEL)
    dfv = jnp.where(found, n, 0).astype(jnp.float32)
    idf = jnp.log(1.0 + (ndocs - dfv + 0.5) / (dfv + 0.5))
    tff = tfs.astype(jnp.float32)
    dl = doc_lens[jnp.where(lane_ok, docs, 0)]
    denom = tff + BM25_K1 * (1.0 - BM25_B + BM25_B * dl / avgdl)
    contrib = jnp.where(
        lane_ok, idf[:, None] * tff * (BM25_K1 + 1.0) / denom, 0.0)
    scores = jnp.zeros(doc_lens.shape[0], jnp.float32).at[
        jnp.where(lane_ok, docs, 0).ravel()].add(contrib.ravel())
    vals, ids = jax.lax.top_k(scores, k)
    return ids, vals


def _make_bm25(width: int, k: int):
    def body(post_offsets, postings, idx, n, found, doc_lens, ndocs,
             avgdl):
        docs = _decode_window(post_offsets, postings, idx, n, width=width)
        tfs = jnp.ones(docs.shape, jnp.int32)  # v1: no tf column
        return _bm25_tail(docs, tfs, n, found, doc_lens, ndocs, avgdl,
                          width, k)

    return jax.jit(body)


def _make_bm25_v2(width: int, k: int, block_size: int):
    def body(term_block_off, blk_first, blk_width, blk_woff, post_words,
             blk_tf_width, blk_tf_woff, tf_words, idx, n, found,
             doc_lens, ndocs, avgdl):
        docs = _decode_window_v2(
            term_block_off, blk_first, blk_width, blk_woff, post_words,
            idx, n, width=width, block_size=block_size)
        tfs = _tf_window_v2(
            term_block_off, blk_tf_width, blk_tf_woff, tf_words,
            idx, n, width=width, block_size=block_size)
        return _bm25_tail(docs, tfs, n, found, doc_lens, ndocs, avgdl,
                          width, k)

    return jax.jit(body)


def _make_bm25_blocks(k: int, block_size: int):
    """Jitted BM25 scatter-add over an (S, block_size) SURVIVOR-BLOCK
    window instead of whole (T, width) term windows — the device form
    of Block-Max pruning.  The host picks the surviving global block
    ids (``bl``) from the v2.1 bound columns and pre-folds each block's
    ``weight * idf`` into ``widf``; the kernel decodes exactly those
    blocks (lane 0 reads the skip table's absolute ``blk_first``, other
    lanes bit-extract deltas, one per-row cumsum), scores them, and
    ``lax.top_k``s the dense column.  Padded rows carry ``cnt == 0``
    and contribute nothing."""

    def body(blk_first, blk_width, blk_woff, post_words,
             blk_tf_width, blk_tf_woff, tf_words,
             bl, cnt, widf, doc_lens, avgdl):
        lane = jnp.arange(block_size, dtype=jnp.int32)
        w = blk_width[bl][:, None]
        off = jnp.maximum(lane - 1, 0)[None, :] * w
        delta = _bit_window(post_words, blk_woff[bl][:, None]
                            + (off >> 5), off, w) + 1
        vals = jnp.where(lane[None, :] == 0,
                         blk_first[bl][:, None], delta)
        docs = jnp.cumsum(vals, axis=1, dtype=jnp.int32)
        tw = blk_tf_width[bl][:, None]
        toff = lane[None, :] * tw
        tf = _bit_window(tf_words, blk_tf_woff[bl][:, None]
                         + (toff >> 5), toff, tw) + 1
        lane_ok = lane[None, :] < cnt[:, None]
        tff = tf.astype(jnp.float32)
        dl = doc_lens[jnp.where(lane_ok, docs, 0)]
        denom = tff + BM25_K1 * (1.0 - BM25_B + BM25_B * dl / avgdl)
        contrib = jnp.where(
            lane_ok, widf[:, None] * tff * (BM25_K1 + 1.0) / denom, 0.0)
        scores = jnp.zeros(doc_lens.shape[0], jnp.float32).at[
            jnp.where(lane_ok, docs, 0).ravel()].add(contrib.ravel())
        svals, ids = jax.lax.top_k(scores, k)
        return ids, svals

    return jax.jit(body)


def _make_topk(k: int):
    def body(df_order, df, lo):
        pick = jax.lax.dynamic_slice(df_order, (lo,), (k,))
        return pick, df[pick]

    return jax.jit(body)


class DeviceEngine:
    """Batched query API over one artifact resident in device memory.

    Mirrors :class:`.engine.Engine`'s surface exactly (same inputs,
    same outputs, byte-identical answers); ``shards`` sizes the 1-D
    batch mesh (default: ``$MRI_SERVE_SHARDS`` or every local device).
    The host LRU posting cache does not apply here — decodes are
    vectorized device work, so the cache is present but idle (capacity
    kept for stats-surface parity).
    """

    engine_name = "device"

    def __init__(self, path, cache_terms: int = 4096,
                 shards: int | None = None,
                 decode_budget: int | None = None):
        if artifact_mod.is_segment_managed(path):
            raise artifact_mod.ArtifactError(
                f"{path} is segment-managed (segments.manifest.json "
                "present): the device engine serves single artifacts "
                "only — use create_engine with host/auto")
        self.artifact = artifact_mod.load_artifact(path)
        art = self.artifact
        if art.max_doc_id >= int(_SENTINEL):
            raise artifact_mod.ArtifactError(
                f"{art.path}: max_doc_id {art.max_doc_id} collides with "
                f"the device engine's padding sentinel")
        cols = artifact_mod.device_columns(art)
        self.vocab_size = cols["vocab"]
        self._width = cols["width"]
        self._sdtype = f"S{self._width}"
        self._group = cols["max_prefix_group"]
        self._h_df = cols["df"]
        self._h_letter_dir = cols["letter_dir"]

        if shards is None:
            shards = envknobs.get(SHARDS_ENV)
        self._mesh = make_mesh(shards)
        self._num_shards = self._mesh.devices.size
        self._decode_budget = int(
            decode_budget if decode_budget is not None
            else envknobs.get(DECODE_BUDGET_ENV))

        rep = NamedSharding(self._mesh, P())
        put = lambda a: jax.device_put(a, rep)  # noqa: E731
        self._d_key_hi = put(cols["key_hi"])
        self._d_key_lo = put(cols["key_lo"])
        self._d_rows = put(cols["rows"])
        self._d_df = put(cols["df"])
        self._d_df_order = put(cols["df_order"])
        self._fmt = cols["format"]
        if self._fmt >= artifact_mod.VERSION_V2:
            self._block_size = cols["block_size"]
            self._d_term_block_off = put(cols["term_block_off"])
            self._d_blk_first = put(cols["blk_first"])
            self._d_blk_width = put(cols["blk_width"])
            self._d_blk_woff = put(cols["blk_woff"])
            self._d_post_words = put(cols["post_words"])
            self._d_blk_tf_width = put(cols["blk_tf_width"])
            self._d_blk_tf_woff = put(cols["blk_tf_woff"])
            self._d_tf_words = put(cols["tf_words"])
            self._decode_cols = (
                self._d_term_block_off, self._d_blk_first,
                self._d_blk_width, self._d_blk_woff, self._d_post_words)
            self._d_post_offsets = self._d_postings = None
        else:
            self._block_size = 0
            self._d_post_offsets = put(cols["post_offsets"])
            self._d_postings = put(cols["postings"])
            self._decode_cols = (self._d_post_offsets, self._d_postings)
        self._d_doc_lens = None  # lazy: uploaded at first top_k_scored
        self._bm25_scalars = None

        # posting tiers: powers of 4 from 8 up to the global max df, so
        # every batch decodes at the smallest static width covering it
        max_df = int(self._h_df.max()) if self.vocab_size else 1
        tiers, t = [], _MIN_LANES
        while True:
            tiers.append(t)
            if t >= max_df:
                break
            t *= 4
        self._tiers = tiers

        nsteps = max(self.vocab_size, 1).bit_length() + 1
        self._lookup_fn = _make_lookup(self._mesh, nsteps, self._group)
        self._decode_fns: dict[int, object] = {}
        self._bool_fns: dict[tuple, object] = {}
        self._topk_fns: dict[int, object] = {}
        self._bm25_fns: dict[tuple, object] = {}
        self._blocks_fns: dict[tuple, object] = {}

        # per-engine obs registry: describe() stays a view over it and
        # the daemon folds it into the Prometheus exposition
        self.metrics = obs_metrics.Registry()
        self.metrics.gauge("mri_engine_vocab_terms").set(self.vocab_size)
        self.metrics.gauge("mri_engine_artifact_bytes").set(art.nbytes)
        self._cache = LRUCache(cache_terms, registry=self.metrics,
                               prefix="mri_serve_cache")  # idle on the device path
        self._ops = OpTimer(registry=self.metrics)
        # decode-plane counters, host-engine names: the device decodes
        # inside jitted kernels, so the tallies are computed host-side
        # from the artifact's block/offset columns per resolved term
        self._c_blocks_decoded = \
            self.metrics.counter("mri_engine_blocks_decoded_total")
        self._c_blocks_skipped = \
            self.metrics.counter("mri_engine_blocks_skipped_total")
        self._c_bytes_decoded = \
            self.metrics.counter("mri_engine_bytes_decoded_total")
        self.planner = planner_mod.Planner(self.metrics)
        # host-side BM25 memos feeding the pruning plan: per-term f64
        # contributions (theta bootstrap) and per-block upper bounds
        self._bm25_host = None  # (doc_lens f64, ndocs, avgdl)
        self._score_memo: dict[int, np.ndarray] = {}
        self._bound_memo: dict[int, tuple] = {}
        self._memo_cap = max(int(cache_terms), 1)

    # -- shape bucketing ------------------------------------------------

    def _bucket(self, n: int) -> int:
        """Padded batch size: power-of-two lanes per shard, min 8."""
        D = self._num_shards
        return D * max(_MIN_LANES, _next_pow2(-(-n // D)))

    def _tier(self, max_len: int) -> int:
        for t in self._tiers:
            if t >= max_len:
                return t
        return self._tiers[-1]

    def _decode_fn(self, width: int):
        fn = self._decode_fns.get(width)
        if fn is None:
            if self._fmt >= artifact_mod.VERSION_V2:
                fn = _make_decode_v2(self._mesh, width, self._block_size)
            else:
                fn = _make_decode(self._mesh, width)
            self._decode_fns[width] = fn
        return fn

    # -- term resolution ------------------------------------------------

    def encode_batch(self, terms) -> np.ndarray:
        return encode_terms(terms, self._width)

    def _split_keys(self, q: np.ndarray):
        """S-dtype batch -> (rows u8, key_hi u32, key_lo u32), the
        device mirror of the artifact's key columns."""
        B, w = len(q), self._width
        rows = np.ascontiguousarray(q).view(np.uint8).reshape(B, w)
        k8 = rows if w >= 8 else np.pad(rows, ((0, 0), (0, 8 - w)))
        k8 = np.ascontiguousarray(k8[:, :8])
        q_hi = np.ascontiguousarray(k8[:, :4]).view(">u4").ravel()
        q_lo = np.ascontiguousarray(k8[:, 4:]).view(">u4").ravel()
        return rows, q_hi.astype(np.uint32), q_lo.astype(np.uint32)

    def _resolve(self, batch):
        """(idx i32, found bool, df i32) per query, host numpy."""
        q = np.asarray(batch, dtype=self._sdtype)
        B = len(q)
        if B == 0 or self.vocab_size == 0:
            return (np.zeros(B, dtype=np.int32),
                    np.zeros(B, dtype=bool),
                    np.zeros(B, dtype=np.int32))
        rows, q_hi, q_lo = self._split_keys(q)
        Bp = self._bucket(B)
        if Bp != B:
            rows = np.vstack(
                [rows, np.zeros((Bp - B, self._width), np.uint8)])
            q_hi = np.concatenate([q_hi, np.zeros(Bp - B, np.uint32)])
            q_lo = np.concatenate([q_lo, np.zeros(Bp - B, np.uint32)])
        idx, found, dfv = self._lookup_fn(
            self._d_key_hi, self._d_key_lo, self._d_rows, self._d_df,
            q_hi, q_lo, rows)
        idx = np.asarray(idx)[:B]
        found = np.asarray(found)[:B]
        dfv = np.asarray(dfv)[:B]
        coll = obs_attrib.active()
        if coll is not None:
            for t, i, ok, d in zip(q.tolist(), idx.tolist(),
                                   found.tolist(), dfv.tolist()):
                coll.term(t, int(i), bool(ok), int(d), "device")
        return idx, found, dfv

    def lookup(self, batch):
        """Host-API parity: (lex idx, found) per query."""
        # mrilint: allow(trace) resolution is attributed in _resolve
        idx, found, _ = self._resolve(batch)
        return idx.astype(np.int64), found

    # -- single-term answers --------------------------------------------

    def df(self, batch) -> np.ndarray:
        with self._ops.time("df"):
            _, _, dfv = self._resolve(batch)
            return dfv.astype(np.int64)

    def _note_decode(self, uidx) -> None:
        """Count one decode pass over terms ``uidx`` (host-side mirror
        of the kernels' work: block/byte spans from the artifact's
        offset columns) on the registry and the attribution collector.
        The feed sits beside the counter incs, so per-request reports
        can never drift from the registry (the parity gate)."""
        uidx = np.asarray(uidx, dtype=np.int64)
        if not len(uidx):
            return
        art = self.artifact
        if self._fmt >= artifact_mod.VERSION_V2:
            b0 = art.term_block_off[uidx]
            b1 = art.term_block_off[uidx + 1]
            blocks = int((b1 - b0).sum())
            nbytes = int((art.blk_woff[b1]
                          - art.blk_woff[b0]).sum()) * 4
        else:
            blocks = len(uidx)
            nbytes = int(self._h_df[uidx].sum()) * 4
        self._c_blocks_decoded.inc(blocks)
        self._c_bytes_decoded.inc(nbytes)
        coll = obs_attrib.active()
        if coll is not None:
            coll.decoded(blocks, nbytes)

    def _decode_batch(self, idx, n, width):
        """Chunked (len(idx), width) sentinel-padded decode, bucketed so
        B * width stays under the decode budget per device call."""
        B = len(idx)
        D = self._num_shards
        per = max(1, self._decode_budget // max(width, 1) // D)
        cap = D * max(_MIN_LANES, _pow2_floor(per))
        out = np.empty((B, width), dtype=np.int32)
        fn = self._decode_fn(width)
        step = min(self._bucket(B), cap)
        for at in range(0, B, step):
            part_idx = idx[at:at + step]
            part_n = n[at:at + step]
            L = len(part_idx)
            Bp = min(self._bucket(L), step)
            if Bp != L:
                part_idx = np.concatenate(
                    [part_idx, np.zeros(Bp - L, np.int32)])
                part_n = np.concatenate(
                    [part_n, np.zeros(Bp - L, np.int32)])
            win = fn(*self._decode_cols,
                     part_idx.astype(np.int32), part_n.astype(np.int32))
            out[at:at + L] = np.asarray(win)[:L]
        return out

    def postings(self, batch) -> list[np.ndarray | None]:
        with self._ops.time("postings"):
            idx, found, dfv = self._resolve(batch)
            B = len(found)
            if B == 0:
                return []
            if not found.any():
                return [None] * B
            self._note_decode(idx[found])
            width = self._tier(int(dfv.max()))
            win = self._decode_batch(idx, np.where(found, dfv, 0), width)
            return [win[i, :dfv[i]] if found[i] else None
                    for i in range(B)]

    # -- compound queries -----------------------------------------------

    def top_k(self, letter, k: int) -> list[tuple[bytes, int]]:
        letter = letter_index(letter)
        with self._ops.time("top_k"):
            lo = int(self._h_letter_dir[letter])
            hi = int(self._h_letter_dir[letter + 1])
            k_eff = min(max(k, 0), hi - lo)
            if k_eff == 0:
                return []
            fn = self._topk_fns.get(k_eff)
            if fn is None:
                fn = self._topk_fns[k_eff] = _make_topk(k_eff)
            pick, dfs = fn(self._d_df_order, self._d_df, np.int32(lo))
            art = self.artifact
            return [(art.term(int(i)), int(d))
                    for i, d in zip(np.asarray(pick), np.asarray(dfs))]

    def _bool_fn(self, op: str, T: int, width: int):
        fn = self._bool_fns.get((op, T, width))
        if fn is None:
            if self._fmt >= artifact_mod.VERSION_V2:
                fn = _make_bool_v2(op, width, self._block_size)
            else:
                fn = _make_bool(op, width)
            self._bool_fns[(op, T, width)] = fn
        return fn

    def _run_bool(self, op: str, uidx: np.ndarray) -> np.ndarray:
        """Shared AND/OR tail: pad the unique term set to a power of
        two (AND repeats the first run — intersection-neutral; OR pads
        empty runs — union-neutral), call the (op, T, W) kernel, slice
        the count."""
        self._note_decode(uidx)
        n = self._h_df[uidx].astype(np.int32)
        T = _next_pow2(len(uidx))
        if T != len(uidx):
            pad = T - len(uidx)
            if op == "and":
                uidx = np.concatenate([uidx, np.repeat(uidx[:1], pad)])
                n = np.concatenate([n, np.repeat(n[:1], pad)])
            else:
                uidx = np.concatenate([uidx, np.zeros(pad, np.int32)])
                n = np.concatenate([n, np.zeros(pad, np.int32)])
        width = self._tier(int(n.max()) if len(n) else 1)
        out, cnt = self._bool_fn(op, T, width)(
            *self._decode_cols, uidx.astype(np.int32), n)
        return np.asarray(out)[:int(cnt)].astype(np.int32)

    def query_and(self, batch) -> np.ndarray:
        with self._ops.time("and"):
            idx, found, _ = self._resolve(batch)
            if len(found) == 0 or not found.all():
                return np.zeros(0, dtype=np.int32)
            return self._run_bool("and", np.unique(idx))

    def query_or(self, batch) -> np.ndarray:
        with self._ops.time("or"):
            idx, found, _ = self._resolve(batch)
            uidx = np.unique(idx[found])
            if len(uidx) == 0:
                return np.zeros(0, dtype=np.int32)
            return self._run_bool("or", uidx)

    # -- ranked retrieval -----------------------------------------------

    def _bm25_device(self):
        """Upload the doc-length column + corpus scalars once."""
        if self._d_doc_lens is None:
            doc_lens, ndocs, avgdl = artifact_mod.bm25_corpus(
                self.artifact)
            rep = NamedSharding(self._mesh, P())
            self._d_doc_lens = jax.device_put(
                doc_lens.astype(np.float32), rep)
            self._bm25_scalars = (np.float32(ndocs), np.float32(avgdl))
        return self._d_doc_lens, self._bm25_scalars

    def _bm25_fn(self, T: int, width: int, k: int):
        fn = self._bm25_fns.get((T, width, k))
        if fn is None:
            if self._fmt >= artifact_mod.VERSION_V2:
                fn = _make_bm25_v2(width, k, self._block_size)
            else:
                fn = _make_bm25(width, k)
            self._bm25_fns[(T, width, k)] = fn
        return fn

    def _bm25_host_cols(self):
        """Float64 host mirror of the corpus stats (theta bootstrap)."""
        if self._bm25_host is None:
            self._bm25_host = artifact_mod.bm25_corpus(self.artifact)
        return self._bm25_host

    def _term_contribs(self, i: int) -> np.ndarray:
        """Term ``i``'s BM25 contributions, descending (f64, host)."""
        hit = self._score_memo.get(i)
        if hit is not None:
            return hit
        doc_lens, ndocs, avgdl = self._bm25_host_cols()
        art = self.artifact
        docs = art.decode_postings(i)
        tf = art.decode_tf(i).astype(np.float64)
        dfi = len(docs)
        idf = np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5))
        denom = tf + BM25_K1 * (1.0 - BM25_B
                                + BM25_B * doc_lens[docs] / avgdl)
        srt = np.sort(idf * tf * (BM25_K1 + 1.0) / denom)[::-1]
        if len(self._score_memo) >= self._memo_cap:
            self._score_memo.clear()
        self._score_memo[i] = srt
        return srt

    def _term_bounds(self, i: int) -> tuple:
        """(per-block f64 upper bounds, their max, idf) for term i."""
        hit = self._bound_memo.get(i)
        if hit is not None:
            return hit
        doc_lens, ndocs, avgdl = self._bm25_host_cols()
        dfi = int(self._h_df[i])
        idf = np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5))
        ubs = planner_mod.block_upper_bounds(
            self.artifact, i, idf, avgdl, BM25_K1, BM25_B)
        if len(self._bound_memo) >= self._memo_cap:
            self._bound_memo.clear()
        self._bound_memo[i] = (
            ubs, float(ubs.max()) if len(ubs) else 0.0, idf)
        return self._bound_memo[i]

    def _top_k_scored_pruned(self, occ: list[int], k: int, mode: str
                             ) -> list[tuple[int, float]]:
        """Block-survivor form of pruned ranked retrieval: the host
        derives theta (the k-th best contribution of the strongest
        term) and keeps only blocks whose bound plus every other term's
        summed bounds clears it; the kernel decodes and scatter-adds
        exactly those blocks.  Every true top-k doc's blocks all
        survive (its total is a lower bound on every such test), so the
        returned doc set matches exhaustive scoring; partially-covered
        losers score strictly below the k-th best and cannot displace.
        ``maxscore`` masks whole terms, ``bmw`` masks per block."""
        art = self.artifact
        doc_lens_d, (_ndocs32, avgdl32) = self._bm25_device()
        D = int(doc_lens_d.shape[0])
        weight: dict[int, int] = {}
        for i in occ:
            weight[i] = weight.get(i, 0) + 1
        terms = [(i, w) + self._term_bounds(i)
                 for i, w in weight.items()]
        total = sum(w * umax for _i, w, _ubs, umax, _idf in terms)
        theta = 0.0
        for i, w, _ubs, _umax, _idf in terms:
            srt = self._term_contribs(i)
            if len(srt) >= k:
                theta = max(theta, w * float(srt[k - 1]))
        coll = obs_attrib.active()
        if coll is not None:
            coll.theta(theta)
        margin = planner_mod.DEVICE_MARGIN
        bl_parts, widf_parts = [], []
        nb_total = 0
        for i, w, ubs, umax, idf in terms:
            b0 = int(art.term_block_off[i])
            nb = len(ubs)
            nb_total += nb * w
            rest = total - w * umax
            if mode == "maxscore":
                sel = np.arange(nb, dtype=np.int64) \
                    if w * umax + rest >= theta * margin \
                    else np.zeros(0, dtype=np.int64)
            else:
                sel = np.nonzero(w * ubs + rest >= theta * margin)[0]
            if not len(sel):
                continue
            # one survivor row per query occurrence: the scatter-add
            # then accumulates duplicates exactly like the exhaustive
            # kernel's duplicated term rows
            for _ in range(int(w)):
                bl_parts.append(sel + b0)
                widf_parts.append(
                    np.full(len(sel), np.float32(idf), np.float32))
        if not bl_parts:
            self._c_blocks_skipped.inc(nb_total)
            if coll is not None:
                coll.skipped(nb_total)
            self.planner.note_ranked(mode, 0, nb_total, 0)
            return []
        bl = np.concatenate(bl_parts).astype(np.int32)
        widf = np.concatenate(widf_parts)
        cnt = self.artifact.blk_cnt[bl].astype(np.int32)
        S = len(bl)
        nbytes = int((art.blk_woff[bl.astype(np.int64) + 1]
                      - art.blk_woff[bl]).sum()) * 4
        self._c_blocks_decoded.inc(S)
        self._c_blocks_skipped.inc(nb_total - S)
        self._c_bytes_decoded.inc(nbytes)
        if coll is not None:
            coll.decoded(S, nbytes)
            coll.skipped(nb_total - S)
        Sp = max(_MIN_LANES, _next_pow2(S))
        if Sp != S:
            bl = np.concatenate([bl, np.zeros(Sp - S, np.int32)])
            cnt = np.concatenate([cnt, np.zeros(Sp - S, np.int32)])
            widf = np.concatenate([widf, np.zeros(Sp - S, np.float32)])
        k_eff = min(max(k, 0), D)
        fn = self._blocks_fns.get((Sp, k_eff))
        if fn is None:
            fn = self._blocks_fns[(Sp, k_eff)] = _make_bm25_blocks(
                k_eff, self._block_size)
        ids, vals = fn(self._d_blk_first, self._d_blk_width,
                       self._d_blk_woff, self._d_post_words,
                       self._d_blk_tf_width, self._d_blk_tf_woff,
                       self._d_tf_words, bl, cnt, widf,
                       doc_lens_d, avgdl32)
        self.planner.note_ranked(mode, S, nb_total - S, 0)
        ids, vals = np.asarray(ids), np.asarray(vals)
        return [(int(d), float(s)) for d, s in zip(ids, vals)
                if s > 0.0]

    def top_k_scored(self, batch, k: int) -> list[tuple[int, float]]:
        """BM25-ranked ``(doc_id, score)``, best first, ties by doc id —
        the device mirror of ``Engine.top_k_scored`` (float32 on
        device, so scores agree with the host to ~1e-6 relative).  On a
        v2.1 artifact the planner can swap the whole-term windows for a
        survivor-block window (:meth:`_top_k_scored_pruned`)."""
        with self._ops.time("top_k_scored"):
            idx, found, dfv = self._resolve(batch)
            doc_lens, (ndocs, avgdl) = self._bm25_device()
            D = int(doc_lens.shape[0])
            if k <= 0 or D == 0 or not found.any():
                if k > 0:
                    self.planner.note_ranked("exhaustive", 0, 0, 0)
                return []
            occ = [int(i) for i, ok in zip(idx, found) if ok]
            mode = self.planner.plan_ranked(
                self.artifact, [int(d) for d, ok in zip(dfv, found)
                                if ok], k)
            if mode != "exhaustive":
                return self._top_k_scored_pruned(occ, k, mode)
            self.planner.note_ranked("exhaustive", 0, 0, 0)
            self._note_decode(np.asarray(occ))
            # duplicates accumulate (host parity): keep the full batch,
            # padded to a power of two with never-found zero lanes
            T = _next_pow2(len(idx))
            if T != len(idx):
                pad = T - len(idx)
                idx = np.concatenate([idx, np.zeros(pad, np.int32)])
                found = np.concatenate([found, np.zeros(pad, bool)])
                dfv = np.concatenate([dfv, np.zeros(pad, np.int32)])
            n = np.where(found, dfv, 0).astype(np.int32)
            width = self._tier(int(n.max()) if len(n) else 1)
            k_eff = min(max(k, 0), D)
            if self._fmt >= artifact_mod.VERSION_V2:
                cols = self._decode_cols + (
                    self._d_blk_tf_width, self._d_blk_tf_woff,
                    self._d_tf_words)
            else:
                cols = self._decode_cols
            ids, vals = self._bm25_fn(T, width, k_eff)(
                *cols, idx.astype(np.int32), n, found, doc_lens,
                ndocs, avgdl)
            ids, vals = np.asarray(ids), np.asarray(vals)
            return [(int(d), float(s))
                    for d, s in zip(ids, vals) if s > 0.0]

    # -- bookkeeping ----------------------------------------------------

    @property
    def cache(self) -> LRUCache:
        return self._cache

    def cache_stats(self) -> dict:
        return self._cache.stats()

    def op_stats(self) -> dict:
        return self._ops.stats()

    def compile_stats(self) -> dict:
        """Jit-cache census: the bench's zero-recompile assertion
        compares this before/after the steady-state run."""
        fns = ([self._lookup_fn] + list(self._decode_fns.values())
               + list(self._bool_fns.values())
               + list(self._topk_fns.values())
               + list(self._bm25_fns.values())
               + list(self._blocks_fns.values()))
        return {
            "jit_functions": len(fns),
            "jit_cache_entries": sum(f._cache_size() for f in fns),
        }

    def describe(self) -> dict:
        return {
            "engine": self.engine_name,
            "format": self._fmt,
            "vocab": self.vocab_size,
            "artifact_bytes": self.artifact.nbytes,
            "cache": self.cache_stats(),
            "ops": self.op_stats(),
            "planner": self.planner.describe(),
            "device": {
                "platform": jax.default_backend(),
                "shards": self._num_shards,
                "devices": [str(d) for d in self._mesh.devices.ravel()],
                "tiers": self._tiers,
                "max_prefix_group": self._group,
                **self.compile_stats(),
            },
        }

    def close(self) -> None:
        self._cache.clear()
        self._d_key_hi = self._d_key_lo = self._d_rows = None
        self._d_df = self._d_post_offsets = self._d_postings = None
        self._d_df_order = self._d_doc_lens = None
        self._decode_cols = ()
        if self._fmt >= artifact_mod.VERSION_V2:
            self._d_term_block_off = self._d_blk_first = None
            self._d_blk_width = self._d_blk_woff = None
            self._d_post_words = self._d_blk_tf_width = None
            self._d_blk_tf_woff = self._d_tf_words = None
        self._decode_fns.clear()
        self._bool_fns.clear()
        self._topk_fns.clear()
        self._bm25_fns.clear()
        self._blocks_fns.clear()
        self._bm25_host = None
        self._score_memo.clear()
        self._bound_memo.clear()
        self.artifact.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
