"""Vectorized query engine over a mmapped ``index.mri``.

Batched lookups are the unit of work (DrJAX's batched-array formulation,
arxiv 2403.07128, applied to serving): a batch of query terms becomes
one ``S``-dtype numpy array, term resolution is ONE ``np.searchsorted``
over big-endian u64 prefix keys (lexicographic order of NUL-padded
bytes == numeric order of the keys) plus a vectorized exact-match
gather — no per-query Python in the hot path.  Postings decode through
an LRU hot-term cache; multi-term AND intersects sorted runs smallest-
first with a galloping ``searchsorted`` probe; top-k-by-df per letter
is an O(k) slice of the artifact's ``df_order`` permutation.
"""

from __future__ import annotations

import numpy as np

from . import artifact as artifact_mod
from .cache import LRUCache
from ..obs import metrics as obs_metrics
# OpTimer's historical home is this module; the implementation moved to
# obs.timing (unified with PhaseTimer over the obs histogram) and is
# re-exported here so ``from .engine import OpTimer`` keeps working.
from ..obs.timing import OpTimer  # noqa: F401
from ..utils import envknobs


def _normalize(term) -> bytes:
    """Query-side mirror of the tokenizer's cleaning: lowercase, alpha
    only.  A term that cleans to something else can't be in the index."""
    if isinstance(term, bytes):
        term = term.decode("latin-1")
    term = term.lower()
    return term.encode("ascii") if term.isascii() and term.isalpha() \
        else b""


def encode_terms(terms, width: int) -> np.ndarray:
    """Normalize str/bytes queries into the engines' S-dtype batch
    array.  Terms that normalize away or exceed the vocabulary width
    become b'' (never found).  Shared by both engine backends so the
    interchange format is identical."""
    cleaned = [_normalize(t) for t in terms]
    return np.array(
        [t if len(t) <= width else b"" for t in cleaned],
        dtype=f"S{width}")


def letter_index(letter) -> int:
    """'a'..'z' (str/bytes) or 0..25 -> letter_dir slot, or ValueError."""
    if isinstance(letter, (str, bytes)):
        letter = (letter.encode() if isinstance(letter, str) else letter)
        letter = letter[0] - ord("a")
    if not 0 <= letter < 26:
        raise ValueError(f"letter index out of range: {letter}")
    return letter


class Engine:
    """Batched query API over one loaded artifact.

    ``path`` is an output directory (its ``index.mri``) or the artifact
    file itself.  All answers are exact — the parity suite holds every
    one byte-equal to a naive scan of the emitted letter files.
    """

    engine_name = "host"

    def __init__(self, path, cache_terms: int = 4096):
        self.artifact = artifact_mod.load_artifact(path)
        art = self.artifact
        V, width = art.vocab, max(art.width, 1)
        self.vocab_size = V
        # Materialized fixed-width term table (artifact.term_table):
        # NUL-padded rows viewed as one S-dtype column for exact-match
        # gathers, plus big-endian u64 prefix keys — the binary-search
        # column.
        rows, terms, key8 = artifact_mod.term_table(art)
        self._rows = rows
        self._terms = terms
        self._keys = key8.view(">u8").ravel()
        self._df = art.df
        # every tally below lives on this per-engine obs registry: the
        # legacy describe()/stats dicts are views over it, and the
        # daemon folds it into the Prometheus exposition
        self.metrics = obs_metrics.Registry()
        self.metrics.gauge("mri_engine_vocab_terms").set(V)
        self.metrics.gauge("mri_engine_artifact_bytes").set(art.nbytes)
        self._cache = LRUCache(cache_terms, registry=self.metrics,
                               prefix="mri_serve_cache")
        self._tf_cache = LRUCache(cache_terms, registry=self.metrics,
                                  prefix="mri_serve_tf_cache")
        self._ops = OpTimer(registry=self.metrics)
        self._sdtype = f"S{width}"
        self._width = width
        # small-batch term-resolution memo: encoded query bytes ->
        # lex index (-1: absent).  Zipf query streams resolve the same
        # few terms over and over; a dict probe replaces the whole
        # searchsorted arm for them.
        self._memo: dict[bytes, int] = {}
        self._c_blocks_decoded = \
            self.metrics.counter("mri_engine_blocks_decoded_total")
        self._c_blocks_skipped = \
            self.metrics.counter("mri_engine_blocks_skipped_total")
        self._c_bytes_decoded = \
            self.metrics.counter("mri_engine_bytes_decoded_total")
        self._bm25_cols = None  # lazy (doc_lens, ndocs, avgdl)

    # -- term resolution ------------------------------------------------

    def encode_batch(self, terms) -> np.ndarray:
        """Normalize a list of str/bytes queries into the S-dtype batch
        array ``lookup`` consumes.  Terms that normalize away or exceed
        the vocabulary width become b'' (never found)."""
        return encode_terms(terms, self._width)

    def lookup(self, batch: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Resolve a batch (S-dtype array from :meth:`encode_batch`, or
        anything ``np.asarray`` coerces to one) to ``(idx, found)`` —
        lex term indices (valid only where ``found``).
        """
        q = np.asarray(batch, dtype=self._sdtype)
        V = self.vocab_size
        if V == 0:
            return (np.zeros(len(q), dtype=np.int64),
                    np.zeros(len(q), dtype=bool))
        n = len(q)
        memo = self._memo
        if 0 < n <= 8:
            hits = [memo.get(t) for t in q.tolist()]
            if None not in hits:
                at = np.array(hits, dtype=np.int64)
                found = at >= 0
                at[~found] = 0
                return at, found
        # S -> S8 cast pads (width < 8) or truncates (width > 8) to the
        # 8-byte prefix; big-endian u64 view preserves lex order.
        qkeys = np.ascontiguousarray(q.astype("S8")).view(">u8")
        lo = np.searchsorted(self._keys, qkeys, side="left")
        hi = np.searchsorted(self._keys, qkeys, side="right")
        at = np.minimum(lo, V - 1)
        found = (hi > lo) & (self._terms[at] == q) & (q != b"")
        # Rare arm: several vocabulary terms share a query's full
        # 8-byte prefix and the match isn't the group's first entry.
        ambiguous = np.nonzero((hi - lo > 1) & ~found & (q != b""))[0]
        for i in ambiguous:
            j = lo[i] + np.searchsorted(self._terms[lo[i]:hi[i]], q[i])
            if j < hi[i] and self._terms[j] == q[i]:
                at[i] = j
                found[i] = True
        if n <= 8:
            if len(memo) > (1 << 16):
                memo.clear()
            for t, i, ok in zip(q.tolist(), at.tolist(), found.tolist()):
                memo[t] = i if ok else -1
        return at, found

    # -- single-term answers --------------------------------------------

    def df(self, batch) -> np.ndarray:
        """Document frequency per query (0 when absent), vectorized."""
        with self._ops.time("df"):
            idx, found = self.lookup(batch)
            if self.vocab_size == 0:
                return np.zeros(len(found), dtype=np.int64)
            return np.where(found, self._df[idx], 0).astype(np.int64)

    def postings_by_index(self, idx: int) -> np.ndarray:
        """Decoded ascending doc ids of lex term ``idx`` (LRU-cached)."""
        idx = int(idx)
        hit = self._cache.get(idx)
        if hit is not None:
            return hit
        art = self.artifact
        decoded = art.decode_postings(idx)
        if art.version == artifact_mod.VERSION_V2:
            b0 = int(art.term_block_off[idx])
            b1 = int(art.term_block_off[idx + 1])
            self._c_blocks_decoded.inc(b1 - b0)
            self._c_bytes_decoded.inc(
                int(art.blk_woff[b1] - art.blk_woff[b0]) * 4)
        else:
            self._c_blocks_decoded.inc()
            self._c_bytes_decoded.inc(decoded.nbytes)
        decoded.setflags(write=False)
        self._cache.put(idx, decoded)
        return decoded

    def tf_by_index(self, idx: int) -> np.ndarray:
        """Per-doc term frequencies of lex term ``idx``, aligned with
        :meth:`postings_by_index` (all ones on a v1 artifact)."""
        idx = int(idx)
        hit = self._tf_cache.get(idx)
        if hit is not None:
            return hit
        decoded = self.artifact.decode_tf(idx)
        decoded.setflags(write=False)
        self._tf_cache.put(idx, decoded)
        return decoded

    def postings(self, batch) -> list[np.ndarray | None]:
        """Decoded postings per query term; None where absent."""
        with self._ops.time("postings"):
            idx, found = self.lookup(batch)
            return [self.postings_by_index(i) if ok else None
                    for i, ok in zip(idx.tolist(), found.tolist())]

    # -- compound queries -----------------------------------------------

    def top_k(self, letter, k: int) -> list[tuple[bytes, int]]:
        """The letter's k highest-df terms, (term, df), in emit order —
        exactly the first k lines of ``<letter>.txt``."""
        letter = letter_index(letter)
        with self._ops.time("top_k"):
            art = self.artifact
            lo = int(art.letter_dir[letter])
            hi = int(art.letter_dir[letter + 1])
            pick = art.df_order[lo:min(lo + max(k, 0), hi)]
            return [(art.term(i), int(self._df[i])) for i in pick]

    def _and_probe(self, acc: np.ndarray, run: np.ndarray) -> np.ndarray:
        """Keep the members of sorted ``acc`` present in sorted ``run``
        (galloping ``searchsorted`` probe)."""
        pos = np.searchsorted(run, acc)
        ok = pos < len(run)
        ok[ok] = run[pos[ok]] == acc[ok]
        return acc[ok]

    def _and_skip(self, acc: np.ndarray, idx: int) -> np.ndarray:
        """v2 AND arm: intersect ``acc`` against term ``idx`` WITHOUT
        decoding its whole postings run.  The per-block skip table
        (``blk_max``) routes every surviving candidate to the single
        block that could hold it; only those blocks are bit-unpacked.
        """
        art = self.artifact
        b0 = int(art.term_block_off[idx])
        b1 = int(art.term_block_off[idx + 1])
        blk = np.searchsorted(art.blk_max[b0:b1], acc)
        ok = blk < (b1 - b0)
        blk, cand = blk[ok], acc[ok]
        if not len(cand):
            self._c_blocks_skipped.inc(b1 - b0)
            return cand
        need = np.unique(blk)
        ids, _ = art.decode_blocks(need + b0)
        self._c_blocks_decoded.inc(len(need))
        self._c_blocks_skipped.inc((b1 - b0) - len(need))
        self._c_bytes_decoded.inc(int(
            (art.blk_woff[need + b0 + 1]
             - art.blk_woff[need + b0]).sum()) * 4)
        # rows beyond a block's count repeat its last real doc id
        # (cumsum of zero deltas), so a plain membership test is exact.
        rows = ids[np.searchsorted(need, blk)]
        return cand[(rows == cand[:, None]).any(axis=1)]

    def query_and(self, batch) -> np.ndarray:
        """Docs containing EVERY term.  Any absent term → empty.  The
        intersection gallops smallest-run-first: probe the larger sorted
        run with ``searchsorted`` at the surviving candidates only.  On
        a v2 artifact an uncached large run is never fully decoded —
        the skip table gallops past whole blocks (``--stats`` counts
        them)."""
        with self._ops.time("and"):
            idx, found = self.lookup(batch)
            if len(found) == 0 or not found.all():
                return np.zeros(0, dtype=np.int32)
            uniq = list(set(idx.tolist()))
            uniq.sort(key=lambda i: int(self._df[i]))
            acc = self.postings_by_index(uniq[0])
            v2 = self.artifact.version == artifact_mod.VERSION_V2
            B = self.artifact.block_size
            for i in uniq[1:]:
                if len(acc) == 0:
                    break
                cached = self._cache.peek(i)
                if cached is not None:
                    acc = self._and_probe(acc, cached)
                elif v2 and len(acc) * B < int(self._df[i]):
                    acc = self._and_skip(acc, i)
                else:
                    acc = self._and_probe(acc, self.postings_by_index(i))
            return np.ascontiguousarray(acc, dtype=np.int32)

    def query_or(self, batch) -> np.ndarray:
        """Docs containing ANY term (absent terms contribute nothing)."""
        with self._ops.time("or"):
            idx, found = self.lookup(batch)
            runs = [self.postings_by_index(i)
                    for i in sorted(set(idx[found].tolist()))]
            if not runs:
                return np.zeros(0, dtype=np.int32)
            out = runs[0] if len(runs) == 1 else \
                np.unique(np.concatenate(runs))
            return np.asarray(out, dtype=np.int32)

    # -- ranked retrieval -----------------------------------------------

    def _bm25_corpus(self) -> tuple[np.ndarray, int, float]:
        """``(doc_lens, ndocs, avgdl)`` — v2 reads the packed doc-length
        column; v1 reconstructs lengths from the postings themselves
        (every stored pair counts 1: the no-tf fallback), lazily and
        once."""
        if self._bm25_cols is None:
            self._bm25_cols = artifact_mod.bm25_corpus(self.artifact)
        return self._bm25_cols

    def top_k_scored(self, batch, k: int) -> list[tuple[int, float]]:
        """BM25-ranked ``(doc_id, score)`` for the query terms, best
        first, ties broken by ascending doc id.  Absent terms contribute
        nothing; duplicated query terms accumulate twice (same as the
        scoring oracle).  Parameters: k1=BM25_K1, b=BM25_B; idf is the
        Robertson-Sparck-Jones ``ln(1 + (N - df + 0.5)/(df + 0.5))``."""
        with self._ops.time("top_k_scored"):
            idx, found = self.lookup(batch)
            doc_lens, ndocs, avgdl = self._bm25_corpus()
            scores = np.zeros(len(doc_lens), dtype=np.float64)
            k1, b = BM25_K1, BM25_B
            for i, ok in zip(idx.tolist(), found.tolist()):
                if not ok:
                    continue
                docs = self.postings_by_index(i)
                tf = self.tf_by_index(i).astype(np.float64)
                dfi = len(docs)
                idf = np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5))
                denom = tf + k1 * (1.0 - b + b * doc_lens[docs] / avgdl)
                scores[docs] += idf * tf * (k1 + 1.0) / denom
            cand = np.nonzero(scores > 0.0)[0]
            top = cand[np.lexsort((cand, -scores[cand]))][:max(k, 0)]
            return [(int(d), float(scores[d])) for d in top]

    # -- bookkeeping ----------------------------------------------------

    @property
    def cache(self) -> LRUCache:
        return self._cache

    def cache_stats(self) -> dict:
        return self._cache.stats()

    def op_stats(self) -> dict:
        return self._ops.stats()

    def decode_stats(self) -> dict:
        """Skip/decode counters — the gallop win, observable."""
        return {
            "blocks_decoded": self._c_blocks_decoded.value,
            "blocks_skipped": self._c_blocks_skipped.value,
            "bytes_decoded": self._c_bytes_decoded.value,
        }

    def describe(self) -> dict:
        """Engine identity + counters for ``mri query --stats``."""
        return {
            "engine": self.engine_name,
            "format": self.artifact.version,
            "vocab": self.vocab_size,
            "artifact_bytes": self.artifact.nbytes,
            "cache": self.cache_stats(),
            "ops": self.op_stats(),
            "decode": self.decode_stats(),
        }

    def close(self) -> None:
        self._cache.clear()
        self._tf_cache.clear()
        self._memo.clear()
        self._bm25_cols = None
        self._df = self._keys = self._terms = self._rows = None
        self.artifact.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


#: ``engine="auto"`` picks the device engine only when jax is importable
#: AND its default backend is an accelerator — a JAX_PLATFORMS=cpu
#: process (tier-1, most laptops) serves from the host engine unless
#: the caller asks for ``device`` explicitly.
ENGINE_CHOICES = ("host", "device", "auto")
ENGINE_ENV = "MRI_SERVE_ENGINE"

#: BM25 free parameters (README "Format v2": classic defaults).
BM25_K1 = 1.2
BM25_B = 0.75

SCORE_CHOICES = ("df", "bm25")
SCORE_ENV = "MRI_SERVE_SCORE"


def resolve_score(score: str | None = None) -> str:
    """``df``/``bm25`` (+ MRI_SERVE_SCORE default) -> concrete mode."""
    score = score or envknobs.get(SCORE_ENV)
    if score not in SCORE_CHOICES:
        raise ValueError(
            f"unknown score mode {score!r} (choices: {SCORE_CHOICES})")
    return score


def resolve_engine(engine: str | None = None) -> str:
    """``host``/``device``/``auto``(+ env override) -> concrete name."""
    engine = engine or envknobs.get(ENGINE_ENV) or "auto"
    if engine not in ENGINE_CHOICES:
        raise ValueError(
            f"unknown engine {engine!r} (choices: {ENGINE_CHOICES})")
    if engine != "auto":
        return engine
    try:
        import jax
        return "device" if jax.default_backend() != "cpu" else "host"
    except Exception:
        return "host"


def create_engine(path, engine: str | None = None, *,
                  cache_terms: int = 4096, shards: int | None = None):
    """Open ``path`` with the selected backend (:data:`ENGINE_CHOICES`).

    Both engines answer the same API byte-identically; ``shards`` only
    applies to the device engine's batch-dimension mesh.
    """
    which = resolve_engine(engine)
    if which == "device":
        from .device_engine import DeviceEngine
        return DeviceEngine(path, cache_terms=cache_terms, shards=shards)
    return Engine(path, cache_terms=cache_terms)
