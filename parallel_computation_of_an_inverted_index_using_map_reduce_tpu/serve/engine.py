"""Vectorized query engine over a mmapped ``index.mri``.

Batched lookups are the unit of work (DrJAX's batched-array formulation,
arxiv 2403.07128, applied to serving): a batch of query terms becomes
one ``S``-dtype numpy array, term resolution is ONE ``np.searchsorted``
over big-endian u64 prefix keys (lexicographic order of NUL-padded
bytes == numeric order of the keys) plus a vectorized exact-match
gather — no per-query Python in the hot path.  Postings decode through
an LRU hot-term cache; multi-term AND intersects sorted runs smallest-
first with a galloping ``searchsorted`` probe; top-k-by-df per letter
is an O(k) slice of the artifact's ``df_order`` permutation.
"""

from __future__ import annotations

import numpy as np

from . import artifact as artifact_mod
from .cache import LRUCache


def _normalize(term) -> bytes:
    """Query-side mirror of the tokenizer's cleaning: lowercase, alpha
    only.  A term that cleans to something else can't be in the index."""
    if isinstance(term, bytes):
        term = term.decode("latin-1")
    term = term.lower()
    return term.encode("ascii") if term.isascii() and term.isalpha() \
        else b""


class Engine:
    """Batched query API over one loaded artifact.

    ``path`` is an output directory (its ``index.mri``) or the artifact
    file itself.  All answers are exact — the parity suite holds every
    one byte-equal to a naive scan of the emitted letter files.
    """

    def __init__(self, path, cache_terms: int = 4096):
        self.artifact = artifact_mod.load_artifact(path)
        art = self.artifact
        V, width = art.vocab, max(art.width, 1)
        self.vocab_size = V
        # Materialized fixed-width term table: (V, width) NUL-padded
        # rows scattered from the compact blob in two vectorized ops,
        # then viewed as one S-dtype column for exact-match gathers.
        lens = np.diff(art.term_offsets)
        rows = np.zeros((max(V, 1), width), dtype=np.uint8)
        if V:
            rows[np.arange(width) < lens[:, None]] = art.term_blob
        self._rows = rows
        self._terms = rows.view(f"S{width}").ravel()[:V]
        # Big-endian u64 prefix keys: the binary-search column.
        w8 = max(width, 8)
        pad = rows if width >= 8 else np.pad(rows, ((0, 0), (0, 8 - width)))
        self._keys = np.ascontiguousarray(pad[:, :8]).view(">u8").ravel()[:V]
        self._df = art.df
        self._cache = LRUCache(cache_terms)
        self._sdtype = f"S{width}"
        self._width = width

    # -- term resolution ------------------------------------------------

    def encode_batch(self, terms) -> np.ndarray:
        """Normalize a list of str/bytes queries into the S-dtype batch
        array ``lookup`` consumes.  Terms that normalize away or exceed
        the vocabulary width become b'' (never found)."""
        cleaned = [_normalize(t) for t in terms]
        return np.array(
            [t if len(t) <= self._width else b"" for t in cleaned],
            dtype=self._sdtype)

    def lookup(self, batch: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Resolve a batch (S-dtype array from :meth:`encode_batch`, or
        anything ``np.asarray`` coerces to one) to ``(idx, found)`` —
        lex term indices (valid only where ``found``).
        """
        q = np.asarray(batch, dtype=self._sdtype)
        V = self.vocab_size
        if V == 0:
            return (np.zeros(len(q), dtype=np.int64),
                    np.zeros(len(q), dtype=bool))
        # S -> S8 cast pads (width < 8) or truncates (width > 8) to the
        # 8-byte prefix; big-endian u64 view preserves lex order.
        qkeys = np.ascontiguousarray(q.astype("S8")).view(">u8")
        lo = np.searchsorted(self._keys, qkeys, side="left")
        hi = np.searchsorted(self._keys, qkeys, side="right")
        at = np.minimum(lo, V - 1)
        found = (hi > lo) & (self._terms[at] == q) & (q != b"")
        # Rare arm: several vocabulary terms share a query's full
        # 8-byte prefix and the match isn't the group's first entry.
        ambiguous = np.nonzero((hi - lo > 1) & ~found & (q != b""))[0]
        for i in ambiguous:
            j = lo[i] + np.searchsorted(self._terms[lo[i]:hi[i]], q[i])
            if j < hi[i] and self._terms[j] == q[i]:
                at[i] = j
                found[i] = True
        return at, found

    # -- single-term answers --------------------------------------------

    def df(self, batch) -> np.ndarray:
        """Document frequency per query (0 when absent), vectorized."""
        idx, found = self.lookup(batch)
        if self.vocab_size == 0:
            return np.zeros(len(found), dtype=np.int64)
        return np.where(found, self._df[idx], 0).astype(np.int64)

    def postings_by_index(self, idx: int) -> np.ndarray:
        """Decoded ascending doc ids of lex term ``idx`` (LRU-cached)."""
        idx = int(idx)
        hit = self._cache.get(idx)
        if hit is not None:
            return hit
        decoded = self.artifact.decode_postings(idx)
        decoded.setflags(write=False)
        self._cache.put(idx, decoded)
        return decoded

    def postings(self, batch) -> list[np.ndarray | None]:
        """Decoded postings per query term; None where absent."""
        idx, found = self.lookup(batch)
        return [self.postings_by_index(i) if ok else None
                for i, ok in zip(idx.tolist(), found.tolist())]

    # -- compound queries -----------------------------------------------

    def top_k(self, letter, k: int) -> list[tuple[bytes, int]]:
        """The letter's k highest-df terms, (term, df), in emit order —
        exactly the first k lines of ``<letter>.txt``."""
        if isinstance(letter, (str, bytes)):
            letter = (letter.encode() if isinstance(letter, str)
                      else letter)
            letter = letter[0] - ord("a")
        if not 0 <= letter < 26:
            raise ValueError(f"letter index out of range: {letter}")
        art = self.artifact
        lo, hi = int(art.letter_dir[letter]), int(art.letter_dir[letter + 1])
        pick = art.df_order[lo:min(lo + max(k, 0), hi)]
        return [(art.term(i), int(self._df[i])) for i in pick]

    def query_and(self, batch) -> np.ndarray:
        """Docs containing EVERY term.  Any absent term → empty.  The
        intersection gallops smallest-run-first: probe the larger sorted
        run with ``searchsorted`` at the surviving candidates only."""
        idx, found = self.lookup(batch)
        if len(found) == 0 or not found.all():
            return np.zeros(0, dtype=np.int32)
        runs = sorted((self.postings_by_index(i) for i in set(idx.tolist())),
                      key=len)
        acc = runs[0]
        for run in runs[1:]:
            if len(acc) == 0:
                break
            pos = np.searchsorted(run, acc)
            ok = pos < len(run)
            ok[ok] = run[pos[ok]] == acc[ok]
            acc = acc[ok]
        return acc

    def query_or(self, batch) -> np.ndarray:
        """Docs containing ANY term (absent terms contribute nothing)."""
        idx, found = self.lookup(batch)
        runs = [self.postings_by_index(i)
                for i in sorted(set(idx[found].tolist()))]
        if not runs:
            return np.zeros(0, dtype=np.int32)
        out = runs[0] if len(runs) == 1 else \
            np.unique(np.concatenate(runs))
        return np.asarray(out, dtype=np.int32)

    # -- bookkeeping ----------------------------------------------------

    @property
    def cache(self) -> LRUCache:
        return self._cache

    def cache_stats(self) -> dict:
        return self._cache.stats()

    def close(self) -> None:
        self._cache.clear()
        self._df = self._keys = self._terms = self._rows = None
        self.artifact.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
