"""Vectorized query engine over a mmapped ``index.mri``.

Batched lookups are the unit of work (DrJAX's batched-array formulation,
arxiv 2403.07128, applied to serving): a batch of query terms becomes
one ``S``-dtype numpy array, term resolution is ONE ``np.searchsorted``
over big-endian u64 prefix keys (lexicographic order of NUL-padded
bytes == numeric order of the keys) plus a vectorized exact-match
gather — no per-query Python in the hot path.  Postings decode through
an LRU hot-term cache; multi-term AND intersects sorted runs smallest-
first with a galloping ``searchsorted`` probe; top-k-by-df per letter
is an O(k) slice of the artifact's ``df_order`` permutation.
"""

from __future__ import annotations

import array
import time

import numpy as np

from . import artifact as artifact_mod
from . import planner as planner_mod
from .cache import LRUCache
from ..obs import attribution as obs_attrib
from ..obs import metrics as obs_metrics
# OpTimer's historical home is this module; the implementation moved to
# obs.timing (unified with PhaseTimer over the obs histogram) and is
# re-exported here so ``from .engine import OpTimer`` keeps working.
from ..obs.timing import OpTimer  # noqa: F401
from ..utils import envknobs


def _normalize(term) -> bytes:
    """Query-side mirror of the tokenizer's cleaning: lowercase, alpha
    only.  A term that cleans to something else can't be in the index."""
    if isinstance(term, bytes):
        term = term.decode("latin-1")
    term = term.lower()
    return term.encode("ascii") if term.isascii() and term.isalpha() \
        else b""


def encode_terms(terms, width: int) -> np.ndarray:
    """Normalize str/bytes queries into the engines' S-dtype batch
    array.  Terms that normalize away or exceed the vocabulary width
    become b'' (never found).  Shared by both engine backends so the
    interchange format is identical."""
    cleaned = [_normalize(t) for t in terms]
    return np.array(
        [t if len(t) <= width else b"" for t in cleaned],
        dtype=f"S{width}")


def _union_add(cand: np.ndarray, scores: np.ndarray,
               docs: np.ndarray, add: np.ndarray
               ) -> tuple[np.ndarray, np.ndarray]:
    """Merge a term's (docs, contributions) into the sorted candidate
    accumulator.  Both doc arrays are ascending and internally unique,
    so positional fancy-index adds are exact (no ``np.add.at``)."""
    merged = np.union1d(cand, docs)
    out = np.zeros(len(merged), dtype=np.float64)
    out[np.searchsorted(merged, cand)] = scores
    out[np.searchsorted(merged, docs)] += add
    return merged, out


def letter_index(letter) -> int:
    """'a'..'z' (str/bytes) or 0..25 -> letter_dir slot, or ValueError."""
    if isinstance(letter, (str, bytes)):
        letter = (letter.encode() if isinstance(letter, str) else letter)
        letter = letter[0] - ord("a")
    if not 0 <= letter < 26:
        raise ValueError(f"letter index out of range: {letter}")
    return letter


class Engine:
    """Batched query API over one loaded artifact.

    ``path`` is an output directory (its ``index.mri``) or the artifact
    file itself.  All answers are exact — the parity suite holds every
    one byte-equal to a naive scan of the emitted letter files.
    """

    engine_name = "host"

    def __init__(self, path, cache_terms: int = 4096):
        if artifact_mod.is_segment_managed(path):
            raise artifact_mod.ArtifactError(
                f"{path} is segment-managed (segments.manifest.json "
                "present): its root index.mri may be stale — open it "
                "with serve.engine.create_engine, which routes to the "
                "multi-segment engine")
        self.artifact = artifact_mod.load_artifact(path)
        art = self.artifact
        V, width = art.vocab, max(art.width, 1)
        self.vocab_size = V
        # Materialized fixed-width term table (artifact.term_table):
        # NUL-padded rows viewed as one S-dtype column for exact-match
        # gathers, plus big-endian u64 prefix keys — the binary-search
        # column.
        rows, terms, key8 = artifact_mod.term_table(art)
        self._rows = rows
        self._terms = terms
        self._keys = key8.view(">u8").ravel()
        self._df = art.df
        # every tally below lives on this per-engine obs registry: the
        # legacy describe()/stats dicts are views over it, and the
        # daemon folds it into the Prometheus exposition
        self.metrics = obs_metrics.Registry()
        self.metrics.gauge("mri_engine_vocab_terms").set(V)
        self.metrics.gauge("mri_engine_artifact_bytes").set(art.nbytes)
        self._cache = LRUCache(cache_terms, registry=self.metrics,
                               prefix="mri_serve_cache")
        self._tf_cache = LRUCache(cache_terms, registry=self.metrics,
                                  prefix="mri_serve_tf_cache")
        self._ops = OpTimer(registry=self.metrics)
        self._sdtype = f"S{width}"
        self._width = width
        # small-batch term-resolution memo: encoded query bytes ->
        # lex index (-1: absent).  Zipf query streams resolve the same
        # few terms over and over; a dict probe replaces the whole
        # searchsorted arm for them.
        self._memo: dict[bytes, int] = {}
        self._c_blocks_decoded = \
            self.metrics.counter("mri_engine_blocks_decoded_total")
        self._c_blocks_skipped = \
            self.metrics.counter("mri_engine_blocks_skipped_total")
        self._c_bytes_decoded = \
            self.metrics.counter("mri_engine_bytes_decoded_total")
        self._bm25_cols = None  # lazy (doc_lens, ndocs, avgdl)
        # corpus-stats override seam (multi-segment serving): when set,
        # (ndocs, avgdl) and the per-term scoring df come from the
        # GLOBAL live corpus instead of this artifact, so per-segment
        # BM25 contributions stay bit-identical to a single-artifact
        # build of the same live state
        self._corpus_override = None  # (ndocs, avgdl, df_fn)
        self.planner = planner_mod.Planner(self.metrics)
        # BM25 per-term memos keyed by lex index: contributions are
        # query-independent (idf, tf and doc length are all properties
        # of the term/corpus), so the pruned evaluators reuse them
        # across a query stream instead of re-deriving per query.
        self._score_memo: dict[int, tuple] = {}
        self._bound_memo: dict[int, tuple] = {}
        self._memo_cap = max(int(cache_terms), 1)
        # ranked-path resolution memo: encoded batch bytes -> the occ
        # list (present lex indices, occurrence order) — one dict probe
        # replaces lookup + the zip/filter for repeated queries
        self._occ_memo: dict[bytes, list] = {}
        # inlined timing for the ranked hot path (the contextmanager
        # form costs a couple of microseconds per call — real money at
        # the QPS the lean small-query path runs at)
        self._h_topk = self._ops.histogram("top_k_scored")
        # native (C++) serve kernels.  The knob is resolved ONCE per
        # engine: a daemon SIGHUP reload swaps the engine, which is the
        # re-resolution point for this and every other serve knob.  The
        # handle itself builds lazily on the first eligible op (the
        # first load compiles the extension); answers are byte-
        # identical either way, so a mid-stream fallback is invisible.
        self._native_mode = resolve_native()
        self._native = None
        self._native_err: str | None = None
        self._idf_memo: dict[int, float] = {}
        #: query key -> (prep id, dfs): the frozen C-side arguments a
        #: warm native ranked query is re-issued with, plus the ranked
        #: plan memo keyed (query key, k) against the raw planner token
        self._nat_prep: dict[bytes, tuple] = {}
        self._plan_memo: dict[tuple, tuple] = {}
        # per-k {query key -> (prep id, mode, mode code, env token)}
        # plus reusable marshalling arrays for the coalesced path
        self._batch_memo: dict[int, dict] = {}
        self._ba_pids = array.array("q")
        self._ba_modes = array.array("i")
        self._c_native_ops = self.metrics.counter(
            "mri_native_ops_total")
        self._c_native_fallback = self.metrics.counter(
            "mri_native_fallback_total")
        if self._native_mode == "1":
            self._native_handle()  # required -> fail loudly up front

    # -- native serve kernels -------------------------------------------

    def _native_handle(self):
        """The lazily-built ``NativeServe`` handle, or None when native
        is off, unsupported (v1 artifact) or unavailable (no compiled
        extension).  Under ``MRI_SERVE_NATIVE=1`` unavailability raises
        instead of silently serving numpy."""
        if self._native is not None:
            return self._native
        if self._native_mode != "0" and self._native_err is None:
            art = self.artifact
            if art.version < artifact_mod.VERSION_V2:
                self._native_err = "v1 artifact (native needs v2+)"
            else:
                try:
                    from .. import native as native_mod
                    doc_lens, _, avgdl = self._bm25_corpus()
                    self._native = native_mod.NativeServe(
                        artifact_mod.serve_columns(art), doc_lens,
                        avgdl, BM25_K1, BM25_B,
                        cache_cap=self._memo_cap)
                except Exception as e:
                    self._native_err = f"{type(e).__name__}: {e}"
        if self._native is None and self._native_mode == "1":
            raise RuntimeError(
                "MRI_SERVE_NATIVE=1 but the native serve kernels are "
                f"unavailable: {self._native_err}")
        return self._native

    def _close_native(self) -> None:
        if self._native is not None:
            self._native.close()
            self._native = None
        self._native_err = None
        self._nat_prep.clear()
        self._plan_memo.clear()
        self._batch_memo.clear()

    def _term_idf(self, i: int) -> float:
        """The scalar idf the native scorer receives for lex term
        ``i`` — the exact double :meth:`_term_scores` computes, so both
        backends multiply by bit-equal factors (memoized)."""
        hit = self._idf_memo.get(i)
        if hit is None:
            _, ndocs, _ = self._bm25_corpus()
            dfi = self._scoring_df(i, int(self._df[i]))
            hit = float(np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5)))
            if len(self._idf_memo) >= self._memo_cap:
                self._idf_memo.clear()
            self._idf_memo[i] = hit
        return hit

    # -- term resolution ------------------------------------------------

    def encode_batch(self, terms) -> np.ndarray:
        """Normalize a list of str/bytes queries into the S-dtype batch
        array ``lookup`` consumes.  Terms that normalize away or exceed
        the vocabulary width become b'' (never found)."""
        return encode_terms(terms, self._width)

    def lookup(self, batch: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Resolve a batch (S-dtype array from :meth:`encode_batch`, or
        anything ``np.asarray`` coerces to one) to ``(idx, found)`` —
        lex term indices (valid only where ``found``).
        """
        q = np.asarray(batch, dtype=self._sdtype)
        V = self.vocab_size
        if V == 0:
            return (np.zeros(len(q), dtype=np.int64),
                    np.zeros(len(q), dtype=bool))
        n = len(q)
        memo = self._memo
        # one ContextVar.get per lookup: the entire disabled-path cost
        # of per-term attribution
        coll = obs_attrib.active()
        if 0 < n <= 8:
            hits = [memo.get(t) for t in q.tolist()]
            if None not in hits:
                at = np.array(hits, dtype=np.int64)
                found = at >= 0
                at[~found] = 0
                if coll is not None:
                    self._feed_terms(coll, q, at, found, "memo")
                return at, found
        # S -> S8 cast pads (width < 8) or truncates (width > 8) to the
        # 8-byte prefix; big-endian u64 view preserves lex order.
        qkeys = np.ascontiguousarray(q.astype("S8")).view(">u8")
        lo = np.searchsorted(self._keys, qkeys, side="left")
        hi = np.searchsorted(self._keys, qkeys, side="right")
        at = np.minimum(lo, V - 1)
        found = (hi > lo) & (self._terms[at] == q) & (q != b"")
        # Rare arm: several vocabulary terms share a query's full
        # 8-byte prefix and the match isn't the group's first entry.
        ambiguous = np.nonzero((hi - lo > 1) & ~found & (q != b""))[0]
        for i in ambiguous:
            j = lo[i] + np.searchsorted(self._terms[lo[i]:hi[i]], q[i])
            if j < hi[i] and self._terms[j] == q[i]:
                at[i] = j
                found[i] = True
        if n <= 8:
            if len(memo) > (1 << 16):
                memo.clear()
            for t, i, ok in zip(q.tolist(), at.tolist(), found.tolist()):
                memo[t] = i if ok else -1
        if coll is not None:
            self._feed_terms(coll, q, at, found, "bisect")
        return at, found

    def _feed_terms(self, coll, q, at, found, path: str) -> None:
        """Per-term attribution entries for one resolved batch."""
        for t, i, ok in zip(q.tolist(), at.tolist(), found.tolist()):
            coll.term(t, i, ok, int(self._df[i]) if ok else 0, path)

    # -- single-term answers --------------------------------------------

    def df(self, batch) -> np.ndarray:
        """Document frequency per query (0 when absent), vectorized."""
        with self._ops.time("df"):
            idx, found = self.lookup(batch)
            if self.vocab_size == 0:
                return np.zeros(len(found), dtype=np.int64)
            return np.where(found, self._df[idx], 0).astype(np.int64)

    def postings_by_index(self, idx: int) -> np.ndarray:
        """Decoded ascending doc ids of lex term ``idx`` (LRU-cached)."""
        idx = int(idx)
        hit = self._cache.get(idx)
        if hit is not None:
            return hit
        art = self.artifact
        decoded = None
        if self._native_mode != "0" \
                and art.version >= artifact_mod.VERSION_V2:
            nat = self._native_handle()
            if nat is not None:
                res = nat.decode_postings(idx, int(self._df[idx]))
                if res is not None:
                    decoded, tf = res
                    self._c_native_ops.inc()
                    # the tf column came out of the same block walk —
                    # warm its cache so _term_scores never re-decodes
                    if self._tf_cache.peek(idx) is None:
                        tf.setflags(write=False)
                        self._tf_cache.put(idx, tf)
                else:
                    self._c_native_fallback.inc()
        if decoded is None:
            decoded = art.decode_postings(idx)
        coll = obs_attrib.active()
        if art.version >= artifact_mod.VERSION_V2:
            b0 = int(art.term_block_off[idx])
            b1 = int(art.term_block_off[idx + 1])
            nbytes = int(art.blk_woff[b1] - art.blk_woff[b0]) * 4
            self._c_blocks_decoded.inc(b1 - b0)
            self._c_bytes_decoded.inc(nbytes)
            if coll is not None:
                coll.decoded(b1 - b0, nbytes)
        else:
            self._c_blocks_decoded.inc()
            self._c_bytes_decoded.inc(decoded.nbytes)
            if coll is not None:
                coll.decoded(1, decoded.nbytes)
        decoded.setflags(write=False)
        self._cache.put(idx, decoded)
        return decoded

    def tf_by_index(self, idx: int) -> np.ndarray:
        """Per-doc term frequencies of lex term ``idx``, aligned with
        :meth:`postings_by_index` (all ones on a v1 artifact)."""
        idx = int(idx)
        hit = self._tf_cache.get(idx)
        if hit is not None:
            return hit
        decoded = self.artifact.decode_tf(idx)
        decoded.setflags(write=False)
        self._tf_cache.put(idx, decoded)
        return decoded

    def postings(self, batch) -> list[np.ndarray | None]:
        """Decoded postings per query term; None where absent."""
        with self._ops.time("postings"):
            idx, found = self.lookup(batch)
            return [self.postings_by_index(i) if ok else None
                    for i, ok in zip(idx.tolist(), found.tolist())]

    # -- compound queries -----------------------------------------------

    def top_k(self, letter, k: int) -> list[tuple[bytes, int]]:
        """The letter's k highest-df terms, (term, df), in emit order —
        exactly the first k lines of ``<letter>.txt``."""
        letter = letter_index(letter)
        with self._ops.time("top_k"):
            art = self.artifact
            lo = int(art.letter_dir[letter])
            hi = int(art.letter_dir[letter + 1])
            pick = art.df_order[lo:min(lo + max(k, 0), hi)]
            return [(art.term(i), int(self._df[i])) for i in pick]

    def _and_probe(self, acc: np.ndarray, run: np.ndarray) -> np.ndarray:
        """Keep the members of sorted ``acc`` present in sorted ``run``
        (galloping ``searchsorted`` probe)."""
        pos = np.searchsorted(run, acc)
        ok = pos < len(run)
        ok[ok] = run[pos[ok]] == acc[ok]
        return acc[ok]

    def _and_skip(self, acc: np.ndarray, idx: int) -> np.ndarray:
        """v2 AND arm: intersect ``acc`` against term ``idx`` WITHOUT
        decoding its whole postings run.  The per-block skip table
        (``blk_max``) routes every surviving candidate to the single
        block that could hold it; only those blocks are bit-unpacked.
        """
        art = self.artifact
        b0 = int(art.term_block_off[idx])
        b1 = int(art.term_block_off[idx + 1])
        blk = np.searchsorted(art.blk_max[b0:b1], acc)
        ok = blk < (b1 - b0)
        blk, cand = blk[ok], acc[ok]
        coll = obs_attrib.active()
        if not len(cand):
            self._c_blocks_skipped.inc(b1 - b0)
            if coll is not None:
                coll.skipped(b1 - b0)
            return cand
        need = np.unique(blk)
        ids, _ = art.decode_blocks(need + b0)
        nbytes = int((art.blk_woff[need + b0 + 1]
                      - art.blk_woff[need + b0]).sum()) * 4
        self._c_blocks_decoded.inc(len(need))
        self._c_blocks_skipped.inc((b1 - b0) - len(need))
        self._c_bytes_decoded.inc(nbytes)
        if coll is not None:
            coll.decoded(len(need), nbytes)
            coll.skipped((b1 - b0) - len(need))
        # rows beyond a block's count repeat its last real doc id
        # (cumsum of zero deltas), so a plain membership test is exact.
        rows = ids[np.searchsorted(need, blk)]
        return cand[(rows == cand[:, None]).any(axis=1)]

    def query_and(self, batch) -> np.ndarray:
        """Docs containing EVERY term.  Any absent term → empty.  The
        intersection gallops smallest-run-first: probe the larger sorted
        run with ``searchsorted`` at the surviving candidates only.  On
        a v2 artifact an uncached large run is never fully decoded —
        the skip table gallops past whole blocks (``--stats`` counts
        them)."""
        with self._ops.time("and"):
            idx, found = self.lookup(batch)
            if len(found) == 0 or not found.all():
                return np.zeros(0, dtype=np.int32)
            uniq = list(set(idx.tolist()))
            uniq.sort(key=lambda i: int(self._df[i]))
            acc = self.postings_by_index(uniq[0])
            v2 = self.artifact.version >= artifact_mod.VERSION_V2
            B = self.artifact.block_size
            nat = self._native_handle() \
                if self._native_mode != "0" and v2 else None
            coll = obs_attrib.active()
            for i in uniq[1:]:
                if len(acc) == 0:
                    break
                cached = self._cache.peek(i)
                # native takes the gallop arm only when the run is NOT
                # already decoded in cache: probing a cached array is a
                # single numpy searchsorted, cheaper than re-walking
                # blocks in C
                arm = self.planner.plan_and(
                    len(acc), int(self._df[i]),
                    native=nat is not None and cached is None)
                if arm == "merge":
                    # merge only fires when the partner run is at most
                    # ~2x the accumulator, so decoding it whole is
                    # cheap even when uncached
                    run = cached if cached is not None \
                        else self.postings_by_index(i)
                    acc = np.intersect1d(acc, run, assume_unique=True)
                    continue
                if arm == "native":
                    res = nat.query_and(
                        np.ascontiguousarray(acc, dtype=np.int32), i)
                    if res is not None:
                        acc, dec, skp = res
                        self._c_native_ops.inc()
                        self._c_blocks_decoded.inc(dec)
                        self._c_blocks_skipped.inc(skp)
                        if coll is not None:
                            coll.decoded(dec, 0)
                            coll.skipped(skp)
                        continue
                    self._c_native_fallback.inc()
                if cached is not None:
                    acc = self._and_probe(acc, cached)
                elif v2 and len(acc) * B < int(self._df[i]):
                    acc = self._and_skip(acc, i)
                else:
                    acc = self._and_probe(acc, self.postings_by_index(i))
            return np.ascontiguousarray(acc, dtype=np.int32)

    def query_or(self, batch) -> np.ndarray:
        """Docs containing ANY term (absent terms contribute nothing)."""
        with self._ops.time("or"):
            idx, found = self.lookup(batch)
            runs = [self.postings_by_index(i)
                    for i in sorted(set(idx[found].tolist()))]
            if not runs:
                return np.zeros(0, dtype=np.int32)
            out = runs[0] if len(runs) == 1 else \
                np.unique(np.concatenate(runs))
            return np.asarray(out, dtype=np.int32)

    # -- ranked retrieval -----------------------------------------------

    def _bm25_corpus(self) -> tuple[np.ndarray, int, float]:
        """``(doc_lens, ndocs, avgdl)`` — v2 reads the packed doc-length
        column; v1 reconstructs lengths from the postings themselves
        (every stored pair counts 1: the no-tf fallback), lazily and
        once.  Under a corpus override (multi-segment serving) the
        doc-length column stays LOCAL (it is indexed by this artifact's
        doc ids) while ndocs/avgdl are the injected global values."""
        if self._bm25_cols is None:
            cols = artifact_mod.bm25_corpus(self.artifact)
            if self._corpus_override is not None:
                ndocs, avgdl, _ = self._corpus_override
                cols = (cols[0], ndocs, avgdl)
            self._bm25_cols = cols
        return self._bm25_cols

    def set_corpus_override(self, ndocs: int, avgdl: float,
                            df_fn) -> None:
        """Score this artifact as ONE SEGMENT of a larger live corpus.

        ``ndocs``/``avgdl`` replace the artifact's own corpus stats and
        ``df_fn(lex_idx) -> int`` supplies the global live document
        frequency per local term, so every BM25 contribution this
        engine computes equals — bit for bit — what a from-scratch
        single-artifact build of the whole live corpus would compute
        for the same (term, doc).  Clears every stats-dependent memo;
        segment engines are per-generation immutable, so the multi-
        segment engine calls this exactly once, right after opening."""
        self._corpus_override = (int(ndocs), float(avgdl), df_fn)
        self._bm25_cols = None
        self._score_memo.clear()
        self._bound_memo.clear()
        self._occ_memo.clear()
        self._idf_memo.clear()
        # the native handle bakes avgdl in at construction — rebuild it
        # lazily against the overridden stats
        self._close_native()

    def _scoring_df(self, i: int, dfi: int) -> int:
        """The df that enters the idf term for lex index ``i``: the
        local ``dfi`` normally, the global live df under an override."""
        if self._corpus_override is not None:
            return int(self._corpus_override[2](i))
        return dfi

    def top_k_scored(self, batch, k: int) -> list[tuple[int, float]]:
        """BM25-ranked ``(doc_id, score)`` for the query terms, best
        first, ties broken by ascending doc id.  Absent terms contribute
        nothing; duplicated query terms accumulate twice (same as the
        scoring oracle).  Parameters: k1=BM25_K1, b=BM25_B; idf is the
        Robertson-Sparck-Jones ``ln(1 + (N - df + 0.5)/(df + 0.5))``.

        The planner picks the evaluation: exhaustive scores every
        posting; ``bmw``/``maxscore`` prune with the v2.1 per-block
        max-score columns and return the same top-k byte-identically
        (the pruned sums are re-accumulated in occurrence order, see
        :meth:`_top_k_pruned`)."""
        t0 = time.perf_counter()
        try:
            coll = obs_attrib.active()
            occ = None
            key = batch.tobytes() if isinstance(batch, np.ndarray) \
                else None
            if key is not None:
                occ = self._occ_memo.get(key)
            if occ is None:
                idx, found = self.lookup(batch)
                occ = [i for i, ok in zip(idx.tolist(),
                                          found.tolist()) if ok]
                if key is not None:
                    if len(self._occ_memo) > (1 << 16):
                        self._occ_memo.clear()
                    self._occ_memo[key] = occ
            elif coll is not None:
                art = self.artifact
                for i in occ:
                    coll.term(art.term(i), i, True,
                              int(self._df[i]), "cache")
            if occ and k > 0 and self._native_mode != "0":
                nat = self._native_handle()
                if nat is not None:
                    res = None
                    prep = self._nat_prep.get(key) \
                        if key is not None else None
                    if prep is None:
                        pid = nat.prep_query(
                            occ, [self._term_idf(i) for i in occ])
                        if pid is not None:
                            prep = (pid,
                                    [int(self._df[i]) for i in occ])
                            if key is not None:
                                if len(self._nat_prep) > (1 << 16):
                                    self._nat_prep.clear()
                                    self._plan_memo.clear()
                                    self._batch_memo.clear()
                                    nat.clear_preps()
                                self._nat_prep[key] = prep
                    if prep is not None:
                        raw = _planner_raw_token()
                        pk = (key, k)
                        pm = self._plan_memo.get(pk)
                        if pm is not None and pm[1] == raw:
                            mode = pm[0]
                        else:
                            mode = self.planner.plan_ranked(
                                self.artifact, prep[1], k)
                            if key is not None:
                                if len(self._plan_memo) > (1 << 16):
                                    self._plan_memo.clear()
                                self._plan_memo[pk] = (mode, raw)
                        res = nat.top_k_bm25_fast(prep[0], k, mode)
                        if key is None:
                            nat.free_prep(prep[0])
                    if res is not None:
                        pairs, scored, skipped, ncand = res
                        self._c_native_ops.inc()
                        self.planner.note_ranked(
                            mode, scored, skipped, ncand,
                            backend="native")
                        return pairs
                    self._c_native_fallback.inc()
            if occ and k > 0 and len(occ) <= 2:
                out = self._top_k_small(occ, k, coll)
                if out is not None:
                    return out
            mode = self.planner.plan_ranked(
                self.artifact, [int(self._df[i]) for i in occ], k)
            if mode != "exhaustive":
                return self._top_k_pruned(occ, k, mode, coll)
            out = self._top_k_exhaustive(occ, k)
            self.planner.note_ranked("exhaustive", 0, 0, len(out))
            return out
        finally:
            self._h_topk.observe(time.perf_counter() - t0)

    def top_k_scored_batch(self, batches, k: int):
        """Answer a coalesced group of ranked queries — the daemon /
        scale-out-router micro-batch regime — returning one
        ``top_k_scored`` result list per encoded batch, byte-identical
        to issuing them serially.

        With the native backend every warm query in the group resolves
        to a prepared id and the whole group crosses into C ONCE
        (``mri_serve_topk_batch``), amortizing the per-call dispatch
        (ctypes marshalling, latency observation, planner accounting)
        that dominates single-query serving on small corpora.  Cold
        queries, attribution-collected requests, and the numpy backend
        all take the per-query path, so semantics (memo fills, EXPLAIN
        spans, counters) are unchanged."""
        if k <= 0 or self._native_mode == "0" \
                or obs_attrib.active() is not None:
            return [self.top_k_scored(b, k) for b in batches]
        nat = self._native_handle()
        if nat is None:
            return [self.top_k_scored(b, k) for b in batches]
        t0 = time.perf_counter()
        out: list = [None] * len(batches)
        pids = self._ba_pids
        modes_i = self._ba_modes
        del pids[:]
        del modes_i[:]
        ncold = 0
        raw = _planner_raw_token()
        bmk = self._batch_memo.get(k)
        if bmk is None:
            bmk = self._batch_memo[k] = {}
        bm_get = bmk.get
        app_p = pids.append
        app_m = modes_i.append
        for qi, batch in enumerate(batches):
            key = batch.tobytes() if isinstance(batch, np.ndarray) \
                else None
            ent = bm_get(key) if key is not None else None
            if ent is None or ent[3] != raw:
                prep = self._nat_prep.get(key) if key is not None \
                    else None
                occ = self._occ_memo.get(key) if key is not None \
                    else None
                if prep is None or occ is None:
                    # cold query: the single path fills every memo
                    # (occ, prep, plan) so the next group runs warm
                    out[qi] = self.top_k_scored(batch, k)
                    ncold += 1
                    continue
                mode = self.planner.plan_ranked(
                    self.artifact, prep[1], k)
                ent = (prep[0], mode, nat.MODES[mode], raw)
                if len(bmk) > (1 << 16):
                    bmk.clear()
                bmk[key] = ent
            app_p(ent[0])
            app_m(ent[2])
        if pids:
            nq = len(pids)
            res = nat.top_k_bm25_batch(pids, modes_i, nq, k)
            if res is None:
                self._c_native_fallback.inc()
                for qi in range(len(batches)):
                    if out[qi] is None:
                        out[qi] = self.top_k_scored(batches[qi], k)
            else:
                pairs_list, scored, skipped, ncand = res
                self._c_native_ops.inc(nq)
                counts = {}
                for ci, nm in enumerate(nat.MODE_NAMES):
                    c = modes_i.count(ci)
                    if c:
                        counts[nm] = c
                self.planner.note_ranked_batch(
                    counts, nat.MODE_NAMES[modes_i[-1]],
                    scored, skipped, ncand, backend="native")
                if ncold == 0:
                    out = pairs_list
                else:
                    it = iter(pairs_list)
                    for qi in range(len(batches)):
                        if out[qi] is None:
                            out[qi] = next(it)
            # one ranked-op latency observation for the fused group
            # (cold queries above observed their own)
            self._h_topk.observe(time.perf_counter() - t0)
        return out

    def _top_k_small(self, occ: list[int], k: int, coll=None):
        """Lean 1-2 occurrence ranked path over memoized contributions.

        The Zipf-head query mix is dominated by short queries whose
        terms' contributions are already in ``_score_memo``; for those
        this path replaces the general TAAT machinery with a handful of
        numpy calls: dense-accumulate the memoized contributions (the
        exhaustive float addition order, so scores stay byte-identical)
        and, under bmw/maxscore, drop every doc provably below theta =
        the best single-term k-th contribution BEFORE the selection
        sort.  Returns None when a term isn't memoized yet or the
        corpus is too large for a dense throwaway accumulator — the
        general paths handle the query and fill the memo."""
        memo = self._score_memo
        h1 = memo.get(occ[0])
        if h1 is None:
            return None
        docs1, c1, srt1 = h1
        n1 = len(docs1)
        art = self.artifact
        planner = self.planner
        margin = planner_mod.THETA_MARGIN
        mode = planner.resolve_cached()
        if len(occ) == 1 or occ[1] == occ[0]:
            w = float(len(occ))
            # same plan the general dispatch would make (dfs has one
            # entry per occurrence, duplicates included)
            if mode != "exhaustive" and art.has_block_scores \
                    and k < n1 * len(occ):
                if mode == "auto":
                    mode = "bmw" if n1 > 4 * art.block_size \
                        else "maxscore"
                scores = c1 if w == 1.0 else w * c1
                theta = w * float(srt1[k - 1]) if n1 >= k else 0.0
                if coll is not None:
                    coll.theta(theta)
                if theta > 0.0:
                    keep = scores >= theta * margin
                    cand, sc = docs1[keep], scores[keep]
                else:
                    cand, sc = docs1, scores
                planner.note_ranked(mode, 0, 0, len(cand))
                order = np.lexsort((cand, -sc))[:k]
                top = cand[order]
                return list(zip(top.tolist(), sc[order].tolist()))
            out = self._top_k_exhaustive(occ, k)
            planner.note_ranked("exhaustive", 0, 0, len(out))
            return out
        h2 = memo.get(occ[1])
        if h2 is None:
            return None
        docs2, c2, srt2 = h2
        n2 = len(docs2)
        doc_lens, _, _ = self._bm25_corpus()
        ndocs = len(doc_lens)
        if ndocs > (1 << 16):
            return None
        if mode == "exhaustive" or not art.has_block_scores \
                or k >= n1 + n2:
            out = self._top_k_exhaustive(occ, k)
            planner.note_ranked("exhaustive", 0, 0, len(out))
            return out
        if mode == "auto":
            mode = "bmw" if max(n1, n2) > 4 * art.block_size \
                else "maxscore"
        scores = np.zeros(ndocs, dtype=np.float64)
        scores[docs1] = c1
        scores[docs2] += c2
        theta = float(srt1[k - 1]) if n1 >= k else 0.0
        if n2 >= k:
            t2 = float(srt2[k - 1])
            if t2 > theta:
                theta = t2
        if coll is not None:
            coll.theta(theta)
        if theta > 0.0:
            cand = (scores >= theta * margin).nonzero()[0]
        else:
            cand = (scores > 0.0).nonzero()[0]
        sc = scores[cand]
        planner.note_ranked(mode, 0, 0, len(cand))
        order = np.lexsort((cand, -sc))[:k]
        top = cand[order]
        return list(zip(top.tolist(), sc[order].tolist()))

    def _top_k_exhaustive(self, occ: list[int], k: int
                          ) -> list[tuple[int, float]]:
        """Score every posting of every query term into a dense
        accumulator — the reference evaluation the pruned paths must
        reproduce byte-for-byte.  Per-term contributions come from
        :meth:`_term_scores` (identical expression, memoized), added in
        occurrence order exactly as the inline loop always did."""
        doc_lens, ndocs, avgdl = self._bm25_corpus()
        scores = np.zeros(len(doc_lens), dtype=np.float64)
        for i in occ:
            docs, contrib, _ = self._term_scores(i)
            scores[docs] += contrib
        cand = np.nonzero(scores > 0.0)[0]
        top = cand[np.lexsort((cand, -scores[cand]))][:max(k, 0)]
        return [(int(d), float(scores[d])) for d in top]

    def _term_scores(self, i: int) -> tuple:
        """``(docs, contrib, contrib_sorted_desc)`` for lex term ``i``.

        ``contrib`` holds the term's BM25 contribution at each of its
        docs, computed with exactly the exhaustive scorer's expression
        so pruned partial sums stay elementwise bit-equal; the values
        are query-independent, so they memoize per engine."""
        hit = self._score_memo.get(i)
        if hit is not None:
            return hit
        doc_lens, ndocs, avgdl = self._bm25_corpus()
        k1, b = BM25_K1, BM25_B
        # int64 up front: fancy indexing with int32 index arrays pays a
        # per-query widening conversion that doubles its cost
        docs = self.postings_by_index(i).astype(np.int64)
        tf = self.tf_by_index(i).astype(np.float64)
        dfi = self._scoring_df(i, len(docs))
        idf = np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5))
        denom = tf + k1 * (1.0 - b + b * doc_lens[docs] / avgdl)
        contrib = idf * tf * (k1 + 1.0) / denom
        docs.setflags(write=False)
        contrib.setflags(write=False)
        srt = np.sort(contrib)[::-1]
        if len(self._score_memo) >= self._memo_cap:
            self._score_memo.clear()
        self._score_memo[i] = (docs, contrib, srt)
        return self._score_memo[i]

    def _term_bounds(self, i: int) -> tuple:
        """``(per-block upper bounds, their max)`` for lex term ``i``
        on a v2.1 artifact (float64, memoized)."""
        hit = self._bound_memo.get(i)
        if hit is not None:
            return hit
        doc_lens, ndocs, avgdl = self._bm25_corpus()
        dfi = self._scoring_df(i, int(self._df[i]))
        idf = np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5))
        ubs = planner_mod.block_upper_bounds(
            self.artifact, i, idf, avgdl, BM25_K1, BM25_B)
        if len(self._bound_memo) >= self._memo_cap:
            self._bound_memo.clear()
        self._bound_memo[i] = (ubs, float(ubs.max()) if len(ubs)
                               else 0.0)
        return self._bound_memo[i]

    def _decode_block_scores(self, i: int, need: np.ndarray, b0: int
                             ) -> tuple[np.ndarray, np.ndarray]:
        """Decode only blocks ``need`` (term-relative) of term ``i``
        and score them: ``(docs ascending, contrib)`` — contributions
        elementwise bit-equal to :meth:`_term_scores` values."""
        art = self.artifact
        sel = need + b0
        ids, cnt = art.decode_blocks(sel)
        tfm, _ = art.decode_tf_blocks(sel)
        nbytes = int((art.blk_woff[sel + 1] - art.blk_woff[sel]).sum()) * 4
        self._c_blocks_decoded.inc(len(need))
        self._c_bytes_decoded.inc(nbytes)
        coll = obs_attrib.active()
        if coll is not None:
            coll.decoded(len(need), nbytes)
        mask = np.arange(ids.shape[1])[None, :] < cnt[:, None]
        docs = ids[mask].astype(np.int64)
        tf = tfm[mask].astype(np.float64)
        doc_lens, ndocs, avgdl = self._bm25_corpus()
        k1, b = BM25_K1, BM25_B
        dfi = self._scoring_df(i, int(self._df[i]))
        idf = np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5))
        denom = tf + k1 * (1.0 - b + b * doc_lens[docs] / avgdl)
        return docs, idf * tf * (k1 + 1.0) / denom

    def _top_k_pruned(self, occ: list[int], k: int, mode: str,
                      coll=None) -> list[tuple[int, float]]:
        """MaxScore / Block-Max WAND top-k over the v2.1 bound columns.

        Terms are processed in descending weighted-upper-bound order.
        While the remaining terms' summed bounds can still reach the
        heap threshold theta, a term is *essential*: all its postings
        are admitted as candidates.  Past that point a term can only
        reorder docs already above threshold: candidates that provably
        cannot reach theta are dropped, and (bmw) only blocks whose
        quantized bound clears theta — or that hold a surviving
        candidate — are decoded at all.  Theta is the running k-th best
        partial score, monotonically nondecreasing, and every
        comparison carries ``THETA_MARGIN`` slack so float
        associativity can never prune a true top-k doc.  Survivor
        scores are finally re-accumulated in the query's occurrence
        order — the exhaustive addition order — which makes the
        returned (doc, score) pairs byte-identical to exhaustive
        evaluation.  (Queries with <= 2 scoring occurrences skip that
        rescore: sums of one or two floats are order-independent.)"""
        if k <= 0 or not occ:
            self.planner.note_ranked(mode, 0, 0, 0)
            return []
        margin = planner_mod.THETA_MARGIN
        art = self.artifact
        weight: dict[int, int] = {}
        for i in occ:
            weight[i] = weight.get(i, 0) + 1
        terms = []
        for i, w in weight.items():
            ubs, umax = self._term_bounds(i)
            terms.append((i, float(w), float(w) * umax, ubs))
        terms.sort(key=lambda t: (-t[2], t[0]))
        n = len(terms)
        suffix = [0.0] * (n + 1)
        for p in range(n - 1, -1, -1):
            suffix[p] = suffix[p + 1] + terms[p][2]
        theta = 0.0
        cand = scores = None  # ascending int64 docs + aligned partials
        scored = skipped = 0
        shift = art.block_size.bit_length() - 1
        for pos, (i, w, wu, ubs) in enumerate(terms):
            nb = len(ubs)
            thr = theta * margin
            if theta <= 0.0 or suffix[pos] >= thr:
                # essential: admit every posting of this term
                docs, contrib, srt = self._term_scores(i)
                add = contrib if w == 1.0 else w * contrib
                scored += nb
                if cand is None:
                    cand = docs  # int64 already, never mutated
                    scores = np.array(add, dtype=np.float64)
                    if len(srt) >= k:
                        theta = w * float(srt[k - 1])
                        if coll is not None:
                            coll.theta(theta)
                    continue
                cand, scores = _union_add(cand, scores, docs, add)
            else:
                # non-essential: drop hopeless candidates first
                keep = scores + suffix[pos] >= thr
                cand, scores = cand[keep], scores[keep]
                cached = self._score_memo.get(i)
                if cached is not None:
                    docs, contrib, _ = cached
                    pos2 = np.searchsorted(docs, cand)
                    ok = pos2 < len(docs)
                    ok[ok] = docs[pos2[ok]] == cand[ok]
                    hitpos = pos2[ok]
                    add = contrib[hitpos]
                    if w != 1.0:
                        add = w * add
                    if mode == "bmw":
                        # exact per-doc bounds are available for free:
                        # admit any doc this term alone could still
                        # push past theta
                        live = w * contrib + suffix[pos + 1] >= thr \
                            if w != 1.0 \
                            else contrib + suffix[pos + 1] >= thr
                        live[hitpos] = False
                        new = np.nonzero(live)[0]
                        if len(new):
                            # admit at zero and let the probe below
                            # add the contribution exactly once
                            cand, scores = _union_add(
                                cand, scores, docs[new],
                                np.zeros(len(new)))
                            pos2 = np.searchsorted(docs, cand)
                            ok = pos2 < len(docs)
                            ok[ok] = docs[pos2[ok]] == cand[ok]
                            hitpos = pos2[ok]
                            add = contrib[hitpos]
                            if w != 1.0:
                                add = w * add
                    scores[ok] += add
                    touched = len(np.unique(hitpos >> shift)) \
                        if len(hitpos) else 0
                    scored += touched
                    skipped += nb - touched
                else:
                    b0 = int(art.term_block_off[i])
                    blk = np.searchsorted(art.blk_max[b0:b0 + nb], cand)
                    hitb = blk[blk < nb]
                    if mode == "bmw":
                        seed = np.nonzero(
                            w * ubs + suffix[pos + 1] >= thr)[0]
                        need = np.union1d(hitb, seed)
                    else:
                        need = np.unique(hitb)
                    need = need.astype(np.int64)
                    scored += len(need)
                    skipped += nb - len(need)
                    self._c_blocks_skipped.inc(nb - len(need))
                    if coll is not None:
                        coll.skipped(nb - len(need))
                    if len(need) >= nb:
                        # no block escaped — decode the whole term
                        # through the memoizing path instead (bit-equal
                        # values), so later queries over this term take
                        # the cached arm / the lean small-query path
                        docs, contrib, _ = self._term_scores(i)
                        cand, scores = _union_add(
                            cand, scores, docs,
                            contrib if w == 1.0 else w * contrib)
                    elif len(need):
                        docs, contrib = self._decode_block_scores(
                            i, need, b0)
                        # admitting every decoded doc (a superset of
                        # the candidates) is safe: a doc first seen
                        # here was provably below theta at every
                        # earlier term, so it can only be pruned or
                        # rescored exactly below the k-th best
                        cand, scores = _union_add(
                            cand, scores, docs,
                            contrib if w == 1.0 else w * contrib)
            if len(cand) >= k:
                kth = float(np.partition(
                    scores, len(scores) - k)[len(scores) - k])
                if kth > theta:
                    theta = kth
                    if coll is not None:
                        coll.theta(theta)
        if len(occ) > 2:
            if theta > 0.0:
                keep = scores >= theta * margin
                cand, scores = cand[keep], scores[keep]
            scores = self._rescore(occ, cand)
        self.planner.note_ranked(mode, scored, skipped, len(cand))
        pos3 = scores > 0.0
        cand, scores = cand[pos3], scores[pos3]
        order = np.lexsort((cand, -scores))[:k]
        return [(int(cand[j]), float(scores[j])) for j in order]

    def _rescore(self, occ: list[int], cand: np.ndarray) -> np.ndarray:
        """Re-accumulate the survivors' scores term-by-term in query
        occurrence order — the exhaustive path's float addition order —
        so a pruned 3+-term query returns byte-identical scores even
        though its partial sums were built bound-first."""
        art = self.artifact
        out = np.zeros(len(cand), dtype=np.float64)
        if not len(cand):
            return out
        for i in occ:
            cached = self._score_memo.get(i)
            if cached is not None:
                docs, contrib, _ = cached
            else:
                b0 = int(art.term_block_off[i])
                b1 = int(art.term_block_off[i + 1])
                blk = np.searchsorted(art.blk_max[b0:b1], cand)
                hitb = np.unique(blk[blk < (b1 - b0)]).astype(np.int64)
                if not len(hitb):
                    continue
                docs, contrib = self._decode_block_scores(i, hitb, b0)
            pos = np.searchsorted(docs, cand)
            ok = pos < len(docs)
            ok[ok] = docs[pos[ok]] == cand[ok]
            out[ok] += contrib[pos[ok]]
        return out

    # -- bookkeeping ----------------------------------------------------

    @property
    def cache(self) -> LRUCache:
        return self._cache

    def cache_stats(self) -> dict:
        return self._cache.stats()

    def op_stats(self) -> dict:
        return self._ops.stats()

    def decode_stats(self) -> dict:
        """Skip/decode counters — the gallop win, observable."""
        return {
            "blocks_decoded": self._c_blocks_decoded.value,
            "blocks_skipped": self._c_blocks_skipped.value,
            "bytes_decoded": self._c_bytes_decoded.value,
        }

    def describe(self) -> dict:
        """Engine identity + counters for ``mri query --stats``."""
        return {
            "engine": self.engine_name,
            "format": self.artifact.version,
            "vocab": self.vocab_size,
            "artifact_bytes": self.artifact.nbytes,
            "cache": self.cache_stats(),
            "ops": self.op_stats(),
            "decode": self.decode_stats(),
            "planner": self.planner.describe(),
            "native": {
                "mode": self._native_mode,
                "active": self._native is not None,
                "error": self._native_err,
                "ops": self._c_native_ops.value,
                "fallbacks": self._c_native_fallback.value,
            },
        }

    def close(self) -> None:
        self._close_native()
        self._cache.clear()
        self._tf_cache.clear()
        self._memo.clear()
        self._score_memo.clear()
        self._bound_memo.clear()
        self._occ_memo.clear()
        self._idf_memo.clear()
        self._bm25_cols = None
        self._df = self._keys = self._terms = self._rows = None
        self.artifact.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


#: ``engine="auto"`` routes by a measured batch-size crossover probe
#: (:class:`AutoEngine`) instead of backend name: small batches always
#: serve from the host engine; the first large batch races both
#: engines once and the winner's threshold sticks for the process.
ENGINE_CHOICES = ("host", "device", "auto")
ENGINE_ENV = "MRI_SERVE_ENGINE"
CROSSOVER_ENV = "MRI_SERVE_CROSSOVER"

#: Batches below this never trigger the crossover probe — building the
#: device engine (jit compiles included) is only worth racing when the
#: batch is big enough that the device could plausibly win.
PROBE_BATCH_MIN = 8192

#: BM25 free parameters (README "Format v2": classic defaults).
BM25_K1 = 1.2
BM25_B = 0.75

SCORE_CHOICES = ("df", "bm25")
SCORE_ENV = "MRI_SERVE_SCORE"

NATIVE_ENV = "MRI_SERVE_NATIVE"
NATIVE_CHOICES = ("auto", "0", "1")

# Fast raw-token probe for the native ranked-plan memo: the planner's
# resolve_cached() re-reads $MRI_SERVE_PLANNER every call so mid-session
# flips take effect immediately, and the memo below must invalidate on
# the same signal.  CPython's os.environ backing dict returns the raw
# token without the Environ wrapper's decode layer (~4x cheaper on the
# warm path); fall back to the portable getter when unavailable.
# mrilint: allow(env-knobs) raw-string cache token only; the parse
# still goes through the declared knob via planner.resolve_cached
import os as _os  # noqa: E402

try:
    _PLAN_ENV_DB = _os.environ._data
    _PLAN_ENV_KEY = _os.environ.encodekey(planner_mod.PLANNER_ENV)
    _PLAN_ENV_DB.get(_PLAN_ENV_KEY)
except Exception:  # pragma: no cover - non-CPython environ layout
    _PLAN_ENV_DB, _PLAN_ENV_KEY = None, None


def _planner_raw_token():
    """The raw (undecoded) $MRI_SERVE_PLANNER value, or ``None``."""
    if _PLAN_ENV_DB is not None:
        return _PLAN_ENV_DB.get(_PLAN_ENV_KEY)
    return _os.environ.get(planner_mod.PLANNER_ENV)


def resolve_native(mode: str | None = None) -> str:
    """``auto``/``0``/``1`` (+ $MRI_SERVE_NATIVE default), validated.
    Resolved once per engine; a daemon reload swaps the engine and so
    re-resolves it."""
    mode = mode or envknobs.get(NATIVE_ENV)
    if mode not in NATIVE_CHOICES:
        raise ValueError(
            f"unknown native mode {mode!r} (choices: {NATIVE_CHOICES})")
    return mode


def resolve_score(score: str | None = None) -> str:
    """``df``/``bm25`` (+ MRI_SERVE_SCORE default) -> concrete mode."""
    score = score or envknobs.get(SCORE_ENV)
    if score not in SCORE_CHOICES:
        raise ValueError(
            f"unknown score mode {score!r} (choices: {SCORE_CHOICES})")
    return score


def resolve_engine(engine: str | None = None) -> str:
    """``host``/``device``/``auto`` (+ env override), validated.
    ``auto`` is a real backend now — the crossover router — and is
    returned as itself rather than being resolved to a name here."""
    engine = engine or envknobs.get(ENGINE_ENV) or "auto"
    if engine not in ENGINE_CHOICES:
        raise ValueError(
            f"unknown engine {engine!r} (choices: {ENGINE_CHOICES})")
    return engine


class AutoEngine:
    """Crossover router over both engines.

    Answers every query from the host engine until a batch at least
    ``PROBE_BATCH_MIN`` wide arrives; the first such batch races the
    host and device engines head-to-head and the measured winner fixes
    the routing threshold for the engine's lifetime (``describe()``
    records the probe).  ``$MRI_SERVE_CROSSOVER`` overrides the probe:
    0 pins host, N>0 routes batches >= N to the device engine.  Only
    the batch-shaped single-term ops (df/postings/lookup) route;
    compound and ranked queries stay on the host engine, whose planner
    owns the pruning machinery.
    """

    engine_name = "auto"

    def __init__(self, path, cache_terms: int = 4096,
                 shards: int | None = None):
        self._host = Engine(path, cache_terms=cache_terms)
        self._path = path
        self._cache_terms = cache_terms
        self._shards = shards
        self._device = None
        self._device_failed = False
        cross = envknobs.get(CROSSOVER_ENV)
        self._fixed = None if cross is None else max(int(cross), 0)
        self._measured: int | None = None
        self._probe: dict | None = None

    # -- delegation -----------------------------------------------------

    @property
    def artifact(self):
        return self._host.artifact

    @property
    def vocab_size(self):
        return self._host.vocab_size

    @property
    def metrics(self):
        return self._host.metrics

    @property
    def planner(self):
        return self._host.planner

    @property
    def cache(self):
        return self._host.cache

    def __getattr__(self, name):
        # everything not routing-sensitive answers from the host engine
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._host, name)

    # -- routing --------------------------------------------------------

    def _get_device(self):
        if self._device is None and not self._device_failed:
            try:
                from .device_engine import DeviceEngine
                self._device = DeviceEngine(
                    self._path, cache_terms=self._cache_terms,
                    shards=self._shards)
            except Exception:
                self._device_failed = True
        return self._device

    def _run_probe(self, batch) -> None:
        """Race both engines on this batch, best-of-3 each, once."""
        import time
        dev = self._get_device()
        if dev is None:
            self._measured = 1 << 62
            return
        host_s = dev_s = float("inf")
        for eng in (self._host, dev):
            eng.df(batch)  # warm caches / compile
        for _ in range(3):
            t0 = time.perf_counter()
            self._host.df(batch)
            host_s = min(host_s, time.perf_counter() - t0)
            t0 = time.perf_counter()
            dev.df(batch)
            dev_s = min(dev_s, time.perf_counter() - t0)
        self._measured = len(batch) if dev_s < host_s else 1 << 62
        self._probe = {
            "batch": len(batch),
            "host_s": host_s,
            "device_s": dev_s,
            "winner": "device" if dev_s < host_s else "host",
        }

    def _pick(self, batch):
        n = len(batch)
        if self._fixed is not None:
            if self._fixed > 0 and n >= self._fixed:
                dev = self._get_device()
                if dev is not None:
                    return dev
            return self._host
        if n < PROBE_BATCH_MIN or self._device_failed:
            return self._host
        if self._measured is None:
            self._run_probe(batch)
        if self._measured is not None and n >= self._measured:
            dev = self._get_device()
            if dev is not None:
                return dev
        return self._host

    # -- query API ------------------------------------------------------

    # Every op below is pure routing: the chosen backend times the op
    # and feeds the attribution collector itself, so a second span here
    # would double-count.

    def encode_batch(self, terms):
        return self._host.encode_batch(terms)

    def lookup(self, batch):
        # mrilint: allow(trace) delegation; routed engine attributes
        return self._pick(batch).lookup(batch)

    def df(self, batch):
        # mrilint: allow(trace) delegation; routed engine attributes
        return self._pick(batch).df(batch)

    def postings(self, batch):
        # mrilint: allow(trace) delegation; routed engine attributes
        return self._pick(batch).postings(batch)

    def query_and(self, batch):
        # mrilint: allow(trace) delegation; host engine attributes
        return self._host.query_and(batch)

    def query_or(self, batch):
        # mrilint: allow(trace) delegation; host engine attributes
        return self._host.query_or(batch)

    def top_k(self, letter, k):
        # mrilint: allow(trace) delegation; host engine attributes
        return self._host.top_k(letter, k)

    def top_k_scored(self, batch, k):
        # mrilint: allow(trace) delegation; host engine attributes
        return self._host.top_k_scored(batch, k)

    def top_k_scored_batch(self, batches, k):
        # mrilint: allow(trace) delegation; host engine attributes
        return self._host.top_k_scored_batch(batches, k)

    # -- bookkeeping ----------------------------------------------------

    def describe(self) -> dict:
        d = self._host.describe()
        d["engine"] = self.engine_name
        d["auto"] = {
            "crossover": (self._fixed if self._fixed is not None
                          else self._measured),
            "probe": self._probe,
            "device_ready": self._device is not None,
        }
        return d

    def close(self) -> None:
        if self._device is not None:
            self._device.close()
            self._device = None
        self._host.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def create_engine(path, engine: str | None = None, *,
                  cache_terms: int = 4096, shards: int | None = None):
    """Open ``path`` with the selected backend (:data:`ENGINE_CHOICES`).

    All engines answer the same API byte-identically; ``shards`` only
    applies to the device engine's batch-dimension mesh.
    """
    which = resolve_engine(engine)
    from ..cluster import shard as cluster_shard
    if cluster_shard.has_sidecar(path):
        if which == "device":
            raise artifact_mod.ArtifactError(
                f"{path} is a cluster shard (cluster_shard.json "
                "present): the device engine serves plain artifacts "
                "only (use host or auto, which route to the shard "
                "engine)")
        return cluster_shard.ShardEngine(path, cache_terms=cache_terms)
    if artifact_mod.is_segment_managed(path):
        if which == "device":
            raise artifact_mod.ArtifactError(
                f"{path} is segment-managed: the device engine serves "
                "single artifacts only (use host or auto, which route "
                "to the multi-segment engine)")
        from .multi_engine import MultiSegmentEngine
        return MultiSegmentEngine(path, cache_terms=cache_terms)
    if which == "device":
        from .device_engine import DeviceEngine
        return DeviceEngine(path, cache_terms=cache_terms, shards=shards)
    if which == "auto":
        return AutoEngine(path, cache_terms=cache_terms, shards=shards)
    return Engine(path, cache_terms=cache_terms)
