"""Read side of the index: the ``index.mri`` serving artifact.

The build engines (models/, native/) end at 26 letter files — a
write-only artifact.  This package is the query path: a compact,
memory-mappable columnar artifact packed at emit time
(:mod:`~.artifact`), two byte-identical vectorized query engines over
it — host numpy over mmap views (:mod:`~.engine`) and device-resident
jit/shard_map (:mod:`~.device_engine`, selected via
:func:`create_engine`) — and the LRU hot-term cache the host engine
decodes postings through (:mod:`~.cache`).  ``mri-tpu query`` (cli.py)
and ``tools/bench_serve.py`` sit on top, and :mod:`~.daemon` keeps one
engine resident behind a JSON-lines protocol (``mri-tpu serve``) with
micro-batch coalescing, admission control, deadlines, graceful drain,
and crash-safe hot reload.
"""

from .artifact import ARTIFACT_NAME, ArtifactError, load_artifact
from .daemon import ServeDaemon
from .engine import (ENGINE_CHOICES, AutoEngine, Engine,
                     create_engine, resolve_engine)

__all__ = ["ARTIFACT_NAME", "ArtifactError", "ENGINE_CHOICES",
           "AutoEngine", "Engine",
           "ServeDaemon", "create_engine", "load_artifact",
           "resolve_engine"]
