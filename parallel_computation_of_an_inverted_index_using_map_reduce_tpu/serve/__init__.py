"""Read side of the index: the ``index.mri`` serving artifact.

The build engines (models/, native/) end at 26 letter files — a
write-only artifact.  This package is the query path: a compact,
memory-mappable columnar artifact packed at emit time
(:mod:`~.artifact`), a zero-copy vectorized query engine over it
(:mod:`~.engine`), and the LRU hot-term cache the engine decodes
postings through (:mod:`~.cache`).  ``mri-tpu query`` (cli.py) and
``tools/bench_serve.py`` sit on top.
"""

from .artifact import ARTIFACT_NAME, ArtifactError, load_artifact
from .engine import Engine

__all__ = ["ARTIFACT_NAME", "ArtifactError", "Engine", "load_artifact"]
