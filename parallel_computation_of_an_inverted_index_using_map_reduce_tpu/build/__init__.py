"""Out-of-core build tier: spill-run files + streaming shard merge.

:mod:`.spill` owns the on-disk format (checksummed section files,
atomic writes, quarantine); :mod:`.ooc` owns the numpy merge / emit /
artifact assembly over those files.  The scan/reduce orchestration
lives in ``models/inverted_index.py::_run_cpu_parallel`` — it routes
here when ``MRI_BUILD_SPILL_BYTES`` is set.
"""

from . import ooc, spill  # noqa: F401
