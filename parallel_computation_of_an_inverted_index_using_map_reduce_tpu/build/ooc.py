"""Numpy assembly for the out-of-core build's reduce side.

Three pure stages over :mod:`.spill` containers, all vectorized (no
per-term Python loops):

* :func:`merge_shard` — k-way merge of every run's slice of one
  term-hash shard into lex-sorted terms with doc-ascending postings
  (peak memory O(corpus / shards)).
* :func:`letter_slice` / :func:`emit_order` — pull one letter's terms
  out of every merged shard file and produce the (df desc, word asc)
  emit permutation the letter writers need.
* :func:`lex_concat` + :func:`doc_lengths` — whole-index assembly for
  the artifact packer.

All term comparisons use numpy ``S``-dtype rows NUL-padded to a common
width, which orders identically to the native radix lex sort (both are
bytewise with NUL below every letter).
"""

from __future__ import annotations

import numpy as np

ALPHABET_SIZE = 26


def as_terms(u8rows: np.ndarray, width: int) -> np.ndarray:
    """View a ``(t, w)`` uint8 matrix as ``S{width}`` rows, NUL-padding
    on the right when ``w < width``."""
    width = max(int(width), 1)
    t, w = u8rows.shape
    if w < width:
        padded = np.zeros((t, width), dtype=np.uint8)
        padded[:, :w] = u8rows
        u8rows = padded
    elif w > width:
        raise ValueError(f"term rows wider ({w}) than target ({width})")
    return np.ascontiguousarray(u8rows).reshape(-1).view(f"S{width}")


def terms_to_u8(terms: np.ndarray) -> np.ndarray:
    """Inverse of :func:`as_terms`: ``(t, width)`` uint8 rows."""
    width = terms.dtype.itemsize
    return terms.view(np.uint8).reshape(terms.shape[0], width)


def gather_pairs(order: np.ndarray, src_off: np.ndarray):
    """Pair-gather index for a term permutation.

    Given per-term pair offsets ``src_off`` (``T + 1`` entries) and a
    term permutation ``order``, returns ``(idx, new_off)`` where
    ``pairs[idx]`` lists the pairs in permuted-term order and
    ``new_off`` is the permuted cumulative offset table.
    """
    counts = (src_off[1:] - src_off[:-1])[order]
    new_off = np.zeros(order.shape[0] + 1, dtype=np.int64)
    np.cumsum(counts, out=new_off[1:])
    total = int(new_off[-1])
    if total == 0:
        return np.empty(0, dtype=np.int64), new_off
    idx = (np.arange(total, dtype=np.int64)
           - np.repeat(new_off[:-1], counts)
           + np.repeat(src_off[:-1][order], counts))
    return idx, new_off


def run_shard_slice(reader, shard: int, width: int) -> dict | None:
    """One run's slice of one term-hash shard (terms already lex-sorted
    by the native run pack); ``None`` when the run has no terms there."""
    term_off = reader.meta["shard_term_off"]
    pair_off = reader.meta["shard_pair_off"]
    t0, t1 = int(term_off[shard]), int(term_off[shard + 1])
    if t1 == t0:
        return None
    p0, p1 = int(pair_off[shard]), int(pair_off[shard + 1])
    return {
        "terms": as_terms(reader.read_rows("vocab", t0, t1), width),
        "df": reader.read_rows("df", t0, t1).astype(np.int64, copy=False),
        "postings": reader.read_rows("postings", p0, p1),
        "tf": reader.read_rows("tf", p0, p1),
    }


def merge_shard(readers, shard: int, width: int) -> dict:
    """Merge every run's slice of ``shard`` into one sorted shard.

    Output terms are lex-ascending; each term's postings run is
    doc-ascending with its tf column.  Raises ``ValueError`` on a
    duplicate (term, doc) pair — runs cover disjoint document sets, so
    a collision means a window was double-counted or a run is corrupt.
    """
    width = max(int(width), 1)
    parts = [p for p in (run_shard_slice(r, shard, width) for r in readers)
             if p is not None]
    if not parts:
        return _empty_shard(width)
    terms_cat = np.concatenate([p["terms"] for p in parts])
    df_cat = np.concatenate([p["df"] for p in parts])
    uniq, inv = np.unique(terms_cat, return_inverse=True)
    pair_term = np.repeat(inv, df_cat)
    pair_doc = np.concatenate([p["postings"] for p in parts])
    pair_tf = np.concatenate([p["tf"] for p in parts])
    order = np.lexsort((pair_doc, pair_term))
    pair_term = pair_term[order]
    pair_doc = pair_doc[order]
    pair_tf = pair_tf[order]
    if pair_term.shape[0] > 1:
        dup = (pair_term[1:] == pair_term[:-1]) \
            & (pair_doc[1:] == pair_doc[:-1])
        if dup.any():
            at = int(np.flatnonzero(dup)[0])
            raise ValueError(
                f"duplicate (term, doc) pair in shard {shard}: "
                f"term {bytes(uniq[pair_term[at]])!r} doc "
                f"{int(pair_doc[at])}")
    df = np.bincount(pair_term, minlength=uniq.shape[0]).astype(np.int64)
    offsets = np.zeros(uniq.shape[0] + 1, dtype=np.int64)
    np.cumsum(df, out=offsets[1:])
    u8 = terms_to_u8(uniq)
    return {
        "vocab": u8,
        "word_lens": np.count_nonzero(u8, axis=1).astype(np.int32),
        "df": df,
        "offsets": offsets,
        "postings": pair_doc.astype(np.int32, copy=False),
        "tf": pair_tf.astype(np.int32, copy=False),
        "letter_off": letter_offsets(u8),
        "width": width,
    }


def _empty_shard(width: int) -> dict:
    return {
        "vocab": np.zeros((0, width), dtype=np.uint8),
        "word_lens": np.zeros(0, dtype=np.int32),
        "df": np.zeros(0, dtype=np.int64),
        "offsets": np.zeros(1, dtype=np.int64),
        "postings": np.zeros(0, dtype=np.int32),
        "tf": np.zeros(0, dtype=np.int32),
        "letter_off": np.zeros(ALPHABET_SIZE + 1, dtype=np.int64),
        "width": width,
    }


def letter_offsets(u8rows: np.ndarray) -> np.ndarray:
    """27-entry first-letter offset table over lex-sorted term rows."""
    firsts = u8rows[:, 0] if u8rows.shape[0] else \
        np.zeros(0, dtype=np.uint8)
    off = np.zeros(ALPHABET_SIZE + 1, dtype=np.int64)
    for letter in range(ALPHABET_SIZE):
        off[letter] = np.searchsorted(firsts, ord("a") + letter)
    off[ALPHABET_SIZE] = u8rows.shape[0]
    return off


def letter_slice(shard_file, letter: int, width: int) -> dict | None:
    """One merged shard file's slice of one letter; ``None`` if empty."""
    letter_off = shard_file.section("letter_off")
    t0, t1 = int(letter_off[letter]), int(letter_off[letter + 1])
    if t1 == t0:
        return None
    offs = shard_file.read_rows("offsets", t0, t1 + 1)
    p0, p1 = int(offs[0]), int(offs[-1])
    return {
        "terms": as_terms(shard_file.read_rows("vocab", t0, t1), width),
        "df": shard_file.read_rows("df", t0, t1),
        "offsets": (offs - p0).astype(np.int64, copy=False),
        "postings": shard_file.read_rows("postings", p0, p1),
    }


def concat_letter(parts: list) -> dict:
    """Concatenate per-shard letter slices into lex-sorted letter arrays.

    Shards partition terms by hash, so across shards the slices are
    disjoint; one argsort restores the global lex order.
    """
    terms_cat = np.concatenate([p["terms"] for p in parts])
    df_cat = np.concatenate([p["df"] for p in parts])
    src_off = np.zeros(terms_cat.shape[0] + 1, dtype=np.int64)
    np.cumsum(df_cat, out=src_off[1:])
    postings_cat = np.concatenate([p["postings"] for p in parts])
    lex = np.argsort(terms_cat, kind="stable")
    idx, offsets = gather_pairs(lex, src_off)
    return {
        "terms": terms_cat[lex],
        "df": df_cat[lex],
        "offsets": offsets,
        "postings": postings_cat[idx],
    }


def emit_order(df: np.ndarray) -> np.ndarray:
    """Emit permutation for one letter's lex-sorted terms: df
    descending, ties word-ascending (the reference's line order)."""
    return np.argsort(-df, kind="stable").astype(np.int64)


def doc_lengths(readers, max_doc_id: int) -> np.ndarray:
    """Per-document cleaned token counts from every run's doc section
    (float64, ``max_doc_id + 1`` entries — the artifact's dtype)."""
    lens = np.zeros(int(max_doc_id) + 1, dtype=np.float64)
    for reader in readers:
        ids = reader.section("doc_ids")
        toks = reader.section("doc_tokens")
        if ids.shape[0]:
            np.add.at(lens, ids, toks.astype(np.float64))
    return lens
