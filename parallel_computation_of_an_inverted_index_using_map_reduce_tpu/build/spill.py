"""Checksummed spill files for the out-of-core build.

One container format serves both tiers of the disk pipeline:

* **run files** (``run-wKKK-NNNN.bin``) — one scan worker's term-hash-
  sharded postings runs, flushed whenever the worker's estimated
  postings footprint crosses ``MRI_BUILD_SPILL_BYTES`` (and once more
  at scan end).  Terms are (shard asc, lex asc); every term's postings
  run is doc-ascending with a parallel tf column.
* **shard files** (``shard-NNNN.bin``) — one merged term-hash shard,
  produced by the reduce phase's k-way merge over every run's slice of
  that shard.  Terms are lex-ascending with a 27-entry letter offset
  table so letter emitters can slice without searching.

Layout: ``b"MRISPILL"`` magic, u32 version, u32 header length, a JSON
header (``{"meta": {...}, "sections": {name: {offset, nbytes, dtype,
shape, adler32}}}``), then the raw little-endian array sections.  Every
section carries its own adler32 so a torn or bit-flipped file is caught
up front (:func:`verify_file`) and quarantined (:func:`quarantine`)
instead of corrupting output.  Writes are atomic (tmp + rename) and all
spill state lives under a per-process ``.spill-<pid>`` directory inside
the output dir, so a SIGKILLed build leaves only stale directories that
:func:`clean_stale_dirs` removes on the next run.
"""

from __future__ import annotations

import json
import logging
import os
import signal
from pathlib import Path

import numpy as np

from .. import faults
from ..utils import envknobs
from ..utils.checksum import adler32_hex

log = logging.getLogger("mri.build.spill")

MAGIC = b"MRISPILL"
VERSION = 1
_HEADER_FIXED = len(MAGIC) + 8  # magic + u32 version + u32 header length

RUN_SECTIONS = ("vocab", "word_lens", "df", "offsets", "postings", "tf",
                "doc_ids", "doc_tokens")
SHARD_SECTIONS = ("vocab", "word_lens", "df", "offsets", "postings", "tf",
                  "letter_off")

# module-global run-write counter feeding the MRI_SPILL_KILL_AFTER
# crash hook (mirrors the native MRI_EMIT_KILL_AFTER_LETTERS hook)
_runs_written = 0


class SpillError(RuntimeError):
    """A spill file failed validation (bad magic/header/checksum)."""


def spill_dir(out_dir) -> Path:
    """This process's private spill directory under the output dir."""
    return Path(out_dir) / f".spill-{os.getpid()}"


def clean_stale_dirs(out_dir) -> int:
    """Remove leftover ``.spill-*`` directories from crashed builds.

    Returns the number of directories removed.  Safe to call on every
    run start: live builds only ever touch their own pid-suffixed dir.
    """
    removed = 0
    root = Path(out_dir)
    if not root.is_dir():
        return 0
    for entry in sorted(root.glob(".spill-*")):
        if not entry.is_dir():
            continue
        if entry == spill_dir(out_dir):
            continue
        for child in sorted(entry.iterdir()):
            child.unlink()
        entry.rmdir()
        removed += 1
        log.warning("removed stale spill dir %s", entry)
    return removed


def remove_dir(path) -> None:
    """Best-effort removal of this run's own spill directory."""
    root = Path(path)
    if not root.is_dir():
        return
    for child in sorted(root.iterdir()):
        try:
            child.unlink()
        except OSError:
            pass
    try:
        root.rmdir()
    except OSError:
        pass


def write_file(path, meta: dict, sections: dict[str, np.ndarray]) -> int:
    """Atomically write one spill container; returns bytes written."""
    path = Path(path)
    table = {}
    payloads = []
    for name, arr in sections.items():
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        table[name] = {
            "nbytes": len(raw),
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "adler32": adler32_hex(raw),
        }
        payloads.append(raw)
    # section offsets depend on the header's own encoded length, which
    # in turn depends on the offsets' digit counts — iterate to the
    # fixed point (header length is monotone in the offsets, so this
    # converges in a couple of rounds)
    for name in table:
        table[name]["offset"] = 0
    base = _HEADER_FIXED + len(_encode_header(meta, table))
    for _ in range(8):
        off = base
        for name, raw in zip(table, payloads):
            table[name]["offset"] = off
            off += len(raw)
        header = _encode_header(meta, table)
        if _HEADER_FIXED + len(header) == base:
            break
        base = _HEADER_FIXED + len(header)
    else:  # pragma: no cover - defensive
        raise SpillError(f"unstable spill header encoding for {path}")
    tmp = path.with_name(path.name + ".tmp")
    # mrilint: allow(fault-boundary) atomic tmp+rename publish of build-internal scratch, not corpus I/O; spill-corrupt injects at write_run
    with open(tmp, "wb") as fh:
        fh.write(MAGIC)
        fh.write(int(VERSION).to_bytes(4, "little"))
        fh.write(len(header).to_bytes(4, "little"))
        fh.write(header)
        for raw in payloads:
            fh.write(raw)
        fh.flush()
    # no fsync: spill files are consumed by this same process and a
    # crashed build's stale dir is deleted (never replayed) on rerun,
    # so durability buys nothing — the per-section checksums already
    # catch torn bytes, and fsync-per-run dominated small-budget builds
    os.replace(tmp, path)
    return off


def _encode_header(meta: dict, table: dict) -> bytes:
    return json.dumps({"meta": meta, "sections": table},
                      sort_keys=True).encode()


class SpillFile:
    """Seekable reader over one spill container.

    Parses the header eagerly; section payloads are read on demand so a
    reducer can pull one shard's row range without touching the rest of
    the file (the point of the exercise: reduce memory stays
    O(corpus / shards), not O(corpus)).
    """

    def __init__(self, path):
        self.path = Path(path)
        # mrilint: allow(fault-boundary) build-internal scratch reader; damage surfaces as SpillError -> quarantine + reported skips
        self._fh = open(self.path, "rb")
        try:
            head = self._fh.read(_HEADER_FIXED)
            if len(head) != _HEADER_FIXED or head[:len(MAGIC)] != MAGIC:
                raise SpillError(f"bad spill magic in {self.path}")
            version = int.from_bytes(head[8:12], "little")
            if version != VERSION:
                raise SpillError(
                    f"unsupported spill version {version} in {self.path}")
            hlen = int.from_bytes(head[12:16], "little")
            try:
                header = json.loads(self._fh.read(hlen))
                self.meta = dict(header["meta"])
                self.sections = dict(header["sections"])
            except (ValueError, KeyError, TypeError) as exc:
                raise SpillError(
                    f"bad spill header in {self.path}: {exc}") from None
        except BaseException:
            self._fh.close()
            raise

    def section(self, name: str) -> np.ndarray:
        """Read one full section."""
        info = self.sections[name]
        self._fh.seek(info["offset"])
        raw = self._fh.read(info["nbytes"])
        if len(raw) != info["nbytes"]:
            raise SpillError(f"truncated section {name!r} in {self.path}")
        return np.frombuffer(raw, dtype=np.dtype(info["dtype"])) \
                 .reshape(info["shape"])

    def read_rows(self, name: str, lo: int, hi: int) -> np.ndarray:
        """Read rows ``[lo, hi)`` of a section without reading the rest."""
        info = self.sections[name]
        shape = list(info["shape"])
        row_items = 1
        for dim in shape[1:]:
            row_items *= dim
        itemsize = np.dtype(info["dtype"]).itemsize
        nbytes = (hi - lo) * row_items * itemsize
        self._fh.seek(info["offset"] + lo * row_items * itemsize)
        raw = self._fh.read(nbytes)
        if len(raw) != nbytes:
            raise SpillError(f"truncated section {name!r} in {self.path}")
        return np.frombuffer(raw, dtype=np.dtype(info["dtype"])) \
                 .reshape([hi - lo] + shape[1:])

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def verify_file(path) -> None:
    """Full checksum walk; raises :class:`SpillError` on any damage.

    Reducers call this on every run up front, so a torn spill (crash or
    ``spill-corrupt`` injection) is caught before its bytes can reach a
    letter file or the artifact.
    """
    with SpillFile(path) as sf:
        for name, info in sf.sections.items():
            sf._fh.seek(info["offset"])
            raw = sf._fh.read(info["nbytes"])
            if len(raw) != info["nbytes"]:
                raise SpillError(f"truncated section {name!r} in {path}")
            got = adler32_hex(raw)
            if got != info["adler32"]:
                raise SpillError(
                    f"checksum mismatch in section {name!r} of {path}: "
                    f"{got} != {info['adler32']}")


def quarantine(path) -> Path:
    """Sideline a damaged spill file as ``<name>.corrupt`` (same move
    the checkpoint layer makes) so a rerun can't trip over it."""
    path = Path(path)
    target = path.with_name(path.name + ".corrupt")
    os.replace(path, target)
    log.warning("quarantined corrupt spill file %s -> %s",
                path, target.name)
    return target


def run_path(dir_path, worker: int, run_index: int) -> Path:
    return Path(dir_path) / f"run-w{worker:03d}-{run_index:04d}.bin"


def shard_path(dir_path, shard: int) -> Path:
    return Path(dir_path) / f"shard-{shard:04d}.bin"


def write_run(dir_path, worker: int, run_index: int, pack: dict,
              windows: list) -> tuple[Path, int]:
    """Write one worker's run file from a ``HostIndexStream.runpack``
    dict; returns ``(path, bytes_written)``.

    ``windows`` lists the ``(window_index, doc_lo, doc_hi)`` manifest
    ranges whose documents this run covers — recorded in the header for
    debugging, authoritative in the caller's in-memory slot state (so a
    run whose *header* is torn can still be attributed for skips).
    """
    global _runs_written
    meta = {
        "kind": "run",
        "worker": int(worker),
        "run": int(run_index),
        "shards": int(pack["shard_term_off"].shape[0] - 1),
        "vocab": int(pack["vocab"]),
        "width": int(pack["width"]),
        "pairs": int(pack["pairs"]),
        "docs": int(pack["doc_ids"].shape[0]),
        "max_doc_id": int(pack["max_doc_id"]),
        "raw_tokens": int(pack["raw_tokens"]),
        "windows": [[int(a), int(b), int(c)] for a, b, c in windows],
        "shard_term_off": [int(x) for x in pack["shard_term_off"]],
        "shard_pair_off": [int(x) for x in pack["shard_pair_off"]],
    }
    sections = {
        "vocab": pack["vocab_packed"],
        "word_lens": pack["word_lens"],
        "df": pack["df"],
        "offsets": pack["offsets"],
        "postings": pack["postings"],
        "tf": pack["tf"],
        "doc_ids": pack["doc_ids"],
        "doc_tokens": pack["doc_tokens"],
    }
    path = run_path(dir_path, worker, run_index)
    nbytes = write_file(path, meta, sections)
    inj = faults.active()
    if inj is not None:
        inj.on_spill_written(str(path))
    _runs_written += 1
    kill_after = envknobs.get("MRI_SPILL_KILL_AFTER")
    if kill_after is not None and _runs_written >= kill_after:
        log.warning("MRI_SPILL_KILL_AFTER=%d tripped after %s",
                    kill_after, path.name)
        os.kill(os.getpid(), signal.SIGKILL)
    return path, nbytes


def write_shard(dir_path, shard: int, merged: dict) -> tuple[Path, int]:
    """Write one merged shard file from an ``ooc.merge_shard`` dict."""
    meta = {
        "kind": "shard",
        "shard": int(shard),
        "vocab": int(merged["df"].shape[0]),
        "width": int(merged["width"]),
        "pairs": int(merged["postings"].shape[0]),
    }
    sections = {
        "vocab": merged["vocab"],
        "word_lens": merged["word_lens"],
        "df": merged["df"],
        "offsets": merged["offsets"],
        "postings": merged["postings"],
        "tf": merged["tf"],
        "letter_off": merged["letter_off"],
    }
    path = shard_path(dir_path, shard)
    return path, write_file(path, meta, sections)
