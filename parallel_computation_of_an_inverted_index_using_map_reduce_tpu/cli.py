"""Command-line driver, compatible with the reference's invocation.

Reference: ``./tema1 <num_mappers> <num_reducers> <input_file>``
(main.c:248-255, README.md).  Here the same three positionals work —
outputs a.txt..z.txt land in the CWD by default, exactly like the
reference — plus flags for the TPU-era knobs:

    python -m parallel_computation_of_an_inverted_index_using_map_reduce_tpu \
        4 26 test_small.txt --backend=tpu --output-dir=out --stats
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import faults
from .audit import AuditError, verify_output_dir
from .config import IndexConfig
from .corpus.manifest import read_manifest
from .models.inverted_index import build_index
from .utils import envknobs
from .utils.checkpoint import CheckpointCorrupt

_EPILOG = """\
exit codes:
  0  clean run (output complete and, under --audit, integrity-checked);
     for 'serve': graceful drain completed
  1  serve daemon forced exit (second SIGTERM/SIGINT during drain)
  2  error (bad arguments, I/O failure, integrity/audit failure)
  3  degraded (completed, but skipped unreadable documents or lost
     windows after exhausting retry/respawn budgets; see the
     'degradation' block of --stats)

fault-spec grammar (test/bench only; clauses joined by ';'):
  read-error:doc=2:times=2       transient OSError, first 2 attempts
  read-error:all:times=-1        permanent OSError on every doc
  slow-read:doc=1:ms=50          sleep before the read
  truncate:doc=4:bytes=10        document bytes cut short
  reader-death:window=1          silent reader-thread death
  sigkill:window=2               SIGKILL at stream window boundary
  worker-death:worker=1:window=2 scan worker dies at a window (the
                                 lease/requeue recovery rescans it)
  worker-death:window=2          ... whichever worker scans window 2
  reducer-death:reducer=0        reduce worker dies pre-emit (a
                                 survivor re-emits its letter range)
  scan-error:window=3            native scan failure on window 3
  scan-error:window=3:silent=1   window silently dropped (--audit
                                 catches the corruption)
  handler-crash:req=3            serve daemon: request 3's handler dies
                                 (answered with a counted 'internal')
  client-disconnect:req=2        serve daemon: peer vanishes as
                                 response 2 is written
  slow-client:req=1:ms=200       serve daemon: response write stalls
  reload-corrupt                 serve daemon: next hot reload fails
                                 verification (old artifact keeps
                                 serving, 'reload_rejected' counted)
  dispatcher-hang:ms=500         serve daemon: the dispatch loop wedges
                                 for ms on its next batch (proves the
                                 watchdog: stall event + flight dump +
                                 healthz readiness flip)
  append-torn-manifest           segments: the staged manifest is torn
                                 mid-publish — the append aborts and
                                 the old generation keeps serving
  compact-crash                  segments: crash after the merged
                                 segment is built, before the swap
  tombstone-corrupt              segments: staged tombstone bitmap
                                 corrupted; the write is rejected
  wal-torn-record                segments: the WAL append tears before
                                 its fsync — the mutation is rejected
                                 un-acked; recover quarantines the
                                 torn tail bytes
  fetch-partial                  replication: one shipped segment file
                                 is truncated in flight — the replica's
                                 adler32 check rejects it and refetches
  lease-steal                    replication: a foreign owner grabs the
                                 mutation lease — the next mutation is
                                 rejected 'lease_lost' until the TTL
                                 expires
  shard-dead:shard=0             router: the next send to shard 0
                                 hits a reset connection (failover
                                 retries another replica)
  shard-slow:shard=1:ms=50       router: sends to shard 1 stall 50 ms
                                 (the hedge path's test hook)
  router-conn-reset:req=3        router: client connection 3 is reset
                                 mid-stream (exactly-once: admitted
                                 requests still answer or count)
  shard-blackout:shard=0         router: EVERY send to shard 0 dies,
                                 all replicas, permanently — drives
                                 the partial-result/breaker paths
  overload-storm:req=8:times=16  daemon: requests 8..23 shed with a
                                 typed 'overloaded' answer (synthetic
                                 sustained overload for admission-
                                 control soaks)
  chaos:seed=5:n=3               sample 3 faults deterministically
                                 (bounds: windows= workers= reducers=
                                 docs= reqs= kinds=a,b,c)

verify mode:
  mri-tpu --verify DIR           re-check DIR's letter files (and
                                 index.mri, when present) against its
                                 index.manifest.json (written by
                                 --audit runs); a segment-managed DIR
                                 additionally re-hashes every live
                                 segment + tombstone file against
                                 segments.manifest.json; exit 0 ok,
                                 2 mismatch

incremental indexing (live index; see README "Incremental indexing"):
  mri-tpu append DIR --add F...  index new files as one immutable
                                 segment and publish the next manifest
                                 generation (first append seeds the
                                 manifest from DIR's index.mri)
  mri-tpu delete DIR --docs N... tombstone global doc ids (query-
                                 invisible at once; space reclaimed at
                                 compaction)
  mri-tpu compact DIR            k-way merge the cheapest adjacent
                                 segment run into one replacement
                                 segment, dropping its tombstones
  mri-tpu compact DIR --prune    also delete retired segment dirs no
                                 longer referenced by the manifest
                                 (only safe with no live readers on
                                 older generations)

durability & replication (see README "Durability & replication"):
  mri-tpu recover DIR            replay the mutation WAL after a crash:
                                 acknowledged-but-unpublished records
                                 are applied, torn tail records land in
                                 segments.wal.corrupt, mutation scratch
                                 is swept; idempotent (a primary daemon
                                 runs this on every start)
  mri-tpu replicate DIR --from HOST:PORT
                                 one catch-up round against a primary
                                 daemon: snapshot diff, adler32-verified
                                 segment fetches, WAL tail adoption —
                                 never re-indexes
  mri-tpu serve DIR --replica-of HOST:PORT
                                 run a read-only replica: catches up
                                 every MRI_REPLICA_POLL_MS ms, rejects
                                 mutations, healthz says
                                 'replica_lagging' until the first
                                 round lands; promote by stopping it
                                 and running 'mri-tpu recover DIR'

query mode (the serving read path; needs an --artifact build):
  mri-tpu query DIR word...          df + postings per word (JSON lines)
  mri-tpu query DIR --batch-file F   one query word per line (an empty
                                 file is an empty batch: exit 0, no
                                 output)
  mri-tpu query DIR --op and w1 w2   docs containing every word
  mri-tpu query DIR --op or  w1 w2   docs containing any word
  mri-tpu query DIR --top-k 5 --letter t   the letter's 5 highest-df
                                 terms (== head -5 DIR/t.txt)
  mri-tpu query DIR --score bm25 --top-k 5 w1 w2   the 5 best-scoring
                                 docs for the words (BM25: tf + df +
                                 doc-length norm; format-v2 artifacts
                                 carry real tf, v1 scores with tf=1)
  mri-tpu query DIR --engine device  answer from the device-resident
                                 jit/shard_map engine (--engine auto,
                                 the default, picks it on accelerator
                                 backends); byte-identical to host
  a missing/torn index.mri exits 2 with one line on stderr, never
  garbage answers

serve mode (resident daemon; loads the artifact ONCE):
  mri-tpu serve DIR --listen 127.0.0.1:7070
                                 JSON-lines protocol over TCP — one
                                 request object per line, one response
                                 line back; ops df/postings/and/or/
                                 top_k plus stats/healthz/reload;
                                 pending requests coalesce into micro-
                                 batches for the vectorized batch path
                                 (MRI_SERVE_COALESCE_US window); the
                                 pending queue is bounded (MRI_SERVE_
                                 QUEUE_DEPTH) with counted 'overloaded'
                                 shedding, requests may carry
                                 deadline_ms ('deadline_expired' when
                                 missed before dispatch); SIGTERM/
                                 SIGINT = graceful drain then exit 0
                                 (second signal forces exit 1); SIGHUP
                                 = crash-safe hot reload of index.mri
                                 (a failed verification keeps the old
                                 artifact and counts reload_rejected)

cluster mode (doc-sharded scale-out; see README "Cluster serving"):
  mri-tpu shard LIST --shards 4 --out DIR [--mode size-balanced]
                                 partition the corpus into 4 doc-
                                 shards under DIR/shard-N, build each
                                 with the unchanged --artifact path,
                                 and stamp global BM25 stats into
                                 per-shard sidecars; --verify byte-
                                 checks every per-shard manifest
  mri-tpu serve DIR/shard-N --listen ...   a shard daemon is a plain
                                 serve daemon over the shard dir (the
                                 sidecar makes it answer global ids)
  mri-tpu router --shards h:1|h:2,h:3 --listen HOST:PORT
                                 scatter-gather front-end: same JSON-
                                 lines protocol, data ops fan out and
                                 gather (D-way ranked merge); '|'
                                 joins replicas of one shard — hedged
                                 requests (MRI_CLUSTER_HEDGE_MS) and
                                 failover ride per-replica health
                                 probes (MRI_CLUSTER_HEALTH_MS);
                                 answers are byte-identical to one
                                 monolithic daemon over the same
                                 corpus, BM25 floats included
  mri-tpu top ROUTER:PORT        fleet view: the router's stats carry
                                 per-shard replica health rows with
                                 circuit-breaker state and per-shard
                                 partial-coverage readiness
  degraded serving: requests may carry partial_policy 'fail' (default:
                                 a dead shard is a typed
                                 shard_unavailable error) or
                                 'allow:min_coverage=F' (answer from
                                 the live shards, flagged partial:true
                                 with coverage metadata);
                                 MRI_CLUSTER_PARTIAL sets the router
                                 default, MRI_CLUSTER_RETRY_BUDGET
                                 bounds retry/hedge amplification,
                                 MRI_SERVE_CODEL_TARGET_MS arms CoDel
                                 admission control in shard daemons

metrics mode (Prometheus text exposition; obs/ registry):
  mri-tpu metrics DIR            open DIR's artifact, print the engine
                                 registry in Prometheus text format
  mri-tpu metrics HOST:PORT      ask a running serve daemon (the
                                 'metrics' admin op) and print its text
  mri-tpu serve DIR --listen-metrics PORT
                                 daemon also serves the same text over
                                 plain HTTP on 127.0.0.1:PORT (a scrape
                                 endpoint; 0 = ephemeral)

top mode (operational health; see README "Operational health"):
  mri-tpu top HOST:PORT          live dashboard over a running daemon's
                                 stats/slo/healthz admin ops: rolling
                                 qps + latency quantiles (10s/1m/5m),
                                 SLO ratios and burn rates, readiness
                                 with reasons; redraws every --interval
                                 seconds, Ctrl-C exits 0
  mri-tpu top HOST:PORT --once --json   one machine-readable sample
                                 (scripting and parity checks)
  mri-tpu top DIR                one static engine metrics snapshot of
                                 a built artifact (nothing rolls
                                 without a daemon)
"""


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mri-tpu",
        description="TPU-native inverted-index MapReduce",
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("num_mappers", type=int,
                   help="host shard count (reference mapper threads; "
                        "backend=cpu scan workers; output-invariant)")
    p.add_argument("num_reducers", type=int,
                   help="reduce partition count (reference reducer threads; "
                        "backend=cpu letter-range reduce workers; "
                        "output-invariant)")
    p.add_argument("file_list", help="manifest: count header then one path per line")
    p.add_argument("--backend", choices=("tpu", "cpu", "oracle"), default="tpu",
                   help="tpu: device engine; cpu: one native host call; "
                        "oracle: pure-Python conformance backend")
    p.add_argument("--output-dir", default=".", help="where a.txt..z.txt are written (default: CWD)")
    p.add_argument("--pad-multiple", type=int, default=1 << 16)
    p.add_argument("--checkpoint", default=None,
                   help="save/resume the tokenized map-phase pairs at this path")
    p.add_argument("--profile-dir", default=None, help="write a jax.profiler trace here")
    p.add_argument("--stats", action="store_true", help="print a JSON stats line to stdout")
    p.add_argument("--skew", action="store_true",
                   help="also measure letter vs hash-bucket partition skew on device")
    p.add_argument("--stream-chunk-docs", type=int, default=None,
                   help="streaming mode: window size in whole documents "
                        "(bounded host/device memory; default: one-shot)")
    p.add_argument("--pipeline-chunk-docs", type=int, default=None,
                   help="pipelined fast path: documents per upload window "
                        "(default: auto, two windows; 0 = one-shot engine)")
    p.add_argument("--device-tokenize", action="store_true",
                   help="all-device engine: raw corpus bytes up, finished "
                        "index down (the whole map phase as one XLA program; "
                        "single chip; exact, with host fallback for tokens "
                        "longer than --device-tokenize-width)")
    p.add_argument("--device-tokenize-width", type=int, default=48,
                   help="device word-row bytes (multiple of 4)")
    p.add_argument("--device-shards", type=int, default=None,
                   help="mesh size: shard the device engine over this many "
                        "chips (default: all visible devices; 1 = single "
                        "chip)")
    p.add_argument("--overlap-tail-fraction", type=float, default=None,
                   help="windowed overlap plan: this fraction of corpus "
                        "bytes (the last doc range) is indexed on host "
                        "while earlier windows' device sorts + fetches fly "
                        "in the background (single chip; hides link RTT)")
    p.add_argument("--overlap-device-windows", type=int, default=2,
                   choices=(1, 2),
                   help="overlap plan device windows: 2 = earliest first "
                        "fetch, 1 = half the dispatch RPCs")
    p.add_argument("--overlap-window-split", type=float, default=0.55,
                   help="first device window's share of the overlap "
                        "plan's device bytes; larger shrinks the LAST "
                        "window and with it the residual fetch wait")
    p.add_argument("--stream-checkpoint", default=None,
                   help="crash-resumable streaming (single-chip "
                        "--device-tokenize --stream-chunk-docs "
                        "--device-shards 1): persist the verified "
                        "accumulator here; a rerun of the same command "
                        "resumes at the last checkpointed window")
    p.add_argument("--stream-checkpoint-every", type=int, default=2,
                   help="windows between stream checkpoints")
    p.add_argument("--host-threads", type=int, default=None,
                   help="host map-phase threads — backend=cpu scan workers "
                        "pulling windows from a shared steal queue "
                        "(default: num_mappers if > 1, else min(cores, 8)); "
                        "output-invariant")
    p.add_argument("--emit-ownership", choices=("merged", "letter"),
                   default="merged",
                   help="merged: one host writes all 26 files; letter: "
                        "multi-chip owners emit their own letter ranges "
                        "(the reference's reducer ownership, multi-host mode)")
    p.add_argument("--emit-backend", choices=("auto", "native", "python"),
                   default="auto",
                   help="letter-file writer: auto = native vectorized emit "
                        "when available, python = the pure-Python parity "
                        "oracle; byte-identical either way")
    p.add_argument("--io-prefetch", type=int, default=2,
                   help="backend=cpu read-ahead depth: window arenas the "
                        "reader thread keeps filled while the native scan "
                        "runs (0 = one-shot load, no pipeline)")
    p.add_argument("--resume", choices=("strict", "auto"), default="strict",
                   help="checkpoint-trust policy: strict = a corrupt "
                        "checkpoint is a hard error; auto = quarantine it "
                        "to <path>.corrupt and restart fresh (crash-safe "
                        "rerun after SIGKILL mid-save)")
    p.add_argument("--fault-spec", default=None,
                   help="arm the deterministic fault injector (faults.py "
                        "grammar, e.g. 'read-error:doc=2:times=2' or "
                        "'worker-death:window=2;chaos:seed=5:n=3'; also "
                        f"readable from ${faults.ENV_VAR}) — test/bench "
                        "only, never needed for production runs")
    p.add_argument("--artifact", action="store_true",
                   help="also pack the compact mmap serving artifact "
                        "(index.mri) next to the letter files at emit "
                        "time — the read path 'mri-tpu query' and "
                        "serve.Engine load (serve/artifact.py format)")
    p.add_argument("--audit", action="store_true",
                   help="integrity audit: per-window feed ledger + merge "
                        "invariant checks before emit, and an "
                        "index.manifest.json output manifest (per-file "
                        "adler32) after it; audit failures exit 2, never "
                        "silently wrong bytes")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="write a Chrome trace_event JSON timeline of the "
                        "build here (reader/scan/reduce/merge spans; load "
                        "in chrome://tracing or ui.perfetto.dev)")
    return p


def _query_main(argv: list[str]) -> int:
    """``mri-tpu query DIR ...`` — serve from an --artifact build."""
    p = argparse.ArgumentParser(
        prog="mri-tpu query",
        description="batched lookups against a built index.mri artifact")
    p.add_argument("index_dir", help="output dir of an --artifact run "
                                     "(or the index.mri file itself)")
    p.add_argument("terms", nargs="*", help="query words")
    p.add_argument("--batch-file", default=None,
                   help="read query words from this file, one per line")
    p.add_argument("--op", choices=("and", "or"), default=None,
                   help="combine ALL query words into one multi-term "
                        "query instead of answering each separately")
    p.add_argument("--top-k", type=int, default=None, metavar="K",
                   help="df mode: the K highest-df terms of --letter's "
                        "range; bm25 mode (--score bm25): the K best-"
                        "scoring documents for the query words")
    p.add_argument("--letter", default=None,
                   help="letter for --top-k (a..z)")
    p.add_argument("--score", choices=("df", "bm25"), default=None,
                   help="--top-k scoring mode: df = per-letter highest-"
                        "df terms (today's behavior), bm25 = ranked "
                        "document retrieval over the query words (tf + "
                        "df + doc-length norm; needs a v2 artifact for "
                        "real tf, v1 scores with tf=1). Default: "
                        "MRI_SERVE_SCORE env, else df")
    p.add_argument("--engine", choices=("host", "device", "auto"),
                   default=None,
                   help="query backend: host = numpy over mmap views; "
                        "device = jit/shard_map over device-resident "
                        "columns (batched lookups sharded across "
                        "chips); auto = device when jax's default "
                        "backend is an accelerator, else host "
                        "(default: MRI_SERVE_ENGINE env, else auto). "
                        "Answers are byte-identical either way")
    p.add_argument("--stats", action="store_true",
                   help="print an engine stats JSON line last (engine/"
                        "shard info, cache hit/miss/eviction counters, "
                        "per-op timing)")
    p.add_argument("--explain", action="store_true",
                   help="print a per-request cost report JSON line "
                        "after the answers: per-term df and resolution "
                        "path, planner decision with its theta "
                        "progression, blocks scored/skipped, bytes "
                        "decoded, cache hits/misses (per segment on a "
                        "segment-managed dir)")
    # intermixed: ``query DIR --op and the dog`` must not feed "the dog"
    # back into --op's greedy positional scan.
    args = p.parse_intermixed_args(argv)

    from .serve import ArtifactError, create_engine
    from .serve.engine import resolve_score

    try:
        score = resolve_score(args.score)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    terms = list(args.terms)
    if args.batch_file is not None:
        try:
            # mrilint: allow(fault-boundary) operator-supplied batch file, not corpus I/O; OSError maps to exit 2 below
            with open(args.batch_file, "r", encoding="utf-8") as f:
                terms.extend(line.strip() for line in f if line.strip())
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if args.top_k is None and not terms:
        # an empty --batch-file is a valid (empty) batch: answer it
        # with no output, exit 0 — only a missing query is an error
        if args.batch_file is not None:
            return 0
        print("error: no query terms (positional words, --batch-file, "
              "or --top-k with --letter)", file=sys.stderr)
        return 2
    ranked = args.top_k is not None and score == "bm25"
    if args.top_k is not None and not ranked and args.letter is None:
        print("error: --top-k needs --letter (or --score bm25 with "
              "query terms)", file=sys.stderr)
        return 2
    if ranked and not terms:
        print("error: --score bm25 --top-k needs query terms",
              file=sys.stderr)
        return 2
    try:
        engine = create_engine(args.index_dir, args.engine)
    except (ArtifactError, ValueError) as e:
        # ValueError covers construction-time knob reads (KnobError,
        # e.g. a bad $MRI_SERVE_NATIVE) — same one-line exit-2
        # contract the lazily-read knobs get from the query guard
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.explain:
        from .obs import attribution as obs_attrib
        if ranked:
            explain_op = "top_k_scored"
        elif args.top_k is not None:
            explain_op = "top_k"
        elif args.op is not None:
            explain_op = f"query_{args.op}"
        else:
            explain_op = "df+postings"
        explain_cm = obs_attrib.collect(explain_op)
    else:
        explain_cm = None
    try:
        coll = explain_cm.__enter__() if explain_cm is not None else None
        if ranked:
            top = engine.top_k_scored(engine.encode_batch(terms),
                                      args.top_k)
            print(json.dumps({
                "score": "bm25", "k": args.top_k, "terms": terms,
                "docs": [{"doc": d, "score": round(s, 6)}
                         for d, s in top]}))
        elif args.top_k is not None:
            top = engine.top_k(args.letter, args.top_k)
            print(json.dumps({
                "letter": args.letter,
                "top": [{"term": t.decode("ascii"), "df": d}
                        for t, d in top]}))
        if terms and not ranked and args.op is not None:
            batch = engine.encode_batch(terms)
            docs = (engine.query_and(batch) if args.op == "and"
                    else engine.query_or(batch))
            print(json.dumps({"op": args.op, "terms": terms,
                              "docs": docs.tolist()}))
        elif terms and not ranked:
            batch = engine.encode_batch(terms)
            dfs = engine.df(batch)
            posts = engine.postings(batch)
            for term, d, ids in zip(terms, dfs.tolist(), posts):
                print(json.dumps({
                    "term": term, "found": ids is not None, "df": d,
                    "postings": ids.tolist() if ids is not None else []}))
        if coll is not None:
            explain_cm.__exit__(None, None, None)
            explain_cm = None
            print(json.dumps({"explain": coll.report()}))
        if args.stats:
            print(json.dumps(engine.describe()))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        if explain_cm is not None:
            explain_cm.__exit__(None, None, None)
        engine.close()
    return 0


def _serve_main(argv: list[str]) -> int:
    """``mri-tpu serve DIR --listen HOST:PORT`` — the resident daemon
    (serve/daemon.py).  Blocks until drained by SIGTERM/SIGINT."""
    import signal
    import threading

    p = argparse.ArgumentParser(
        prog="mri-tpu serve",
        description="resident JSON-lines query daemon over a built "
                    "index.mri artifact (see the main --help epilog "
                    "for the protocol and signal semantics)")
    p.add_argument("index_dir", help="output dir of an --artifact run "
                                     "(or the index.mri file itself)")
    p.add_argument("--listen", default="127.0.0.1:0", metavar="HOST:PORT",
                   help="bind address (port 0 = ephemeral; the chosen "
                        "port is printed in the 'listening' JSON line)")
    p.add_argument("--engine", choices=("host", "device", "auto"),
                   default=None,
                   help="query backend (same choices as 'query')")
    p.add_argument("--cache-terms", type=int, default=4096,
                   help="hot-term LRU capacity (host engine)")
    p.add_argument("--shards", type=int, default=None,
                   help="device engine mesh size")
    p.add_argument("--fault-spec", default=None,
                   help="arm the deterministic fault injector "
                        "(serve kinds: handler-crash/client-disconnect/"
                        "slow-client/reload-corrupt/dispatcher-hang) "
                        "— test/bench only")
    p.add_argument("--listen-metrics", type=int, default=None,
                   metavar="PORT",
                   help="also serve Prometheus text metrics over plain "
                        "HTTP on 127.0.0.1:PORT (0 = ephemeral; the "
                        "chosen port is printed in the 'listening' line)")
    p.add_argument("--replica-of", default=None, metavar="HOST:PORT",
                   help="run as a read-only replica of the primary "
                        "daemon at HOST:PORT: catch up by segment "
                        "shipping every MRI_REPLICA_POLL_MS, reject "
                        "mutations, report replica_lagging in healthz "
                        "until the first round succeeds")
    args = p.parse_args(argv)

    # the daemon is the one long-lived process: route every mri_tpu.*
    # logger through the structured obs funnel (MRI_OBS_LOG_FORMAT).
    # NOT done for in-process embedding (ServeDaemon.start()) — a host
    # application owns its own logging tree.
    from .obs import logging as obs_logging
    obs_logging.configure()

    if args.fault_spec is not None:
        try:
            faults.install(args.fault_spec)
        except faults.FaultSpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    host, _, port_s = args.listen.rpartition(":")
    try:
        port = int(port_s)
        if not host or not (0 <= port <= 65535):
            raise ValueError
    except ValueError:
        print(f"error: --listen must be HOST:PORT, got {args.listen!r}",
              file=sys.stderr)
        return 2

    from .serve import ArtifactError
    from .serve.daemon import ServeDaemon

    if args.listen_metrics is not None and not (
            0 <= args.listen_metrics <= 65535):
        print(f"error: --listen-metrics must be 0..65535, got "
              f"{args.listen_metrics}", file=sys.stderr)
        return 2

    from . import segments
    if args.replica_of is not None:
        try:
            segments.replica.parse_addr(args.replica_of)
        except segments.SegmentError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    try:
        # resolved before the daemon exists so a bad value is the
        # one-line exit-2 knob contract, not a traceback mid-serve
        gc_freeze = envknobs.get("MRI_SERVE_GC_FREEZE")
        # construction runs startup WAL recovery (primaries) before the
        # first engine open — a torn directory rejects here, exit 2
        daemon = ServeDaemon(args.index_dir, host, port,
                             engine=args.engine,
                             cache_terms=args.cache_terms,
                             shards=args.shards,
                             metrics_port=args.listen_metrics,
                             replica_of=args.replica_of)
    except (ArtifactError, segments.SegmentError, ValueError,
            OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        daemon.start()
    except OSError as e:
        print(f"error: cannot listen on {args.listen}: {e}",
              file=sys.stderr)
        return 2

    if gc_freeze:
        # The startup heap (interpreter, imports, engine) is permanent;
        # without this, request churn — an admission-shed storm runs
        # tens of thousands of allocations a second — schedules full
        # cyclic-GC passes whose stop-the-world scan of that heap
        # lands as multi-ms spikes in OTHER tenants' tail latency.
        # Freeze it so every future pass scans only the churn.  CLI
        # path only: an embedding application owns its own collector.
        import gc
        gc.collect()
        gc.freeze()

    stop = threading.Event()

    def _on_stop_signal(signum, frame):
        if stop.is_set():
            # second signal: the drain is not fast enough for the
            # operator — documented forced exit, code 1
            # mrilint: allow(exit-code) the one sanctioned exit-1 path
            os._exit(1)
        stop.set()

    def _on_hup(signum, frame):
        # reload off the signal frame AND off the dispatcher: open +
        # verify happen on this throwaway thread, only the engine swap
        # touches the dispatch lock
        threading.Thread(target=daemon.reload, name="mri-serve-reload",
                         daemon=True).start()

    def _on_quit(signum, frame):
        # SIGQUIT = dump the flight recorder and keep serving: the
        # file write runs on a throwaway thread, off the signal frame
        threading.Thread(target=daemon.dump_flight, args=("sigquit",),
                         name="mri-serve-flight", daemon=True).start()

    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, _on_stop_signal)
        signal.signal(signal.SIGINT, _on_stop_signal)
        signal.signal(signal.SIGHUP, _on_hup)
        signal.signal(signal.SIGQUIT, _on_quit)

    bound_host, bound_port = daemon.address
    listening = {"event": "listening", "host": bound_host,
                 "port": bound_port, "pid": os.getpid(),
                 "engine": daemon._engine.engine_name}
    if daemon.metrics_address is not None:
        listening["metrics_port"] = daemon.metrics_address[1]
    print(json.dumps(listening), flush=True)
    try:
        while not stop.is_set():
            stop.wait(0.2)
        rc = daemon.drain()
    except Exception:
        # unexpected serve crash: preserve the black box before the
        # traceback takes the process down
        daemon.dump_flight("crash")
        raise
    print(json.dumps({"event": "drained",
                      "counters": daemon.final_stats["counters"]},
                     sort_keys=True), flush=True)
    return rc


def _shard_main(argv: list[str]) -> int:
    """``mri-tpu shard SRC --shards D --out DIR`` — partition a corpus
    into D buildable doc-shards with global-BM25 sidecars
    (cluster/partition.py)."""
    p = argparse.ArgumentParser(
        prog="mri-tpu shard",
        description="partition a corpus manifest into D doc-shards, "
                    "build each with the unchanged --artifact path, "
                    "and stamp global BM25 stats into per-shard "
                    "sidecars so a router over the shards answers "
                    "byte-identically to a monolithic build")
    p.add_argument("file_list", help="source corpus manifest (count "
                                     "header then one path per line)")
    p.add_argument("--shards", type=int, required=True, metavar="D",
                   help="number of doc-shards (1 <= D <= corpus size)")
    p.add_argument("--out", required=True, metavar="DIR",
                   help="cluster directory; shard s builds into "
                        "DIR/shard-s")
    p.add_argument("--mode", choices=("round-robin", "size-balanced"),
                   default="round-robin",
                   help="doc assignment: round-robin by manifest "
                        "position (default) or greedy size-balanced "
                        "over file bytes")
    p.add_argument("--mappers", type=int, default=1,
                   help="per-shard build mapper count")
    p.add_argument("--reducers", type=int, default=2,
                   help="per-shard build reducer count")
    p.add_argument("--verify", action="store_true",
                   help="after building (or against an existing DIR), "
                        "byte-verify every per-shard manifest and gid "
                        "map against the recomputed assignment")
    p.add_argument("--verify-only", action="store_true",
                   help="skip the build; just verify DIR")
    args = p.parse_args(argv)

    from .cluster import partition as part_mod
    try:
        if not args.verify_only:
            cluster = part_mod.partition(
                args.file_list, args.shards, args.out,
                mode=args.mode, mappers=args.mappers,
                reducers=args.reducers,
                progress=lambda msg: print(
                    json.dumps({"event": "progress", "detail": msg}),
                    flush=True))
            print(json.dumps({"event": "partitioned", **cluster},
                             sort_keys=True), flush=True)
        if args.verify or args.verify_only:
            summary = part_mod.verify(args.file_list, args.out)
            print(json.dumps({"event": "verified", **summary},
                             sort_keys=True), flush=True)
    except part_mod.PartitionError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def _router_main(argv: list[str]) -> int:
    """``mri-tpu router --shards SPEC --listen HOST:PORT`` — the
    scatter-gather front-end over shard daemons (cluster/router.py).
    Blocks until drained by SIGTERM/SIGINT, mirroring 'serve'."""
    import signal
    import threading

    p = argparse.ArgumentParser(
        prog="mri-tpu router",
        description="scatter-gather router over doc-shard serve "
                    "daemons: same JSON-lines protocol as 'serve', "
                    "data ops fan out to every shard and gather "
                    "through a D-way merge; hedged requests and "
                    "replica failover ride shard health")
    p.add_argument("--shards", required=True, metavar="SPEC",
                   help="shard endpoints: shards joined by ',', "
                        "replicas of one shard joined by '|' — "
                        "'h:1|h:2,h:3' is two shards, the first with "
                        "two replicas")
    p.add_argument("--listen", default="127.0.0.1:0", metavar="HOST:PORT",
                   help="bind address (port 0 = ephemeral; the chosen "
                        "port is printed in the 'listening' JSON line)")
    p.add_argument("--hedge-ms", type=float, default=None,
                   help="hedge delay: -1 adaptive shard p95 (default, "
                        "from MRI_CLUSTER_HEDGE_MS), 0 off, >0 fixed ms")
    p.add_argument("--fault-spec", default=None,
                   help="arm the deterministic fault injector "
                        "(cluster kinds: shard-dead/shard-slow/"
                        "router-conn-reset/shard-blackout/"
                        "overload-storm) — test/bench only")
    args = p.parse_args(argv)

    from .obs import logging as obs_logging
    obs_logging.configure()

    if args.fault_spec is not None:
        try:
            faults.install(args.fault_spec)
        except faults.FaultSpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    from .cluster import router as router_mod
    try:
        shard_addrs = router_mod.parse_shard_arg(args.shards)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    host, _, port_s = args.listen.rpartition(":")
    try:
        port = int(port_s)
        if not host or not (0 <= port <= 65535):
            raise ValueError
    except ValueError:
        print(f"error: --listen must be HOST:PORT, got {args.listen!r}",
              file=sys.stderr)
        return 2

    try:
        router = router_mod.RouterDaemon(shard_addrs, host, port,
                                         hedge_ms=args.hedge_ms)
    except ValueError as e:
        # covers construction-time knob reads (KnobError, e.g. a bad
        # $MRI_CLUSTER_HEDGE_MS) — the one-line exit-2 contract
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        router.start()
    except OSError as e:
        print(f"error: cannot listen on {args.listen}: {e}",
              file=sys.stderr)
        return 2

    stop = threading.Event()

    def _on_stop_signal(signum, frame):
        if stop.is_set():
            # mrilint: allow(exit-code) the one sanctioned exit-1 path
            os._exit(1)
        stop.set()

    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, _on_stop_signal)
        signal.signal(signal.SIGINT, _on_stop_signal)

    bound_host, bound_port = router.address
    print(json.dumps({"event": "listening", "host": bound_host,
                      "port": bound_port, "pid": os.getpid(),
                      "shards": len(shard_addrs),
                      "replicas": [len(r) for r in shard_addrs]}),
          flush=True)
    while not stop.is_set():
        stop.wait(0.2)
    rc = router.drain()
    print(json.dumps({"event": "drained",
                      "counters": router.final_stats["counters"]},
                     sort_keys=True), flush=True)
    return rc


def _metrics_main(argv: list[str]) -> int:
    """``mri-tpu metrics TARGET`` — Prometheus text exposition.

    TARGET is either a running daemon's HOST:PORT (asks it via the
    'metrics' admin op) or an --artifact output dir / index.mri path
    (opens a throwaway engine and prints its registry)."""
    import socket

    p = argparse.ArgumentParser(
        prog="mri-tpu metrics",
        description="print Prometheus text-format metrics from a "
                    "running serve daemon (HOST:PORT) or a built "
                    "artifact (DIR)")
    p.add_argument("target", help="serve daemon HOST:PORT, or the "
                                  "output dir of an --artifact run")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="daemon connect/read timeout in seconds")
    args = p.parse_args(argv)

    host, _, port_s = args.target.rpartition(":")
    is_addr = bool(host) and port_s.isdigit() and int(port_s) <= 65535
    if is_addr and not os.path.exists(args.target):
        try:
            # mrilint: allow(fault-boundary) operator scrape RPC, not corpus I/O; OSError maps to exit 2 below
            with socket.create_connection((host, int(port_s)),
                                          timeout=args.timeout) as sock:
                sock.sendall(b'{"op": "metrics", "id": 1}\n')
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
        except OSError as e:
            print(f"error: cannot reach daemon at {args.target}: {e}",
                  file=sys.stderr)
            return 2
        try:
            resp = json.loads(buf)
        except ValueError:
            print(f"error: bad response from {args.target}",
                  file=sys.stderr)
            return 2
        if not resp.get("ok"):
            print(f"error: daemon refused metrics: "
                  f"{resp.get('error', 'unknown')}", file=sys.stderr)
            return 2
        sys.stdout.write(resp.get("text", ""))
        return 0

    from .serve import ArtifactError, create_engine
    try:
        engine = create_engine(args.target, None)
    except ArtifactError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        sys.stdout.write(engine.metrics.render_text())
    finally:
        engine.close()
    return 0


def _flightdump_main(argv: list[str]) -> int:
    """``mri-tpu flightdump HOST:PORT`` — pull a running daemon's
    flight recorder (last N completed request cost-reports + slow
    offenders) as one JSON document, without waiting for a crash."""
    import socket

    p = argparse.ArgumentParser(
        prog="mri-tpu flightdump",
        description="dump a running serve daemon's flight recorder "
                    "(bounded ring of recent request cost-reports, "
                    "MRI_OBS_FLIGHT_RING) as one JSON document")
    p.add_argument("target", metavar="HOST:PORT",
                   help="a running serve daemon's protocol address")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="also write the dump to this file (stdout "
                        "always gets the JSON)")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="daemon connect/read timeout in seconds")
    args = p.parse_args(argv)

    host, _, port_s = args.target.rpartition(":")
    if not (host and port_s.isdigit() and int(port_s) <= 65535):
        print(f"error: target must be HOST:PORT, got {args.target!r}",
              file=sys.stderr)
        return 2
    try:
        # mrilint: allow(fault-boundary) operator RPC, not corpus I/O; OSError maps to exit 2 below
        with socket.create_connection((host, int(port_s)),
                                      timeout=args.timeout) as sock:
            sock.sendall(b'{"op": "flightdump", "id": 1}\n')
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(65536)
                if not chunk:
                    break
                buf += chunk
    except OSError as e:
        print(f"error: cannot reach daemon at {args.target}: {e}",
              file=sys.stderr)
        return 2
    try:
        resp = json.loads(buf)
    except ValueError:
        print(f"error: bad response from {args.target}", file=sys.stderr)
        return 2
    if not resp.get("ok"):
        print(f"error: daemon refused flightdump: "
              f"{resp.get('error', 'unknown')}", file=sys.stderr)
        return 2
    text = json.dumps(resp.get("flight", {}), sort_keys=True)
    print(text)
    if args.out is not None:
        try:
            # mrilint: allow(fault-boundary) operator-chosen output file; OSError maps to exit 2 below
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(text + "\n")
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    return 0


def _top_sample(addr: tuple, timeout: float) -> dict:
    """One dashboard poll: ``healthz`` + ``stats`` + ``slo`` pipelined
    over a single daemon connection, matched back up by request id."""
    import socket

    reqs = (b'{"op":"healthz","id":1}\n'
            b'{"op":"stats","id":2}\n'
            b'{"op":"slo","id":3}\n')
    by_id: dict = {}
    # mrilint: allow(fault-boundary) operator dashboard RPC, not corpus I/O; callers map OSError to exit 2
    with socket.create_connection(addr, timeout=timeout) as sock:
        sock.sendall(reqs)
        # mrilint: allow(fault-boundary) response framing on the same operator RPC
        f = sock.makefile("rb")
        try:
            for _ in range(3):
                line = f.readline()
                if not line:
                    break
                resp = json.loads(line)
                by_id[resp.get("id")] = resp
        finally:
            f.close()
    health = dict(by_id.get(1, {}))
    health.pop("id", None)
    return {
        "healthz": health,
        "stats": by_id.get(2, {}).get("stats", {}),
        "slo": by_id.get(3, {}).get("slo", {}),
    }


def _top_num(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def _top_render(target: str, sample: dict) -> str:
    """One plain-text dashboard frame over a poll's sample."""
    h = sample.get("healthz") or {}
    st = sample.get("stats") or {}
    slo = sample.get("slo") or {}
    ready = "ready" if h.get("ready") else "NOT READY"
    reasons = ",".join(h.get("reasons") or []) or "-"
    counters = st.get("counters") or {}
    lines = [
        f"mri top — {target} — {ready} ({h.get('status', '?')})",
        f"queue_depth={st.get('queue_depth', h.get('queue_depth', 0))}"
        f"  inflight={st.get('inflight', 0)}"
        f"  connections={st.get('connections', 0)}"
        f"  reasons={reasons}",
        "",
        f"{'window':<8}{'qps':>12}{'shed/s':>10}{'err/s':>10}"
        f"{'p50 ms':>10}{'p99 ms':>10}",
    ]
    rolling = st.get("rolling") or {}
    for label in ("10s", "1m", "5m"):
        w = rolling.get(label) or {}
        lines.append(f"{label:<8}{_top_num(w.get('qps')):>12}"
                     f"{_top_num(w.get('shed_per_s')):>10}"
                     f"{_top_num(w.get('error_per_s')):>10}"
                     f"{_top_num(w.get('p50_ms')):>10}"
                     f"{_top_num(w.get('p99_ms')):>10}")
    tenants = st.get("tenants") or {}
    if tenants:
        # per-tenant QoS slice, all from the same single stats poll:
        # admission vs shed, cache absorption, live lane depth, 1m
        # tail latency and the worst 1m SLO burn
        lines.append("")
        lines.append(f"{'tenant':<12}{'wt':>4}{'rate':>8}"
                     f"{'admitted':>10}{'shed':>8}{'hits':>8}"
                     f"{'depth':>7}{'p95 ms':>10}{'burn 1m':>9}")
        for name in sorted(tenants):
            t = tenants[name] or {}
            admitted = (t.get("requests", 0) or 0) \
                - (t.get("shed", 0) or 0)
            burns = [b for b in (t.get("burn_1m") or {}).values()
                     if isinstance(b, (int, float))]
            rate = t.get("rate_rps")
            lines.append(
                f"{name:<12}{_top_num(t.get('weight')):>4}"
                f"{('-' if rate is None else f'{rate:g}'):>8}"
                f"{admitted:>10}{_top_num(t.get('shed')):>8}"
                f"{_top_num(t.get('cache_hits')):>8}"
                f"{_top_num(t.get('queue_depth')):>7}"
                f"{_top_num(t.get('p95_ms')):>10}"
                f"{_top_num(max(burns) if burns else None):>9}")
    for name in sorted(slo):
        entry = slo[name] or {}
        head = f"slo {name} (target {entry.get('target')}"
        if entry.get("threshold_ms") is not None:
            head += f", <= {entry['threshold_ms']} ms"
        lines.append("")
        lines.append(head + ")")
        lines.append(f"  {'window':<8}{'ratio':>12}{'burn':>10}"
                     f"{'events':>10}")
        for label in ("10s", "1m", "5m"):
            pt = (entry.get("windows") or {}).get(label) or {}
            lines.append(f"  {label:<8}"
                         f"{_top_num(pt.get('ratio')):>12}"
                         f"{_top_num(pt.get('burn')):>10}"
                         f"{_top_num(pt.get('total')):>10}")
    cluster = st.get("cluster") or {}
    if cluster.get("shards"):
        # router target: one fleet row per replica, all from the same
        # single pipelined stats poll — no extra connections
        lines.append("")
        lines.append(f"{'shard':<8}{'replica':<22}{'state':<10}"
                     f"{'breaker':<11}{'p95 ms':>10}  reasons")
        answerable = 0
        for sh in cluster["shards"]:
            p95 = sh.get("p95_ms")
            reps = sh.get("replicas") or []
            if any(r.get("ready")
                   and r.get("breaker", "closed") != "open"
                   for r in reps):
                answerable += 1
            for rep in reps:
                state = "ready" if rep.get("ready") else "DOWN"
                if rep.get("primary"):
                    state += "*"
                why = ",".join(rep.get("reasons") or []) or "-"
                lines.append(
                    f"{sh.get('shard', '?'):<8}"
                    f"{rep.get('addr', '?'):<22}{state:<10}"
                    f"{rep.get('breaker', 'closed'):<11}"
                    f"{_top_num(p95):>10}  {why}")
        # degraded-serving readiness: a shard can answer (and so count
        # toward partial coverage) while any replica is ready with a
        # breaker still admitting traffic
        nshards = len(cluster["shards"])
        cov_line = (f"coverage: {answerable}/{nshards} shards "
                    f"answerable")
        if answerable < nshards:
            cov_line += "  [DEGRADED]"
        cov_line += (f"  partial_default="
                     f"{cluster.get('partial_default') or 'fail'}"
                     f"  breakers_open="
                     f"{cluster.get('breakers_open', 0)}")
        lines.append(cov_line)
    lines.append("")
    nonzero = "  ".join(f"{k}={v}" for k, v in counters.items() if v)
    lines.append("counters: " + (nonzero or "-"))
    return "\n".join(lines) + "\n"


def _top_static(args) -> int:
    """``mri-tpu top DIR`` — one static engine metrics snapshot of a
    built artifact.  Nothing rolls without a daemon, so there is no
    live refresh in this mode."""
    from .serve import ArtifactError, create_engine
    try:
        engine = create_engine(args.target, None)
    except ArtifactError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        desc = engine.describe()
        text = engine.metrics.render_text()
    finally:
        engine.close()
    if args.as_json:
        print(json.dumps({"engine": desc, "metrics_text": text},
                         sort_keys=True))
    else:
        print(f"mri top — {args.target} (static artifact snapshot)")
        print(json.dumps(desc, sort_keys=True))
        sys.stdout.write(text)
    return 0


def _top_main(argv: list[str]) -> int:
    """``mri-tpu top TARGET`` — the live operational-health dashboard.

    HOST:PORT polls a running daemon's ``stats``/``slo``/``healthz``
    admin ops and redraws every ``--interval`` seconds (Ctrl-C exits
    0); ``--once --json`` prints one machine-readable sample — the
    mode scripts and the parity test consume.  DIR prints one static
    engine snapshot."""
    import time as time_mod

    p = argparse.ArgumentParser(
        prog="mri-tpu top",
        description="live operational-health dashboard for a running "
                    "serve daemon (HOST:PORT — rolling rates, latency "
                    "quantiles, SLO burn, readiness) or one static "
                    "metrics snapshot of a built artifact (DIR)")
    p.add_argument("target", help="serve daemon HOST:PORT, or the "
                                  "output dir of an --artifact run")
    p.add_argument("--interval", type=float, default=1.0,
                   help="refresh period in seconds (live mode)")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (no screen clear)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (implies --once)")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="daemon connect/read timeout in seconds")
    args = p.parse_args(argv)
    once = args.once or args.as_json

    host, _, port_s = args.target.rpartition(":")
    is_addr = bool(host) and port_s.isdigit() and int(port_s) <= 65535
    if not is_addr or os.path.exists(args.target):
        return _top_static(args)

    addr = (host, int(port_s))
    try:
        while True:
            try:
                sample = _top_sample(addr, args.timeout)
            except (OSError, ValueError) as e:
                print(f"error: cannot poll daemon at {args.target}: "
                      f"{e}", file=sys.stderr)
                return 2
            if args.as_json:
                print(json.dumps(sample, sort_keys=True))
            else:
                if not once:
                    sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                sys.stdout.write(_top_render(args.target, sample))
                sys.stdout.flush()
            if once:
                return 0
            time_mod.sleep(max(0.05, args.interval))
    except KeyboardInterrupt:
        return 0


def _segments_main(cmd: str, argv: list[str]) -> int:
    """``mri-tpu append|delete|compact DIR ...`` — incremental indexing.

    Mutations are serialized under the segments lock and published by
    atomic manifest swap: readers on the old generation are never
    disturbed, and a failed mutation leaves the old manifest live."""
    p = argparse.ArgumentParser(
        prog=f"mri-tpu {cmd}",
        description={
            "append": "index new files as one immutable segment and "
                      "publish the next manifest generation",
            "delete": "tombstone global doc ids (query-invisible "
                      "immediately; space reclaimed at compaction)",
            "compact": "merge the cheapest adjacent segment run into "
                       "one replacement segment, dropping tombstones",
        }[cmd])
    p.add_argument("index_dir", help="an --artifact output dir (first "
                                     "append seeds segments/ from its "
                                     "index.mri)")
    if cmd == "append":
        p.add_argument("--add", nargs="+", required=True, metavar="FILE",
                       help="text files to index as the new segment")
    elif cmd == "delete":
        p.add_argument("--docs", nargs="+", required=True, type=int,
                       metavar="ID", help="global doc ids to tombstone")
    else:
        p.add_argument("--force", action="store_true",
                       help="compact even below the "
                            "MRI_SEGMENT_COMPACT_TRIGGER segment count")
        p.add_argument("--prune", action="store_true",
                       help="after compacting, delete retired segment "
                            "dirs no longer referenced by the manifest "
                            "(unsafe while readers hold old generations)")
    p.add_argument("--fault-spec", default=None,
                   help="inject faults (see mri-tpu --help for grammar)")
    args = p.parse_args(argv)

    if args.fault_spec is not None:
        try:
            faults.install(args.fault_spec)
        except faults.FaultSpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    from . import segments
    try:
        if cmd == "append":
            missing = [f for f in args.add if not os.path.exists(f)]
            if missing:
                print(f"error: input files do not exist: {missing}",
                      file=sys.stderr)
                return 2
            res = segments.append_files(args.index_dir, args.add)
        elif cmd == "delete":
            res = segments.delete_docs(args.index_dir, args.docs)
        else:
            res = segments.compact(args.index_dir, force=args.force)
        print(json.dumps(res, sort_keys=True))
        if cmd == "compact" and args.prune:
            pruned = segments.prune_retired(args.index_dir)
            print(json.dumps({"pruned": pruned}, sort_keys=True))
    except segments.SegmentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except faults.InjectedCompactCrash as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def _recover_main(argv: list[str]) -> int:
    """``mri-tpu recover DIR`` — roll a live index directory forward to
    the last acknowledged mutation (segments/wal.py): replay WAL
    records above the manifest's generation, quarantine torn tail
    records, sweep mutation scratch.  Idempotent; also runs implicitly
    when a primary daemon starts."""
    p = argparse.ArgumentParser(
        prog="mri-tpu recover",
        description="replay the mutation WAL after a crash: apply "
                    "acknowledged-but-unpublished records, quarantine "
                    "torn tail records, remove mutation scratch")
    p.add_argument("index_dir", help="a live (segment-managed) index "
                                     "directory")
    p.add_argument("--fault-spec", default=None,
                   help="inject faults (see mri-tpu --help for grammar)")
    args = p.parse_args(argv)
    if args.fault_spec is not None:
        try:
            faults.install(args.fault_spec)
        except faults.FaultSpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    from . import segments
    try:
        report = segments.recover(args.index_dir)
    except segments.SegmentError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report, sort_keys=True))
    return 0


def _replicate_main(argv: list[str]) -> int:
    """``mri-tpu replicate DIR --from HOST:PORT`` — one catch-up round
    against a primary daemon (segments/replica.py): fetch the segment
    files this directory is missing (adler32-verified, staged, then
    atomically adopted) plus the primary's WAL tail.  Never re-indexes.
    Run it in a loop — or use ``mri-tpu serve --replica-of`` — for a
    live replica."""
    p = argparse.ArgumentParser(
        prog="mri-tpu replicate",
        description="catch a local index directory up to a primary "
                    "daemon by segment shipping (snapshot diff + "
                    "verified fetch + WAL tail adoption)")
    p.add_argument("index_dir", help="the replica's index directory "
                                     "(created if empty)")
    p.add_argument("--from", dest="source", required=True,
                   metavar="HOST:PORT",
                   help="the primary daemon's --listen address")
    p.add_argument("--timeout", type=float, default=30.0,
                   help="per-RPC socket timeout in seconds")
    p.add_argument("--fault-spec", default=None,
                   help="inject faults (see mri-tpu --help for grammar)")
    args = p.parse_args(argv)
    if args.fault_spec is not None:
        try:
            faults.install(args.fault_spec)
        except faults.FaultSpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    from . import segments
    try:
        addr = segments.replica.parse_addr(args.source)
        res = segments.replicate(args.index_dir, addr,
                                 timeout=args.timeout)
    except (segments.SegmentError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps(res, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    # --verify DIR / query DIR / serve DIR / metrics TARGET are
    # standalone modes (no reference positionals): pre-parse them so
    # 'mri-tpu --verify out/' and 'mri-tpu query out/ word' work
    # without dummy mapper counts.
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "query":
        return _query_main(argv[1:])
    if argv and argv[0] == "serve":
        return _serve_main(argv[1:])
    if argv and argv[0] == "shard":
        return _shard_main(argv[1:])
    if argv and argv[0] == "router":
        return _router_main(argv[1:])
    if argv and argv[0] == "metrics":
        return _metrics_main(argv[1:])
    if argv and argv[0] == "flightdump":
        return _flightdump_main(argv[1:])
    if argv and argv[0] == "top":
        return _top_main(argv[1:])
    if argv and argv[0] in ("append", "delete", "compact"):
        return _segments_main(argv[0], argv[1:])
    if argv and argv[0] == "recover":
        return _recover_main(argv[1:])
    if argv and argv[0] == "replicate":
        return _replicate_main(argv[1:])
    if "--verify" in argv:
        i = argv.index("--verify")
        if i + 1 >= len(argv):
            print("error: --verify needs an output directory",
                  file=sys.stderr)
            return 2
        ok, problems = verify_output_dir(argv[i + 1])
        for line in problems:
            print(f"verify: {line}", file=sys.stderr)
        if ok:
            print(f"verify: {argv[i + 1]} matches its index manifest")
        return 0 if ok else 2
    args = make_parser().parse_args(argv)
    # Satellite: validate the reference positionals up front with ONE
    # clear line on stderr — not an IndexConfig traceback, not a
    # confusing manifest parse error three layers down.
    if args.num_mappers < 1:
        print(f"error: num_mappers must be >= 1, got {args.num_mappers}",
              file=sys.stderr)
        return 2
    if args.num_reducers < 1:
        print(f"error: num_reducers must be >= 1, got {args.num_reducers}",
              file=sys.stderr)
        return 2
    if not os.path.exists(args.file_list):
        print(f"error: input list {args.file_list!r} does not exist",
              file=sys.stderr)
        return 2
    if args.fault_spec is not None:
        try:
            faults.install(args.fault_spec)
        except faults.FaultSpecError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    try:
        manifest = read_manifest(args.file_list)
        config = IndexConfig(
            num_mappers=args.num_mappers,
            num_reducers=args.num_reducers,
            backend=args.backend,
            output_dir=args.output_dir,
            pad_multiple=args.pad_multiple,
            checkpoint_path=args.checkpoint,
            profile_dir=args.profile_dir,
            collect_skew_stats=args.skew,
            stream_chunk_docs=args.stream_chunk_docs,
            pipeline_chunk_docs=args.pipeline_chunk_docs,
            overlap_tail_fraction=args.overlap_tail_fraction,
            overlap_device_windows=args.overlap_device_windows,
            overlap_window_split=args.overlap_window_split,
            device_tokenize=args.device_tokenize,
            device_tokenize_width=args.device_tokenize_width,
            device_shards=args.device_shards,
            stream_checkpoint=args.stream_checkpoint,
            stream_checkpoint_every=args.stream_checkpoint_every,
            host_threads=args.host_threads,
            emit_ownership=args.emit_ownership,
            emit_backend=args.emit_backend,
            io_prefetch=args.io_prefetch,
            resume=args.resume,
            audit=args.audit,
            artifact=args.artifact,
            trace_out=args.trace_out,
        )
        stats = build_index(manifest, config)
    except AuditError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except (OSError, ValueError, CheckpointCorrupt) as e:
        # Covers RetryPolicy.from_env too: a bad MRI_READ_* value is a
        # one-line configuration error, not a worker-thread traceback.
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.stats:
        print(json.dumps(stats, sort_keys=True))
    degradation = stats.get("degradation") or {}
    skipped = degradation.get("skipped_docs") or []
    if skipped:
        print(f"warning: completed DEGRADED — skipped {len(skipped)} "
              f"unreadable document(s) (doc ids {sorted(skipped)}); "
              f"exit {faults.EXIT_DEGRADED}", file=sys.stderr)
        return faults.EXIT_DEGRADED
    return 0


if __name__ == "__main__":
    sys.exit(main())
