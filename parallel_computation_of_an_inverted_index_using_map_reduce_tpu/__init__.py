"""TPU-native inverted-index MapReduce framework.

A ground-up re-design of the capabilities of
rares46/Parallel-Computation-Of-An-Inverted-Index-Using-Map-Reduce
(reference: /root/reference/main.c, a pthread fork-join MapReduce) as an
idiomatic JAX/XLA pipeline:

- host frontend: corpus manifest + vectorized tokenizer + sorted vocab
  (reference map phase, main.c:85-124)
- device engine: ``lax.sort`` over packed (term, doc) pairs, boundary
  unique, segmented document-frequency reduction, emit-order sort
  (reference reduce phase, main.c:126-242)
- host emit: byte-identical ``<letter>.txt`` postings files
  (format of main.c:227-234)
- multi-chip (``parallel/``): ``shard_map`` over a 1-D mesh with a
  hash-bucket ``all_to_all`` shuffle replacing the reference's 26 spill
  files (main.c:332-341)

Import alias: ``import mri_tpu`` re-exports this package.
"""

__version__ = "0.1.0"

from .config import IndexConfig
from .corpus.manifest import Manifest, read_manifest, write_manifest, manifest_from_dir
from .text.tokenizer import TokenizedCorpus, tokenize_corpus, clean_token
from .models.inverted_index import InvertedIndexModel, build_index
from .models.oracle import oracle_index

__all__ = [
    "IndexConfig",
    "Manifest",
    "read_manifest",
    "write_manifest",
    "manifest_from_dir",
    "TokenizedCorpus",
    "tokenize_corpus",
    "clean_token",
    "InvertedIndexModel",
    "build_index",
    "oracle_index",
    "__version__",
]
