"""Integrity audit for the fault-tolerant host path (``--audit`` /
``--verify``).

Recovery code has a failure mode worse than crashing: producing a
*plausible but wrong* index.  A worker death mishandled by one window
drops that window's postings silently — every letter file still parses,
df counts still look sane, and nothing downstream notices.  This module
makes that class of bug loud, in three layers:

:class:`WindowLedger`
    Per-window accounting at feed time: which worker scanned which
    global window, how many docs/bytes, and an adler32 checksum of the
    exact arrays handed to the native scan.  A dead worker's entries
    are discarded with its native handle (the windows come back via the
    steal queue), so at merge time the ledger must hold *exactly* one
    live entry per planned window — a silently dropped or doubly-fed
    window fails :meth:`~WindowLedger.check_complete` naming the window.

:func:`check_merge`
    Merge invariants before emit, O(pairs) in C++
    (``mri_hidxm_audit``): per-term df sums must equal the summed
    worker run lengths, and every run must be strictly ascending; plus
    Python-side cross-checks of pair totals and vocab-union
    cardinality against the per-worker scan stats.

:func:`write_output_manifest` / :func:`verify_output_dir`
    ``index.manifest.json`` next to the letter files — per-file adler32
    + size (the same checksum the per-window ledger uses: ~10x md5's
    speed on this container, which keeps the manifest write inside the
    run's <5 %-of-e2e audit budget; byte-exact conformance
    fingerprinting stays ``formatter.letters_md5``'s job)
    — and the re-check the CLI exposes as ``--verify DIR``, so any
    consumer can prove an output directory is exactly what the run
    emitted.

All failures raise :class:`AuditError` (the CLI maps it to exit 2):
an integrity violation must never exit 0 or 3.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from pathlib import Path

from .text import formatter

#: Written next to a.txt..z.txt by ``--audit`` runs; read by ``--verify``.
MANIFEST_NAME = "index.manifest.json"


class AuditError(RuntimeError):
    """An integrity invariant failed — the output cannot be trusted."""


def window_checksum(buf, ends, ids) -> int:
    """adler32 over one window's bytes + doc structure — cheap enough
    to run per window in the scan loop (the <5 %% audit budget), strong
    enough to catch a wrong-window or torn-arena feed."""
    c = zlib.adler32(buf)
    c = zlib.adler32(ends, c)
    return zlib.adler32(ids, c)


class WindowLedger:
    """Thread-safe which-worker-fed-which-window accounting.

    Scan workers :meth:`record` after each successful native feed;
    the recovery layer :meth:`discard_worker` when a worker dies (its
    native handle — and thus its windows' postings — die with it);
    :meth:`check_complete` is the pre-merge gate.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[int, dict] = {}   # window -> live entry
        self._dups: list[int] = []            # double-fed live windows
        self._dead: set = set()               # discarded workers

    def record(self, window_index: int, *, worker, docs: int,
               nbytes: int, checksum: int) -> None:
        with self._lock:
            if worker in self._dead:
                return  # zombie feed after retirement: already requeued
            prev = self._entries.get(window_index)
            if prev is not None:
                self._dups.append(window_index)
            self._entries[window_index] = {
                "worker": worker, "docs": int(docs),
                "bytes": int(nbytes), "checksum": int(checksum),
            }

    def discard_worker(self, worker) -> int:
        """Forget everything ``worker`` fed (called with its native
        handle's discard); returns how many entries were dropped."""
        with self._lock:
            self._dead.add(worker)
            drop = [wi for wi, e in self._entries.items()
                    if e["worker"] == worker]
            for wi in drop:
                del self._entries[wi]
            self._dups = [wi for wi in self._dups if wi in self._entries]
            return len(drop)

    def check_complete(self, num_windows: int,
                       missing_ok=()) -> None:
        """Every planned window 1..num_windows must have exactly one
        live entry, except those in ``missing_ok`` (windows the run
        already reported as skipped — the degraded arm).  Raises
        :class:`AuditError` naming the offending windows."""
        allowed = set(missing_ok)
        with self._lock:
            missing = [wi for wi in range(1, num_windows + 1)
                       if wi not in self._entries and wi not in allowed]
            dups = sorted(set(self._dups))
        if missing:
            raise AuditError(
                f"audit: window {', '.join(map(str, missing))} of "
                f"{num_windows} never reached the native scan — "
                "postings silently dropped")
        if dups:
            raise AuditError(
                f"audit: window {', '.join(map(str, dups))} fed to the "
                "scan more than once — postings double-counted")

    def summary(self) -> dict:
        with self._lock:
            return {
                "windows": len(self._entries),
                "docs": sum(e["docs"] for e in self._entries.values()),
                "bytes": sum(e["bytes"] for e in self._entries.values()),
            }


def check_merge(merge, streams) -> None:
    """Merge invariants before any reducer emits (``--audit``).

    ``merge`` is a native ``HostIndexMerge`` over ``streams`` (the live
    workers' ``HostIndexStream`` handles).  The native walk proves df
    sums and per-run monotonicity; the Python side cross-checks the
    scan totals the merge folded.
    """
    rc, bad_term = merge.audit()
    if rc == 1:
        raise AuditError(
            f"audit: merged df of global term {bad_term} does not equal "
            "the sum of its worker run lengths — a worker's postings "
            "were lost or double-merged")
    if rc == 2:
        raise AuditError(
            f"audit: posting run of global term {bad_term} is not "
            "strictly ascending — window postings interleaved wrongly")
    if rc != 0:
        raise AuditError(f"audit: native merge walk failed (rc={rc})")
    infos = [s.info() for s in streams]
    mstats = merge.stats()
    pairs = sum(i["pairs"] for i in infos)
    if pairs != mstats["unique_pairs"]:
        raise AuditError(
            f"audit: merge folded {mstats['unique_pairs']} (term, doc) "
            f"pairs but the workers scanned {pairs}")
    vocab = mstats["unique_terms"]
    lo = max((i["vocab"] for i in infos), default=0)
    hi = sum(i["vocab"] for i in infos)
    if not lo <= vocab <= hi:
        raise AuditError(
            f"audit: merged vocab {vocab} outside the union bounds "
            f"[{lo}, {hi}] of the worker vocabularies")


def check_spill(run_pairs: int, merged_pairs: int, run_vocab_hi: int,
                merged_vocab: int) -> None:
    """Spill-tier merge invariants (``--audit``, out-of-core path).

    The disk tier's analogue of :func:`check_merge`: every (term, doc)
    pair written to a verified run must come back out of the per-shard
    k-way merge exactly once (per-term ascending order and pair
    uniqueness are enforced inside the merge itself), and the merged
    vocabulary can't exceed the sum of the runs' vocabularies.
    """
    if run_pairs != merged_pairs:
        raise AuditError(
            f"audit: shard merge folded {merged_pairs} (term, doc) "
            f"pairs but the spill runs hold {run_pairs}")
    if merged_vocab > run_vocab_hi:
        raise AuditError(
            f"audit: merged vocab {merged_vocab} exceeds the sum "
            f"{run_vocab_hi} of the spill runs' vocabularies")


def letter_checksums(out_dir) -> dict[str, tuple[str, int]]:
    """``{filename: (adler32_hex, size_bytes)}`` for a.txt..z.txt, plus
    the ``index.mri`` serving artifact when the run packed one — a torn
    artifact must fail ``--verify`` exactly like a torn letter file."""
    out_dir = Path(out_dir)
    out: dict[str, tuple[str, int]] = {}
    for letter in range(26):
        name = formatter.letter_filename(letter)
        data = (out_dir / name).read_bytes()
        out[name] = (f"{zlib.adler32(data):08x}", len(data))
    from .serve import artifact as artifact_mod

    art = out_dir / artifact_mod.ARTIFACT_NAME
    if art.exists():
        out[artifact_mod.ARTIFACT_NAME] = artifact_mod.checksum(art)
    return out


def write_output_manifest(out_dir, extra: dict | None = None) -> dict:
    """Write ``index.manifest.json`` (atomic tmp+rename) with per-file
    adler32 + size for a.txt..z.txt; returns the manifest dict."""
    out_dir = Path(out_dir)
    files = {name: {"adler32": crc, "bytes": size}
             for name, (crc, size) in
             letter_checksums(out_dir).items()}
    doc = {"version": 1, "files": files}
    if extra:
        doc.update(extra)
    tmp = out_dir / (MANIFEST_NAME + ".tmp")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n",
                   encoding="utf-8")
    os.replace(tmp, out_dir / MANIFEST_NAME)
    return doc


def verify_segments(out_dir) -> list[str]:
    """Re-hash every live segment artifact + tombstone file against
    ``segments.manifest.json`` (whose own body checksum gates the walk).
    Returns a problem list — empty when clean OR when the directory was
    never segment-managed."""
    from . import segments
    from .serve import artifact as artifact_mod

    out_dir = Path(out_dir)
    try:
        man = segments.load_manifest(out_dir)
    except segments.SegmentError as e:
        return [str(e)]
    if man is None:
        return []
    problems: list[str] = []
    for entry in man.entries:
        sdir = segments.segment_dir(out_dir, entry.name)
        art = sdir / artifact_mod.ARTIFACT_NAME
        try:
            crc, size = artifact_mod.checksum(art)
        except OSError as e:
            problems.append(f"{art}: {e}")
        else:
            if crc != entry.adler32 or size != entry.bytes:
                problems.append(
                    f"{art}: checksum mismatch (manifest "
                    f"{entry.adler32}/{entry.bytes}B, on disk "
                    f"{crc}/{size}B)")
        if entry.tombstones is None:
            continue
        tpath = sdir / entry.tombstones
        try:
            data = tpath.read_bytes()
        except OSError as e:
            problems.append(f"{tpath}: {e}")
            continue
        crc = f"{zlib.adler32(data):08x}"
        if crc != entry.tomb_adler32 or len(data) != entry.tomb_bytes:
            problems.append(
                f"{tpath}: checksum mismatch (manifest "
                f"{entry.tomb_adler32}/{entry.tomb_bytes}B, on disk "
                f"{crc}/{len(data)}B)")
    return problems


def verify_output_dir(out_dir) -> tuple[bool, list[str]]:
    """Re-hash ``out_dir`` against its ``index.manifest.json`` and — for
    a segment-managed directory — its ``segments.manifest.json``.

    Returns ``(ok, problems)`` — problems is a human-readable list of
    every mismatch/missing file (empty when ok).  Never raises on
    content mismatch; a missing/corrupt manifest is itself a problem.
    A directory that is only segment-managed (appends into a dir that
    never had an ``--audit`` batch build) skips the letter-file check.
    """
    out_dir = Path(out_dir)
    problems: list[str] = []
    mpath = out_dir / MANIFEST_NAME
    from .segments import is_segmented

    seg_managed = is_segmented(out_dir)
    if mpath.exists() or not seg_managed:
        try:
            doc = json.loads(mpath.read_text(encoding="utf-8"))
            expected = doc["files"]
        except (OSError, ValueError, KeyError) as e:
            return False, [f"{mpath}: unreadable manifest ({e})"]
        try:
            actual = letter_checksums(out_dir)
        except OSError as e:
            return False, [f"{out_dir}: {e}"]
        for name, (crc, size) in actual.items():
            want = expected.get(name)
            if want is None:
                problems.append(f"{name}: present but not in manifest")
            elif want["adler32"] != crc or want["bytes"] != size:
                problems.append(
                    f"{name}: checksum mismatch (manifest "
                    f"{want['adler32']}/{want['bytes']}B, on disk "
                    f"{crc}/{size}B)")
        for name in expected:
            if name not in actual:
                problems.append(f"{name}: in manifest but missing on disk")
    if seg_managed:
        problems.extend(verify_segments(out_dir))
    return not problems, problems
