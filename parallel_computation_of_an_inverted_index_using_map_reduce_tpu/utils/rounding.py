"""Shared shape-rounding helper (single definition for the package)."""

from __future__ import annotations


def round_up(n: int, multiple: int) -> int:
    """Smallest positive multiple of ``multiple`` that is >= ``n``.

    Always at least one multiple (n <= 0 rounds to ``multiple``), so
    padded device shapes are never empty.
    """
    return ((max(n, 1) + multiple - 1) // multiple) * multiple
