"""Partition-skew statistics (device-computed).

The reference partitions the shuffle by first letter, which is ~1000x
skewed on real text (partial_t = 156,038 tokens vs partial_x = 154,
SURVEY.md §2.3); the TPU engine partitions by term hash, which is
near-uniform.  This module measures both on device via the Pallas
histogram kernel so the imbalance is observable per run (the
reference offers no such observability — printf only, SURVEY.md §5).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..config import ALPHABET_SIZE
from ..ops.pallas import kernels as pk
from .rounding import round_up as _round_up


def partition_skew(term_ids, letter_of_term, num_buckets: int) -> dict:
    """Compare letter-partition vs hash-bucket-partition balance.

    ``term_ids`` are the emitted pair term ids (any length);
    ``letter_of_term`` maps term id -> 0..25.  Returns per-partition
    counts and the max/mean imbalance ratio for both policies.
    """
    terms = np.asarray(term_ids, dtype=np.int32)
    letters = np.asarray(letter_of_term, dtype=np.int32)
    n = _round_up(terms.shape[0], pk.BLOCK)
    pad_letters = np.full(n, ALPHABET_SIZE, np.int32)
    pad_buckets = np.full(n, num_buckets, np.int32)
    if terms.size:
        pad_letters[: terms.shape[0]] = letters[terms]
        pad_buckets[: terms.shape[0]] = terms % num_buckets

    letter_counts = np.asarray(pk.bucket_histogram(jnp.asarray(pad_letters), ALPHABET_SIZE))
    bucket_counts = np.asarray(pk.bucket_histogram(jnp.asarray(pad_buckets), num_buckets))

    def imbalance(counts: np.ndarray) -> float:
        mean = counts.mean()
        return float(counts.max() / mean) if mean > 0 else 0.0

    return {
        "letter_counts": letter_counts,
        "bucket_counts": bucket_counts,
        "letter_imbalance": imbalance(letter_counts),
        "bucket_imbalance": imbalance(bucket_counts),
        "num_buckets": num_buckets,
    }
