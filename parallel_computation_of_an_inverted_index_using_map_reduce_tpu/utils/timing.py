"""Phase timing + structured run stats (shim).

The reference's only observability is a handful of printfs (mapper
ranges at main.c:327, "REDUCER" at main.c:141) and no timers at all
(SURVEY.md §5).  The implementation now lives in ``obs.timing``,
unified with the serve engines' OpTimer over the obs histogram; this
module keeps the historical import path working.
"""

from __future__ import annotations

from ..obs.timing import PhaseTimer

__all__ = ["PhaseTimer"]
