"""Phase timing + structured run stats.

The reference's only observability is a handful of printfs (mapper
ranges at main.c:327, "REDUCER" at main.c:141) and no timers at all
(SURVEY.md §5).  Here every pipeline phase is timed and counted.
"""

from __future__ import annotations

import contextlib
import json
import time


class PhaseTimer:
    """Accumulates wall-time per named phase and arbitrary counters."""

    def __init__(self) -> None:
        self.phases: dict[str, float] = {}
        self.counters: dict[str, int | float] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + (time.perf_counter() - t0)

    def count(self, name: str, value) -> None:
        self.counters[name] = value

    @property
    def total_seconds(self) -> float:
        return sum(self.phases.values())

    def report(self) -> dict:
        return {
            "phases_ms": {k: round(v * 1e3, 3) for k, v in self.phases.items()},
            "total_ms": round(self.total_seconds * 1e3, 3),
            **self.counters,
        }

    def dumps(self) -> str:
        return json.dumps(self.report(), sort_keys=True)
