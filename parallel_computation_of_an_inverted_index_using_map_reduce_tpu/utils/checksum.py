"""Canonical adler32 helpers: one spelling for every container checksum.

Three subsystems grew identical hand-rolled adler32 hex helpers — the
``MRISPILL`` per-section checksums in ``build/spill.py``, the packed
artifact's whole-file checksum in ``serve/artifact.py``, and the
segment manifest's body checksum in ``segments/manifest.py`` (plus the
staged-bytes rider in ``segments/tombstones.py``).  The WAL
(``segments/wal.py``) would have been the fourth copy.  The canonical
spelling lives here; the old call sites are thin shims over it.

Deliberately stdlib-only and policy-free: hashing bytes for a checksum
is not a fault-injection boundary (there is no retry decision to make
here — callers own their own error handling), so this module carries a
file-level allow-list entry in mrilint's ``fault-boundary`` check.
"""

from __future__ import annotations

import zlib
from pathlib import Path


def adler32_hex(data: bytes) -> str:
    """Adler-32 of ``data`` as 8 lowercase hex digits — the repo-wide
    container checksum format (spill sections, segment manifests,
    packed artifacts, tombstone stages, WAL records)."""
    return f"{zlib.adler32(data) & 0xFFFFFFFF:08x}"


def file_checksum(path) -> tuple[str, int]:
    """``(adler32 hex, byte length)`` of a whole file's contents."""
    data = Path(path).read_bytes()
    return adler32_hex(data), len(data)
