"""Map-phase checkpoint: durable tokenized pairs.

The reference's spill files are accidentally a checkpoint — they persist
after the run and the reduce phase could be re-run from them alone
(SURVEY.md §5 "checkpoint/resume — absent, but latent").  Here that is a
first-class artifact: the tokenized (term_ids, doc_ids, vocab) triple,
saved once between the map and reduce phases, lets the device phase be
re-run without touching the corpus.
"""

from __future__ import annotations

import hashlib
import os
from pathlib import Path

import numpy as np

_FORMAT_VERSION = 2


def manifest_fingerprint(manifest) -> str:
    """Identity of the *file list* (count + paths), not file contents.

    Resume deliberately trusts the checkpoint over the corpus bytes —
    that is what makes re-running the reduce phase possible after the
    corpus is gone, exactly like the reference's leftover spill files.
    A changed file count or renamed path is a different corpus and is
    rejected at load.
    """
    h = hashlib.md5()
    h.update(str(len(manifest)).encode())
    for p in manifest.paths:
        h.update(b"\0" + p.encode("utf-8", "surrogateescape"))
    return h.hexdigest()


def save_pairs(path: str | Path, corpus, fingerprint: str = "") -> None:
    """Atomically persist a TokenizedCorpus."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            version=np.int64(_FORMAT_VERSION),
            fingerprint=np.bytes_(fingerprint.encode()),
            term_ids=corpus.term_ids,
            doc_ids=corpus.doc_ids,
            vocab=corpus.vocab,
            letter_of_term=corpus.letter_of_term,
            pairs_deduped=np.int64(1 if corpus.pairs_deduped else 0),
            raw_tokens=np.int64(corpus.raw_tokens if corpus.raw_tokens is not None else -1),
        )
    os.replace(tmp, path)


def load_pairs(path: str | Path, expect_fingerprint: str | None = None):
    """Restore a TokenizedCorpus; reject version or manifest mismatch."""
    from ..text.tokenizer import TokenizedCorpus

    with np.load(path) as z:
        version = int(z["version"])
        if version != _FORMAT_VERSION:
            raise ValueError(f"checkpoint {path!r} has version {version}, expected {_FORMAT_VERSION}")
        saved_fp = bytes(z["fingerprint"]).decode()
        if expect_fingerprint is not None and saved_fp != expect_fingerprint:
            raise ValueError(
                f"checkpoint {path!r} was written for a different manifest "
                f"(saved {saved_fp[:12]}…, current {expect_fingerprint[:12]}…); "
                "delete the checkpoint or restore the original file list"
            )
        raw = int(z["raw_tokens"]) if "raw_tokens" in z.files else -1
        return TokenizedCorpus(
            term_ids=z["term_ids"],
            doc_ids=z["doc_ids"],
            vocab=z["vocab"],
            letter_of_term=z["letter_of_term"],
            pairs_deduped=bool(int(z["pairs_deduped"])) if "pairs_deduped" in z.files else False,
            raw_tokens=raw if raw >= 0 else None,
        )
