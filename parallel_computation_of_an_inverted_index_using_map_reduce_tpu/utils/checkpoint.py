"""Map-phase checkpoint: durable tokenized pairs.

The reference's spill files are accidentally a checkpoint — they persist
after the run and the reduce phase could be re-run from them alone
(SURVEY.md §5 "checkpoint/resume — absent, but latent").  Here that is a
first-class artifact: the tokenized (term_ids, doc_ids, vocab) triple,
saved once between the map and reduce phases, lets the device phase be
re-run without touching the corpus.
"""

from __future__ import annotations

import hashlib
import logging
import os
import struct
import zipfile
from pathlib import Path

import numpy as np

from .. import faults

log = logging.getLogger("mri_tpu.checkpoint")

_FORMAT_VERSION = 2


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file exists but cannot be read back (truncated
    write, disk corruption, or a non-checkpoint file at the path).

    Wraps the opaque ``zipfile.BadZipFile``/EOF errors a damaged npz
    raises, naming the path and the remediation.
    """

    def __init__(self, path, cause):
        self.path = str(path)
        super().__init__(
            f"checkpoint {self.path!r} is corrupt or truncated "
            f"({cause.__class__.__name__}: {cause}); delete it, or move "
            f"it aside and rerun — --resume=auto quarantines it to "
            f"{self.path!r}.corrupt and restarts automatically")


# error classes a torn/garbage npz surfaces from np.load + member reads
_CORRUPT_ERRORS = (zipfile.BadZipFile, zipfile.LargeZipFile, EOFError,
                   KeyError, struct.error, OSError)


def quarantine(path: str | Path) -> str:
    """Move a corrupt checkpoint aside to ``<path>.corrupt`` (atomic
    rename; any previous quarantine at that name is replaced) so the
    run can start fresh without destroying the forensic evidence."""
    dest = str(path) + ".corrupt"
    os.replace(path, dest)
    log.warning("quarantined corrupt checkpoint to %s", dest)
    return dest


def manifest_fingerprint(manifest) -> str:
    """Identity of the *file list* (count + paths), not file contents.

    Resume deliberately trusts the checkpoint over the corpus bytes —
    that is what makes re-running the reduce phase possible after the
    corpus is gone, exactly like the reference's leftover spill files.
    A changed file count or renamed path is a different corpus and is
    rejected at load.
    """
    h = hashlib.md5()
    h.update(str(len(manifest)).encode())
    # Virtual manifests (corpus/synthetic.py, corpus/realtext.py) carry
    # their full identity here — generator parameters / source-corpus
    # hash + doc count — and their path labels are constant-pattern
    # placeholders, so hashing them would cost O(num_docs) string
    # formats per run (seconds at the 1M-doc scale) for zero identity.
    extra = getattr(manifest, "fingerprint_extra", "")
    if extra:
        h.update(extra.encode())
    else:
        for p in manifest.paths:
            h.update(b"\0" + p.encode("utf-8", "surrogateescape"))
    return h.hexdigest()


def save_pairs(path: str | Path, corpus, fingerprint: str = "") -> None:
    """Atomically persist a TokenizedCorpus."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            version=np.int64(_FORMAT_VERSION),
            fingerprint=np.bytes_(fingerprint.encode()),
            term_ids=corpus.term_ids,
            doc_ids=corpus.doc_ids,
            vocab=corpus.vocab,
            letter_of_term=corpus.letter_of_term,
            pairs_deduped=np.int64(1 if corpus.pairs_deduped else 0),
            raw_tokens=np.int64(corpus.raw_tokens if corpus.raw_tokens is not None else -1),
        )
    os.replace(tmp, path)
    inj = faults.active()
    if inj is not None:
        inj.on_checkpoint_saved(str(path))


# v2: virtual-manifest fingerprints hash fingerprint_extra INSTEAD of
# the O(num_docs) constant-pattern path labels — pre-v2 checkpoints of
# virtual manifests carry a different fingerprint, so the version bump
# makes the one-time invalidation an explicit version error rather
# than a confusing "different manifest" rejection.
_STREAM_FORMAT_VERSION = 2


def stream_fingerprint(manifest, *, width: int, chunk_docs: int,
                       pad_multiple: int) -> str:
    """Identity of a resumable stream: the manifest PLUS every config
    knob that moves window boundaries or row shape.  Resuming under a
    different chunking would re-feed or skip documents; a different
    width changes the row layout — both are rejected at load."""
    return (f"{manifest_fingerprint(manifest)}:w{width}"
            f":c{chunk_docs}:p{pad_multiple}")


def save_stream_state(path: str | Path, state: dict, fed_tokens: int,
                      window_pos: int, fingerprint: str) -> None:
    """Atomically persist a DeviceStreamEngine snapshot.

    Uncompressed ``np.savez`` on purpose: at the 1M-doc scale the
    accumulator prefix is hundreds of MB and this container has one
    core — compression would cost minutes per checkpoint while local
    disk takes seconds.
    """
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    cols = {f"col_{i}": c for i, c in enumerate(state["columns"])}
    with open(tmp, "wb") as f:
        np.savez(
            f,
            version=np.int64(_STREAM_FORMAT_VERSION),
            fingerprint=np.bytes_(fingerprint.encode()),
            width=np.int64(state["width"]),
            count=np.int64(state["count"]),
            cap=np.int64(state["cap"]),
            live_groups=np.int64(state["live_groups"]),
            max_word_len=np.int64(state["max_word_len"]),
            windows_fed=np.int64(state["windows_fed"]),
            # loop position in the window iteration — distinct from
            # windows_fed, which skips empty (tok_count == 0) windows
            window_pos=np.int64(window_pos),
            fed_tokens=np.int64(fed_tokens),
            # resolved accumulator-growth history (may be absent in
            # snapshots from engines that predate the key)
            rows_curve=np.asarray(state.get("rows_curve", []), np.int64),
            num_columns=np.int64(len(state["columns"])),
            **cols,
        )
    os.replace(tmp, path)
    inj = faults.active()
    if inj is not None:
        inj.on_checkpoint_saved(str(path))


def load_stream_state(path: str | Path,
                      expect_fingerprint: str) -> dict:
    """Restore a stream snapshot; reject version/fingerprint mismatch
    (ValueError) and raise :class:`CheckpointCorrupt` — never a raw
    zipfile error — for a damaged/truncated file."""
    try:
        with np.load(path) as z:
            version = int(z["version"])
            if version != _STREAM_FORMAT_VERSION:
                raise ValueError(
                    f"stream checkpoint {path!r} has version {version}, "
                    f"expected {_STREAM_FORMAT_VERSION}")
            saved_fp = bytes(z["fingerprint"]).decode()
            if saved_fp != expect_fingerprint:
                raise ValueError(
                    f"stream checkpoint {path!r} was written for a different "
                    f"manifest or stream config (saved {saved_fp[:20]}…, "
                    f"current {expect_fingerprint[:20]}…); delete it or "
                    "restore the original run configuration")
            return {
                "width": int(z["width"]),
                "count": int(z["count"]),
                "cap": int(z["cap"]),
                "live_groups": int(z["live_groups"]),
                "max_word_len": int(z["max_word_len"]),
                "windows_fed": int(z["windows_fed"]),
                "window_pos": int(z["window_pos"]),
                "fed_tokens": int(z["fed_tokens"]),
                "rows_curve": (z["rows_curve"].tolist()
                               if "rows_curve" in z.files else []),
                "columns": [z[f"col_{i}"]
                            for i in range(int(z["num_columns"]))],
            }
    except FileNotFoundError:
        raise
    except _CORRUPT_ERRORS as e:
        raise CheckpointCorrupt(path, e) from e


def load_pairs(path: str | Path, expect_fingerprint: str | None = None):
    """Restore a TokenizedCorpus; reject version or manifest mismatch
    (ValueError) and raise :class:`CheckpointCorrupt` for a damaged or
    truncated file (satellite: a half-written npz used to surface as a
    bare ``zipfile.BadZipFile`` with no path or remediation)."""
    from ..text.tokenizer import TokenizedCorpus

    try:
        with np.load(path) as z:
            version = int(z["version"])
            if version != _FORMAT_VERSION:
                raise ValueError(f"checkpoint {path!r} has version {version}, expected {_FORMAT_VERSION}")
            saved_fp = bytes(z["fingerprint"]).decode()
            if expect_fingerprint is not None and saved_fp != expect_fingerprint:
                raise ValueError(
                    f"checkpoint {path!r} was written for a different manifest "
                    f"(saved {saved_fp[:12]}…, current {expect_fingerprint[:12]}…); "
                    "delete the checkpoint or restore the original file list"
                )
            raw = int(z["raw_tokens"]) if "raw_tokens" in z.files else -1
            return TokenizedCorpus(
                term_ids=z["term_ids"],
                doc_ids=z["doc_ids"],
                vocab=z["vocab"],
                letter_of_term=z["letter_of_term"],
                pairs_deduped=bool(int(z["pairs_deduped"])) if "pairs_deduped" in z.files else False,
                raw_tokens=raw if raw >= 0 else None,
            )
    except FileNotFoundError:
        raise
    except _CORRUPT_ERRORS as e:
        raise CheckpointCorrupt(path, e) from e
