"""Single declaration point for every ``MRI_*`` environment knob.

Every knob the package, the benches, and the tools read from the
environment is declared here once — name, type, default, bounds, and a
help line — and read through :func:`get`.  Invalid values raise a
one-line :class:`KnobError` (a ``ValueError``) naming the variable, so
every CLI surface maps it to exit 2 instead of surfacing a bare
``int()`` traceback three layers down a worker thread.

The ``mrilint`` env-knobs checker rejects raw ``os.environ["MRI_*"]``
reads anywhere else, and the readme-knobs checker keeps the README
table in sync with :func:`markdown_table`.  This module is
deliberately stdlib-only and free of package-relative imports so the
linter can load it standalone (no jax import) via its file path.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Iterator


class KnobError(ValueError):
    """One-line validation error naming the knob (CLI exit 2)."""


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    cast: Callable[[str], Any]
    default: Any
    help: str
    scope: str = "build"
    minimum: Any = None
    exclusive: bool = False
    choices: tuple | None = None

    def parse(self, raw: str) -> Any:
        """Cast + validate ``raw``; one-line :class:`KnobError` on bad."""
        try:
            val = self.cast(raw)
        except ValueError:
            raise KnobError(
                f"{self.name}={raw!r} is not a valid "
                f"{self.cast.__name__}") from None
        if self.choices is not None and val not in self.choices:
            raise KnobError(
                f"{self.name}={raw!r} not in {self.choices}")
        if self.minimum is not None and (
                val < self.minimum
                or (self.exclusive and val == self.minimum)):
            bound = (f"> {self.minimum}" if self.exclusive
                     else f">= {self.minimum}")
            raise KnobError(f"{self.name} must be {bound}, got {raw!r}")
        return val


_REGISTRY: dict[str, Knob] = {}


def declare(name: str, cast: Callable[[str], Any], default: Any,
            help: str, *, scope: str = "build", minimum: Any = None,
            exclusive: bool = False, choices: tuple | None = None) -> Knob:
    if name in _REGISTRY:
        raise ValueError(f"knob {name} declared twice")
    knob = Knob(name=name, cast=cast, default=default, help=help,
                scope=scope, minimum=minimum, exclusive=exclusive,
                choices=choices)
    _REGISTRY[name] = knob
    return knob


def get(name: str) -> Any:
    """The knob's parsed value from the environment, or its default.

    ``KeyError`` on an undeclared name is a programming error, caught
    by the env-knobs lint rule before it ships.
    """
    knob = _REGISTRY[name]
    raw = os.environ.get(name)
    if raw is None:
        return knob.default
    return knob.parse(raw)


def is_set(name: str) -> bool:
    """Whether the (declared) knob is present in the environment."""
    _ = _REGISTRY[name]
    return name in os.environ


def knobs() -> Iterator[Knob]:
    """All declared knobs, sorted by (scope, name)."""
    return iter(sorted(_REGISTRY.values(),
                       key=lambda k: (k.scope, k.name)))


_SCOPE_TITLES = {
    "build": "Build / index pipeline",
    "faults": "Fault injection & retries",
    "serve": "Query serving",
    "obs": "Observability",
    "bench": "Benchmarks",
    "test": "Test hooks",
}


def markdown_table() -> str:
    """The README env-knob table (kept in sync by the lint rule)."""
    out: list[str] = []
    scope = None
    for k in knobs():
        if k.scope != scope:
            scope = k.scope
            if out:
                out.append("")
            out.append(f"**{_SCOPE_TITLES.get(scope, scope)}**")
            out.append("")
            out.append("| Knob | Type | Default | Meaning |")
            out.append("|---|---|---|---|")
        if k.default is None:
            default = "unset"
        elif k.default == "":
            default = "`\"\"`"
        else:
            default = f"`{k.default}`"
        constraint = ""
        if k.choices is not None:
            constraint = " one of " + "/".join(
                f"`{c}`" if c != "" else "`\"\"`" for c in k.choices)
        elif k.minimum is not None:
            op = ">" if k.exclusive else ">="
            constraint = f" ({op} {k.minimum})"
        out.append(f"| `{k.name}` | {k.cast.__name__}{constraint} "
                   f"| {default} | {k.help} |")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------
# Declarations.  Scopes: build (index pipeline), faults, serve, bench,
# test (crash-injection hooks the e2e suite arms in subprocesses).
# ---------------------------------------------------------------------

# -- build / index pipeline -------------------------------------------
declare("MRI_CPU_WINDOW_BYTES", int, None,
        "Bytes per host scan window (default: the backend's ~2 MB); "
        "tests force tiny values for deterministic multi-window plans.")
declare("MRI_STEAL_SHUFFLE_SEED", int, None,
        "Seeded shuffle of the steal queue's window order (unset: "
        "manifest order).")
declare("MRI_WINDOW_DEADLINE_S", float, None,
        "Per-window watchdog deadline; a wedged worker past it is "
        "abandoned and its window requeued (unset: no watchdog).")
declare("MRI_WORKER_RESPAWNS", int, 1,
        "Scan-worker respawn budget after crashes (clamped to >= 0).")
declare("MRI_TPU_PALLAS", str, "auto",
        "Fused Pallas dedup kernel: auto (TPU only), force "
        "(interpret mode off-TPU), off (XLA everywhere).")
declare("MRI_TPU_CKPT_BUDGET_S", float, 120.0,
        "Snapshot-tax budget: a projected checkpoint save slower than "
        "this is skipped (recorded, not paid).")
declare("MRI_TPU_CKPT_LINK_MBPS", float, 8.0,
        "Assumed device->host link rate seeding the checkpoint cost "
        "projection (re-calibrated from measured saves).")
declare("MRI_TPU_CKPT_STRETCH", int, 4,
        "Max consecutive over-budget checkpoint skips before one save "
        "is forced.")
declare("MRI_BUILD_SHARDS", int, 8,
        "Term-hash shard count for the out-of-core build (spill runs "
        "and the streaming merge partition by term hash, not letter).",
        minimum=1)
declare("MRI_BUILD_SPILL_BYTES", int, None,
        "Per-worker postings memory budget; when set, scan workers "
        "spill term-hash-sharded sorted runs to disk at this estimated "
        "footprint and reducers k-way-merge the runs (unset: the "
        "all-in-memory merge).",
        minimum=1)
declare("MRI_NATIVE_SANITIZE", str, "",
        "Native tokenizer build variant: \"\" (production), asan, or "
        "ubsan — sanitized builds get suffix-tagged .so names.",
        choices=("", "asan", "ubsan"))

# -- fault injection & retries ----------------------------------------
declare("MRI_FAULTS", str, None,
        "Fault-injection spec armed at first faults.active() call "
        "(subprocess arming); same grammar as --fault-spec.",
        scope="faults")
declare("MRI_READ_RETRIES", int, 3,
        "Read attempts per document (counts the first try).",
        scope="faults", minimum=1)
declare("MRI_READ_BACKOFF_MS", float, 5.0,
        "Initial retry backoff in milliseconds (doubles per retry).",
        scope="faults", minimum=0)
declare("MRI_READ_DEADLINE_S", float, 1.0,
        "Total per-document retry deadline in seconds.",
        scope="faults", minimum=0, exclusive=True)

# -- query serving ----------------------------------------------------
declare("MRI_SERVE_ENGINE", str, None,
        "Engine when 'mri query' gets no --engine flag: host, device, "
        "or auto (validated by serve.engine.resolve_engine).",
        scope="serve")
declare("MRI_SERVE_SHARDS", int, None,
        "Device-engine shard count (unset: all visible devices).",
        scope="serve")
declare("MRI_SERVE_DEVICE_DECODE_BUDGET", int, 1 << 24,
        "Max postings rows the device engine decodes per batch tier.",
        scope="serve")
declare("MRI_SERVE_COALESCE_US", int, 200,
        "Daemon micro-batch coalescing window in microseconds "
        "(0: dispatch immediately).",
        scope="serve", minimum=0)
declare("MRI_SERVE_QUEUE_DEPTH", int, 1024,
        "Daemon admission queue depth; requests past it are shed as "
        "'overloaded'.",
        scope="serve", minimum=1)
declare("MRI_SERVE_MAX_BATCH", int, 1024,
        "Max coalesced requests dispatched as one engine batch.",
        scope="serve", minimum=1)
declare("MRI_SERVE_DRAIN_S", float, 5.0,
        "Graceful-drain deadline after SIGTERM/SIGINT before inflight "
        "requests are abandoned.",
        scope="serve", minimum=0, exclusive=True)
declare("MRI_SERVE_FORMAT", int, 3,
        "Artifact format packed when no explicit version is requested: "
        "1 (plain delta postings), 2 (block-bitpacked + skip table) or "
        "3 (v2.1: adds the per-block max-score columns).",
        scope="serve", choices=(1, 2, 3))
declare("MRI_SERVE_BLOCK_SIZE", int, 128,
        "Format-v2 postings block size in doc ids (power of two).",
        scope="serve", minimum=2)
declare("MRI_SERVE_SCORE_BITS", int, 8,
        "v2.1 max-score column width in bits: 8 (saturating u8 max-tf "
        "/ min-doclen) or 16.",
        scope="serve", choices=(8, 16))
declare("MRI_SERVE_SCORE", str, "df",
        "Default top_k scoring mode when no --score flag is given: "
        "df (document frequency) or bm25 (ranked retrieval).",
        scope="serve", choices=("df", "bm25"))
declare("MRI_SERVE_PLANNER", str, "auto",
        "Ranked-query planner: auto (df/k heuristic), exhaustive "
        "(score every posting), bmw (Block-Max WAND) or maxscore.",
        scope="serve", choices=("auto", "exhaustive", "bmw", "maxscore"))
declare("MRI_SERVE_NATIVE", str, "auto",
        "Native (C++) serve kernels for v2 decode/AND/BM25: auto "
        "(on when the compiled library loads), 1 (require native — "
        "engine creation fails loudly if the .so is unavailable) or "
        "0 (numpy only).  Answers are byte-identical either way.",
        scope="serve", choices=("auto", "0", "1"))
declare("MRI_SERVE_CROSSOVER", int, None,
        "--engine auto host->device batch-size crossover: unset probes "
        "it by measurement, 0 pins host, N>0 routes batches >= N to "
        "the device engine.",
        scope="serve")
declare("MRI_SEGMENT_COMPACT_TRIGGER", int, 4,
        "Segment count at which compaction kicks in; also the width "
        "of the adjacent merge window each round folds.",
        scope="serve", minimum=2)
declare("MRI_SEGMENT_MAX_SEGMENTS", int, 16,
        "Hard segment-count backstop: the daemon auto-compacts after "
        "an append while the live set exceeds it.",
        scope="serve", minimum=1)
declare("MRI_SEGMENT_TOMBSTONE_FLUSH", int, 1,
        "Daemon delete batching: buffer delete ops and publish ONE "
        "tombstone generation every N ops (N=1 publishes immediately; "
        "a compact or drain flushes the remainder; CLI deletes always "
        "publish).",
        scope="serve", minimum=1)
declare("MRI_SEGMENT_WAL", int, 1,
        "Mutation write-ahead log: 1 fsyncs a checksummed WAL record "
        "before every segment mutation publish (crash replay via 'mri "
        "recover' / daemon start), 0 disables logging (replay of an "
        "existing log still runs).",
        scope="serve", choices=(0, 1))
declare("MRI_SEGMENT_LEASE_TTL_S", float, 0.0,
        "Primary-election lease TTL in seconds: mutations renew a "
        "TTL'd lease inside segments.lock and are rejected with "
        "'lease_lost' once another holder owns it; 0 disables "
        "leasing (single-writer deployments).",
        scope="serve", minimum=0)
declare("MRI_REPLICA_POLL_MS", int, 500,
        "Replica catch-up poll period in ms for 'mri serve "
        "--replica-of' (each poll ships missing segments + WAL tail "
        "from the primary).",
        scope="serve", minimum=1)
declare("MRI_CLUSTER_HEDGE_MS", float, -1.0,
        "Router hedging delay in ms: a shard RPC unanswered this long "
        "is re-sent to another replica of the same shard. -1 adapts "
        "per shard (rolling p95 of recent RPC latency, 1 ms floor), "
        "0 disables hedging, positive values are a fixed delay.",
        scope="serve", minimum=-1.0)
declare("MRI_CLUSTER_HEALTH_MS", int, 500,
        "Router health-probe period in ms: each replica's `healthz` "
        "is polled on its pipelined connection and the readiness "
        "reasons (draining/stalled/overloaded/replica_lagging) steer "
        "replica selection away before requests fail.",
        scope="serve", minimum=1)
declare("MRI_CLUSTER_INFLIGHT", int, 1024,
        "Router admission cap: client requests in flight (scattered "
        "but not yet gathered) beyond this are shed with "
        "`overloaded`, mirroring the daemon's bounded queue.",
        scope="serve", minimum=1)
declare("MRI_CLUSTER_RPC_TIMEOUT_MS", float, 30000.0,
        "Router-side ceiling in ms on one shard RPC (including "
        "failover retries) when the client request carries no "
        "deadline_ms of its own.",
        scope="serve", minimum=1.0)
declare("MRI_CLUSTER_PARTIAL", str, "fail",
        "Router default partial-result policy for requests carrying "
        "no partial_policy field: 'fail' (any unanswerable shard "
        "fails the whole request — byte-compat default) or "
        "'allow[:min_coverage=F]' (answer from the shards that did "
        "answer, flagged with partial+coverage metadata, provided at "
        "least fraction F of the corpus answered; F defaults to 0).",
        scope="serve")
declare("MRI_CLUSTER_RETRY_BUDGET", float, 0.1,
        "Router retry/hedge token budget per shard, as a ratio of "
        "live (first-attempt) traffic: each original shard leg "
        "deposits this many tokens and every retry or hedge spends "
        "one, so brownout amplification is capped near (1 + ratio)x "
        "instead of compounding; 0 disables retries and hedges "
        "(first attempt only).",
        scope="serve", minimum=0.0)
declare("MRI_SERVE_CODEL_TARGET_MS", float, 0.0,
        "CoDel-style adaptive admission target in ms: once the "
        "dispatcher's observed queue delay stays above this for a "
        "full MRI_SERVE_CODEL_INTERVAL_MS, the daemon sheds "
        "('overloaded') early at admission and late at dequeue until "
        "delay drops back under target, keeping executed requests' "
        "queueing near the target under sustained overload; 0 "
        "disables adaptive admission (fixed queue-depth shedding "
        "only).",
        scope="serve", minimum=0.0)
declare("MRI_SERVE_CODEL_INTERVAL_MS", float, 100.0,
        "CoDel sliding interval in ms: queue delay must exceed the "
        "target this long before shedding starts, and it is the base "
        "period of the control law that paces admission sheds while "
        "the daemon stays over target.",
        scope="serve", minimum=1.0)
declare("MRI_SERVE_RESULT_CACHE", int, 1,
        "Generation-keyed query-result cache: 1 answers repeat "
        "queries from the reader thread (daemon) / above the "
        "scatter-gather (router) without touching the engine, keyed "
        "on (op, normalized terms, k, score, manifest generation) so "
        "a mutation's generation bump invalidates exactly; 0 "
        "disables the cache (every request reaches the engine).",
        scope="serve", choices=(0, 1))
declare("MRI_SERVE_RESULT_CACHE_ENTRIES", int, 4096,
        "Entry-count bound on the result cache (LRU beyond it).",
        scope="serve", minimum=1)
declare("MRI_SERVE_RESULT_CACHE_BYTES", int, 8 << 20,
        "Byte bound on the result cache: cached payloads are sized "
        "by their JSON encoding and evicted LRU-first once the sum "
        "exceeds this; 0 removes the byte bound (entry count only).",
        scope="serve", minimum=0)
declare("MRI_SERVE_TENANT_WEIGHTS", str, "",
        "Weighted-fair dequeue shares per tenant as "
        "'name=w,name=w,*=w' (integer weights; '*' sets the default "
        "for unlisted tenants, 1 if absent). Empty string gives every "
        "tenant weight 1 (pure round-robin between active tenants).",
        scope="serve")
declare("MRI_SERVE_TENANT_RATE", str, "",
        "Per-tenant token-bucket admission as "
        "'name=rps[:burst],*=rps[:burst]' (floats; burst defaults to "
        "one second of rps). Requests over a tenant's bucket are shed "
        "with `overloaded` before queueing; empty string disables "
        "rate limiting (weighted-fair dequeue still applies).",
        scope="serve")
declare("MRI_SERVE_TENANT_MAX", int, 32,
        "Cap on distinct tracked tenants: past it, new tenant names "
        "fold into the shared 'other' lane (bounds per-tenant metric "
        "and queue memory against tenant-id cardinality attacks).",
        scope="serve", minimum=1)
declare("MRI_SERVE_GC_FREEZE", int, 1,
        "Daemon-process GC taming (the `mri serve` CLI only, never "
        "in-process embedding): after the engine is loaded, collect "
        "once and gc.freeze() the warm startup heap so cyclic-GC "
        "passes scan only request churn — an admission-shed storm "
        "allocates fast enough to schedule full collections, and a "
        "full pass over the interpreter+engine heap is a multi-ms "
        "stop-the-world spike in someone else's tail latency. 0 "
        "leaves the collector untouched.",
        scope="serve", choices=(0, 1))
declare("MRI_SERVE_TENANT_QUEUE_DEPTH", int, 0,
        "Per-tenant dispatch-queue depth; a tenant whose lane is full "
        "sheds with `overloaded` without displacing other tenants. 0 "
        "inherits MRI_SERVE_QUEUE_DEPTH.",
        scope="serve", minimum=0)

# -- observability ----------------------------------------------------
declare("MRI_OBS_ENABLE", int, 1,
        "Per-request tracing on the daemon: 1 auto-generates trace ids "
        "and records spans into the trace ring, 0 disables recording "
        "(client-provided trace ids are still echoed).",
        scope="obs", choices=(0, 1))
declare("MRI_OBS_TRACE_RING", int, 256,
        "Capacity of the daemon's ring of recent request traces "
        "(served by the `trace` admin op).",
        scope="obs", minimum=1)
declare("MRI_OBS_SLOW_MS", float, 0.0,
        "Slow-query threshold in ms: requests at least this slow emit "
        "one structured JSON line on the mri_tpu.obs logger; 0 "
        "disables the slow log.",
        scope="obs", minimum=0)
declare("MRI_OBS_FLIGHT_RING", int, 64,
        "Capacity of the daemon's flight recorder (last N completed "
        "request cost-reports + slow offenders, dumped as one JSON "
        "file on SIGQUIT, crash, abnormal drain, or the `flightdump` "
        "admin op); 0 disables the recorder.",
        scope="obs", minimum=0)
declare("MRI_OBS_EXEMPLARS", int, 1,
        "OpenMetrics exemplars on the daemon's latency histograms: 1 "
        "attaches the trace_id of a recent bucket-representative "
        "request to each bucket line in the scrape text, 0 omits them.",
        scope="obs", choices=(0, 1))
declare("MRI_OBS_SAMPLE_MS", int, 1000,
        "Rolling-window sampler period in ms: how often the daemon "
        "snapshot-diffs the cumulative registry into per-period "
        "buckets (the 10s/1m/5m SLI windows are built from them).",
        scope="obs", minimum=10)
declare("MRI_OBS_SLO_LATENCY_MS", float, 50.0,
        "Latency SLO threshold in ms: the latency SLI is the fraction "
        "of data requests answered at least this fast.",
        scope="obs", minimum=0.001)
declare("MRI_OBS_SLO_TARGET", float, 0.999,
        "SLO objective (good-event fraction) shared by the "
        "availability and latency SLOs; burn rate over a window is "
        "error-rate / (1 - target).",
        scope="obs", minimum=0.0)
declare("MRI_OBS_STALL_MS", float, 5000.0,
        "Watchdog stall threshold in ms: a monitored daemon thread "
        "(dispatcher, accept) whose heartbeat ages past this is "
        "declared stalled — counted, logged, flight-dumped, and "
        "surfaced as `healthz` readiness `stalled`; 0 disables the "
        "watchdog.",
        scope="obs", minimum=0)
declare("MRI_OBS_OVERLOAD_SHED_RATE", float, 0.5,
        "healthz readiness threshold: the daemon reports `overloaded` "
        "while the shed fraction (sheds / admission attempts) over "
        "the rolling 10s window exceeds this.",
        scope="obs", minimum=0.0)
declare("MRI_OBS_LOG_FORMAT", str, "text",
        "Runtime log rendering for mri_tpu.* loggers once "
        "obs.logging.configure() has run (the serve daemon does): "
        "text keeps classic `LEVEL logger: message` lines, json emits "
        "one structured JSON object per line.",
        scope="obs", choices=("text", "json"))
declare("MRI_OBS_LOG_RATE_LIMIT", int, 200,
        "Per-(logger, event) structured-log rate limit in records/s; "
        "excess records are dropped and counted in "
        "mri_obs_log_dropped_total. 0 disables the limiter.",
        scope="obs", minimum=0)

# -- benchmarks -------------------------------------------------------
declare("MRI_TPU_BENCH_ATTEMPTS", int, 3,
        "Attempts per bench probe before recording a failure.",
        scope="bench")
declare("MRI_TPU_BENCH_TIMEOUTS", str, "480,300,240",
        "Comma list of per-attempt bench timeouts in seconds.",
        scope="bench")
declare("MRI_TPU_BENCH_CORPUS", str, None,
        "Corpus directory override for the e2e bench legs.",
        scope="bench")
declare("MRI_TPU_BENCH_PLATFORM", str, None,
        "Force a JAX platform for bench subprocesses (e.g. cpu).",
        scope="bench")
declare("MRI_TPU_BENCH_PROBE_S", int, 75,
        "SIGALRM deadline for the e2e bench probe.", scope="bench")
declare("MRI_TPU_GRID_PROBE_S", int, 240,
        "SIGALRM deadline for the (mappers, reducers) grid probe.",
        scope="bench")
declare("MRI_TPU_KERNEL_PROBE_S", int, 90,
        "SIGALRM deadline for the Pallas kernel probe.", scope="bench")
declare("MRI_TPU_DEVTOK_PROBE_S", int, 240,
        "SIGALRM deadline for the device-tokenizer probe.",
        scope="bench")
declare("MRI_TPU_BENCH_ATTEST", str, None,
        "Attestation file path (default: BENCH_ATTEST.json next to "
        "bench.py).", scope="bench")
declare("MRI_TPU_SCALE_PLATFORM", str, None,
        "Force a JAX platform for the scale bench.", scope="bench")
declare("MRI_TPU_SCALE_DOCS", int, 1_000_000,
        "Synthetic corpus size for the scale bench.", scope="bench")
declare("MRI_TPU_SCALE_VOCAB", int, 100_000,
        "Synthetic vocabulary size for the scale bench.", scope="bench")
declare("MRI_TPU_SCALE_SHARDS", int, 0,
        "Scale-bench shard count (0: all devices).", scope="bench")
declare("MRI_TPU_SCALE_DEVTOK", int, 0,
        "1: scale bench runs the device-tokenizer streaming path.",
        scope="bench")
declare("MRI_TPU_SCALE_REALTEXT", int, 0,
        "1: synthesize Zipf-ish real-looking text instead of uniform "
        "tokens.", scope="bench")
declare("MRI_TPU_SCALE_SALT", int, 1,
        "1: salt the synthetic corpus per repeat (defeats caching).",
        scope="bench")
declare("MRI_TPU_SCALE_REPEATS", int, 8,
        "Timed repeats per scale-bench configuration.", scope="bench")
declare("MRI_TPU_SCALE_CHUNK", int, 100_000,
        "Docs per streamed chunk in the scale bench.", scope="bench")
declare("MRI_TPU_SCALE_CKPT", str, None,
        "Checkpoint directory for the devtok scale leg (unset: no "
        "checkpointing).", scope="bench")
declare("MRI_TPU_SCALE_CKPT_EVERY", int, 2,
        "Checkpoint cadence in chunks for the devtok scale leg.",
        scope="bench")
declare("MRI_TPU_SCALE_SKEW", str, None,
        "Truthy: report per-letter skew for the realtext corpus.",
        scope="bench")
declare("MRI_TPU_SCALE_CROSSCHECK", str, None,
        "Truthy: cross-check scale-bench output against the oracle.",
        scope="bench")
declare("MRI_BENCH_SWEEP_WORKERS", str, "1,2,4",
        "Comma list of worker counts for the host sweep.",
        scope="bench")
declare("MRI_SERVE_BATCHES", str, "1,32,1024",
        "Comma list of batch sizes for the serve bench.",
        scope="bench")
declare("MRI_SERVE_AB_BATCHES", str, "1,1024,8192,65536",
        "Comma list of batch sizes for the host/device A/B leg.",
        scope="bench")
declare("MRI_SERVE_LOOKUPS", int, 200_000,
        "Total single-term lookups per serve-bench batch size.",
        scope="bench")
declare("MRI_SERVE_AB_MAX_BATCHES", int, 256,
        "Per-batch-size cap on timed batches in A/B mode.",
        scope="bench")
declare("MRI_SERVE_ZIPF_S", float, 1.1,
        "Zipf exponent of the serve-bench term-popularity draw.",
        scope="bench")
declare("MRI_SERVE_SEED", int, 17,
        "RNG seed for serve-bench workloads.", scope="bench")
declare("MRI_SERVE_OPEN_SECONDS", float, 3.0,
        "Per-leg duration of the open-loop serve bench.",
        scope="bench")
declare("MRI_DAEMON_PIPELINE_N", int, 60_000,
        "Requests in the daemon pipelined capacity probe.",
        scope="bench")
declare("MRI_DAEMON_CLOSED_N", int, 3_000,
        "RPCs in the daemon closed-loop latency leg.", scope="bench")
declare("MRI_DAEMON_OPEN_SECONDS", float, 2.0,
        "Per-leg duration of the daemon open-loop bench.",
        scope="bench")
declare("MRI_DAEMON_DEADLINE_MS", float, 25.0,
        "deadline_ms carried by every open-loop bench request.",
        scope="bench")
declare("MRI_DAEMON_LOAD_FACTORS", str, "0.4,0.8,1.6",
        "Comma list of offered-load multipliers over measured "
        "capacity.", scope="bench")
declare("MRI_DAEMON_WINDOW", int, 512,
        "In-flight window of the daemon pipelined probe.",
        scope="bench")
declare("MRI_DAEMON_OPEN_WINDOW", int, 2400,
        "Max in-flight requests in the daemon open-loop bench.",
        scope="bench")
declare("MRI_CLUSTER_BENCH_N", int, 12000,
        "Ranked requests per cluster-bench throughput leg "
        "(--cluster-ab).", scope="bench")
declare("MRI_CLUSTER_BENCH_SHARDS", str, "4,8",
        "Comma list of shard counts the cluster bench sweeps.",
        scope="bench")
declare("MRI_CLUSTER_BENCH_SLOW_MS", float, 20.0,
        "Injected shard-slow delay in ms for the cluster bench's "
        "hedged-vs-unhedged p99 comparison.", scope="bench")

# -- test hooks -------------------------------------------------------
declare("MRI_EMIT_KILL_AFTER_LETTERS", int, None,
        "Crash hook: SIGKILL the process after N complete letter "
        "files (kill-mid-emit durability test).", scope="test")
declare("MRI_SPILL_KILL_AFTER", int, None,
        "Crash hook: SIGKILL the process after N complete spill run "
        "files (kill-at-spill-boundary resume test).", scope="test")
declare("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", int, 0,
        "Crash hook: die at a deterministic device-stream position "
        "(0: disabled).", scope="test")
declare("MRI_TPU_TESTS_ON_TPU", str, "",
        "Truthy: run the test suite against the real chip instead of "
        "the forced-CPU default.", scope="test")
