"""Pipelined window executor: overlap read, tokenize, and emit.

The reference interleaves read and scan serially inside each mapper
(main.c:97-116).  Here a dedicated reader thread fills window arenas
from a recycling ring while the consumer runs the GIL-releasing native
scan on the previous window, and the final emit happens once at the
end — a read → tokenize → emit pipeline across windows instead of
serial whole-corpus phases.  On a single core the win is the removed
copies; with spare cores the read genuinely hides behind the scan.

Failure semantics (faults.py): the reader thread has an explicit
lifecycle — :meth:`PipelinedWindowReader.close` joins it with a
timeout (context-manager exit does the same), and the consumer side
runs a watchdog so a reader that dies silently raises
:class:`ReaderDied` and one that hangs raises :class:`ReaderHang`
instead of deadlocking the scan forever.  Documents the reader skips
after exhausting their retry budget land in the reader's
:attr:`~PipelinedWindowReader.report`.
"""

from __future__ import annotations

import queue
import threading
import time

from .. import faults
from .arena import WindowArena
from .reader import read_window_into


class ReaderDied(RuntimeError):
    """The reader thread exited without delivering a result or an
    exception — the fire-and-forget daemon failure mode."""


class ReaderHang(RuntimeError):
    """The reader thread is alive but made no progress within the
    watchdog window (hung filesystem / device)."""


class PipelinedWindowReader:
    """Iterate filled :class:`WindowArena` s, reading ahead on a thread.

    ``depth`` is the prefetch distance (arena ring holds ``depth + 1``
    buffers: up to ``depth`` filled ahead plus the one being consumed).
    The consumer MUST hand each arena back via :meth:`recycle` once the
    scan is done with its views — that is what bounds memory and what
    the reader blocks on.  Reader exceptions re-raise in the consumer;
    abandoning the iterator mid-loop unblocks and stops the reader
    (same stop-event contract as corpus.manifest.prefetch_document_ranges),
    and :meth:`close` — also the context-manager exit — joins the
    thread so no daemon leaks past the loop's lifetime.

    ``watchdog_s`` bounds how long the consumer waits for the next
    window with the reader thread still alive before raising
    :class:`ReaderHang` (None disables); a reader thread that died
    without posting anything raises :class:`ReaderDied` immediately.

    ``read_wait_s`` / ``consume_wait_s`` accumulate the time the reader
    sat blocked on a free arena and the consumer sat blocked on a filled
    one — the pipeline-bubble split the bench stage report uses.  Each
    counter is written by exactly one thread (reader / consumer), so
    per-reader instances are race-free and the multi-worker path merges
    them by plain summation.

    ``windows`` is either a concrete window list (read in plan order)
    or a shared :class:`~..corpus.scheduler.StealQueue`: then each of K
    readers pulls the next undrained window when it has a free arena —
    the work-stealing schedule that keeps fast workers busy past a slow
    disk stripe.  Fault hooks fire on the window's GLOBAL plan index in
    both modes, so injection specs mean the same thing at any K.
    """

    def __init__(self, manifest, windows, depth: int = 2,
                 byte_capacity: int = 1 << 21, doc_capacity: int = 256,
                 arenas: list[WindowArena] | None = None,
                 watchdog_s: float | None = 30.0,
                 policy: "faults.RetryPolicy | None" = None,
                 report: "faults.DegradationReport | None" = None,
                 worker: int | None = None,
                 trace=None):
        self._manifest = manifest
        # a shared StealQueue (duck-typed on pop_window) or a plan list
        self._queue = windows if hasattr(windows, "pop_window") else None
        self._windows = [] if self._queue is not None else list(windows)
        # lease attribution under the steal-queue schedule: pops are
        # charged to this worker id so a worker death can requeue
        # exactly its windows (scheduler.StealQueue.fail_worker)
        self._worker = worker
        # optional obs.chrometrace.TraceEvents collector (--trace-out):
        # the reader thread records one "read" span per window
        self._trace = trace
        self._trace_tid = 100 + (worker or 0)  # chrometrace.READER_BASE
        self._depth = max(int(depth), 1)
        self._watchdog_s = watchdog_s
        self.policy = policy if policy is not None else faults.default_policy()
        self.report = report if report is not None else faults.current_report()
        self._ready: queue.Queue = queue.Queue()
        self._free: queue.Queue = queue.Queue()
        if arenas is None:
            arenas = [WindowArena(byte_capacity=byte_capacity,
                                  doc_capacity=doc_capacity)
                      for _ in range(self._depth + 1)]
        self.arenas = arenas  # caller may recycle the ring across runs
        for a in arenas:
            self._free.put(a)
        self._done = object()
        self._stop = threading.Event()
        self.read_wait_s = 0.0     # owned by: reader thread
        self.read_busy_s = 0.0     # owned by: reader thread
        self.consume_wait_s = 0.0  # owned by: consumer thread
        # Reading starts NOW, not at first iteration: the first window
        # has nothing to hide behind once consumption starts, so let it
        # fill while the caller sets up its scan state.
        self._thread = threading.Thread(target=self._reader, daemon=True)
        self._thread.start()

    def _get(self, q: queue.Queue):
        # bounded get that gives up when the other side is gone, so
        # neither thread can deadlock holding ring buffers
        while not self._stop.is_set():
            try:
                return q.get(timeout=0.05)
            except queue.Empty:
                continue
        return None

    def _iter_windows(self):
        """(global_index, (lo, hi)) pairs from the plan list or, under
        the multi-worker schedule, whatever the shared queue still
        holds — each pop is this reader 'stealing' the next window."""
        if self._queue is None:
            yield from enumerate(self._windows, start=1)
            return
        while True:
            if self._worker is not None:
                item = self._queue.pop_window(worker=self._worker)
            else:
                item = self._queue.pop_window()
            if item is None:
                return
            yield item

    def _reader(self) -> None:
        try:
            for wi, (lo, hi) in self._iter_windows():
                inj = faults.active()
                if inj is not None:
                    inj.on_reader_window(wi)
                t0 = time.perf_counter()
                arena = self._get(self._free)
                self.read_wait_s += time.perf_counter() - t0
                if arena is None:
                    return
                t0 = time.perf_counter()
                read_window_into(self._manifest, lo, hi, arena,
                                 policy=self.policy, report=self.report)
                t1 = time.perf_counter()
                self.read_busy_s += t1 - t0
                if self._trace is not None:
                    self._trace.span("read", t0, t1,
                                     tid=self._trace_tid,
                                     args={"window": wi})
                # the consumer needs the global plan index to ack the
                # lease (and the audit ledger keys on it)
                arena.window_index = wi
                self._ready.put(arena)
                # window wi is now fully read and handed downstream —
                # the crash-injection boundary the SIGKILL e2e tests
                # aim at (same global numbering at any worker count)
                if inj is not None:
                    inj.on_window_boundary(wi)
            self._ready.put(self._done)
        except faults.ReaderThreadDeath:
            # injected silent death: exit WITHOUT posting, so the
            # consumer watchdog — not this handler — must catch it
            return
        except BaseException as e:  # surfaced on the consumer side
            self._ready.put(e)

    def recycle(self, arena: WindowArena) -> None:
        """Return a consumed arena to the ring (MUST be called once per
        yielded arena, after the native scan no longer reads its views)."""
        self._free.put(arena)

    def close(self, timeout: float = 5.0) -> bool:
        """Stop the reader and join its thread (idempotent).

        Returns True when the thread exited within ``timeout``.  The
        stop event unblocks a reader waiting on a free arena; a reader
        stuck inside a hung read() can outlive the join — the False
        return (plus the daemon flag) means it can never block process
        exit, only linger.
        """
        self._stop.set()
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def __enter__(self) -> "PipelinedWindowReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _next_item(self):
        """Watchdog get: poll the ready queue, noticing a dead or hung
        reader instead of blocking forever."""
        t0 = time.perf_counter()
        while True:
            try:
                item = self._ready.get(timeout=0.05)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    raise ReaderDied(
                        "reader thread exited without delivering a "
                        "window or an error (see faults.py "
                        "reader-death)") from None
                waited = time.perf_counter() - t0
                if (self._watchdog_s is not None
                        and waited > self._watchdog_s):
                    raise ReaderHang(
                        f"reader made no progress in {waited:.1f}s "
                        "(watchdog_s exceeded); a hung filesystem "
                        "would otherwise deadlock the scan") from None
        self.consume_wait_s += time.perf_counter() - t0
        return item

    def __iter__(self):
        try:
            while True:
                item = self._next_item()
                if item is self._done:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self._stop.set()
