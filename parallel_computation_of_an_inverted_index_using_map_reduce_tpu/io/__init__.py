"""Host I/O subsystem: zero-copy ingest for the native host pipeline.

The reference reads each input file into a fresh heap block per mapper
(main.c:90-101); our previous host path did the Python equivalent —
``read_doc()`` bytes objects joined with ``b"".join`` and re-copied into
numpy — which put two token-scale copies and an allocator storm in
front of every scan.  This package replaces that with reusable window
arenas (`arena`), ``readinto``-based manifest readers (`reader`), and a
prefetching window executor (`executor`) that overlaps file reads with
the GIL-releasing native scan.
"""

from .arena import WindowArena
from .executor import PipelinedWindowReader, ReaderDied, ReaderHang
from .reader import plan_byte_windows, read_doc_into, read_window_into

__all__ = [
    "WindowArena",
    "PipelinedWindowReader",
    "ReaderDied",
    "ReaderHang",
    "plan_byte_windows",
    "read_doc_into",
    "read_window_into",
]
