"""Reusable document-window buffers.

A :class:`WindowArena` owns one uint8 byte buffer plus the int64
cumulative-end and int32 doc-id arrays the native entry points consume
(`mri_hidx_feed`, `mri_host_index`, `mri_stream_feed*` all share the
``(data, ends, ids)`` window ABI).  Filling an arena in place and
handing the native scan raw pointers removes both per-window copies the
old path paid — the ``b"".join`` of per-doc bytes objects and the
``np.frombuffer``/``np.full`` marshalling — and lets a ring of arenas
recycle the same pages window after window.
"""

from __future__ import annotations

import numpy as np


class WindowArena:
    """One reusable window: concatenated doc bytes + ends + doc ids.

    Grows geometrically when a window outsizes it and never shrinks, so
    a steady-state ring settles at the largest window seen and stops
    allocating.  Not thread-safe; a ring hands each arena to exactly one
    thread at a time (see executor.PipelinedWindowReader).
    """

    def __init__(self, byte_capacity: int = 1 << 21, doc_capacity: int = 256):
        self._buf = np.empty(max(int(byte_capacity), 1), dtype=np.uint8)
        self._ends = np.empty(max(int(doc_capacity), 1), dtype=np.int64)
        self._ids = np.empty(max(int(doc_capacity), 1), dtype=np.int32)
        self.used_bytes = 0
        self.num_docs = 0
        # global plan index of the window currently held (stamped by
        # the executor's reader thread; 0 = not window-tagged)
        self.window_index = 0

    def reset(self) -> "WindowArena":
        self.used_bytes = 0
        self.num_docs = 0
        return self

    def _grow_bytes(self, need: int) -> None:
        cap = self._buf.shape[0]
        while cap < need:
            cap *= 2
        buf = np.empty(cap, dtype=np.uint8)
        buf[: self.used_bytes] = self._buf[: self.used_bytes]
        self._buf = buf

    def _grow_docs(self) -> None:
        cap = self._ends.shape[0] * 2
        ends = np.empty(cap, dtype=np.int64)
        ids = np.empty(cap, dtype=np.int32)
        ends[: self.num_docs] = self._ends[: self.num_docs]
        ids[: self.num_docs] = self._ids[: self.num_docs]
        self._ends = ends
        self._ids = ids

    def view(self, nbytes: int) -> memoryview:
        """A writable view of the next ``nbytes`` (not yet committed)."""
        need = self.used_bytes + int(nbytes)
        if need > self._buf.shape[0]:
            self._grow_bytes(need)
        return memoryview(self._buf.data)[self.used_bytes:need]

    def commit(self, doc_id: int, nbytes: int) -> None:
        """Record one document occupying the next ``nbytes`` as written.

        ``nbytes`` may be smaller than the :meth:`view` request (short
        read); the arena advances by what was actually written.
        """
        if self.num_docs >= self._ends.shape[0]:
            self._grow_docs()
        self.used_bytes += int(nbytes)
        self._ends[self.num_docs] = self.used_bytes
        self._ids[self.num_docs] = doc_id
        self.num_docs += 1

    def append_bytes(self, doc_id: int, data: bytes) -> None:
        """Copy-in fallback for sources that only yield bytes objects."""
        n = len(data)
        self.view(n)[:] = data
        self.commit(doc_id, n)

    def feed_views(self):
        """``(buf, ends, ids)`` prefix views sized to the committed docs —
        zero-copy slices of the backing arrays, valid until the next
        :meth:`reset`/:meth:`view` growth."""
        return (
            self._buf[: self.used_bytes],
            self._ends[: self.num_docs],
            self._ids[: self.num_docs],
        )

    def contents(self) -> list[bytes]:
        """Per-doc bytes copies (compat path for list-of-bytes callers)."""
        out = []
        start = 0
        for i in range(self.num_docs):
            end = int(self._ends[i])
            out.append(self._buf[start:end].tobytes())
            start = end
        return out
