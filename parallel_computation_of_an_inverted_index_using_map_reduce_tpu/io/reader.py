"""Zero-copy manifest readers.

``read_doc_into`` is the single dispatch point between real-file
manifests (which gain a ``readinto`` fast path writing straight into an
arena) and the virtual corpus manifests (`corpus.synthetic`,
`corpus.realtext`), which are duck types whose ``read_doc`` generates
bytes — those fall back to one copy into the arena, still skipping the
join/marshal copies downstream.
"""

from __future__ import annotations

import sys

from .arena import WindowArena


def read_doc_into(manifest, index: int, dest: memoryview) -> int:
    """Read document ``index`` into ``dest``; bytes actually written.

    Dispatches to ``manifest.read_doc_into`` when the manifest offers
    one (real files, ``readinto``), else copies ``read_doc()`` output.
    ``dest`` is sized from the manifest's recorded document size; a
    document that shrank since the manifest was written yields a short
    count, one that grew is truncated to the recorded size (manifest
    sizes are authoritative for window planning).
    """
    fast = getattr(manifest, "read_doc_into", None)
    if fast is not None:
        return fast(index, dest)
    data = manifest.read_doc(index)
    n = min(len(data), len(dest))
    dest[:n] = data[:n]
    return n


def plan_byte_windows(manifest, target_bytes: int) -> list[tuple[int, int]]:
    """Contiguous ``[lo, hi)`` document ranges of ~``target_bytes`` each.

    Mirrors the byte-balanced window planning of the device paths: every
    window holds whole documents, at least one per window, split when
    the running size reaches the target.
    """
    n = len(manifest)
    windows: list[tuple[int, int]] = []
    lo = 0
    acc = 0
    for i in range(n):
        acc += int(manifest.sizes[i])
        if acc >= target_bytes:
            windows.append((lo, i + 1))
            lo = i + 1
            acc = 0
    if lo < n:
        windows.append((lo, n))
    return windows


def read_window_into(manifest, lo: int, hi: int,
                     arena: WindowArena) -> WindowArena:
    """Fill ``arena`` with documents ``[lo, hi)`` (arena is reset first).

    Unreadable documents are skipped with a warning — the same contract
    as corpus.manifest.iter_document_ranges, so a vanished file degrades
    the index instead of killing the run.
    """
    arena.reset()
    for i in range(lo, hi):
        size = int(manifest.sizes[i])
        try:
            dest = arena.view(size)
            n = read_doc_into(manifest, i, dest)
        except OSError as e:
            print(f"warning: skipping unreadable document "
                  f"{manifest.paths[i]}: {e}", file=sys.stderr)
            continue
        arena.commit(manifest.doc_id(i), n)
    return arena
