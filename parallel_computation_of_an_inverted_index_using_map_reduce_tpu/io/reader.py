"""Zero-copy manifest readers.

``read_doc_into`` is the single dispatch point between real-file
manifests (which gain a ``readinto`` fast path writing straight into an
arena) and the virtual corpus manifests (`corpus.synthetic`,
`corpus.realtext`), which are duck types whose ``read_doc`` generates
bytes — those fall back to one copy into the arena, still skipping the
join/marshal copies downstream.

Failure semantics (faults.py): every document read runs under the
pipeline :class:`~..faults.RetryPolicy` — transient OSErrors are
retried with backoff inside a per-document deadline, and only a
*persistent* failure degrades the run by skipping the document, which
is recorded (doc id, path, reason) in the active
:class:`~..faults.DegradationReport` instead of being a lone stderr
line the caller can't act on.
"""

from __future__ import annotations

import logging

from .. import faults
from .arena import WindowArena

log = logging.getLogger("mri_tpu.io")


def read_doc_into(manifest, index: int, dest: memoryview) -> int:
    """Read document ``index`` into ``dest``; bytes actually written.

    Dispatches to ``manifest.read_doc_into`` when the manifest offers
    one (real files, ``readinto``), else copies ``read_doc()`` output.
    ``dest`` is sized from the manifest's recorded document size; a
    document that shrank since the manifest was written yields a short
    count, one that grew is truncated to the recorded size (manifest
    sizes are authoritative for window planning).
    """
    inj = faults.active()
    cap = None
    if inj is not None:
        cap = inj.on_read(index, manifest.paths[index])
    fast = getattr(manifest, "read_doc_into", None)
    if fast is not None:
        n = fast(index, dest)
    else:
        data = manifest.read_doc(index)
        n = min(len(data), len(dest))
        dest[:n] = data[:n]
    if cap is not None:
        n = min(n, cap)
    return n


def plan_byte_windows(manifest, target_bytes: int) -> list[tuple[int, int]]:
    """Contiguous ``[lo, hi)`` document ranges of ~``target_bytes`` each.

    Mirrors the byte-balanced window planning of the device paths: every
    window holds whole documents, at least one per window, split when
    the running size reaches the target.
    """
    n = len(manifest)
    windows: list[tuple[int, int]] = []
    lo = 0
    acc = 0
    for i in range(n):
        acc += int(manifest.sizes[i])
        if acc >= target_bytes:
            windows.append((lo, i + 1))
            lo = i + 1
            acc = 0
    if lo < n:
        windows.append((lo, n))
    return windows


def read_window_into(manifest, lo: int, hi: int, arena: WindowArena,
                     policy: "faults.RetryPolicy | None" = None,
                     report: "faults.DegradationReport | None" = None,
                     ) -> WindowArena:
    """Fill ``arena`` with documents ``[lo, hi)`` (arena is reset first).

    Each document read is retried per ``policy`` (default: the
    env-tuned pipeline policy); a document that stays unreadable is
    skipped and recorded in ``report`` (default: the run's active
    report) — the same degrade-don't-die contract as
    corpus.manifest.iter_document_ranges, now with the outcome
    *reported* instead of merely printed.  One counted warning line per
    window covers every skip in it.
    """
    if policy is None:
        policy = faults.default_policy()
    if report is None:
        report = faults.current_report()
    arena.reset()
    window_skips = 0
    for i in range(lo, hi):
        size = int(manifest.sizes[i])
        dest = arena.view(size)
        try:
            n = policy.run(
                lambda: read_doc_into(manifest, i, dest),
                doc_id=manifest.doc_id(i), path=manifest.paths[i],
                report=report)
        except OSError as e:
            report.record_skip(doc_id=manifest.doc_id(i),
                               path=manifest.paths[i], reason=str(e))
            window_skips += 1
            continue
        arena.commit(manifest.doc_id(i), n)
    if window_skips:
        log.warning("skipped %d unreadable document(s) in window "
                    "[%d, %d) after retries", window_skips, lo, hi)
    return arena
