"""Framework configuration.

The reference exposes exactly three positional CLI args — num_mappers,
num_reducers, input list (main.c:248-255) — plus compile-time caps
(main.c:7-11).  Here those become an explicit, validated config object;
mapper/reducer counts map onto host shards and device hash buckets.
"""

from __future__ import annotations

import dataclasses


# Reference compile-time caps (main.c:7-11).  MAX_WORD bounds the *cleaned*
# token: the reference keeps at most MAX_WORD-1 = 299 letters per token
# (main.c:105 loop guard `j < MAX_WORD - 1`).
MAX_WORD_LETTERS = 299
ALPHABET_SIZE = 26


@dataclasses.dataclass(frozen=True)
class IndexConfig:
    """End-to-end pipeline configuration.

    ``num_mappers`` / ``num_reducers`` keep the reference CLI's meaning as
    *host shard count* and *reduce partition count*; on device, the work is
    balanced by sort/hash regardless (the reference's 1000x letter skew,
    SURVEY.md §2.3, does not survive the redesign).
    """

    # CLI-compat knobs.  The reference's output is invariant to its thread
    # counts (SURVEY.md §2.3 determinism), and so is ours: ``num_mappers``
    # sets the host map-phase thread count when ``host_threads`` is unset
    # (the reference's mapper threads, main.c:348-365, re-expressed —
    # byte-identical output at any count).  On ``backend="cpu"`` with
    # read-ahead on, that count is K scan workers, each with its own
    # arena ring + reader + incremental native handle, pulling byte
    # windows from a shared steal queue; ``num_reducers`` is then M
    # reducer threads owning contiguous letter ranges
    # (corpus/scheduler.plan_letter_ranges — the reference's reducer
    # ownership, main.c:129-130) over the merged vocabulary.  On device,
    # reduce is balanced by sort/hash regardless, so the reference's
    # 1000x letter skew (SURVEY.md §2.3) cannot recur.
    num_mappers: int = 1
    num_reducers: int = 1
    # "tpu"    — device engine (jit sort pipeline; pipelined/one-shot plans)
    # "cpu"    — whole pipeline in one native C++ call, no accelerator
    #            (the reference's all-on-host regime without its
    #            pathologies; falls back to "oracle" if g++ is absent)
    # "oracle" — pure-Python dict oracle, the conformance seam
    backend: str = "tpu"
    output_dir: str = "."         # where a.txt .. z.txt are written
    # Pad token-count up to a multiple of this so XLA re-uses compiled
    # programs across similarly-sized corpora instead of recompiling.
    pad_multiple: int = 1 << 16
    # Device shards for the multi-chip engine (parallel/dist_engine.py):
    # None = all visible devices; 1 = force the single-chip engine.
    device_shards: int | None = None
    profile_dir: str | None = None  # write a jax.profiler trace of the device phase
    # Host tokenizer: C++ (native/tokenizer.cc, built on first use) with
    # automatic fallback to the vectorized numpy path.
    use_native: bool = True
    # Durable map-phase artifact (the analogue of the reference's spill
    # files, which double as a checkpoint — SURVEY.md §5): save the
    # tokenized pair arrays here, and resume from them if present.
    checkpoint_path: str | None = None
    # Measure shuffle-partition skew on device (utils/stats.py): letter
    # partitioning vs hash buckets.  Off the hot path; adds a device
    # round-trip, so opt-in.
    collect_skew_stats: bool = False
    # Streaming mode (SURVEY.md §5 long-context): process the corpus in
    # windows of this many whole documents with a bounded device
    # accumulator (ops/streaming.py) instead of one-shot arrays.  None =
    # single-shot.  Output is byte-identical either way.
    stream_chunk_docs: int | None = None
    # Single-chip pipelined fast path (native tokenizer + provisional-key
    # device sort): documents per upload window.  None = auto (two windows:
    # window 1's upload overlaps window 2's tokenize); 0 disables the
    # pipelined path entirely (forces the one-shot engine).
    pipeline_chunk_docs: int | None = None
    # Windowed overlap plan (single-chip pipelined variant for
    # high-latency host<->device links): this fraction of corpus bytes —
    # the LAST contiguous doc range — is indexed on the host (numpy sort
    # of its packed keys) while the earlier windows' device sorts and
    # async fetches are still in flight, so the device round-trip
    # latency hides under host work instead of serializing after it.
    # Emit concatenates the per-window runs in doc order (no merge
    # pass).  None = disabled (plain pipelined plan); must be in (0, 1).
    overlap_tail_fraction: float | None = None
    # Device windows for the overlap plan: 2 issues the first fetch
    # earlier; 1 halves the dispatch RPCs (wins when per-call link
    # overhead dominates the hidden round trip).
    overlap_device_windows: int = 2
    # Byte split between the two device windows (first window's share
    # of the device fraction).  The fetch wait left after the scan is
    # proportional to the LAST window's bytes (its fetch is issued
    # latest), so a larger first window shrinks the residual — at the
    # cost of issuing that bigger upload later into the scan.  A grid
    # probe, like the tail fraction.
    overlap_window_split: float = 0.55
    # Device-side tokenizer (ops/device_tokenizer.py): raw corpus bytes
    # go up, the finished index comes down — the ENTIRE map phase (byte
    # classify, token segmentation, cleaning, dedup, df, postings) as
    # one XLA program; no host scan at all.  Exact (no hashing): words
    # live as fixed-width byte rows sorted lexicographically; a cleaned
    # token longer than ``device_tokenize_width`` aborts to the host
    # path (WidthOverflow), keeping output byte-identical always.
    # Single chip; wins where the host<->device link is cheap (local
    # PCIe) — on a high-RTT link the host-scan plans win end-to-end.
    device_tokenize: bool = False
    # Word-row width in bytes (multiple of 4; >= the longest cleaned
    # token or the run falls back).  48 covers real text with margin
    # (reference corpus max: 38 letters).
    device_tokenize_width: int = 48
    # Host map-phase threads: the native tokenizer's fork-join worker
    # count AND the pipelined cpu path's scan-worker count (one arena
    # ring + reader + native handle per worker, windows from a shared
    # steal queue; merged at vocab scale — output-identical at any
    # count).  None = ``num_mappers`` if > 1, else auto (min(cores, 8)).
    host_threads: int | None = None
    # Crash-resumable streaming for the single-chip all-device engine:
    # persist the bounded row accumulator's VERIFIED valid prefix plus
    # the stream position here every ``stream_checkpoint_every``
    # windows (utils/checkpoint.save_stream_state, atomic).  A rerun
    # with the same manifest + stream config resumes at the last
    # checkpointed window instead of restarting — the durable-spill
    # role of the reference's partial_<letter>.txt files
    # (main.c:332-341), which survive a crash and make the remaining
    # work re-runnable.  Motivated by a real failure: the round-3
    # 1M-doc on-chip run lost ~9 minutes of stream to a TPU worker
    # crash (SCALE_r03.json device_stream_real_tpu).
    stream_checkpoint: str | None = None
    stream_checkpoint_every: int = 2
    # Checkpoint-trust policy at resume time:
    #   "strict" — a corrupt checkpoint file is a hard error
    #              (utils/checkpoint.CheckpointCorrupt); mismatched
    #              version/fingerprint stays a hard error in both modes
    #   "auto"   — a corrupt file is quarantined to ``<path>.corrupt``
    #              and the run restarts fresh (crash-safe auto-resume:
    #              a SIGKILL mid-save must never wedge the rerun)
    resume: str = "strict"
    # Letter-file writer:
    #   "auto"   — native vectorized emit (tokenizer.cc EmitLettersRuns:
    #              pre-rendered id strings, single-allocation render,
    #              atomic tmp+rename per letter) when the library is
    #              loadable, else the pure-Python formatter
    #   "native" — require the native path (error if unavailable)
    #   "python" — force the pure-Python formatter (the byte-parity
    #              oracle; same atomic write contract)
    # Output is byte-identical across all three.  backend="cpu" fuses
    # scan and emit inside one native call, so this knob governs the
    # device engines' emit tail; an all-Python cpu run is use_native=False
    # (the oracle).
    emit_backend: str = "auto"
    # Read-ahead depth for the host pipeline (backend="cpu"): the reader
    # thread keeps up to this many ~2 MB window arenas filled while the
    # native scan (GIL released) chews the current one.  0 disables the
    # pipelined ingest path (one-shot load + native call).
    io_prefetch: int = 2
    # Integrity audit (audit.py): per-window feed ledger + merge
    # invariant checks before emit on the parallel host path, and an
    # ``index.manifest.json`` output manifest (per-letter-file md5)
    # written after every emit — ``--verify`` re-checks it later.
    # Recovery bugs surface as AuditError (exit 2), never as silently
    # wrong bytes.  Cheap (<5% of cpu_ms; bench tracks ``audit_ms``),
    # but off by default to keep the measured hot path exact.
    audit: bool = False
    # Emit-side ownership for the multi-chip pipelined path:
    #   "merged" — one host assembles and writes all 26 files (default)
    #   "letter" — pairs are exchanged by *letter owner*
    #              (corpus/scheduler.plan_letter_ranges — the reference's
    #              reducer ownership, main.c:129-150) and each owner
    #              emits only its own letter files; no global merge
    #              anywhere.  The multi-host emit strategy.
    emit_ownership: str = "merged"
    # Serving artifact (serve/artifact.py): pack a compact mmap-able
    # ``index.mri`` next to the letter files at emit time, so the query
    # engine (``mri-tpu query``, serve.Engine) never re-parses text.
    # Needs the merged postings on one host: incompatible with the
    # letter-ownership emit and the overlap plan's split emit.
    artifact: bool = False
    # Chrome trace_event export (obs.chrometrace): write the run's
    # per-stage timeline — reader windows, per-worker scans, reducer
    # emit ranges, merge, artifact pack — to this file after the build,
    # loadable in chrome://tracing / Perfetto.  Host pipeline only; the
    # oracle and tpu backends write a valid but sparse trace.
    trace_out: str | None = None

    def resolved_host_threads(self) -> int:
        """The map-phase thread count this run will actually use."""
        if self.host_threads is not None:
            return self.host_threads
        if self.num_mappers > 1:
            return self.num_mappers
        from .native import default_threads

        return default_threads()

    def __post_init__(self) -> None:
        if self.num_mappers < 1:
            raise ValueError(f"num_mappers must be >= 1, got {self.num_mappers}")
        if self.num_reducers < 1:
            raise ValueError(f"num_reducers must be >= 1, got {self.num_reducers}")
        if self.backend not in ("tpu", "cpu", "oracle"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.pad_multiple < 1:
            raise ValueError("pad_multiple must be >= 1")
        if self.device_shards is not None and self.device_shards < 1:
            raise ValueError(
                f"device_shards must be >= 1 or None (auto), got {self.device_shards}")
        if self.backend != "tpu":
            # device-era options the host backends do not implement: fail
            # loudly rather than silently ignore a flag the user passed
            for flag in ("stream_chunk_docs", "checkpoint_path", "profile_dir"):
                if getattr(self, flag) is not None:
                    raise ValueError(
                        f"{flag} requires backend='tpu', got backend={self.backend!r}")
            if self.collect_skew_stats:
                raise ValueError(
                    "collect_skew_stats requires backend='tpu', "
                    f"got backend={self.backend!r}")
        if self.pipeline_chunk_docs is not None and self.pipeline_chunk_docs < 0:
            raise ValueError(
                "pipeline_chunk_docs must be >= 1, 0 (disabled) or None (auto), "
                f"got {self.pipeline_chunk_docs}")
        if self.backend not in ("tpu",) and self.pipeline_chunk_docs is not None:
            raise ValueError(
                f"pipeline_chunk_docs requires backend='tpu', got backend={self.backend!r}")
        if self.overlap_tail_fraction is not None:
            if not 0.0 < self.overlap_tail_fraction < 1.0:
                raise ValueError(
                    "overlap_tail_fraction must be in (0, 1) or None, "
                    f"got {self.overlap_tail_fraction}")
            if self.backend != "tpu":
                raise ValueError(
                    "overlap_tail_fraction requires backend='tpu', "
                    f"got backend={self.backend!r}")
            if self.pipeline_chunk_docs == 0:
                raise ValueError(
                    "overlap_tail_fraction requires the pipelined path "
                    "(pipeline_chunk_docs=0 disables it)")
            if self.stream_chunk_docs is not None:
                raise ValueError(
                    "overlap_tail_fraction is incompatible with "
                    "stream_chunk_docs (the streaming engine has its own "
                    "window pipeline)")
            if self.emit_ownership == "letter":
                raise ValueError(
                    "overlap_tail_fraction is single-chip; "
                    "emit_ownership='letter' is the multi-chip emit path")
        if self.artifact:
            if self.emit_ownership == "letter":
                raise ValueError(
                    "artifact requires the merged emit (one host holds "
                    "the global postings); emit_ownership='letter' "
                    "splits them across owners")
            if self.overlap_tail_fraction is not None:
                raise ValueError(
                    "artifact is incompatible with overlap_tail_fraction "
                    "(the overlap plan emits from two disjoint partial "
                    "indexes, never materializing merged postings)")
        if self.overlap_device_windows not in (1, 2):
            raise ValueError(
                f"overlap_device_windows must be 1 or 2, "
                f"got {self.overlap_device_windows}")
        if not (0.0 < self.overlap_window_split < 1.0):
            raise ValueError(
                f"overlap_window_split must be in (0, 1), "
                f"got {self.overlap_window_split}")
        # upper bound 296 (< MAX_WORD_LETTERS): a width that could hold
        # a 299+-letter token would silently skip the reference's 299
        # cap (main.c:105) instead of falling back to the host path
        if not (4 <= self.device_tokenize_width <= 296
                and self.device_tokenize_width % 4 == 0):
            raise ValueError(
                "device_tokenize_width must be a multiple of 4 in [4, 296], "
                f"got {self.device_tokenize_width}")
        if self.device_tokenize:
            if self.backend != "tpu":
                raise ValueError(
                    "device_tokenize requires backend='tpu', "
                    f"got backend={self.backend!r}")
            for flag in ("checkpoint_path",
                         "pipeline_chunk_docs", "overlap_tail_fraction"):
                if getattr(self, flag) is not None:
                    raise ValueError(
                        f"device_tokenize is a complete engine; {flag} "
                        "belongs to the host-scan plans")
            if self.collect_skew_stats:
                raise ValueError(
                    "device_tokenize is incompatible with collect_skew_stats "
                    "(no host-side pair ids exist)")
            # letter + stream_chunk_docs is rejected by the general
            # emit_ownership='letter' block below
        if self.host_threads is not None and self.host_threads < 1:
            raise ValueError(
                f"host_threads must be >= 1 or None (auto), got {self.host_threads}")
        if self.emit_backend not in ("auto", "native", "python"):
            raise ValueError(
                f"emit_backend must be 'auto', 'native' or 'python', "
                f"got {self.emit_backend!r}")
        if self.io_prefetch < 0:
            raise ValueError(
                f"io_prefetch must be >= 0 (0 disables read-ahead), "
                f"got {self.io_prefetch}")
        if self.emit_ownership not in ("merged", "letter"):
            raise ValueError(
                f"emit_ownership must be 'merged' or 'letter', got {self.emit_ownership!r}")
        if self.emit_ownership == "letter":
            if self.backend != "tpu":
                raise ValueError(
                    f"emit_ownership='letter' requires backend='tpu', "
                    f"got backend={self.backend!r}")
            if self.stream_chunk_docs is not None:
                raise ValueError(
                    "emit_ownership='letter' requires the pipelined multi-chip "
                    "path (incompatible with stream_chunk_docs)")
            if self.pipeline_chunk_docs == 0:
                raise ValueError(
                    "emit_ownership='letter' requires the pipelined multi-chip "
                    "path (pipeline_chunk_docs=0 disables it)")
        if self.resume not in ("strict", "auto"):
            raise ValueError(
                f"resume must be 'strict' or 'auto', got {self.resume!r}")
        if self.stream_checkpoint_every < 1:
            raise ValueError(
                f"stream_checkpoint_every must be >= 1, "
                f"got {self.stream_checkpoint_every}")
        if self.stream_checkpoint is not None:
            if not (self.device_tokenize
                    and self.stream_chunk_docs is not None):
                raise ValueError(
                    "stream_checkpoint requires the streaming all-device "
                    "engine (device_tokenize=True with stream_chunk_docs)")
            if self.device_shards != 1:
                raise ValueError(
                    "stream_checkpoint is single-chip only: pass "
                    "device_shards=1 explicitly (None routes to the mesh "
                    "streaming engine when several devices are visible, "
                    f"which has no checkpoint); got {self.device_shards}")
        if self.stream_chunk_docs is not None:
            if self.stream_chunk_docs < 1:
                raise ValueError(
                    f"stream_chunk_docs must be >= 1 or None, got {self.stream_chunk_docs}")
            # options the windowed pipeline does not implement: fail
            # loudly rather than silently ignore a flag the user passed
            if self.checkpoint_path is not None:
                raise ValueError(
                    "stream_chunk_docs is incompatible with checkpoint_path "
                    "(the accumulator itself is the evolving map-phase state)")
            if self.collect_skew_stats:
                raise ValueError(
                    "stream_chunk_docs is incompatible with collect_skew_stats "
                    "(per-window pair ids are discarded after each merge)")
            # device_shards > 1 routes to the distributed streaming
            # accumulator (parallel/dist_streaming.py)
