"""Scale-out serving: doc-sharded cluster with a scatter-gather router.

The reference paper's whole design is partitioned parallelism —
mappers split the corpus, reducers own disjoint key ranges — and this
package applies the same shape to the serving tier (the "Sorting,
Searching, and Simulation in the MapReduce Framework" simulation
argument, PAPERS.md): partition the corpus into D doc-shards, each its
own ``mri serve`` daemon over a plain artifact dir plus a
``cluster_shard.json`` sidecar, and run a router process that speaks
the identical JSON-lines protocol — scatter every data op to all
shards, gather with the same D-way merges
:class:`~..serve.multi_engine.MultiSegmentEngine` uses, stretched over
TCP.

Layout:

* :mod:`.partition` — ``mri shard``: doc assignment (round-robin /
  size-balanced), per-shard artifact builds, global BM25 stats, and
  the byte-verified manifests.
* :mod:`.shard` — the sidecar + :class:`~.shard.ShardEngine` wrapper a
  shard daemon serves through (global doc ids + injected global
  stats, so shard answers need no router-side remapping).
* :mod:`.pool` — persistent pipelined per-replica connections,
  health-probe state, and per-shard replica failover.
* :mod:`.hedge` — the hedging clock (fire a duplicate RPC after
  ``MRI_CLUSTER_HEDGE_MS`` or the shard's rolling p95).
* :mod:`.router` — the ``mri router`` daemon: admission, scatter,
  gather, fleet health, merged scrapes.
"""

from __future__ import annotations

SIDECAR_NAME = "cluster_shard.json"
CLUSTER_MANIFEST = "cluster.json"

__all__ = ["SIDECAR_NAME", "CLUSTER_MANIFEST"]
