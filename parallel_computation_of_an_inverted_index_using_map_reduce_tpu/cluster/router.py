"""``mri router`` — scatter-gather serving over doc-sharded daemons.

The router speaks the daemon's exact JSON-lines protocol on both
sides: clients connect to it as if it were one big ``mri serve`` (same
ops, same error kinds, same ``id``/``trace_id`` echo), and it fans
every data op out to D shard daemons over persistent pipelined
connections, then gathers with the same D-way merges
:class:`~..serve.multi_engine.MultiSegmentEngine` uses in-process —
the cluster is MultiSegmentEngine stretched over TCP.

Fan-out cost: each client query is JSON-encoded ONCE (RPC ids come
from a process-global counter, so one encoded line is valid on every
shard connection simultaneously) and its gather is resolved on
whichever shard connection answers last — no per-request threads, no
router-side queueing beyond the admission gate.

Correctness of the gather (why answers are byte-identical to a
monolithic build of the same corpus):

* shards answer in GLOBAL doc ids with GLOBAL BM25 stats injected at
  engine-open (cluster/shard.py), so ranked scores are bit-equal and
  per-shard ranked streams are disjoint — ``merge_ranked`` over
  ``(-score, gid)`` reproduces the monolith's exact tie order;
* AND/OR/postings streams are ascending and disjoint —
  ``merge_doc_ids`` is a pure ascending merge;
* ``df`` is an elementwise integer sum (each doc lives in exactly one
  shard);
* letter ``top_k`` runs threshold refinement: scatter a k2-deep local
  top, sum exact global dfs for the candidate union, and accept only
  when the kth candidate's global df strictly beats the sum of the
  per-shard k2-th dfs over non-exhausted shards — no unseen term can
  outrank an accepted one.

Tail tolerance: every shard RPC may be **hedged** (a duplicate to a
different ready replica after ``MRI_CLUSTER_HEDGE_MS`` or the shard's
rolling p95; first answer wins) and **fails over** on connection
death, not-ready health probes (PR 14 reasons: draining / stalled /
overloaded / replica_lagging), or retryable error answers.  A query is
acknowledged only after its merged response is written — a replica
killed mid-RPC loses zero acknowledged queries
(``mri_cluster_failovers_total`` counts the reroutes).
"""

from __future__ import annotations

import json
import logging
import queue
import socket
import threading
import time

from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import tracing as obs_tracing
from ..obs import windows as obs_windows
from ..serve import result_cache as result_cache_mod
from ..serve.daemon import ADMIN_OPS, OUTBOUND_DEPTH
from ..serve.multi_engine import merge_doc_ids, merge_ranked
from ..utils import envknobs
from .. import faults
from . import hedge as hedge_mod
from . import pool as pool_mod

log = logging.getLogger("mri_tpu.cluster")

HEDGE_ENV = "MRI_CLUSTER_HEDGE_MS"
HEALTH_ENV = "MRI_CLUSTER_HEALTH_MS"
INFLIGHT_ENV = "MRI_CLUSTER_INFLIGHT"
RPC_TIMEOUT_ENV = "MRI_CLUSTER_RPC_TIMEOUT_MS"
PARTIAL_ENV = "MRI_CLUSTER_PARTIAL"
RETRY_BUDGET_ENV = "MRI_CLUSTER_RETRY_BUDGET"

#: admission counters share the daemon's family names on purpose: the
#: router IS a serve-plane daemon, so the SLO tracker, the rolling
#: windows, and ``mri top`` price it with zero new math
_COUNTER_NAMES = (
    ("requests", "mri_serve_requests_total"),
    ("responses", "mri_serve_responses_total"),
    ("shed", "mri_serve_shed_total"),
    ("deadline_expired", "mri_serve_deadline_expired_total"),
    ("draining_rejected", "mri_serve_draining_rejected_total"),
    ("bad_request", "mri_serve_bad_request_total"),
    ("internal_errors", "mri_serve_internal_errors_total"),
    ("client_disconnects", "mri_serve_client_disconnects_total"),
    ("slow_client_closes", "mri_serve_slow_client_closes_total"),
    ("connections", "mri_serve_connections_total"),
    ("scatter_rpcs", "mri_router_scatter_rpcs_total"),
    ("hedges", "mri_cluster_hedges_total"),
    ("hedge_wins", "mri_cluster_hedge_wins_total"),
    ("failovers", "mri_cluster_failovers_total"),
    ("shard_errors", "mri_cluster_shard_errors_total"),
    ("shard_unavailable", "mri_cluster_shard_unavailable_total"),
    ("partial", "mri_cluster_partial_total"),
    ("retry_denied", "mri_cluster_retry_denied_total"),
)

#: shard error answers the router retries on another replica — the
#: shard refused to serve, it did not serve wrongly
_RETRYABLE = ("draining", "overloaded", "stale_generation")

#: admin ops the router answers itself (everything else is a
#: shard-local concern — mutations go to the shard primaries directly)
_ROUTER_ADMIN = ("stats", "healthz", "metrics", "slo")

_SENTINEL = object()


def parse_shard_arg(spec: str) -> list[list[tuple]]:
    """``--shards`` grammar: shards joined by ``,``, replicas of one
    shard joined by ``|`` — ``h:1|h:2,h:3`` is two shards, the first
    with two replicas.  Returns ``[[(host, port), ...], ...]``."""
    shards = []
    for si, part in enumerate(s for s in spec.split(",") if s.strip()):
        reps = []
        for ep in part.split("|"):
            host, _, port_s = ep.strip().rpartition(":")
            try:
                port = int(port_s)
                if not host or not (0 < port <= 65535):
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"shard {si}: bad endpoint {ep.strip()!r} "
                    "(want HOST:PORT)") from None
            reps.append((host, port))
        shards.append(reps)
    if not shards:
        raise ValueError("--shards lists no endpoints")
    return shards


def parse_partial_policy(spec) -> tuple:
    """``partial_policy`` grammar: ``fail`` (any unanswerable shard
    fails the whole request — the byte-compat default) or
    ``allow[:min_coverage=F]`` (answer from the shards that did
    answer, flagged with ``partial``+``coverage`` metadata, provided
    at least fraction F of the corpus answered; F defaults to 0).
    Returns ``(policy, min_coverage)``."""
    if not isinstance(spec, str):
        raise ValueError("partial_policy must be a string")
    s = spec.strip()
    if s == "fail":
        return ("fail", 1.0)
    if s == "allow":
        return ("allow", 0.0)
    if s.startswith("allow:"):
        key, _, val = s[len("allow:"):].partition("=")
        if key.strip() == "min_coverage":
            try:
                f = float(val)
            except ValueError:
                raise ValueError(
                    f"partial_policy: min_coverage {val!r} is not a "
                    "number") from None
            if 0.0 <= f <= 1.0:
                return ("allow", f)
            raise ValueError(
                "partial_policy: min_coverage must be in [0, 1]")
    raise ValueError(f"partial_policy {spec!r}: want 'fail' or "
                     "'allow[:min_coverage=F]'")


class _ClientConn:
    """One accepted client connection: reader thread (parse + admit),
    writer thread (sole socket writer) — the daemon's _Conn shape."""

    _ids = iter(range(1, 1 << 62))

    def __init__(self, router: "RouterDaemon", sock: socket.socket,
                 addr):
        self.router = router
        self.sock = sock
        self.addr = addr
        self.outbound: queue.Queue = queue.Queue(maxsize=OUTBOUND_DEPTH)
        self.dead = False
        self.reader_done = False
        self.writer_done = False
        cid = next(self._ids)
        self.reader = threading.Thread(
            target=router._reader_loop, args=(self,), daemon=True,
            name=f"mri-router-cread-{cid}")
        self.writer = threading.Thread(
            target=router._writer_loop, args=(self,), daemon=True,
            name=f"mri-router-cwrite-{cid}")

    def start(self) -> None:
        self.reader.start()
        self.writer.start()

    def enqueue(self, payload: dict) -> bool:
        data = (json.dumps(payload, separators=(",", ":"))
                + "\n").encode()
        try:
            self.outbound.put_nowait(data)
            return True
        except queue.Full:
            if not self.dead:
                self.router._count("slow_client_closes")
            self.kill()
            return False

    def enqueue_sentinel(self) -> None:
        try:
            self.outbound.put_nowait(_SENTINEL)
        except queue.Full:
            self.kill()

    def kill(self) -> None:
        self.dead = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    @property
    def finished(self) -> bool:
        return self.reader_done and self.writer_done


class _Scatter:
    """One admitted client data request fanned out to all shards."""

    __slots__ = ("conn", "rid", "op", "tid", "line", "rpc_id",
                 "t_admit", "explain", "k", "done", "lock", "parts",
                 "remaining", "calls", "deadline_timer",
                 "timeout_timer", "hedged", "failovers", "policy",
                 "min_cov", "missing", "ckey", "epoch")

    def __init__(self, conn, rid, op, tid, line, rpc_id, t_admit,
                 explain, k, nshards, policy="fail", min_cov=1.0,
                 ckey=None, epoch=None):
        self.conn = conn
        self.rid = rid
        self.op = op
        self.tid = tid
        self.line = line
        self.rpc_id = rpc_id
        self.t_admit = t_admit
        self.explain = explain
        self.k = k
        self.done = False  # guarded by: self.lock
        self.lock = threading.Lock()
        self.parts: list = [None] * nshards  # guarded by: self.lock
        self.remaining = nshards  # guarded by: self.lock
        self.calls: list = [None] * nshards
        self.deadline_timer = None
        self.timeout_timer = None  # one RPC-timeout timer for all legs
        self.hedged: list = []  # shard idx, for explain
        self.failovers = 0
        self.policy = policy  # partial_policy: "fail" | "allow"
        self.min_cov = min_cov  # docs_fraction floor under "allow"
        self.missing: list = []  # unanswerable shards  # guarded by: self.lock
        self.ckey = ckey   # result-cache key (None: not cacheable)
        self.epoch = epoch  # shard-generation vector at admission


class _ShardCall:
    """One shard's leg of a scatter: replica attempts + hedge timer."""

    __slots__ = ("tried", "conns", "hedge_timer",
                 "t0", "first_replica", "hedge_replica", "live",
                 "attempts", "done")

    def __init__(self):
        self.tried: set = set()  # guarded by: the scatter's lock
        self.conns: list = []  # guarded by: the scatter's lock
        self.hedge_timer = None
        self.t0 = 0.0
        self.first_replica = -1
        self.hedge_replica = -1
        self.live = 0  # in-flight attempts  # guarded by: the scatter's lock
        self.attempts = 0  # lifetime sends incl. hedges  # guarded by: the scatter's lock
        self.done = False  # guarded by: the scatter's lock


class RouterDaemon:
    """The scatter-gather front door.  ``start()`` connects the shard
    pool, probes health, and binds; ``drain()`` is the graceful exit.
    """

    def __init__(self, shard_addrs: list, host: str = "127.0.0.1",
                 port: int = 0, *, hedge_ms: float | None = None,
                 inflight: int | None = None,
                 rpc_timeout_ms: float | None = None,
                 health_ms: int | None = None,
                 drain_s: float = 5.0):
        if not shard_addrs:
            raise ValueError("router needs at least one shard")
        self._host = host
        self._port = port
        self.hedge_ms = hedge_ms if hedge_ms is not None \
            else envknobs.get(HEDGE_ENV)
        self.max_inflight = inflight if inflight is not None \
            else envknobs.get(INFLIGHT_ENV)
        self.rpc_timeout_s = (rpc_timeout_ms if rpc_timeout_ms
                              is not None
                              else envknobs.get(RPC_TIMEOUT_ENV)) / 1e3
        health_ms = health_ms if health_ms is not None \
            else envknobs.get(HEALTH_ENV)
        self.drain_s = drain_s
        self.partial_spec = envknobs.get(PARTIAL_ENV)
        self.partial_default = parse_partial_policy(self.partial_spec)
        self.retry_budget_ratio = envknobs.get(RETRY_BUDGET_ENV)

        self.shards = [pool_mod.ShardClient(
                           i, addrs,
                           retry_budget_ratio=self.retry_budget_ratio)
                       for i, addrs in enumerate(shard_addrs)]
        # per-shard corpus sizes (learned from the shard engines'
        # sidecar-fed describe()) back docs_fraction in coverage
        # metadata; None until the background learner hears back
        self._shard_docs: list = [None] * len(shard_addrs)
        self._total_docs: int | None = None
        self.registry = obs_metrics.Registry()
        self._counts = {key: self.registry.counter(name)
                        for key, name in _COUNTER_NAMES}
        self._g_shards = self.registry.gauge("mri_cluster_shards")
        self._g_shards.set(len(self.shards))
        self._g_ready = self.registry.gauge(
            "mri_cluster_replicas_ready")
        self._g_inflight = self.registry.gauge("mri_serve_inflight")
        self._g_draining = self.registry.gauge("mri_serve_draining")
        self._g_breakers = self.registry.gauge(
            "mri_cluster_breakers_open")
        self._h_request = self.registry.histogram(
            "mri_serve_request_seconds")
        self._rolling = obs_windows.RollingWindows(
            self.registry,
            counters=[name for _key, name in _COUNTER_NAMES],
            histograms=("mri_serve_request_seconds",))
        self._slo = obs_slo.SLOTracker(self._rolling)
        self._obs_enabled = obs_tracing.enabled()
        # whole-answer cache above the scatter, keyed on the vector of
        # per-shard generations learned from health probes — a hot
        # query at a fully-known, agreed epoch never fans out.  The
        # epoch lags a shard mutation by at most one health-probe
        # period (MRI_CLUSTER_HEALTH_MS); until the prober re-agrees,
        # the epoch is unknown and every query bypasses the cache.
        self._result_cache = result_cache_mod.ResultCache(
            registry=self.registry)

        self.clock = hedge_mod.Clock()
        self.prober = pool_mod.HealthProber(
            self.shards, health_ms / 1e3,
            on_transition=self._health_transition)
        self._count_lock = threading.Lock()
        self._inflight = 0  # guarded by: self._count_lock
        self._seq = 0  # data-request ordinal (faults)  # guarded by: self._count_lock
        self._conns: set = set()  # guarded by: self._conn_lock
        self._conn_lock = threading.Lock()
        self._draining = False
        self._drain_guard = threading.Lock()
        self._drain_started = False  # guarded by: self._drain_guard
        self._drained = threading.Event()
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self.final_stats: dict | None = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self.prober.start()
        self._rolling.start()
        threading.Thread(target=self._learn_shard_docs, daemon=True,
                         name="mri-router-docs").start()
        # mrilint: allow(fault-boundary) client-facing listener bind, not corpus I/O; cluster faults inject on the shard side
        self._listener = socket.create_server(
            (self._host, self._port))
        self._listener.listen(128)
        # periodic wake so drain()'s close is noticed even with no
        # incoming connection (same trick as the serve daemon)
        self._listener.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="mri-router-accept")
        self._accept_thread.start()

    @property
    def address(self) -> tuple:
        assert self._listener is not None
        return self._listener.getsockname()[:2]

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> int:
        with self._drain_guard:
            if self._drain_started:
                self._drained.wait()
                return 0
            self._drain_started = True
        self._draining = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        deadline = time.monotonic() + self.drain_s
        while time.monotonic() < deadline:
            with self._count_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.01)
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            c.enqueue_sentinel()
        for c in conns:
            c.writer.join(timeout=1.0)
            c.kill()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        self.prober.stop()
        self.clock.stop()
        for sc in self.shards:
            sc.close()
        self._rolling.stop()
        self.final_stats = self.stats()
        self._drained.set()
        return 0

    # -- health ---------------------------------------------------------

    def _health_transition(self, sc, rep, was_ready) -> None:
        if was_ready and not rep.ready:
            log.warning("shard %d replica %d (%s:%d) went not-ready: "
                        "%s", sc.shard, rep.idx, rep.addr[0],
                        rep.addr[1], rep.reasons)
            with sc._lock:
                if sc.primary == rep.idx:
                    pass  # pick() moves the primary on the next RPC
        self._g_ready.set(sum(s.ready_count() for s in self.shards))

    # -- result-cache epoch ---------------------------------------------

    def _current_epoch(self) -> tuple | None:
        """The per-shard serving-generation vector, or ``None`` while
        it is not fully known.  A shard's generation is known only when
        every READY replica reported the same one on its last healthz —
        a down shard, an unprobed replica, or a mid-catch-up replica
        set makes the epoch unknown and disables caching until the
        prober re-agrees (self-healing within one probe period)."""
        gens = []
        for sc in self.shards:
            seen = {rep.generation for rep in sc.replicas if rep.ready}
            if len(seen) != 1 or None in seen:
                return None
            gens.append(seen.pop())
        epoch = tuple(gens)
        # adopting a changed epoch drops every entry keyed under the
        # old one (they can never be probed again)
        self._result_cache.on_epoch(epoch)
        return epoch

    # -- coverage accounting --------------------------------------------

    def _learn_shard_docs(self) -> None:
        """Per-shard corpus sizes from the shard engines' describe()
        (fed by the cluster_shard.json sidecars) so partial answers
        report a docs_fraction, not just a shard count.  Best-effort:
        retries in the background until every shard has answered once;
        until then coverage falls back to the shard-count fraction."""
        while not self._draining:
            answers = self._rpc_all_blocking({"op": "stats"}, 2.0)
            for s, a in enumerate(answers):
                if not isinstance(a, dict):
                    continue
                eng = (a.get("stats") or {}).get("engine") or {}
                cl = eng.get("cluster") or {}
                ld, td = cl.get("local_docs"), cl.get("total_docs")
                if isinstance(ld, int):
                    self._shard_docs[s] = ld
                if isinstance(td, int):
                    self._total_docs = td
            if all(d is not None for d in self._shard_docs):
                return
            time.sleep(0.5)

    def _coverage(self, missing: list) -> dict:
        """The coverage block a degraded answer carries: how many
        shards answered, which are missing, and the fraction of the
        corpus' documents the answer covers (shard-count fraction when
        per-shard doc counts have not been learned yet)."""
        nd = len(self.shards)
        miss = sorted(set(missing))
        answered = nd - len(miss)
        cov = {"shards_answered": answered, "shards_total": nd,
               "missing": miss}
        docs, total = self._shard_docs, self._total_docs
        if total and all(d is not None for d in docs):
            have = sum(d for i, d in enumerate(docs) if i not in miss)
            frac = have / total
        else:
            frac = answered / nd if nd else 0.0
        cov["docs_fraction"] = round(frac, 6)
        return cov

    # -- client plumbing ------------------------------------------------

    def _count(self, key: str) -> None:
        self._counts[key].inc()

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._draining:
            try:
                sock, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed by drain()
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _ClientConn(self, sock, addr)
            with self._conn_lock:
                self._conns.add(conn)
            self._count("connections")
            conn.start()

    def _reader_loop(self, conn: _ClientConn) -> None:
        try:
            # mrilint: allow(fault-boundary) client-connection framing, not corpus I/O; cluster faults inject on the shard side
            with conn.sock.makefile("rb") as rfile:
                for raw in rfile:
                    self._handle_line(conn, raw)
                    if conn.dead:
                        break
        except OSError:
            pass
        finally:
            conn.reader_done = True
            conn.enqueue_sentinel()
            with self._conn_lock:
                self._conns.discard(conn)

    def _writer_loop(self, conn: _ClientConn) -> None:
        try:
            while True:
                data = conn.outbound.get()
                if data is _SENTINEL:
                    break
                try:
                    conn.sock.sendall(data)
                except OSError:
                    self._count("client_disconnects")
                    break
                self._count("responses")
        finally:
            conn.kill()
            conn.writer_done = True

    # -- admission ------------------------------------------------------

    def _handle_line(self, conn: _ClientConn, raw: bytes) -> None:
        line = raw.strip()
        if not line:
            return
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as e:
            self._count("bad_request")
            conn.enqueue({"error": "bad_request", "detail": str(e)})
            return
        rid = req.get("id")
        op = req.get("op")
        tid = req.get("trace_id")
        if tid is not None and not isinstance(tid, str):
            tid = str(tid)
        if op in ADMIN_OPS:
            self._handle_admin(conn, rid, op, req)
            return
        err = self._validate(req, op)
        if err:
            self._count("bad_request")
            self._reply_error(conn, rid, tid, "bad_request", err)
            return
        pp = req.get("partial_policy")
        if pp is None:
            policy, min_cov = self.partial_default
        else:
            try:
                policy, min_cov = parse_partial_policy(pp)
            except ValueError as e:
                self._count("bad_request")
                self._reply_error(conn, rid, tid, "bad_request",
                                  str(e))
                return
        if self._draining:
            self._count("draining_rejected")
            self._reply_error(conn, rid, tid, "draining",
                              "router is shutting down")
            return
        with self._count_lock:
            self._seq += 1
            seq = self._seq
        inj = faults.active()
        if inj is not None and inj.on_router_client(seq):
            # injected client reset: the peer vanishes before its
            # answer — the scatter never starts, nothing was acked.
            # Faults fire before the cache probe so chaos specs keyed
            # on request ordinal keep biting on hot queries.
            self._count("client_disconnects")
            conn.kill()
            return
        if tid is None and self._obs_enabled:
            tid = obs_tracing.gen_trace_id()
        ckey = epoch = None
        if req.get("partial_policy") is None \
                and req.get("min_generation") is None \
                and not req.get("explain"):
            ckey = result_cache_mod.key_for(
                op, req.get("terms"), req.get("letter"),
                int(req.get("k") or 0), req.get("score") or "df")
        if ckey is not None:
            t_admit = time.monotonic()
            epoch = self._current_epoch()
            hit = self._result_cache.lookup(ckey, epoch)
            if hit is not None:
                # answered above the scatter: a hot query at a known
                # epoch never fans out and never occupies an inflight
                # slot — it stays answerable even at the inflight cap
                self._count("requests")
                if rid is not None:
                    hit["id"] = rid
                if tid is not None:
                    hit.setdefault("trace_id", tid)
                self._h_request.observe(time.monotonic() - t_admit)
                conn.enqueue(hit)
                return
        with self._count_lock:
            if self._inflight >= self.max_inflight:
                self._count("shed")
                self._reply_error(conn, rid, tid, "overloaded",
                                  f"router at {self.max_inflight} "
                                  "inflight")
                return
            self._inflight += 1
        self._count("requests")
        if op == "top_k" and (req.get("score") or "df") == "df":
            # letter top_k needs multi-round refinement: run it on a
            # throwaway thread (rare op; the hot ops stay threadless)
            threading.Thread(
                target=self._letter_topk,
                args=(conn, req, tid, policy, min_cov, ckey, epoch),
                daemon=True, name="mri-router-letter").start()
            return
        self._start_scatter(conn, req, tid, policy, min_cov,
                            ckey=ckey, epoch=epoch)

    # the daemon's validation table, minus engine concerns
    @staticmethod
    def _validate(req: dict, op) -> str | None:
        from ..serve.daemon import ServeDaemon
        return ServeDaemon._validate(req, op)

    def _reply_error(self, conn, rid, tid, kind: str,
                     detail: str) -> None:
        payload = {"error": kind, "detail": detail}
        if rid is not None:
            payload["id"] = rid
        if tid is not None:
            payload["trace_id"] = tid
        conn.enqueue(payload)

    # -- scatter / gather -----------------------------------------------

    def _encode_shard_req(self, req: dict, rpc_id: int, tid,
                          **overrides) -> bytes:
        out = {"id": rpc_id, "op": req["op"]}
        for key in ("terms", "letter", "k", "score", "deadline_ms",
                    "explain", "tenant"):
            v = req.get(key)
            if v is not None:
                out[key] = v
        if tid is not None:
            out["trace_id"] = tid
        out.update(overrides)
        return (json.dumps(out, separators=(",", ":")) + "\n").encode()

    def _start_scatter(self, conn, req: dict, tid,
                       policy: str = "fail",
                       min_cov: float = 1.0,
                       ckey=None, epoch=None) -> None:
        rpc_id = pool_mod.next_rpc_id()
        line = self._encode_shard_req(req, rpc_id, tid)
        sc = _Scatter(conn, req.get("id"), req["op"], tid, line,
                      rpc_id, time.monotonic(),
                      bool(req.get("explain", False)),
                      int(req.get("k") or 0), len(self.shards),
                      policy=policy, min_cov=min_cov,
                      ckey=ckey, epoch=epoch)
        dl = req.get("deadline_ms")
        if dl is not None:
            sc.deadline_timer = self.clock.schedule(
                dl / 1e3, lambda: self._expire(sc))
        # one RPC-timeout timer covers every leg: with D shards a
        # per-leg timer would cost D schedules + D cancels per request
        # on the clock's shared lock, and all legs arm together anyway
        sc.timeout_timer = self.clock.schedule(
            self.rpc_timeout_s, lambda: self._rpc_timeout(sc))
        for shard in range(len(self.shards)):
            call = _ShardCall()
            sc.calls[shard] = call
            self._issue(sc, shard, call)

    def _attempt_cap(self, client) -> int:
        """Hard per-leg send bound: three passes over the replica set
        (the old exclusion-reset semantics), floor 4.  A persistently
        retryable replica — say stale_generation forever — must turn
        into a prompt typed failure, not spin until the deadline."""
        return max(4, 3 * len(client.replicas))

    def _issue(self, sc: _Scatter, shard: int, call: _ShardCall,
               charge_budget: bool = True) -> None:
        """Send (or resend) one shard leg on the best replica.  Never
        called (and never calls anything) while holding ``sc.lock``
        across a socket send — a send-side connection death resolves
        other scatters' callbacks synchronously.

        Every resend is bounded by the per-leg attempt cap; resends
        that answer a typed shed (``charge_budget``) additionally
        spend the shard's token-bucket retry budget, so a
        browning-out shard cannot attract a compounding retry storm.
        Failover after a connection death rides free
        (``charge_budget=False``): the replica is *gone*, not
        refusing, and re-homing its leg is the availability contract,
        not load amplification — a killed replica must not turn a
        burst of in-flight requests into typed failures because the
        bucket could not cover them all at once."""
        client = self.shards[shard]
        with sc.lock:
            if sc.done or call.done:
                return
            fail = None
            if call.attempts > 0:
                if call.attempts >= self._attempt_cap(client):
                    fail = (f"shard {shard}: attempt cap "
                            f"({self._attempt_cap(client)}) reached")
                elif charge_budget and not client.budget.try_spend():
                    self._count("retry_denied")
                    fail = f"shard {shard}: retry budget exhausted"
            ri = -1
            if fail is None:
                ri = client.pick(tuple(call.tried))
                if ri < 0 and call.tried:
                    # every replica tried this round, but a timed-out
                    # RPC or a dead pooled connection is not proof the
                    # replica itself is gone — clear the exclusion set
                    # and re-dial (the attempt cap bounds this)
                    call.tried.clear()
                    ri = client.pick(())
                if ri < 0:
                    fail = (f"shard {shard}: no replica admits "
                            "traffic (down or breaker-open)")
            if fail is None:
                if call.tried and ri not in call.tried:
                    self._count("failovers")
                    sc.failovers += 1
                call.tried.add(ri)
                call.live += 1
                call.attempts += 1
                if call.attempts == 1:
                    client.budget.deposit()
                if call.first_replica < 0:
                    call.first_replica = ri
            call.t0 = call.t0 or time.monotonic()
        if fail is not None:
            self._leg_unanswerable(sc, shard, call, fail)
            return
        # the hedge timer arms BEFORE the send: a stalled send (slow
        # shard, full kernel buffer) is exactly what hedges exist to
        # cover.  (The scatter-wide RPC-timeout timer armed even
        # earlier, in _start_scatter.)
        if call.hedge_timer is None:
            delay = hedge_mod.hedge_delay_s(self.hedge_ms,
                                            client.latency.p95())
            if delay is not None and len(client.replicas) > 1:
                call.hedge_timer = self.clock.schedule(
                    delay, lambda: self._fire_hedge(sc, shard, call))
        try:
            conn = client.conn(ri)
            conn.send(sc.rpc_id, sc.line,
                      lambda payload, s=shard, r=ri:
                      self._on_part(sc, s, r, payload))
        except pool_mod.ConnDead:
            self._count("shard_errors")
            with sc.lock:
                call.live = max(0, call.live - 1)
                retry = call.live == 0 and not (sc.done or call.done)
            if retry:
                self._issue(sc, shard, call, charge_budget=False)
            return
        with sc.lock:
            call.conns.append(conn)
        self._count("scatter_rpcs")

    def _fire_hedge(self, sc: _Scatter, shard: int,
                    call: _ShardCall) -> None:
        client = self.shards[shard]
        with sc.lock:
            if sc.done or call.done:
                return
            ri = client.hedge_pick(call.first_replica)
            if ri < 0 or ri in call.tried:
                return
            if not client.budget.try_spend():
                # hedges ride the same retry budget: a tail-latency
                # duplicate is exactly the load a brownout cannot absorb
                self._count("retry_denied")
                return
            call.tried.add(ri)
            call.live += 1
            call.attempts += 1
        try:
            conn = client.conn(ri)
            conn.send(sc.rpc_id, sc.line,
                      lambda payload, s=shard, r=ri:
                      self._on_part(sc, s, r, payload))
        except pool_mod.ConnDead:
            with sc.lock:
                call.live = max(0, call.live - 1)
            return
        with sc.lock:
            call.conns.append(conn)
            call.hedge_replica = ri
        self._count("scatter_rpcs")
        self._count("hedges")
        sc.hedged.append(shard)

    def _rpc_timeout(self, sc: _Scatter) -> None:
        """Condemn every leg still pending at the timeout and reissue
        each on a fresh replica.  Re-arms itself so the retries get a
        timeout window of their own."""
        stale = []
        with sc.lock:
            if sc.done:
                return
            for shard, call in enumerate(sc.calls):
                if call is None or call.done:
                    continue
                call.live = 0
                stale.append((shard, call, list(call.conns),
                              tuple(call.tried)))
            sc.timeout_timer = self.clock.schedule(
                self.rpc_timeout_s, lambda: self._rpc_timeout(sc))
        for shard, call, conns, tried in stale:
            self._count("shard_errors")
            for c in conns:
                c.forget(sc.rpc_id)
            # an unanswered window is failure evidence for every
            # replica that was in flight — this is what walks a
            # wedged-but-connected replica's breaker open.  The
            # reissue is budget-free like a connection death: a
            # wedged replica's burst of condemned in-flight legs is a
            # failover event, not retry amplification (the attempt
            # cap and the breaker bound it)
            for ri in tried:
                self.shards[shard].replicas[ri].breaker.record_failure()
            self._issue(sc, shard, call, charge_budget=False)

    def _expire(self, sc: _Scatter) -> None:
        salvage = False
        with sc.lock:
            if sc.done:
                return
            if sc.policy == "allow":
                # deadline with partials in hand: give up the pending
                # legs and answer from what arrived (the coverage
                # floor is still enforced in _complete)
                for shard, call in enumerate(sc.calls):
                    if call is None or not call.done:
                        if call is not None:
                            call.done = True
                        sc.missing.append(shard)
                        sc.remaining -= 1
                salvage = len(sc.missing) < len(sc.calls)
            sc.done = True
        if salvage:
            self._complete(sc)
            return
        self._count("deadline_expired")
        self._teardown_calls(sc)
        self._finish(sc, {"error": "deadline_expired",
                          "detail": "deadline passed before all "
                                    "shards answered"})

    def _teardown_calls(self, sc: _Scatter) -> None:
        if sc.timeout_timer is not None:
            self.clock.cancel(sc.timeout_timer)
        for call in sc.calls:
            if call is None:
                continue
            if call.hedge_timer is not None:
                self.clock.cancel(call.hedge_timer)
            for c in call.conns:
                c.forget(sc.rpc_id)

    def _shard_failed(self, sc: _Scatter, shard: int, detail: str,
                      kind: str = "internal") -> None:
        with sc.lock:
            if sc.done:
                return
            sc.done = True
        if kind == "internal":
            self._count("internal_errors")
        elif kind == "deadline_expired":
            self._count("deadline_expired")
        elif kind == "shard_unavailable":
            self._count("shard_unavailable")
        self._teardown_calls(sc)
        payload = {"error": kind, "detail": detail}
        if kind == "shard_unavailable":
            payload["shard"] = shard
        self._finish(sc, payload)

    def _leg_unanswerable(self, sc: _Scatter, shard: int,
                          call: _ShardCall, detail: str) -> None:
        """This shard's leg cannot be answered: replicas exhausted or
        breaker-rejected, attempt cap hit, or retry budget denied.
        Under partial_policy ``allow`` the scatter completes without
        it; under ``fail`` the whole request becomes a typed
        ``shard_unavailable`` error naming the shard."""
        if sc.policy != "allow":
            self._shard_failed(sc, shard, detail,
                               kind="shard_unavailable")
            return
        complete = False
        with sc.lock:
            if sc.done or call.done:
                return
            call.done = True
            sc.missing.append(shard)
            sc.remaining -= 1
            if sc.remaining == 0:
                sc.done = True
                complete = True
        if call.hedge_timer is not None:
            self.clock.cancel(call.hedge_timer)
        for c in call.conns:
            c.forget(sc.rpc_id)
        if complete:
            self._complete(sc)

    def _complete(self, sc: _Scatter) -> None:
        """Every leg settled (answered, or given up under ``allow``):
        cancel the timers, enforce the coverage floor, merge what
        arrived, and flag the answer when shards are missing."""
        for t in (sc.deadline_timer, sc.timeout_timer):
            if t is not None:
                self.clock.cancel(t)
        self._teardown_calls(sc)
        cov = self._coverage(sc.missing) if sc.missing else None
        if cov is not None and (
                cov["shards_answered"] == 0
                or cov["docs_fraction"] < sc.min_cov):
            self._count("shard_unavailable")
            payload = {
                "error": "shard_unavailable",
                "detail": (f"shards {cov['missing']} unanswerable; "
                           f"coverage {cov['docs_fraction']} below "
                           f"min_coverage {sc.min_cov}"
                           if cov["shards_answered"] else
                           "no shard answered"),
                "shard": cov["missing"][0],
                "coverage": cov,
            }
            self._finish(sc, payload)
            return
        try:
            out = self._merge(sc)
            if cov is not None:
                out["partial"] = True
                out["coverage"] = cov
                self._count("partial")
            elif sc.ckey is not None and sc.epoch is not None:
                # only full-coverage answers at the admission-time
                # epoch are cacheable: a partial answer depends on
                # which shards happened to be down, not on the epoch
                self._result_cache.fill(sc.ckey, sc.epoch, out)
            self._finish(sc, out)
        except Exception as e:
            log.exception("gather merge failed")
            self._count("internal_errors")
            self._finish(sc, {"error": "internal",
                              "detail": f"gather failed: {e}"})

    def _on_part(self, sc: _Scatter, shard: int, replica: int,
                 payload) -> None:
        call = sc.calls[shard]
        with sc.lock:
            if sc.done or call.done:
                return
        if payload is None or "error" in payload:
            kind = payload.get("error") if payload else None
            self._count("shard_errors")
            if payload is not None and kind in _RETRYABLE:
                # a refusing replica (overloaded / draining / stale)
                # is breaker pressure, not an invitation to hammer it
                client = self.shards[shard]
                if 0 <= replica < len(client.replicas):
                    client.replicas[replica].breaker.record_failure()
            if payload is not None and kind not in _RETRYABLE:
                detail = (f"shard {shard}: {kind}: "
                          f"{payload.get('detail', '')}")
                self._shard_failed(
                    sc, shard, detail,
                    kind="deadline_expired"
                    if kind == "deadline_expired" else "internal")
                return
            # connection death / refusing replica: another attempt for
            # this leg may still be in flight (a hedge) — only reissue
            # when this was the last one.  A typed shed spends retry
            # budget; a dead connection (payload None) fails over free
            with sc.lock:
                call.live = max(0, call.live - 1)
                retry = call.live == 0 and not (sc.done or call.done)
            if retry:
                self._issue(sc, shard, call,
                            charge_budget=payload is not None)
            return
        client = self.shards[shard]
        client.latency.record(time.monotonic() - call.t0)
        if 0 <= replica < len(client.replicas):
            client.replicas[replica].breaker.record_success()
        merged = None
        with sc.lock:
            if sc.done or call.done:
                return
            call.done = True
            sc.parts[shard] = payload
            sc.remaining -= 1
            if sc.remaining == 0:
                sc.done = True
                merged = True
        if replica == call.hedge_replica:
            self._count("hedge_wins")
        if call.hedge_timer is not None:
            self.clock.cancel(call.hedge_timer)
        for c in call.conns:
            c.forget(sc.rpc_id)
        if merged:
            self._complete(sc)

    def _merge(self, sc: _Scatter) -> dict:
        # a missing shard (partial_policy=allow) left its part None —
        # the merge over the remaining parts IS the monolith's answer
        # restricted to the covered shards (disjoint doc spaces,
        # global BM25 stats), which is the byte-parity contract the
        # chaos soak holds degraded answers to
        parts = [p for p in sc.parts if p is not None]
        if sc.op == "df":
            total = None
            for p in parts:
                row = p["df"]
                total = row if total is None else \
                    [a + b for a, b in zip(total, row)]
            out = {"ok": True, "df": total}
        elif sc.op == "postings":
            nterms = len(parts[0]["postings"])
            merged_posts = []
            for ti in range(nterms):
                cols = [p["postings"][ti] for p in parts
                        if p["postings"][ti] is not None]
                merged_posts.append(
                    merge_doc_ids(cols).tolist() if cols else None)
            out = {"ok": True, "postings": merged_posts}
        elif sc.op in ("and", "or"):
            out = {"ok": True,
                   "docs": merge_doc_ids(
                       [p["docs"] for p in parts]).tolist()}
        else:  # top_k score=bm25 (letter runs its own path)
            ranked = merge_ranked(
                [[(-s, d) for d, s in p["docs"]] for p in parts],
                sc.k)
            out = {"ok": True, "docs": [[d, s] for d, s in ranked]}
        if sc.explain:
            out["explain"] = {
                "router": {
                    "shards": len(self.shards),
                    "hedged_shards": sorted(set(sc.hedged)),
                    "failovers": sc.failovers,
                    "rpc_ms": {
                        str(i): round((time.monotonic()
                                       - sc.calls[i].t0) * 1e3, 3)
                        for i in range(len(sc.parts))
                        if sc.calls[i] is not None},
                },
                "per_shard": {str(i): p.get("explain")
                              for i, p in enumerate(sc.parts)
                              if p is not None},
            }
        return out

    def _finish(self, sc: _Scatter, payload: dict) -> None:
        if sc.rid is not None:
            payload["id"] = sc.rid
        if sc.tid is not None:
            payload.setdefault("trace_id", sc.tid)
        self._h_request.observe(time.monotonic() - sc.t_admit)
        with self._count_lock:
            self._inflight -= 1
        sc.conn.enqueue(payload)

    # -- letter top_k: threshold refinement over local tops -------------

    def _rpc_all_blocking(self, fields: dict,
                          timeout_s: float) -> list:
        """Scatter one op to every shard with per-shard failover,
        blocking until all answer (or raise).  Used by the refinement
        rounds and the metrics merge — rare, latency-tolerant ops."""
        rpc_id = pool_mod.next_rpc_id()
        line = (json.dumps({"id": rpc_id, **fields},
                           separators=(",", ":")) + "\n").encode()
        events = []
        results: list = [None] * len(self.shards)

        def _issue_one(shard: int, tried: set, ev: threading.Event):
            client = self.shards[shard]
            ri = client.pick(tuple(tried))
            if ri < 0:
                ev.set()
                return

            def _cb(payload, shard=shard, ri=ri, tried=tried, ev=ev):
                if payload is None or (isinstance(payload, dict)
                                       and payload.get("error")
                                       in _RETRYABLE):
                    self._count("shard_errors")
                    tried.add(ri)
                    if len(tried) < len(client.replicas):
                        self._count("failovers")
                        _issue_one(shard, tried, ev)
                    else:
                        ev.set()
                    return
                results[shard] = payload
                ev.set()

            tried.add(ri)
            try:
                client.conn(ri).send(rpc_id, line, _cb)
                self._count("scatter_rpcs")
            except pool_mod.ConnDead:
                self._count("shard_errors")
                if len(tried) < len(client.replicas):
                    self._count("failovers")
                    _issue_one(shard, tried, ev)
                else:
                    ev.set()

        for shard in range(len(self.shards)):
            ev = threading.Event()
            events.append(ev)
            _issue_one(shard, set(), ev)
        deadline = time.monotonic() + timeout_s
        for ev in events:
            ev.wait(max(0.0, deadline - time.monotonic()))
        return results

    def _letter_topk(self, conn, req: dict, tid,
                     policy: str = "fail",
                     min_cov: float = 1.0,
                     ckey=None, epoch=None) -> None:
        """Exact global letter top-k: rounds of (local k2-deep tops,
        exact global df sums) until the kth candidate provably beats
        every unseen term.  Termination is guaranteed — k2 doubles
        until every shard's letter range is exhausted.

        Under partial_policy ``allow`` a shard that stops answering
        mid-refinement is moved to the dead set and the refinement
        restricts itself to the survivors — the answer is then the
        restricted-corpus exact top-k, flagged with coverage."""
        k = int(req.get("k") or 0)
        letter = req["letter"]
        dl = req.get("deadline_ms")
        timeout_s = min(self.rpc_timeout_s,
                        dl / 1e3 if dl else self.rpc_timeout_s)
        t_admit = time.monotonic()
        dead: set = set()
        nd = len(self.shards)
        try:
            if k == 0:
                self._answer_letter(conn, req, tid, t_admit, [],
                                    dead, min_cov, ckey, epoch)
                return
            k2 = max(k, 4)
            while True:
                tops = self._rpc_all_blocking(
                    {"op": "top_k", "letter": letter, "k": k2},
                    timeout_s)
                miss = {i for i, t in enumerate(tops)
                        if t is None} | dead
                if miss and policy != "allow":
                    self._fail_letter(
                        conn, req, tid, t_admit,
                        f"shards {sorted(miss)} unanswerable",
                        kind="shard_unavailable", shard=min(miss))
                    return
                if len(miss) == nd:
                    self._fail_letter(
                        conn, req, tid, t_admit, "no shard answered",
                        kind="shard_unavailable",
                        shard=min(miss) if miss else 0)
                    return
                dead = miss
                live = [i for i in range(nd) if i not in dead]
                ltops = [tops[i] for i in live]
                exhausted = [len(t["top"]) < k2 for t in ltops]
                cands = sorted({term for t in ltops
                                for term, _df in t["top"]})
                if not cands:
                    self._answer_letter(conn, req, tid, t_admit, [],
                                        dead, min_cov)
                    return
                dfs = self._rpc_all_blocking(
                    {"op": "df", "terms": cands}, timeout_s)
                dmiss = {i for i in live if dfs[i] is None}
                if dmiss:
                    if policy != "allow":
                        self._fail_letter(
                            conn, req, tid, t_admit,
                            f"shards {sorted(dmiss)} unanswerable",
                            kind="shard_unavailable",
                            shard=min(dmiss))
                        return
                    dead |= dmiss
                    continue  # re-round over the shrunken live set
                gdf = [sum(dfs[i]["df"][j] for i in live)
                       for j in range(len(cands))]
                ranked = sorted(zip(cands, gdf),
                                key=lambda tg: (-tg[1], tg[0]))
                # an unseen term's global df is at most the sum of the
                # k2-th local dfs over shards that still have terms
                threshold = sum(t["top"][-1][1]
                                for t, ex in zip(ltops, exhausted)
                                if not ex and t["top"])
                if all(exhausted) or (
                        len(ranked) >= k
                        and ranked[k - 1][1] > threshold):
                    self._answer_letter(conn, req, tid, t_admit,
                                        ranked[:k], dead, min_cov,
                                        ckey, epoch)
                    return
                k2 *= 2
        except Exception as e:
            log.exception("letter top_k failed")
            self._fail_letter(conn, req, tid, t_admit, str(e))

    def _answer_letter(self, conn, req, tid, t_admit, ranked,
                       missing=(), min_cov: float = 0.0,
                       ckey=None, epoch=None) -> None:
        cov = self._coverage(sorted(missing)) if missing else None
        if cov is not None and cov["docs_fraction"] < min_cov:
            self._fail_letter(
                conn, req, tid, t_admit,
                f"shards {cov['missing']} unanswerable; coverage "
                f"{cov['docs_fraction']} below min_coverage {min_cov}",
                kind="shard_unavailable", shard=cov["missing"][0],
                coverage=cov)
            return
        payload = {"ok": True,
                   "top": [[term, int(df)] for term, df in ranked]}
        if cov is not None:
            payload["partial"] = True
            payload["coverage"] = cov
            self._count("partial")
        elif ckey is not None and epoch is not None:
            self._result_cache.fill(ckey, epoch, payload)
        rid = req.get("id")
        if rid is not None:
            payload["id"] = rid
        if tid is not None:
            payload["trace_id"] = tid
        self._h_request.observe(time.monotonic() - t_admit)
        with self._count_lock:
            self._inflight -= 1
        conn.enqueue(payload)

    def _fail_letter(self, conn, req, tid, t_admit, detail: str,
                     kind: str = "internal", shard: int | None = None,
                     coverage: dict | None = None) -> None:
        if kind == "shard_unavailable":
            self._count("shard_unavailable")
        else:
            self._count("internal_errors")
        self._h_request.observe(time.monotonic() - t_admit)
        with self._count_lock:
            self._inflight -= 1
        payload = {"error": kind, "detail": detail}
        if shard is not None:
            payload["shard"] = shard
        if coverage is not None:
            payload["coverage"] = coverage
        rid = req.get("id")
        if rid is not None:
            payload["id"] = rid
        if tid is not None:
            payload["trace_id"] = tid
        conn.enqueue(payload)

    # -- admin ----------------------------------------------------------

    def _handle_admin(self, conn, rid, op: str, req: dict) -> None:
        # mrilint: allow(trace) stats healthz slo metrics — read-only
        # introspection answered inline from published state
        if op not in _ROUTER_ADMIN:
            self._count("bad_request")
            payload = {"error": "bad_request",
                       "detail": f"op {op!r} is shard-local: send it "
                                 "to the shard primary, not the "
                                 "router"}
        elif op == "healthz":
            reasons = []
            if self._draining:
                reasons.append("draining")
            down = [s.shard for s in self.shards
                    if s.ready_count() == 0]
            if down:
                reasons.append("shard_unavailable")
            payload = {"ok": True, "live": True,
                       "ready": not reasons, "reasons": reasons,
                       "status": reasons[0] if reasons else "ok",
                       "queue_depth": 0,
                       "breakers_open": sum(s.breakers_open()
                                            for s in self.shards)}
            if down:
                payload["shards_down"] = down
        elif op == "slo":
            payload = {"ok": True, "slo": self._slo.report()}
        elif op == "stats":
            payload = {"ok": True, "stats": self.stats()}
        else:  # metrics
            payload = {"ok": True, "text": self.render_metrics()}
        if rid is not None:
            payload["id"] = rid
        tid = req.get("trace_id")
        if tid is not None:
            payload["trace_id"] = tid if isinstance(tid, str) \
                else str(tid)
        conn.enqueue(payload)

    def stats(self) -> dict:
        counters = {key: c.value for key, c in self._counts.items()}
        with self._count_lock:
            inflight = self._inflight
        with self._conn_lock:
            connections = len(self._conns)
        out = {
            "queue_depth": 0,
            "inflight": inflight,
            "draining": self._draining,
            "connections": connections,
            "counters": counters,
            "rolling": self._rolling_stats(),
            "slo": self._slo.report(),
            "result_cache": self._result_cache.stats(),
            "cluster": {
                "shards": [sc.describe() for sc in self.shards],
                "epoch": self._current_epoch(),
                "hedge_ms": self.hedge_ms,
                "rpc_timeout_ms": round(self.rpc_timeout_s * 1e3, 3),
                "partial_default": self.partial_spec,
                "retry_budget_ratio": self.retry_budget_ratio,
                "breakers_open": sum(s.breakers_open()
                                     for s in self.shards),
                "docs": {"per_shard": list(self._shard_docs),
                         "total": self._total_docs},
            },
            "config": {
                "max_inflight": self.max_inflight,
                "drain_s": self.drain_s,
            },
        }
        return out

    def _rolling_stats(self) -> dict:
        out = {}
        roll = self._rolling
        for label, span in obs_windows.WINDOWS:
            p50 = roll.quantile("mri_serve_request_seconds", span,
                                50.0)
            p99 = roll.quantile("mri_serve_request_seconds", span,
                                99.0)
            out[label] = {
                "qps": round(
                    roll.rate("mri_serve_requests_total", span), 3),
                "shed_per_s": round(
                    roll.rate("mri_serve_shed_total", span), 3),
                "deadline_per_s": round(roll.rate(
                    "mri_serve_deadline_expired_total", span), 3),
                "error_per_s": round(roll.rate(
                    "mri_serve_internal_errors_total", span), 3),
                "p50_ms": round(p50 * 1e3, 3) if p50 is not None
                          else None,
                "p99_ms": round(p99 * 1e3, 3) if p99 is not None
                          else None,
            }
        return out

    def render_metrics(self) -> str:
        """Router registry + every shard primary's scrape, merged with
        ``{shard=,replica=}`` labels injected so the families never
        collide — one exposition prices the whole fleet."""
        with self._count_lock:
            self._g_inflight.set(self._inflight)
        self._g_draining.set(1 if self._draining else 0)
        self._g_ready.set(sum(s.ready_count() for s in self.shards))
        state_code = {pool_mod.Breaker.CLOSED: 0,
                      pool_mod.Breaker.HALF_OPEN: 1,
                      pool_mod.Breaker.OPEN: 2}
        open_n = 0
        for s in self.shards:
            for r in s.replicas:
                st = r.breaker.state
                if st != pool_mod.Breaker.CLOSED:
                    open_n += 1
                self.registry.gauge(
                    f"mri_cluster_breaker_state_s{s.shard}_r{r.idx}"
                ).set(state_code[st])
        self._g_breakers.set(open_n)
        self._slo.set_gauges(self.registry)
        parts = [self.registry.render_text()]
        labels: list = [None]
        answers = self._rpc_all_blocking({"op": "metrics"}, 1.0)
        for shard, ans in enumerate(answers):
            if ans is None or "text" not in ans:
                continue
            with self.shards[shard]._lock:
                primary = self.shards[shard].primary
            parts.append(ans["text"])
            labels.append({"shard": str(shard),
                           "replica": str(primary)})
        parts.append(obs_metrics.default_registry().render_text())
        labels.append(None)
        return obs_metrics.merge_expositions(parts, labels=labels)
