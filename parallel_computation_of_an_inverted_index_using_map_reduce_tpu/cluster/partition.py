"""``mri shard``: partition a corpus into D buildable doc-shards.

The partition tool is the cluster's build step: it splits one corpus
manifest into D per-shard manifests, runs the unchanged ``--artifact``
build once per shard, then computes the GLOBAL BM25 statistics and
writes them into each shard's ``cluster_shard.json`` sidecar — after
which every shard daemon answers with global doc ids and globally-
correct BM25 floats (see :mod:`.shard`), and the router stays
stateless about corpus content.

Assignment modes (both produce ascending per-shard gid lists, which
the monotone local→global map in :class:`~.shard.ShardEngine`
requires):

* ``round-robin`` (default) — doc ``g`` (1-based manifest position)
  goes to shard ``(g - 1) % D``; already ascending per shard.
* ``size-balanced`` — greedy LPT over file sizes (largest doc to the
  currently-lightest shard), then each shard's member list is sorted
  ascending before anything is written.

Global-stat computation mirrors
:func:`~..serve.artifact.bm25_corpus` operand for operand: the global
``doc_lens`` float64 array is reassembled from the per-shard doc-length
columns through the gid maps (same values, same ascending-gid order),
so ``ndocs = count_nonzero`` and ``avgdl = mean(doc_lens > 0)`` are
bit-equal to what a from-scratch monolithic build would compute.
Global df is the integer sum of per-shard dfs (every doc lives in
exactly one shard).
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

from ..corpus import manifest as corpus_manifest
from ..serve import artifact as artifact_mod
from . import CLUSTER_MANIFEST, SIDECAR_NAME

MODES = ("round-robin", "size-balanced")


class PartitionError(Exception):
    """Bad arguments or a failed/partial partition (CLI exit 2)."""


def shard_dir(out_dir, shard: int) -> Path:
    return Path(out_dir) / f"shard-{shard}"


def assign(paths: list[str], shards: int,
           mode: str = "round-robin") -> list[list[int]]:
    """Per-shard ascending 1-based gid lists covering every doc once."""
    if shards < 1:
        raise PartitionError(f"--shards must be >= 1, got {shards}")
    if mode not in MODES:
        raise PartitionError(
            f"unknown assignment mode {mode!r} (choices: {MODES})")
    if not paths:
        raise PartitionError("source manifest lists no documents")
    if shards > len(paths):
        raise PartitionError(
            f"--shards {shards} exceeds the corpus size ({len(paths)} "
            "docs) — every shard must own at least one document")
    if mode == "round-robin":
        return [list(range(s + 1, len(paths) + 1, shards))
                for s in range(shards)]
    # size-balanced: greedy LPT on byte sizes.  Ties go to the lowest
    # gid / lowest shard index, so the assignment is deterministic.
    sizes = corpus_manifest._stat_sizes(paths)
    order = sorted(range(len(paths)),
                   key=lambda i: (-int(sizes[i]), i))
    load = [0] * shards
    out: list[list[int]] = [[] for _ in range(shards)]
    for i in order:
        s = min(range(shards), key=lambda j: (load[j], j))
        out[s].append(i + 1)
        load[s] += int(sizes[i])
    for member in out:
        member.sort()
    return out


def _manifest_bytes(paths: list[str]) -> bytes:
    """The exact bytes ``write_manifest`` produces for ``paths`` —
    the byte-verification oracle for ``--verify``."""
    import io
    buf = io.StringIO()
    buf.write(f"{len(paths)}\n")
    for p in paths:
        buf.write(f"{p}\n")
    return buf.getvalue().encode("utf-8")


def _build_shard(list_path: Path, out: Path, *, mappers: int,
                 reducers: int) -> dict:
    from .. import IndexConfig, InvertedIndexModel
    return InvertedIndexModel(IndexConfig(
        num_mappers=mappers, num_reducers=reducers, backend="cpu",
        output_dir=str(out), artifact=True)).run(
            corpus_manifest.read_manifest(str(list_path)))


def partition(src_list, shards: int, out_dir, *,
              mode: str = "round-robin", mappers: int = 1,
              reducers: int = 2, progress=None) -> dict:
    """Partition + build + sidecar-stamp a whole cluster directory.

    Returns the top-level cluster manifest (also written to
    ``out_dir/cluster.json``).  Raises :class:`PartitionError` on bad
    arguments and propagates build failures.
    """
    try:
        paths = list(corpus_manifest.read_manifest(str(src_list)).paths)
    except Exception as e:
        raise PartitionError(f"cannot read corpus manifest "
                             f"{src_list}: {e}") from e
    members = assign(paths, shards, mode)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    # 1. per-shard manifests + artifact builds (unchanged build path)
    arts = []
    for s, gids in enumerate(members):
        sd = shard_dir(out_dir, s)
        sd.mkdir(parents=True, exist_ok=True)
        list_path = sd / "docs.list"
        list_path.write_bytes(
            _manifest_bytes([paths[g - 1] for g in gids]))
        if progress is not None:
            progress(f"shard {s}: building {len(gids)} docs")
        _build_shard(list_path, sd, mappers=mappers, reducers=reducers)
        arts.append(artifact_mod.load_artifact(sd))

    try:
        # 2. global stats, reassembled exactly as bm25_corpus would
        # see them in a monolithic build of the same manifest
        span = len(paths)
        doc_lens = np.zeros(span + 1, dtype=np.float64)
        gdf: dict[bytes, int] = {}
        for s, (gids, art) in enumerate(zip(members, arts)):
            dl = artifact_mod.bm25_corpus(art)[0]
            g = np.asarray(gids, dtype=np.int64)
            n = min(len(dl) - 1, len(g))
            doc_lens[g[:n]] = dl[1:n + 1]
            df = np.asarray(art.df, dtype=np.int64)
            for i in range(art.vocab):
                t = art.term(i)
                gdf[t] = gdf.get(t, 0) + int(df[i])
        ndocs = int(np.count_nonzero(doc_lens))
        avgdl = float(doc_lens[doc_lens > 0].mean()) if ndocs else 1.0

        # 3. sidecars: each shard gets the stats plus the df of every
        # term IT stores (strict — a missing term at serve time means
        # sidecar/artifact drift and fails loudly)
        for s, (gids, art) in enumerate(zip(members, arts)):
            local_terms = [art.term(i).decode("ascii")
                           for i in range(art.vocab)]
            sidecar = {
                "shard": s,
                "shards": shards,
                "mode": mode,
                "total_docs": span,
                "ndocs": ndocs,
                "avgdl": avgdl,
                "gids": list(gids),
                "global_df": {t: gdf[t.encode("ascii")]
                              for t in local_terms},
            }
            _atomic_json(shard_dir(out_dir, s) / SIDECAR_NAME, sidecar)
    finally:
        for art in arts:
            art.close()

    cluster = {
        "shards": shards,
        "mode": mode,
        "total_docs": len(paths),
        "ndocs": ndocs,
        "avgdl": avgdl,
        "dirs": [f"shard-{s}" for s in range(shards)],
        "source": str(src_list),
    }
    _atomic_json(out_dir / CLUSTER_MANIFEST, cluster)
    return cluster


def _atomic_json(path: Path, doc) -> None:
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(doc, indent=1) + "\n", encoding="utf-8")
    os.replace(tmp, path)


def verify(src_list, out_dir) -> dict:
    """Byte-verify a partition against its source manifest.

    Recomputes the assignment from ``cluster.json``'s recorded mode and
    checks (a) every per-shard ``docs.list`` matches the recomputed
    serialization BYTE for byte, (b) each sidecar's gid map matches the
    assignment, and (c) the shard gid lists tile ``1..N`` exactly once.
    Raises :class:`PartitionError` on any mismatch; returns a summary.
    """
    out_dir = Path(out_dir)
    try:
        cluster = json.loads(
            (out_dir / CLUSTER_MANIFEST).read_text(encoding="utf-8"))
    except (OSError, ValueError) as e:
        raise PartitionError(
            f"{out_dir}: cannot read {CLUSTER_MANIFEST} ({e})") from e
    try:
        paths = list(corpus_manifest.read_manifest(str(src_list)).paths)
    except Exception as e:
        raise PartitionError(f"cannot read corpus manifest "
                             f"{src_list}: {e}") from e
    shards = int(cluster["shards"])
    members = assign(paths, shards, str(cluster["mode"]))
    seen: set[int] = set()
    for s, gids in enumerate(members):
        sd = shard_dir(out_dir, s)
        want = _manifest_bytes([paths[g - 1] for g in gids])
        try:
            got = (sd / "docs.list").read_bytes()
        except OSError as e:
            raise PartitionError(
                f"shard {s}: missing manifest ({e})") from e
        if got != want:
            raise PartitionError(
                f"shard {s}: docs.list does not byte-match the "
                f"recomputed assignment (corrupt or hand-edited)")
        try:
            sidecar = json.loads(
                (sd / SIDECAR_NAME).read_text(encoding="utf-8"))
        except (OSError, ValueError) as e:
            raise PartitionError(
                f"shard {s}: bad sidecar ({e})") from e
        if [int(g) for g in sidecar.get("gids", [])] != gids:
            raise PartitionError(
                f"shard {s}: sidecar gid map drifted from the "
                "assignment")
        dup = seen.intersection(gids)
        if dup:
            raise PartitionError(
                f"shard {s}: doc ids {sorted(dup)[:5]} appear in more "
                "than one shard")
        seen.update(gids)
    if seen != set(range(1, len(paths) + 1)):
        missing = sorted(set(range(1, len(paths) + 1)) - seen)[:5]
        raise PartitionError(
            f"partition does not cover the corpus (first missing doc "
            f"ids: {missing})")
    return {"shards": shards, "docs": len(paths),
            "mode": cluster["mode"], "verified": True}
