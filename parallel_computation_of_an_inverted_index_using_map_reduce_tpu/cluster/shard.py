"""Shard-side engine: a plain artifact serving its slice of a cluster.

A shard directory is an ordinary single-artifact build of the docs the
partition tool assigned it, plus a ``cluster_shard.json`` sidecar
holding everything the shard needs to answer *as if it were the whole
corpus*:

* ``gids`` — the ascending global doc id of every local doc (local id
  ``i`` ↔ ``gids[i-1]``).  Both assignment modes write ascending
  lists, so the local→global map is monotone: ascending local postings
  stay ascending, and the single-engine ``(-score, doc_id)`` tie order
  is preserved through the map.
* ``ndocs`` / ``avgdl`` — the GLOBAL corpus stats, computed by the
  partition tool exactly the way :func:`~..serve.artifact.bm25_corpus`
  computes them for a monolithic build (same float64 array, same
  ``mean()``), so they are bit-equal to the from-scratch values.
* ``global_df`` — the global document frequency of every term this
  shard stores (docs live in exactly one shard, so the global df is
  the plain integer sum of the per-shard dfs).

:class:`ShardEngine` wraps the unchanged single-artifact
:class:`~..serve.engine.Engine`, injects the global stats through
``set_corpus_override`` — the same seam the multi-segment engine uses —
and maps doc ids on the way out.  The scatter-gather router therefore
carries NO per-shard state: shards answer in global ids with global
BM25 floats already bit-identical to a monolithic build, and the
router only sums (df), merges (postings/AND/OR), or heap-merges
(ranked) the parts.

``df`` and letter ``top_k`` stay LOCAL on purpose: their global
answers need cross-shard aggregation (sum, threshold refinement) that
only the router can do.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

from ..serve import artifact as artifact_mod
from ..serve import engine as engine_mod
from . import SIDECAR_NAME


def sidecar_path(path) -> Path:
    p = Path(path)
    if p.is_dir():
        return p / SIDECAR_NAME
    return p.parent / SIDECAR_NAME


def has_sidecar(path) -> bool:
    """Cheap create_engine routing probe (no JSON parse)."""
    return os.path.exists(sidecar_path(path))


def load_sidecar(path) -> dict:
    """Parse + structurally validate one shard sidecar."""
    sp = sidecar_path(path)
    try:
        doc = json.loads(sp.read_text(encoding="utf-8"))
    except OSError as e:
        raise artifact_mod.ArtifactError(
            f"{sp}: cannot read shard sidecar ({e})") from e
    except ValueError as e:
        raise artifact_mod.ArtifactError(
            f"{sp}: shard sidecar is not valid JSON ({e})") from e
    try:
        gids = np.asarray(doc["gids"], dtype=np.int64)
        out = {
            "shard": int(doc["shard"]),
            "shards": int(doc["shards"]),
            "mode": str(doc.get("mode", "round-robin")),
            "gids": gids,
            "total_docs": int(doc["total_docs"]),
            "ndocs": int(doc["ndocs"]),
            "avgdl": float(doc["avgdl"]),
            "global_df": {k.encode("ascii"): int(v)
                          for k, v in doc["global_df"].items()},
        }
    except (KeyError, TypeError, ValueError) as e:
        raise artifact_mod.ArtifactError(
            f"{sp}: malformed shard sidecar ({e})") from e
    if len(gids) and not (np.diff(gids) > 0).all():
        raise artifact_mod.ArtifactError(
            f"{sp}: sidecar gid map is not strictly ascending — the "
            "local→global doc map must be monotone")
    return out


class ShardEngine:
    """One cluster shard's engine: local artifact, global answers.

    Wraps the single-artifact :class:`~..serve.engine.Engine` (every
    unlisted attribute delegates to it — metrics, planner, caches,
    encode/lookup all behave identically) and overrides exactly the
    ops whose answers leave the process:

    * ``postings`` / ``query_and`` / ``query_or`` — local doc ids map
      through the monotone gid table.
    * ``top_k_scored`` / ``top_k_scored_batch`` — ranked answers carry
      global ids; scores are already global via the corpus override.
    * ``df`` / ``top_k`` — intentionally LOCAL (router aggregates).
    """

    engine_name = "shard"

    def __init__(self, path, cache_terms: int = 4096):
        self.info = load_sidecar(path)
        self._base = engine_mod.Engine(path, cache_terms=cache_terms)
        try:
            self._gids = self.info["gids"]
            # max_doc_id can trail len(gids) when tail docs are empty
            # (they never enter a posting); it may never exceed it
            docs = int(self._base.artifact.max_doc_id)
            if docs > len(self._gids):
                raise artifact_mod.ArtifactError(
                    f"{sidecar_path(path)}: sidecar maps "
                    f"{len(self._gids)} docs but the artifact "
                    f"references doc id {docs} — rebuild the shard "
                    "(mri shard)")
            self._gdf = self.info["global_df"]
            self._base.set_corpus_override(
                self.info["ndocs"], self.info["avgdl"], self._df_fn)
        except BaseException:
            self._base.close()
            raise

    def _df_fn(self, idx: int) -> int:
        """Global scoring df for local lex index ``idx`` (strict: a
        term missing from the sidecar means the sidecar predates the
        artifact — fail loudly rather than serve divergent floats)."""
        term = self._base.artifact.term(int(idx))
        try:
            return self._gdf[term]
        except KeyError:
            raise artifact_mod.ArtifactError(
                f"shard sidecar has no global df for term "
                f"{term!r} — sidecar/artifact mismatch") from None

    def _to_global(self, docs: np.ndarray) -> np.ndarray:
        """Monotone local→global map; preserves ascending order."""
        if not len(docs):
            return np.zeros(0, dtype=np.int32)
        return self._gids[
            np.asarray(docs, dtype=np.int64) - 1].astype(np.int32)

    # -- ops with globally-visible doc ids ------------------------------

    def postings(self, batch):
        return [self._to_global(r) if r is not None else None
                for r in self._base.postings(batch)]

    def query_and(self, batch) -> np.ndarray:
        return self._to_global(self._base.query_and(batch))

    def query_or(self, batch) -> np.ndarray:
        return self._to_global(self._base.query_or(batch))

    def top_k_scored(self, batch, k: int):
        return [(int(self._gids[d - 1]), s)
                for d, s in self._base.top_k_scored(batch, k)]

    def top_k_scored_batch(self, batches, k: int):
        return [[(int(self._gids[d - 1]), s) for d, s in res]
                for res in self._base.top_k_scored_batch(batches, k)]

    # -- bookkeeping ----------------------------------------------------

    def bm25_stats(self) -> tuple[int, float]:
        """Global ``(ndocs, avgdl)`` every shard scores with."""
        return self.info["ndocs"], self.info["avgdl"]

    def describe(self) -> dict:
        out = self._base.describe()
        out["engine"] = self.engine_name
        out["cluster"] = {
            "shard": self.info["shard"],
            "shards": self.info["shards"],
            "mode": self.info["mode"],
            "local_docs": len(self._gids),
            "total_docs": self.info["total_docs"],
            "ndocs": self.info["ndocs"],
            "avgdl": self.info["avgdl"],
        }
        return out

    def close(self) -> None:
        self._base.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __getattr__(self, name):
        # everything else (df, top_k, encode_batch, lookup, metrics,
        # planner, caches, artifact, ...) is the base engine's
        return getattr(self._base, name)
