"""The router's timer wheel: hedges, RPC timeouts, client deadlines.

One thread, one heap.  Every timed decision the router makes — fire a
hedge RPC because the primary is quiet past the shard's p95, expire a
scatter because the client's ``deadline_ms`` passed, condemn an RPC at
``MRI_CLUSTER_RPC_TIMEOUT_MS`` — is an entry here, so the router needs
no per-request timer threads and a 10k-deep pipeline costs one heap.

Hedge delay policy (``MRI_CLUSTER_HEDGE_MS``):

* ``-1`` (default) — adaptive: the shard's rolling p95 with a 1 ms
  floor.  The canonical tail-at-scale setting: hedges fire only for
  the slowest ~5% of RPCs, bounding duplicate work at ~5%.
* ``0`` — hedging off.
* ``> 0`` — fixed delay in milliseconds.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time

log = logging.getLogger("mri_tpu.cluster")

#: adaptive-mode floor: never hedge inside 1 ms — faster than that the
#: duplicate would race the original's serialization, not its tail
MIN_HEDGE_S = 1e-3


def hedge_delay_s(knob_ms: float, p95_s: float | None) -> float | None:
    """Seconds to wait before hedging, or ``None`` for no hedge."""
    if knob_ms == 0:
        return None
    if knob_ms > 0:
        return knob_ms / 1e3
    if p95_s is None:
        return None  # adaptive with no samples yet: nothing to beat
    return max(MIN_HEDGE_S, p95_s)


class _Timer:
    __slots__ = ("fn", "cancelled")

    def __init__(self, fn):
        self.fn = fn
        self.cancelled = False


class Clock:
    """Single-threaded monotonic timer heap.

    ``schedule`` returns a token for ``cancel``; callbacks run on the
    clock thread and must be quick (the router's are: enqueue a send,
    flip a flag).  A callback that raises is logged and dropped — one
    bad timer must not stop the wheel.
    """

    def __init__(self, name: str = "mri-router-clock"):
        self._heap: list = []  # guarded by: self._cv
        self._cv = threading.Condition()
        self._seq = itertools.count()
        self._stopped = False
        self._cancelled = 0  # cancelled-but-enqueued  # guarded by: self._cv
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=name)
        self._thread.start()

    def schedule(self, delay_s: float, fn) -> _Timer:
        t = _Timer(fn)
        when = time.monotonic() + max(0.0, delay_s)
        item = (when, next(self._seq), t)
        with self._cv:
            heapq.heappush(self._heap, item)
            # wake the wheel only when the new timer is the next to
            # fire: a steady pipeline arms thousands of far-future RPC
            # timeouts per second, and a notify per arm would burn a
            # thread wakeup each (the scatter hot path's biggest cost)
            if self._heap[0] is item:
                self._cv.notify()
        return t

    def cancel(self, token: _Timer) -> None:
        token.cancelled = True  # lazily reaped when it surfaces
        with self._cv:
            self._cancelled += 1
            # rebuild once dead weight dominates, so far-future
            # cancelled timeouts cannot grow the heap without bound
            if self._cancelled > 2048 \
                    and self._cancelled > len(self._heap) // 2:
                self._heap = [e for e in self._heap
                              if not e[2].cancelled]
                heapq.heapify(self._heap)
                self._cancelled = 0

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()
        self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._stopped and (
                        not self._heap
                        or self._heap[0][0] > time.monotonic()):
                    if not self._heap:
                        self._cv.wait()
                    else:
                        self._cv.wait(
                            max(0.0,
                                self._heap[0][0] - time.monotonic()))
                if self._stopped:
                    return
                _, _, timer = heapq.heappop(self._heap)
            if timer.cancelled:
                continue
            try:
                timer.fn()
            except Exception:
                log.exception("router timer callback failed")
