"""Persistent pipelined replica connections + per-shard failover state.

One :class:`ReplicaConn` per (shard, replica) endpoint: a single
long-lived socket carrying many concurrent RPCs, correlated by ``id``
(the daemon echoes it).  RPC ids come from ONE process-global counter,
so the same encoded request line can be scattered verbatim to every
shard — the router JSON-encodes each client query once, not D times.

Threading: each live connection owns a reader thread (parse + resolve
callbacks) and a writer thread draining a deque with one batched
``sendall`` per wakeup — pipelined senders amortize syscalls exactly
like the daemon's writer.  Any socket error condemns the connection:
every pending callback is resolved with ``None`` (the
connection-death sentinel) and the owning :class:`ShardClient` marks
the replica down, which is what the router's failover keys off.

:class:`ShardClient` holds one shard's replica set: health state fed
by the router's healthz prober, the current primary, and a rolling
latency reservoir whose p95 drives adaptive hedging (hedge.py).
"""

from __future__ import annotations

import itertools
import json
import logging
import socket
import threading
import time
from collections import deque

from .. import faults

log = logging.getLogger("mri_tpu.cluster")

#: PR 14 readiness reasons that must push traffic off a replica even
#: though its TCP endpoint still answers.
NOT_READY_REASONS = ("draining", "stalled", "overloaded",
                    "replica_lagging", "reloading")

_rpc_ids = itertools.count(1)


def next_rpc_id() -> int:
    """Process-global RPC id (``next`` on a count is atomic under the
    GIL) — unique across every replica connection, so one encoded
    request line is valid on all of them simultaneously."""
    return next(_rpc_ids)


class ConnDead(Exception):
    """The replica connection is gone (send refused or torn)."""


class ReplicaConn:
    """One pipelined JSON-lines connection to a shard replica."""

    def __init__(self, shard: int, replica: int, addr: tuple,
                 on_dead=None, connect_timeout: float = 5.0):
        self.shard = shard
        self.replica = replica
        self.addr = addr
        self._on_dead = on_dead
        # mrilint: allow(fault-boundary) router->shard dial, not corpus I/O; cluster faults inject at send (shard-slow/router-conn-reset) and by killing real daemons (shard-dead)
        self.sock = socket.create_connection(addr,
                                             timeout=connect_timeout)
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # mrilint: allow(fault-boundary) read framing on the same router->shard RPC socket
        self._rfile = self.sock.makefile("rb")
        self._pending: dict[int, object] = {}  # guarded by: self._lock
        self._lock = threading.Lock()
        self._outq: deque[bytes] = deque()  # guarded by: self._out_cv
        self._out_cv = threading.Condition()
        self.dead = False
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"mri-router-read-s{shard}r{replica}")
        self._writer = threading.Thread(
            target=self._write_loop, daemon=True,
            name=f"mri-router-write-s{shard}r{replica}")
        self._reader.start()
        self._writer.start()

    def send(self, rpc_id: int, data: bytes, cb) -> None:
        """Register ``cb(payload)`` for ``rpc_id`` and enqueue one
        encoded request line.  Raises :class:`ConnDead` when the
        connection is already condemned; a death AFTER enqueue resolves
        the callback with ``None`` instead."""
        inj = faults.active()
        if inj is not None:
            try:
                inj.on_router_send(self.shard, self.replica)
            except faults.InjectedConnReset:
                self._fail()
                raise ConnDead(
                    f"shard {self.shard} replica {self.replica}: "
                    "injected connection reset") from None
        with self._lock:
            if self.dead:
                raise ConnDead(
                    f"shard {self.shard} replica {self.replica} "
                    f"({self.addr[0]}:{self.addr[1]}): connection down")
            self._pending[rpc_id] = cb
        with self._out_cv:
            self._outq.append(data)
            self._out_cv.notify()

    def forget(self, rpc_id: int) -> None:
        """Drop the callback for an RPC the caller no longer wants
        (deadline passed, hedge already won).  A late response is then
        discarded by the reader."""
        with self._lock:
            self._pending.pop(rpc_id, None)

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def _write_loop(self) -> None:
        while True:
            with self._out_cv:
                while not self._outq and not self.dead:
                    self._out_cv.wait()
                if self.dead and not self._outq:
                    return
                chunk = b"".join(self._outq)
                self._outq.clear()
            try:
                self.sock.sendall(chunk)
            except OSError:
                self._fail()
                return

    def _read_loop(self) -> None:
        try:
            for raw in self._rfile:
                line = raw.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                except ValueError:
                    log.warning("shard %d replica %d: undecodable "
                                "response line dropped", self.shard,
                                self.replica)
                    continue
                rid = payload.get("id") if isinstance(payload, dict) \
                    else None
                if rid is None:
                    continue  # unsolicited (id-less bad_request echo)
                with self._lock:
                    cb = self._pending.pop(rid, None)
                if cb is not None:
                    cb(payload)
        except OSError:
            pass
        self._fail()

    def _fail(self) -> None:
        """Condemn the connection once: close, fail every pending RPC
        with the ``None`` death sentinel, notify the owner."""
        with self._lock:
            if self.dead:
                return
            self.dead = True
            orphans = list(self._pending.values())
            self._pending.clear()
        with self._out_cv:
            self._out_cv.notify_all()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        # the makefile handle holds the fd's last reference — close it
        # too or the socket outlives the condemned connection
        try:
            self._rfile.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self._on_dead is not None:
            self._on_dead(self)
        for cb in orphans:
            try:
                cb(None)
            except Exception:
                log.exception("rpc callback failed on connection death")

    def close(self) -> None:
        self._fail()


class _P95Ring:
    """Fixed-size latency reservoir; p95 recomputed every few inserts
    (a 128-float sort is cheap, per-RPC would still be waste)."""

    def __init__(self, size: int = 128, refresh: int = 16):
        self._buf: list[float] = []
        self._size = size
        self._refresh = refresh
        self._i = 0
        self._n = 0
        self._p95: float | None = None
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            if len(self._buf) < self._size:
                self._buf.append(seconds)
            else:
                self._buf[self._i] = seconds
                self._i = (self._i + 1) % self._size
            self._n += 1
            if self._n % self._refresh == 0 or self._p95 is None:
                s = sorted(self._buf)
                self._p95 = s[min(len(s) - 1,
                                  int(0.95 * (len(s) - 1) + 0.5))]

    def p95(self) -> float | None:
        with self._lock:
            return self._p95


class Breaker:
    """Per-replica circuit breaker: closed → open → half-open.

    Failure evidence (error answers, RPC timeouts, connection deaths)
    lands in a ring of per-second ``(ok, err)`` buckets —
    ``obs/windows.py``'s stamped-bucket discipline shrunk to one
    counter pair — so verdicts follow a rolling ``WINDOW_S``-second
    window, not all-time totals.  The breaker opens when the window
    holds at least ``threshold`` failures and strictly more failures
    than successes; an open breaker rejects picks for ``cooldown_s``,
    then admits exactly ONE in-flight probe RPC (half-open) whose
    outcome closes or re-opens it.  A ready ``healthz`` verdict also
    closes it, so recovery is always probe-gated — by the router's own
    traffic or by the health prober, whichever speaks first.
    """

    WINDOW_S = 10

    CLOSED = "closed"
    HALF_OPEN = "half-open"
    OPEN = "open"

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 clock=time.monotonic):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._ok = [0] * self.WINDOW_S
        self._err = [0] * self.WINDOW_S
        self._stamp = [-1] * self.WINDOW_S  # second each bucket holds
        self.state = self.CLOSED
        self._opened_at = 0.0
        self._probing = False

    def _bucket(self, now: float) -> int:
        sec = int(now)
        i = sec % self.WINDOW_S
        if self._stamp[i] != sec:
            self._stamp[i] = sec
            self._ok[i] = 0
            self._err[i] = 0
        return i

    def _window(self, now: float) -> tuple:
        lo = int(now) - self.WINDOW_S + 1
        ok = err = 0
        for i in range(self.WINDOW_S):
            if self._stamp[i] >= lo:
                ok += self._ok[i]
                err += self._err[i]
        return ok, err

    def allow(self, now: float | None = None) -> bool:
        """May the caller send this replica an RPC right now?  Open
        says no until ``cooldown_s`` has passed, then one True answer
        claims the half-open probe slot — callers that get True MUST
        report the RPC's outcome or the slot stays claimed until the
        health prober speaks."""
        now = self._clock() if now is None else now
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if now - self._opened_at < self.cooldown_s:
                    return False
                self.state = self.HALF_OPEN
                self._probing = True
                return True
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        now = self._clock()
        with self._lock:
            if self.state != self.CLOSED:
                self._close_locked()
            self._ok[self._bucket(now)] += 1

    def record_failure(self) -> None:
        now = self._clock()
        with self._lock:
            self._err[self._bucket(now)] += 1
            if self.state == self.HALF_OPEN:
                self._open_locked(now)
            elif self.state == self.CLOSED:
                ok, err = self._window(now)
                if err >= self.threshold and err > ok:
                    self._open_locked(now)

    def note_ready(self) -> None:
        """A ready healthz verdict — probe-gated recovery through the
        prober's channel instead of a live data RPC."""
        with self._lock:
            if self.state != self.CLOSED:
                self._close_locked()

    def _open_locked(self, now: float) -> None:
        self.state = self.OPEN
        self._opened_at = now
        self._probing = False

    def _close_locked(self) -> None:
        self.state = self.CLOSED
        self._probing = False
        # fresh start: the failures that opened the breaker must not
        # re-open it on the first post-recovery error
        self._stamp = [-1] * self.WINDOW_S


class RetryBudget:
    """Token-bucket retry/hedge budget, a ratio of live traffic.

    Every FIRST attempt of a shard leg deposits ``ratio`` tokens;
    every retry or hedge spends one whole token.  Over any window the
    extra load a browning-out shard can attract is therefore capped
    near ``ratio`` × its live traffic plus the small constant ``cap``
    a cold router may bank — no retry storm compounds.  ``ratio`` 0
    disables retries and hedges outright.
    """

    def __init__(self, ratio: float, cap: float = 8.0):
        self.ratio = float(ratio)
        self._cap = max(1.0, float(cap))
        self._tokens = min(self._cap, 2.0) if self.ratio > 0 else 0.0
        self._lock = threading.Lock()
        self.denied = 0  # lifetime try_spend refusals (stats surface)

    def deposit(self) -> None:
        if self.ratio <= 0:
            return
        with self._lock:
            self._tokens = min(self._cap, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            self.denied += 1
            return False

    def tokens(self) -> float:
        with self._lock:
            return round(self._tokens, 3)


class Replica:
    """Health + connection state for one endpoint of one shard."""

    def __init__(self, shard: int, idx: int, addr: tuple):
        self.shard = shard
        self.idx = idx
        self.addr = addr
        self.conn: ReplicaConn | None = None  # guarded by: self.lock
        self.lock = threading.Lock()
        self.ready = False   # last healthz verdict
        self.reasons: list = ["unprobed"]
        self.generation = None  # serving generation from last healthz
        self.last_probe = 0.0
        self.breaker = Breaker()

    def describe(self) -> dict:
        return {"addr": f"{self.addr[0]}:{self.addr[1]}",
                "ready": self.ready,
                "reasons": list(self.reasons),
                "generation": self.generation,
                "breaker": self.breaker.state}


class ShardClient:
    """One doc-shard's replica set, as the router sees it."""

    def __init__(self, shard: int, addrs: list,
                 retry_budget_ratio: float = 0.1):
        self.shard = shard
        self.replicas = [Replica(shard, i, a)
                         for i, a in enumerate(addrs)]
        self.primary = 0  # guarded by: self._lock
        self._lock = threading.Lock()
        self.latency = _P95Ring()
        self.budget = RetryBudget(retry_budget_ratio)

    def conn(self, ri: int) -> ReplicaConn:
        """The live connection for replica ``ri``, dialing on demand.
        Raises :class:`ConnDead` when the endpoint refuses."""
        rep = self.replicas[ri]
        with rep.lock:
            c = rep.conn
            if c is not None and not c.dead:
                return c
            try:
                c = ReplicaConn(self.shard, ri, rep.addr,
                                on_dead=self._conn_died)
            except OSError as e:
                rep.breaker.record_failure()
                raise ConnDead(
                    f"shard {self.shard} replica {ri} "
                    f"({rep.addr[0]}:{rep.addr[1]}): {e}") from e
            rep.conn = c
            return c

    def _conn_died(self, conn: ReplicaConn) -> None:
        rep = self.replicas[conn.replica]
        rep.ready = False
        rep.reasons = ["connection_lost"]
        rep.breaker.record_failure()

    def pick(self, exclude: tuple = ()) -> int:
        """Replica to try next: the primary when it is ready and its
        breaker admits traffic, else the first such replica (and that
        becomes the new primary — a health-based failover the router
        counts), else any non-excluded endpoint whose breaker admits
        as a last resort (an open breaker whose cooldown just expired
        admits its single half-open probe here).  -1 when every
        replica is excluded or breaker-rejected — the signal the
        partial-result gather keys off."""
        with self._lock:
            p = self.primary
            rep = self.replicas[p]
            if p not in exclude and rep.ready and rep.breaker.allow():
                return p
            for r in self.replicas:
                if r.idx != p and r.idx not in exclude and r.ready \
                        and r.breaker.allow():
                    self.primary = r.idx
                    return r.idx
            for r in self.replicas:
                if r.idx not in exclude and not r.ready \
                        and r.breaker.allow():
                    return r.idx
        return -1

    def hedge_pick(self, primary_ri: int) -> int:
        """A DIFFERENT ready replica (breaker permitting) for the
        hedge RPC (-1 if none)."""
        for r in self.replicas:
            if r.idx != primary_ri and r.ready and r.breaker.allow():
                return r.idx
        return -1

    def breakers_open(self) -> int:
        """Replicas currently refusing traffic (open or half-open)."""
        return sum(1 for r in self.replicas
                   if r.breaker.state != Breaker.CLOSED)

    def ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.ready)

    def describe(self) -> dict:
        with self._lock:
            primary = self.primary
        reps = []
        for r in self.replicas:
            d = r.describe()
            d["primary"] = r.idx == primary
            reps.append(d)
        p95 = self.latency.p95()
        return {"shard": self.shard,
                "p95_ms": round(p95 * 1e3, 3) if p95 is not None
                          else None,
                "breakers_open": self.breakers_open(),
                "retry_tokens": self.budget.tokens(),
                "retry_denied": self.budget.denied,
                "replicas": reps}

    def close(self) -> None:
        for r in self.replicas:
            with r.lock:
                c, r.conn = r.conn, None
            if c is not None:
                c.close()


class HealthProber:
    """One thread probing every replica of every shard with pipelined
    ``healthz`` RPCs at a fixed cadence, updating replica readiness
    from PR 14's ``ready``/``reasons`` verdict.  An unanswered probe
    (connection death, or no reply within two cadences) marks the
    replica down; the next cycle re-dials through ``ShardClient.conn``.
    """

    def __init__(self, shards: list, interval_s: float,
                 on_transition=None):
        self.shards = shards
        self.interval_s = interval_s
        self._on_transition = on_transition
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="mri-router-health")

    def start(self) -> None:
        self._probe_all(first=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._probe_all()

    def _probe_all(self, first: bool = False) -> None:
        now = time.monotonic()
        for sc in self.shards:
            for rep in sc.replicas:
                self._probe(sc, rep, now, first)

    def _probe(self, sc: ShardClient, rep: Replica, now: float,
               first: bool) -> None:
        def _verdict(payload, rep=rep, sc=sc):
            was = rep.ready
            if payload is None:
                rep.ready = False
                rep.reasons = ["connection_lost"]
                rep.generation = None
            else:
                rep.ready = bool(payload.get("ready"))
                rep.reasons = list(payload.get("reasons") or ())
                gen = payload.get("generation")
                rep.generation = gen if isinstance(gen, int) \
                    and not isinstance(gen, bool) else None
                if rep.ready:
                    rep.breaker.note_ready()
            rep.last_probe = time.monotonic()
            if was != rep.ready and self._on_transition is not None:
                self._on_transition(sc, rep, was)

        # a probe two cadences old means the endpoint is wedged (alive
        # TCP, no answers): treat as down until it speaks again
        if rep.ready and rep.last_probe \
                and now - rep.last_probe > 3 * self.interval_s:
            was = rep.ready
            rep.ready = False
            rep.reasons = ["probe_timeout"]
            if was and self._on_transition is not None:
                self._on_transition(sc, rep, was)
        rid = next_rpc_id()
        line = (json.dumps({"id": rid, "op": "healthz"},
                           separators=(",", ":")) + "\n").encode()
        try:
            conn = sc.conn(rep.idx)
            conn.send(rid, line, _verdict)
        except ConnDead:
            _verdict(None)
            return
        if first:
            # synchronous first round so the router starts with real
            # readiness instead of an all-down fleet
            deadline = time.monotonic() + max(1.0, self.interval_s)
            while rep.last_probe == 0.0 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
