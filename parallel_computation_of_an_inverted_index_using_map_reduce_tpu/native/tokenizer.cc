// Native host tokenizer: the map phase's hot loop, one pass in C++.
//
// Re-implements (TPU-framework-style, not a translation) what the
// reference mapper does per token — fscanf whitespace split, delete
// non-letters, lowercase, cap at 299 letters (main.c:102-117) — plus
// what its reducer re-derives later: the term dictionary.  Output is
// the integer corpus the device engine consumes: per-token sorted-vocab
// term ids + doc ids, the packed sorted vocab, and first-letter ids.
//
// Two frontends over one incremental core (`StreamState` + `ScanChunk`):
//
//   * one-shot `mri_tokenize` — whole corpus in, sorted-vocab ids out;
//   * streaming `mri_stream_*` — per-chunk feeds return packed
//     `prov_id * stride + doc_id` int32 keys immediately (provisional
//     ids are first-occurrence ids, stable once assigned), so the
//     caller can overlap host->device uploads with tokenizing the next
//     chunk; `mri_stream_finalize` then resolves the sorted vocab, the
//     prov->rank remap, and per-term document frequencies (the
//     combiner's counts) — everything the emit phase needs, with the
//     device program never depending on final vocab order.
//
// Map-phase host parallelism (the reference's N mapper threads over
// size-balanced contiguous file ranges, main.c:307-328, 348-365,
// re-expressed): every entry point takes a `num_threads`; documents are
// partitioned into contiguous byte-balanced ranges (the reference's
// greedy cut at total/N, made total and safe), each scanned by a worker
// with a *thread-local* vocab table and combiner, then merged
// sequentially at vocab scale — per-worker local ids upsert into the
// global table once per unique word, never per token.  Because the doc
// ranges are contiguous and workers are merged in range order, the
// emitted (term, doc) pair sequence is byte-for-byte the same as the
// single-threaded scan for rank-space outputs, and postings stay
// doc-ascending per term for free.  No locks anywhere: workers share
// nothing until the join, the same fork-join shape as the reference's
// map phase but without its serializing spill-file stdio locks
// (main.c:116).
//
// Hot-loop design: 256-entry byte tables (whitespace / lowercase-letter)
// instead of range compares; words hashed in 8-byte blocks AFTER the
// cleaning pass (a per-byte multiply chain serializes at ~4 cycles per
// byte — block hashing cuts the dependency chain 8x); open-addressing
// hash table whose entries carry the word's first 8 cleaned bytes
// inline, so the common case (words <= 8 letters, most English tokens)
// resolves a probe with one in-register compare and never touches the
// arena's cache lines; arena words are zero-padded to 8-byte boundaries
// so longer words compare and rehash block-wise; final std::sort over
// unique words only (vocab-scale, not token-scale).
//
// Build: g++ -O3 -shared -fPIC -o libmri_tokenizer.so tokenizer.cc

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <csignal>
#include <exception>
#include <functional>
#include <new>
#include <system_error>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

constexpr int kMaxWordLetters = 299;  // reference MAX_WORD - 1 (main.c:7,105)
constexpr uint64_t kFnvBasis = 1469598103934665603ull;
constexpr uint64_t kFnvPrime = 1099511628211ull;

struct Entry {
  uint64_t prefix;  // first 8 cleaned bytes, zero-padded (canonical)
  uint32_t offset;  // into arena (8-byte aligned)
  uint32_t len;
  int32_t id;       // provisional (first-occurrence) id; -1 = empty slot
};

inline uint64_t Load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

struct ByteTables {
  bool space[256];
  uint8_t lower[256];  // lowercase letter, or 0 = delete this byte
  ByteTables() {
    std::memset(space, 0, sizeof(space));
    std::memset(lower, 0, sizeof(lower));
    // C-locale isspace set, what fscanf %s splits on (main.c:102).
    for (uint8_t b : {' ', '\t', '\n', '\v', '\f', '\r'}) space[b] = true;
    for (int b = 'a'; b <= 'z'; ++b) lower[b] = static_cast<uint8_t>(b);
    for (int b = 'A'; b <= 'Z'; ++b) lower[b] = static_cast<uint8_t>(b + 32);
  }
};
const ByteTables kTab;

// Block FNV over a zero-padded word (callers guarantee the bytes from
// `len` up to the next 8-byte boundary are zero, making padded loads
// canonical) with a murmur-style finalizer — the low bits index the
// table, so they need the avalanche a plain FNV fold lacks.
inline uint64_t HashWord(const uint8_t* p, uint32_t len) {
  uint64_t h = kFnvBasis;
  for (uint32_t i = 0; i < len; i += 8) h = (h ^ Load64(p + i)) * kFnvPrime;
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  return h;
}

// Block equality for zero-padded words of the same length.
inline bool WordsEqual(const uint8_t* a, const uint8_t* b, uint32_t len) {
  for (uint32_t i = 0; i < len; i += 8)
    if (Load64(a + i) != Load64(b + i)) return false;
  return true;
}

// ---------------------------------------------------------------------------
// SIMD scan support (x86-64 AVX2+BMI2; scalar fallback elsewhere).
//
// The scalar clean loop pays ~10 cycles per corpus byte in branchy
// per-byte work.  Instead: one vector pass builds per-64-byte-group
// space/letter bitmasks, then tokens are walked by bit scanning and
// cleaned 8 raw bytes at a time with a pext byte-compaction (the
// letter-mask bytes select which lowered bytes survive).  Short tokens
// (<= 8 raw bytes — most of real text) first probe a direct-mapped
// raw-bytes -> prov-id cache: raw-equal implies cleaned-equal (cleaning
// deletes NUL bytes, so masked-load equality is sufficient), which
// skips clean+hash+table entirely for hot words.
// ---------------------------------------------------------------------------

#if defined(__x86_64__)

struct MaskSpan {
  std::vector<uint64_t> S;  // space bits (beyond data: 1)
  std::vector<uint64_t> L;  // letter bits
  std::vector<uint64_t> T;  // non-space bits (beyond data: 0)
  size_t base = 0;          // absolute group index of word 0
};

struct LenMasks {
  uint64_t bytes[9];  // low 8*n bits set
  LenMasks() {
    bytes[8] = ~0ull;
    for (int i = 0; i < 8; ++i) bytes[i] = (1ull << (8 * i)) - 1;
  }
};
const LenMasks kLen;

// bit j set -> byte j = 0xFF (the pext byte-selection mask)
struct ByteMaskLut {
  uint64_t m[256];
  ByteMaskLut() {
    for (int mask = 0; mask < 256; ++mask) {
      uint64_t v = 0;
      for (int j = 0; j < 8; ++j)
        if (mask & (1 << j)) v |= 0xFFull << (8 * j);
      m[mask] = v;
    }
  }
};
const ByteMaskLut kByteMask;

__attribute__((target("avx2")))
void BuildMasks(const uint8_t* data, int64_t data_len, int64_t lo, int64_t hi,
                MaskSpan& m) {
  const size_t g0 = static_cast<size_t>(lo) >> 6;
  const size_t g1 = (static_cast<size_t>(hi) + 63) >> 6;  // exclusive
  m.base = g0;
  m.S.assign(g1 - g0 + 2, ~0ull);
  m.L.assign(g1 - g0 + 2, 0);
  m.T.assign(g1 - g0 + 2, 0);
  const __m256i v9 = _mm256_set1_epi8(9), v4 = _mm256_set1_epi8(4),
      vsp = _mm256_set1_epi8(' '), v20 = _mm256_set1_epi8(0x20),
      va = _mm256_set1_epi8('a'), v25 = _mm256_set1_epi8(25);
  for (size_t g = g0; g < g1; ++g) {
    const int64_t p = static_cast<int64_t>(g) << 6;
    uint64_t sm, lm;
    if (p + 64 <= data_len) {
      sm = lm = 0;
      for (int half = 0; half < 2; ++half) {
        __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(data + p + 32 * half));
        __m256i u = _mm256_sub_epi8(v, v9);
        __m256i ctl = _mm256_cmpeq_epi8(_mm256_min_epu8(u, v4), u);  // \t..\r
        __m256i spc = _mm256_or_si256(ctl, _mm256_cmpeq_epi8(v, vsp));
        __m256i lo8 = _mm256_or_si256(v, v20);
        __m256i d = _mm256_sub_epi8(lo8, va);
        __m256i let = _mm256_cmpeq_epi8(_mm256_min_epu8(d, v25), d);
        sm |= static_cast<uint64_t>(
                  static_cast<uint32_t>(_mm256_movemask_epi8(spc)))
              << (32 * half);
        lm |= static_cast<uint64_t>(
                  static_cast<uint32_t>(_mm256_movemask_epi8(let)))
              << (32 * half);
      }
    } else {  // buffer-tail group, scalar (bytes beyond data read as space)
      sm = ~0ull;
      lm = 0;
      for (int64_t j = p; j < data_len; ++j) {
        const uint64_t b = 1ull << (j - p);
        if (!kTab.space[data[j]]) sm &= ~b;
        if (kTab.lower[data[j]]) lm |= b;
      }
    }
    m.S[g - g0] = sm;
    m.L[g - g0] = lm;
    m.T[g - g0] = ~sm;
  }
  // +2 guard words: S stays all-ones (space), T/L all-zero — walks and
  // ExtractBits never read uninitialized memory.
  m.T[g1 - g0] = m.T[g1 - g0 + 1] = 0;
  m.L[g1 - g0] = m.L[g1 - g0 + 1] = 0;
}

// >= 8 mask bits starting at absolute byte position a (low bits).
inline uint64_t ExtractBits(const std::vector<uint64_t>& M, size_t base,
                            int64_t a) {
  const size_t w = (static_cast<size_t>(a) >> 6) - base;
  const unsigned o = static_cast<unsigned>(a) & 63;
  uint64_t x = M[w] >> o;
  if (o) x |= M[w + 1] << (64 - o);
  return x;
}

// First set bit >= pos, capped at end.
inline int64_t NextSet(const std::vector<uint64_t>& M, size_t base,
                       int64_t pos, int64_t end) {
  size_t w = (static_cast<size_t>(pos) >> 6) - base;
  uint64_t x = M[w] >> (pos & 63);
  if (x) {
    const int64_t r = pos + __builtin_ctzll(x);
    return r < end ? r : end;
  }
  const size_t wend = ((static_cast<size_t>(end) + 63) >> 6) - base;
  for (++w; w <= wend; ++w) {
    if (M[w]) {
      const int64_t r =
          (static_cast<int64_t>(w + base) << 6) + __builtin_ctzll(M[w]);
      return r < end ? r : end;
    }
  }
  return end;
}

#endif  // __x86_64__

struct CacheEntry {
  uint64_t tag;
  int32_t id;  // -1 = empty
};
// Second-level cache for 9..16-raw-byte tokens (the chunked-pext slow
// path costs ~3x the short path and covers ~a quarter of real English
// tokens — measured 33 vs 17 ns/token on the reference corpus with
// long-word mixes): 128-bit raw tag, same stream-stable-id guarantee.
struct CacheEntry16 {
  uint64_t tag0, tag1;
  int32_t id;  // -1 = empty
};
constexpr int kRawCacheBits = 13;

// Incremental tokenizer state: one per scanning thread (or the single
// global one when num_threads == 1).  Provisional ids are assigned at
// first occurrence and never change; the combiner (per-(term, doc)
// dedup, the reference reducer's dedup at main.c:176-184 pulled into
// the map phase) and the per-term document-frequency counts live here
// so nothing token-scale survives past a chunk.
struct StreamState {
  std::vector<uint8_t> arena;
  std::vector<Entry> table;
  uint64_t mask;
  int32_t next_id = 0;
  std::vector<uint32_t> word_offsets;  // prov id -> arena offset
  std::vector<uint32_t> word_lens;
  // Combiner state, interleaved so the per-token dedup touches ONE
  // cache line: last_doc = global doc ordinal last seen; df = docs
  // containing the term (meaningful only when scanned with dedup=true).
  struct TermState { int32_t last_doc; int32_t df; };
  std::vector<TermState> combiner;
  int64_t raw_tokens = 0;
  int64_t num_pairs = 0;
  int32_t doc_ordinal = 0;  // global across chunks
  // Direct-mapped raw-bytes -> prov-id caches for the SIMD scan
  // (lazily sized; ids are stream-stable so they never invalidate):
  // <= 8 raw bytes, and 9..16 raw bytes with a 128-bit tag.
  std::vector<CacheEntry> raw_cache;
  std::vector<CacheEntry16> raw_cache16;

  StreamState() : table(1 << 16), mask(table.size() - 1) {
    for (auto& e : table) e.id = -1;
    arena.reserve(1 << 20);
  }

  void Grow() {
    std::vector<Entry> bigger(table.size() * 2);
    for (auto& e : bigger) e.id = -1;
    const uint64_t bmask = bigger.size() - 1;
    for (const Entry& e : table) {
      if (e.id < 0) continue;
      uint64_t s = HashWord(arena.data() + e.offset, e.len) & bmask;
      while (bigger[s].id >= 0) s = (s + 1) & bmask;
      bigger[s] = e;
    }
    table.swap(bigger);
    mask = bmask;
  }

  // Upsert a cleaned word (hash h precomputed; `word` zero-padded to the
  // next 8-byte boundary); returns its prov id.
  int32_t Upsert(const uint8_t* word, int32_t wlen, uint64_t h) {
    const uint64_t prefix = Load64(word);
    uint64_t slot = h & mask;
    for (;;) {
      Entry& e = table[slot];
      if (e.id < 0) {
        const uint32_t off = static_cast<uint32_t>(arena.size());
        arena.insert(arena.end(), word, word + wlen);
        arena.resize((arena.size() + 7) & ~size_t{7}, 0);  // canonical pad
        e.prefix = prefix;
        e.offset = off;
        e.len = wlen;
        e.id = next_id;
        word_offsets.push_back(off);
        word_lens.push_back(wlen);
        combiner.push_back(TermState{-1, 0});
        const int32_t id = next_id++;
        if (static_cast<uint64_t>(next_id) * 10 > table.size() * 7) Grow();
        return id;
      }
      if (e.prefix == prefix && e.len == static_cast<uint32_t>(wlen) &&
          (wlen <= 8 ||
           WordsEqual(arena.data() + e.offset + 8, word + 8, wlen - 8)))
        return e.id;
      slot = (slot + 1) & mask;
    }
  }
};

// Scan a contiguous run of documents; emit (prov_id, doc_id) pairs
// through `emit` — combiner-deduped when `dedup`; repeat occurrences of
// an already-emitted (term, doc) pair go through `emit_dup` instead, so
// a caller can count within-document term frequencies without widening
// the combiner's one-cache-line TermState.  `data` is the whole
// window's concatenated bytes (`data_len` total — loads never read past
// it); this call scans docs `[doc_lo, doc_hi)` whose bytes span
// `[start_pos, doc_ends[doc_hi-1])`.
template <typename Emit, typename EmitDup>
void ScanChunkScalar(StreamState& st, const uint8_t* data, int64_t start_pos,
                     const int64_t* doc_ends, const int32_t* doc_id_values,
                     int32_t doc_lo, int32_t doc_hi, bool dedup, Emit&& emit,
                     EmitDup&& emit_dup) {
  uint8_t word[kMaxWordLetters + 8];  // +8: zero pad for block loads
  int64_t pos = start_pos;
  for (int32_t d = doc_lo; d < doc_hi; ++d, ++st.doc_ordinal) {
    const int64_t end = doc_ends[d];
    const int32_t doc_id = doc_id_values[d];
    const int32_t ordinal = st.doc_ordinal;
    while (pos < end) {
      while (pos < end && kTab.space[data[pos]]) ++pos;  // skip whitespace
      if (pos >= end) break;
      int wlen = 0;
      do {  // clean token: letters only, lowercase, cap at 299
        const uint8_t c = kTab.lower[data[pos]];
        if (c && wlen < kMaxWordLetters) word[wlen++] = c;
      } while (++pos < end && !kTab.space[data[pos]]);
      if (wlen == 0) continue;  // token cleaned to nothing (main.c:113)
      std::memset(word + wlen, 0, 8);  // canonical zero pad for Load64

      const int32_t id = st.Upsert(word, wlen, HashWord(word, wlen));
      ++st.raw_tokens;
      if (dedup) {
        StreamState::TermState& ts = st.combiner[id];
        if (ts.last_doc == ordinal) {  // (term, doc) already out
          emit_dup(id);
          continue;
        }
        ts.last_doc = ordinal;
        ++ts.df;
      }
      ++st.num_pairs;
      emit(id, doc_id);
    }
    pos = end;
  }
}

#if defined(__x86_64__)

// Chunked pext clean of one token's raw bytes [a, b) into `word`
// (zero-padded to the next 8 bytes); returns the cleaned length.  The
// general path for tokens the fixed-width caches cannot tag.
__attribute__((target("avx2,bmi2")))
static inline int CleanTokenChunked(const MaskSpan& m, const uint8_t* data,
                                    int64_t data_len, int64_t a, int64_t b,
                                    uint8_t* word) {
  constexpr uint64_t kLow8 = 0x2020202020202020ull;
  int wlen = 0;
  for (int64_t i = a; i < b; i += 8) {
    const int64_t take = (b - i < 8) ? b - i : 8;
    uint64_t raw;
    if (i + 8 <= data_len) {
      raw = Load64(data + i);
    } else {
      raw = 0;
      std::memcpy(&raw, data + i, static_cast<size_t>(data_len - i));
    }
    raw &= kLen.bytes[take];
    const uint64_t bits = ExtractBits(m.L, m.base, i) &
                          ((take == 8) ? 0xFFull
                                       : ((1ull << take) - 1)) & 0xFF;
    const uint64_t chunk = _pext_u64(raw | kLow8, kByteMask.m[bits]);
    std::memcpy(word + wlen, &chunk, 8);  // buffer is 299 + 8
    const int add = __builtin_popcountll(bits);
    wlen = (wlen + add > kMaxWordLetters) ? kMaxWordLetters : wlen + add;
  }
  if (wlen) std::memset(word + wlen, 0, 8);
  return wlen;
}

// Mask-driven scan: identical observable behavior to ScanChunkScalar
// (fuzz-tested against it via the oracle conformance suite), ~2x faster
// on real text.
template <typename Emit, typename EmitDup>
__attribute__((target("avx2,bmi2")))
void ScanChunkSimd(StreamState& st, const uint8_t* data, int64_t data_len,
                   int64_t start_pos, const int64_t* doc_ends,
                   const int32_t* doc_id_values, int32_t doc_lo,
                   int32_t doc_hi, bool dedup, Emit&& emit,
                   EmitDup&& emit_dup) {
  const int64_t span_end = doc_ends[doc_hi - 1];
  MaskSpan m;
  BuildMasks(data, data_len, start_pos, span_end, m);
  if (st.raw_cache.empty()) {
    st.raw_cache.assign(size_t{1} << kRawCacheBits, CacheEntry{0, -1});
    st.raw_cache16.assign(size_t{1} << kRawCacheBits,
                          CacheEntry16{0, 0, -1});
  }
  CacheEntry* cache = st.raw_cache.data();
  CacheEntry16* cache16 = st.raw_cache16.data();
  constexpr uint64_t kLow8 = 0x2020202020202020ull;
  uint8_t word[kMaxWordLetters + 8];
  int64_t pos = start_pos;
  for (int32_t d = doc_lo; d < doc_hi; ++d, ++st.doc_ordinal) {
    const int64_t end = doc_ends[d];
    const int32_t doc_id = doc_id_values[d];
    const int32_t ordinal = st.doc_ordinal;
    while (pos < end) {
      const int64_t a = NextSet(m.T, m.base, pos, end);
      if (a >= end) break;
      const int64_t b = NextSet(m.S, m.base, a, end);
      pos = b;
      const int64_t len_raw = b - a;
      int32_t id;
      if (len_raw <= 8 && a + 8 <= data_len) {
        const uint64_t raw = Load64(data + a) & kLen.bytes[len_raw];
        CacheEntry& ce =
            cache[(raw * 0x9E3779B97F4A7C15ull) >> (64 - kRawCacheBits)];
        if (ce.id >= 0 && ce.tag == raw) {
          id = ce.id;
        } else {
          const uint64_t bits =
              ExtractBits(m.L, m.base, a) & ((1ull << len_raw) - 1) & 0xFF;
          if (bits == 0) continue;  // cleaned to nothing (main.c:113)
          const uint64_t cleaned = _pext_u64(raw | kLow8, kByteMask.m[bits]);
          const int32_t wlen = __builtin_popcountll(bits);
          uint64_t wbuf[2] = {cleaned, 0};
          id = st.Upsert(reinterpret_cast<const uint8_t*>(wbuf), wlen,
                         HashWord(reinterpret_cast<const uint8_t*>(wbuf),
                                  static_cast<uint32_t>(wlen)));
          ce.tag = raw;
          ce.id = id;
        }
      } else if (len_raw <= 16 && a + 16 <= data_len) {
        // medium tokens: 128-bit raw tag over the same direct-mapped
        // discipline as the short cache
        const uint64_t raw0 = Load64(data + a);
        const uint64_t raw1 = Load64(data + a + 8) & kLen.bytes[len_raw - 8];
        CacheEntry16& ce =
            cache16[((raw0 ^ (raw1 * 0x9E3779B97F4A7C15ull)) *
                     0xC2B2AE3D27D4EB4Full) >> (64 - kRawCacheBits)];
        if (ce.id >= 0 && ce.tag0 == raw0 && ce.tag1 == raw1) {
          id = ce.id;
        } else {
          const int wlen =
              CleanTokenChunked(m, data, data_len, a, b, word);
          if (wlen == 0) continue;  // cleaned to nothing (main.c:113)
          id = st.Upsert(word, wlen, HashWord(word, wlen));
          ce.tag0 = raw0;
          ce.tag1 = raw1;
          ce.id = id;
        }
      } else {  // long or buffer-tail token: chunked pext, uncached
        const int wlen = CleanTokenChunked(m, data, data_len, a, b, word);
        if (wlen == 0) continue;
        id = st.Upsert(word, wlen, HashWord(word, wlen));
      }
      ++st.raw_tokens;
      if (dedup) {
        StreamState::TermState& ts = st.combiner[id];
        if (ts.last_doc == ordinal) {
          emit_dup(id);
          continue;
        }
        ts.last_doc = ordinal;
        ++ts.df;
      }
      ++st.num_pairs;
      emit(id, doc_id);
    }
    pos = end;
  }
}

const bool kHaveSimdScan =
    __builtin_cpu_supports("avx2") && __builtin_cpu_supports("bmi2");

#endif  // __x86_64__

template <typename Emit, typename EmitDup>
void ScanChunk(StreamState& st, const uint8_t* data, int64_t data_len,
               int64_t start_pos, const int64_t* doc_ends,
               const int32_t* doc_id_values, int32_t doc_lo, int32_t doc_hi,
               bool dedup, Emit&& emit, EmitDup&& emit_dup) {
  if (doc_lo >= doc_hi) return;
#if defined(__x86_64__)
  if (kHaveSimdScan) {
    ScanChunkSimd(st, data, data_len, start_pos, doc_ends, doc_id_values,
                  doc_lo, doc_hi, dedup, emit, emit_dup);
    return;
  }
#endif
  (void)data_len;
  ScanChunkScalar(st, data, start_pos, doc_ends, doc_id_values, doc_lo,
                  doc_hi, dedup, emit, emit_dup);
}

// Callers that only need first (term, doc) occurrences drop duplicate
// tokens on the floor.
template <typename Emit>
void ScanChunk(StreamState& st, const uint8_t* data, int64_t data_len,
               int64_t start_pos, const int64_t* doc_ends,
               const int32_t* doc_id_values, int32_t doc_lo, int32_t doc_hi,
               bool dedup, Emit&& emit) {
  ScanChunk(st, data, data_len, start_pos, doc_ends, doc_id_values, doc_lo,
            doc_hi, dedup, emit, [](int32_t) {});
}

// Sorted-vocab order of provisional ids (== strcmp order: letters only).
// Big-endian u64 prefix keys resolve almost every comparison with one
// integer compare (arena words are zero-padded, and 0x00 < any letter,
// so shorter-prefix words sort first automatically); only words sharing
// a full 8-byte prefix fall through to the block loop.
std::vector<int32_t> SortedOrder(const StreamState& st) {
  const uint8_t* base = st.arena.data();
  std::vector<std::pair<uint64_t, int32_t>> keyed(st.next_id);
  for (int32_t i = 0; i < st.next_id; ++i)
    keyed[i] = {__builtin_bswap64(Load64(base + st.word_offsets[i])), i};
  std::sort(keyed.begin(), keyed.end(),
            [&](const std::pair<uint64_t, int32_t>& a,
                const std::pair<uint64_t, int32_t>& b) {
              if (a.first != b.first) return a.first < b.first;
              const int32_t ia = a.second, ib = b.second;
              const uint8_t* pa = base + st.word_offsets[ia];
              const uint8_t* pb = base + st.word_offsets[ib];
              const uint32_t pla = (st.word_lens[ia] + 7) & ~7u;
              const uint32_t plb = (st.word_lens[ib] + 7) & ~7u;
              const uint32_t lim = pla > plb ? pla : plb;
              for (uint32_t i = 8; i < lim; i += 8) {
                const uint64_t ka =
                    i < pla ? __builtin_bswap64(Load64(pa + i)) : 0;
                const uint64_t kb =
                    i < plb ? __builtin_bswap64(Load64(pb + i)) : 0;
                if (ka != kb) return ka < kb;
              }
              return false;  // identical words cannot occur (unique vocab)
            });
  std::vector<int32_t> order(st.next_id);
  for (int32_t i = 0; i < st.next_id; ++i) order[i] = keyed[i].second;
  return order;
}

// ---------------------------------------------------------------------------
// Fork-join map phase: contiguous byte-balanced doc ranges, one worker
// per range, merged in range order (the reference's scheduler,
// main.c:307-323, made total: every doc lands in exactly one range and
// num_threads > num_docs yields empty tail ranges, not UB).
// ---------------------------------------------------------------------------

struct Worker {
  StreamState local;              // thread-local vocab + combiner + df
  std::vector<int32_t> l2g;       // local prov id -> global prov id
  std::vector<int32_t> pair_lids; // current window's emissions
  std::vector<int32_t> pair_docs;
  int64_t raw_in_window = 0;
};

// Cut points: ranges[t] = first doc of worker t (ranges[T] = num_docs).
std::vector<int32_t> PlanRanges(const int64_t* doc_ends, int32_t num_docs,
                                int32_t num_threads) {
  std::vector<int32_t> cuts(num_threads + 1, num_docs);
  cuts[0] = 0;
  const int64_t total = num_docs ? doc_ends[num_docs - 1] : 0;
  int32_t d = 0;
  for (int32_t t = 1; t < num_threads; ++t) {
    const int64_t target = total * t / num_threads;
    while (d < num_docs && (d ? doc_ends[d - 1] : 0) < target) ++d;
    cuts[t] = d;
  }
  return cuts;
}

// Run `fn(t)` for t in [0, T) on T-1 spawned threads + the caller.
// Exceptions inside a worker (bad_alloc on arena/vector growth) are
// captured and rethrown after the join instead of std::terminate-ing
// the process; a failed thread spawn degrades to running that worker
// inline.  Keeps the extern "C" NULL/-2-on-OOM contract intact for
// every thread count.
template <typename Fn>
void ForkJoin(int32_t T, Fn&& fn) {
  if (T == 1) {
    fn(0);
    return;
  }
  std::vector<std::thread> threads;
  std::vector<std::exception_ptr> errs(T);
  threads.reserve(T - 1);
  auto guarded = [&](int32_t t) {
    try {
      fn(t);
    } catch (...) {
      errs[t] = std::current_exception();
    }
  };
  for (int32_t t = 1; t < T; ++t) {
    try {
      threads.emplace_back(guarded, t);
    } catch (const std::system_error&) {
      guarded(t);  // cannot spawn: run this worker's range inline
    }
  }
  guarded(0);
  for (auto& th : threads) th.join();
  for (auto& e : errs)
    if (e) std::rethrow_exception(e);
}

// Scan one window with `workers.size()` threads; each worker appends
// this window's (local_id, doc) pairs to its pair vectors and tracks
// its raw-token delta.  Single-threaded (workers.size() == 1) runs
// inline — no thread spawn.
void ParallelScan(std::vector<Worker>& workers, const uint8_t* data,
                  int64_t data_len, const int64_t* doc_ends,
                  const int32_t* doc_id_values, int32_t num_docs, bool dedup) {
  const int32_t T = static_cast<int32_t>(workers.size());
  const std::vector<int32_t> cuts = PlanRanges(doc_ends, num_docs, T);
  ForkJoin(T, [&](int32_t t) {
    Worker& w = workers[t];
    const int64_t raw0 = w.local.raw_tokens;
    const int32_t lo = cuts[t], hi = cuts[t + 1];
    const int64_t start_pos = lo ? doc_ends[lo - 1] : 0;
    w.pair_lids.clear();
    w.pair_docs.clear();
    ScanChunk(w.local, data, data_len, start_pos, doc_ends, doc_id_values,
              lo, hi, dedup, [&](int32_t id, int32_t doc) {
                w.pair_lids.push_back(id);
                w.pair_docs.push_back(doc);
              });
    w.raw_in_window = w.local.raw_tokens - raw0;
  });
}

// Extend each worker's local->global map with the words it saw for the
// first time this window.  Vocab-scale, sequential, in range order —
// this is the only cross-thread step, the analogue of the reference's
// join barrier (main.c:367-369).
void MergeVocabs(StreamState& global, std::vector<Worker>& workers) {
  for (Worker& w : workers) {
    const uint8_t* base = w.local.arena.data();
    for (int32_t lid = static_cast<int32_t>(w.l2g.size());
         lid < w.local.next_id; ++lid) {
      const uint8_t* word = base + w.local.word_offsets[lid];
      const uint32_t len = w.local.word_lens[lid];
      // worker arenas are zero-padded, so block loads stay canonical
      w.l2g.push_back(global.Upsert(word, len, HashWord(word, len)));
    }
  }
}

// Single-threaded fast path: the lone worker's local state IS the
// global vocab — extend l2g with the identity instead of re-hashing
// every word into a second table.  Returns the vocab-authoritative
// state for any thread count.
StreamState& ResolveVocab(StreamState& global, std::vector<Worker>& workers) {
  if (workers.size() == 1) {
    Worker& w = workers[0];
    for (int32_t lid = static_cast<int32_t>(w.l2g.size());
         lid < w.local.next_id; ++lid)
      w.l2g.push_back(lid);
    return w.local;
  }
  MergeVocabs(global, workers);
  return global;
}

// Fold the workers' combiner df counts (local prov space) into a
// zeroed global-prov-space buffer.  Correct because each document is
// scanned by exactly one worker, so per-(term, doc) dedup is complete
// thread-locally.  THE one fold — finalize's GlobalDf and the
// mid-stream mri_stream_df_snapshot must agree bit for bit (the
// overlap plan diffs snapshots against finalize's totals).
void FoldWorkerDf(const std::vector<Worker>& workers, int32_t* out) {
  for (const Worker& w : workers)
    for (int32_t lid = 0; lid < w.local.next_id; ++lid)
      out[w.l2g[lid]] += w.local.combiner[lid].df;
}

std::vector<int32_t> GlobalDf(const StreamState& global,
                              const std::vector<Worker>& workers) {
  std::vector<int32_t> df(std::max(global.next_id, 1), 0);
  FoldWorkerDf(workers, df.data());
  return df;
}

}  // namespace

extern "C" {

struct TokenizeResult {
  int64_t num_tokens;   // emitted pairs (== raw tokens unless dedup_pairs)
  int64_t raw_tokens;   // tokens scanned before the combiner
  int32_t vocab_size;
  int32_t vocab_width;
  int32_t* term_ids;        // [num_tokens], sorted-vocab ids
  int32_t* doc_ids;         // [num_tokens]
  uint8_t* vocab_packed;    // [vocab_size * vocab_width], NUL padded, sorted
  int32_t* letter_of_term;  // [vocab_size]
};

// data: concatenated document bytes; doc_ends[i] = exclusive end offset of
// doc i; doc_id_values[i] = its (1-based) doc id.  dedup_pairs != 0
// enables the combiner (shrinks the device feed ~4x on real text).
// num_threads >= 1 scans byte-balanced contiguous doc ranges in
// parallel; output arrays are identical for every thread count (pairs
// stay in document order, term ids are sorted-vocab ranks).
// Returns NULL on OOM.
TokenizeResult* mri_tokenize(const uint8_t* data, int64_t len,
                             const int64_t* doc_ends,
                             const int32_t* doc_id_values, int32_t num_docs,
                             int32_t dedup_pairs, int32_t num_threads) try {
  StreamState global;
  std::vector<Worker> workers(std::max(num_threads, 1));
  ParallelScan(workers, data, len, doc_ends, doc_id_values, num_docs,
               dedup_pairs != 0);
  StreamState& vst = ResolveVocab(global, workers);

  const int32_t vocab = vst.next_id;
  const std::vector<int32_t> order = SortedOrder(vst);
  int32_t width = 1;
  for (int32_t i = 0; i < vocab; ++i)
    width = std::max(width, static_cast<int32_t>(vst.word_lens[i]));

  auto* res = static_cast<TokenizeResult*>(std::malloc(sizeof(TokenizeResult)));
  if (!res) return nullptr;
  int64_t n = 0, raw = 0;
  for (const Worker& w : workers) {
    n += static_cast<int64_t>(w.pair_lids.size());
    raw += w.local.raw_tokens;
  }
  res->num_tokens = n;
  res->raw_tokens = raw;
  res->vocab_size = vocab;
  res->vocab_width = width;
  res->term_ids = static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max<int64_t>(n, 1)));
  res->doc_ids = static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max<int64_t>(n, 1)));
  res->vocab_packed = static_cast<uint8_t*>(
      std::calloc(std::max<int64_t>(static_cast<int64_t>(vocab) * width, 1), 1));
  res->letter_of_term = static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max(vocab, 1)));
  if (!res->term_ids || !res->doc_ids || !res->vocab_packed || !res->letter_of_term) {
    std::free(res->term_ids); std::free(res->doc_ids);
    std::free(res->vocab_packed); std::free(res->letter_of_term); std::free(res);
    return nullptr;
  }

  // provisional id -> sorted id remap; pack vocab rows
  std::vector<int32_t> remap(vocab);
  for (int32_t rank = 0; rank < vocab; ++rank) {
    const int32_t prov = order[rank];
    remap[prov] = rank;
    std::memcpy(res->vocab_packed + static_cast<int64_t>(rank) * width,
                vst.arena.data() + vst.word_offsets[prov],
                vst.word_lens[prov]);
    res->letter_of_term[rank] = res->vocab_packed[static_cast<int64_t>(rank) * width] - 'a';
  }
  int64_t i = 0;
  for (const Worker& w : workers)
    for (size_t k = 0; k < w.pair_lids.size(); ++k, ++i) {
      res->term_ids[i] = remap[w.l2g[w.pair_lids[k]]];
      res->doc_ids[i] = w.pair_docs[k];
    }
  return res;
} catch (const std::bad_alloc&) {
  return nullptr;
}

void mri_free_result(TokenizeResult* r) {
  if (!r) return;
  std::free(r->term_ids);
  std::free(r->doc_ids);
  std::free(r->vocab_packed);
  std::free(r->letter_of_term);
  std::free(r);
}

// ---------------------------------------------------------------------------
// Streaming frontend: per-chunk packed provisional keys.
//
// The device engine's pipelined path (ops/engine.sort_prov_chunks)
// sorts `prov_id * stride + doc_id` keys — no final-vocab knowledge —
// so each chunk's keys can start their host->device DMA while the next
// chunk tokenizes.  stride = max_doc_id + 2 (doc ids < stride - 1 and
// INT32_MAX padding stays strictly above every valid key).
//
// With num_threads > 1 the prov ids are assigned at the per-window
// merge (vocab-scale) instead of per token, so the numbering can
// differ from the single-threaded scan — everything downstream is
// invariant to prov numbering (the device sorts keys; emit indirects
// through the prov->rank remap).
// ---------------------------------------------------------------------------

struct StreamChunkResult {
  int64_t num_pairs;   // -1 = packed key would overflow int32 (caller
                       // falls back to the one-shot engine path)
  int64_t raw_tokens;  // this chunk's raw token count
  int32_t* keys;       // [num_pairs] packed prov*stride + doc, combiner-deduped
};

struct StreamFinalResult {
  int32_t vocab_size;
  int32_t vocab_width;
  int64_t raw_tokens;       // whole stream
  int64_t num_pairs;        // whole stream (post-combiner)
  uint8_t* vocab_packed;    // [vocab_size * width], sorted, NUL padded
  int32_t* letter_of_term;  // [vocab_size], rank space
  int32_t* remap;           // [vocab_size], prov id -> sorted rank
  int32_t* df;              // [vocab_size], prov space (combiner counts)
  int32_t* emit_order;      // [vocab_size], ranks in emit order:
                            // (letter, -df, word) per main.c:55-64
};

struct StreamHandle {
  StreamState global;
  std::vector<Worker> workers;  // empty when single-threaded
  int64_t stride = 0;
  bool key_overflow = false;
};

// num_threads > 1: byte-balanced contiguous doc ranges per feed window.
void* mri_stream_new_mt(int64_t stride, int32_t num_threads) {
  auto* h = new (std::nothrow) StreamHandle();
  if (!h) return nullptr;
  h->stride = stride;
  if (num_threads > 1) {
    try {
      h->workers.resize(num_threads);
    } catch (const std::bad_alloc&) {
      delete h;
      return nullptr;
    }
  }
  return h;
}

void mri_stream_free(void* handle) {
  delete static_cast<StreamHandle*>(handle);
}

StreamChunkResult* mri_stream_feed(void* handle, const uint8_t* data,
                                   int64_t len, const int64_t* doc_ends,
                                   const int32_t* doc_id_values,
                                   int32_t num_docs) try {
  auto& h = *static_cast<StreamHandle*>(handle);
  auto* res =
      static_cast<StreamChunkResult*>(std::malloc(sizeof(StreamChunkResult)));
  if (!res) return nullptr;
  std::vector<int32_t> keys;
  const int64_t stride = h.stride;

  if (h.workers.empty()) {  // single-threaded: scan straight into global
    keys.reserve(len / 24 + 16);
    const int64_t raw_before = h.global.raw_tokens;
    ScanChunk(h.global, data, len, 0, doc_ends, doc_id_values, 0, num_docs,
              /*dedup=*/true, [&](int32_t id, int32_t doc) {
                const int64_t key = static_cast<int64_t>(id) * stride + doc;
                if (key >= INT32_MAX) {  // INT32_MAX itself is the pad value
                  h.key_overflow = true;
                  return;
                }
                keys.push_back(static_cast<int32_t>(key));
              });
    res->raw_tokens = h.global.raw_tokens - raw_before;
  } else {  // fork-join scan + vocab-scale merge, then vectorized remap
    ParallelScan(h.workers, data, len, doc_ends, doc_id_values, num_docs,
                 /*dedup=*/true);
    MergeVocabs(h.global, h.workers);
    int64_t n = 0, raw = 0;
    for (const Worker& w : h.workers) {
      n += static_cast<int64_t>(w.pair_lids.size());
      raw += w.raw_in_window;
    }
    res->raw_tokens = raw;
    keys.reserve(n);
    for (const Worker& w : h.workers)
      for (size_t k = 0; k < w.pair_lids.size(); ++k) {
        const int64_t key =
            static_cast<int64_t>(w.l2g[w.pair_lids[k]]) * stride +
            w.pair_docs[k];
        if (key >= INT32_MAX) {
          h.key_overflow = true;
          break;
        }
        keys.push_back(static_cast<int32_t>(key));
      }
  }

  if (h.key_overflow) {
    res->num_pairs = -1;
    res->keys = nullptr;
    return res;
  }
  res->num_pairs = static_cast<int64_t>(keys.size());
  res->keys = static_cast<int32_t*>(
      std::malloc(sizeof(int32_t) * std::max<size_t>(keys.size(), 1)));
  if (!res->keys) {
    std::free(res);
    return nullptr;
  }
  std::memcpy(res->keys, keys.data(), sizeof(int32_t) * keys.size());
  return res;
} catch (const std::bad_alloc&) {
  return nullptr;
}

void mri_stream_chunk_free(StreamChunkResult* r) {
  if (!r) return;
  std::free(r->keys);
  std::free(r);
}

// Device-feed variant for the windowed overlap plan: returns the
// half-bandwidth ``[terms | docs]`` uint16 upload buffer directly
// (0xFFFF padding, each half ``padded`` long with ``padded`` the pair
// count rounded up to ``granule``) — no host-side divmod/pack pass.
// Falls back to packed int32 keys (``keys`` non-null, ``feed_u16``
// null) when a provisional id outgrows uint16; ``num_pairs`` = -1
// signals int32 key overflow (same contract as mri_stream_feed).
struct StreamChunkU16Result {
  int64_t num_pairs;
  int64_t raw_tokens;
  int64_t padded;       // half-length of feed_u16 (0 in keys mode)
  uint16_t* feed_u16;   // [2 * padded] or NULL
  int32_t* keys;        // [num_pairs] or NULL
};

StreamChunkU16Result* mri_stream_feed_u16(void* handle, const uint8_t* data,
                                          int64_t len,
                                          const int64_t* doc_ends,
                                          const int32_t* doc_id_values,
                                          int32_t num_docs,
                                          int64_t granule) try {
  auto& h = *static_cast<StreamHandle*>(handle);
  auto* res = static_cast<StreamChunkU16Result*>(
      std::malloc(sizeof(StreamChunkU16Result)));
  if (!res) return nullptr;
  res->feed_u16 = nullptr;
  res->keys = nullptr;
  res->padded = 0;
  const int64_t stride = h.stride;
  std::vector<int32_t> ids;
  std::vector<int32_t> docs;

  if (h.workers.empty()) {  // single-threaded: scan straight into global
    ids.reserve(len / 24 + 16);
    docs.reserve(len / 24 + 16);
    const int64_t raw_before = h.global.raw_tokens;
    ScanChunk(h.global, data, len, 0, doc_ends, doc_id_values, 0, num_docs,
              /*dedup=*/true, [&](int32_t id, int32_t doc) {
                ids.push_back(id);
                docs.push_back(doc);
              });
    res->raw_tokens = h.global.raw_tokens - raw_before;
  } else {  // fork-join scan + vocab-scale merge, then remap
    ParallelScan(h.workers, data, len, doc_ends, doc_id_values, num_docs,
                 /*dedup=*/true);
    MergeVocabs(h.global, h.workers);
    int64_t n = 0, raw = 0;
    for (const Worker& w : h.workers) {
      n += static_cast<int64_t>(w.pair_lids.size());
      raw += w.raw_in_window;
    }
    res->raw_tokens = raw;
    ids.reserve(n);
    docs.reserve(n);
    for (const Worker& w : h.workers)
      for (size_t k = 0; k < w.pair_lids.size(); ++k) {
        ids.push_back(w.l2g[w.pair_lids[k]]);
        docs.push_back(w.pair_docs[k]);
      }
  }

  const int64_t n = static_cast<int64_t>(ids.size());
  res->num_pairs = n;
  // prov ids are first-occurrence ranks, so the global high-water mark
  // bounds every id in this window.  u16 mode also requires the packed
  // key the DEVICE reconstructs (id * stride + doc, int32) to fit —
  // otherwise fall through to the int32 branch, whose per-key check
  // raises the KeyOverflow contract instead of wrapping on device.
  const bool fits_u16 =
      h.global.next_id <= 0xFFFF &&
      static_cast<int64_t>(h.global.next_id - 1) * stride + (stride - 1) <
          INT32_MAX;
  if (fits_u16) {
    const int64_t g = granule > 0 ? granule : 1;
    const int64_t padded = n ? ((n + g - 1) / g) * g : 0;
    res->padded = padded;
    if (padded) {
      res->feed_u16 = static_cast<uint16_t*>(
          std::malloc(sizeof(uint16_t) * 2 * padded));
      if (!res->feed_u16) {
        std::free(res);
        return nullptr;
      }
      for (int64_t k = 0; k < n; ++k) {
        res->feed_u16[k] = static_cast<uint16_t>(ids[k]);
        res->feed_u16[padded + k] = static_cast<uint16_t>(docs[k]);
      }
      for (int64_t k = n; k < padded; ++k)
        res->feed_u16[k] = res->feed_u16[padded + k] = 0xFFFF;
    }
    return res;
  }
  // prov ids beyond uint16: fall back to packed int32 keys
  res->keys = static_cast<int32_t*>(
      std::malloc(sizeof(int32_t) * std::max<int64_t>(n, 1)));
  if (!res->keys) {
    std::free(res);
    return nullptr;
  }
  for (int64_t k = 0; k < n; ++k) {
    const int64_t key = static_cast<int64_t>(ids[k]) * stride + docs[k];
    if (key >= INT32_MAX) {
      h.key_overflow = true;
      res->num_pairs = -1;
      return res;
    }
    res->keys[k] = static_cast<int32_t>(key);
  }
  return res;
} catch (const std::bad_alloc&) {
  return nullptr;
}

void mri_stream_chunk_u16_free(StreamChunkU16Result* r) {
  if (!r) return;
  std::free(r->feed_u16);
  std::free(r->keys);
  std::free(r);
}

// Current document-frequency snapshot in GLOBAL provisional-id space
// (the combiner's deduped per-(term, doc) counts so far).  Lets the
// windowed overlap plan derive per-window per-term pair counts as
// vocab-scale snapshot diffs instead of token-scale bincounts.  In MT
// mode folds the workers' thread-local counts (each document is
// scanned by exactly one worker, so the fold is exact; l2g is extended
// every feed).  Returns the term count written, or -needed when the
// caller's buffer is too small (call again with >= needed slots).
int32_t mri_stream_df_snapshot(void* handle, int32_t* out, int32_t cap) {
  auto& h = *static_cast<StreamHandle*>(handle);
  const int32_t n = h.global.next_id;
  if (n > cap) return -n;
  std::memset(out, 0, static_cast<size_t>(n) * sizeof(int32_t));
  if (h.workers.empty()) {
    for (int32_t i = 0; i < n; ++i) out[i] = h.global.combiner[i].df;
  } else {
    FoldWorkerDf(h.workers, out);
  }
  return n;
}

void mri_stream_final_free(StreamFinalResult* r);

StreamFinalResult* mri_stream_finalize(void* handle) try {
  auto& h = *static_cast<StreamHandle*>(handle);
  StreamState& st = h.global;
  const int32_t vocab = st.next_id;
  const std::vector<int32_t> order = SortedOrder(st);
  int32_t width = 1;
  for (int32_t i = 0; i < vocab; ++i)
    width = std::max(width, static_cast<int32_t>(st.word_lens[i]));

  // Stream totals + prov-space df: from the global state when
  // single-threaded, folded from the workers otherwise.
  int64_t raw_tokens, num_pairs;
  std::vector<int32_t> df_mt;
  const int32_t* df_src;
  if (h.workers.empty()) {
    raw_tokens = st.raw_tokens;
    num_pairs = st.num_pairs;
    df_mt.resize(std::max(vocab, 1));
    for (int32_t i = 0; i < vocab; ++i) df_mt[i] = st.combiner[i].df;
    df_src = df_mt.data();
  } else {
    raw_tokens = num_pairs = 0;
    for (const Worker& w : h.workers) {
      raw_tokens += w.local.raw_tokens;
      num_pairs += w.local.num_pairs;
    }
    df_mt = GlobalDf(st, h.workers);
    df_src = df_mt.data();
  }

  auto* res =
      static_cast<StreamFinalResult*>(std::malloc(sizeof(StreamFinalResult)));
  if (!res) return nullptr;
  res->vocab_size = vocab;
  res->vocab_width = width;
  res->raw_tokens = raw_tokens;
  res->num_pairs = num_pairs;
  res->vocab_packed = static_cast<uint8_t*>(
      std::calloc(std::max<int64_t>(static_cast<int64_t>(vocab) * width, 1), 1));
  res->letter_of_term =
      static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max(vocab, 1)));
  res->remap =
      static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max(vocab, 1)));
  res->df =
      static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max(vocab, 1)));
  res->emit_order =
      static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max(vocab, 1)));
  if (!res->vocab_packed || !res->letter_of_term || !res->remap || !res->df ||
      !res->emit_order) {
    std::free(res->vocab_packed); std::free(res->letter_of_term);
    std::free(res->remap); std::free(res->df); std::free(res->emit_order);
    std::free(res);
    return nullptr;
  }
  for (int32_t rank = 0; rank < vocab; ++rank) {
    const int32_t prov = order[rank];
    res->remap[prov] = rank;
    std::memcpy(res->vocab_packed + static_cast<int64_t>(rank) * width,
                st.arena.data() + st.word_offsets[prov], st.word_lens[prov]);
    res->letter_of_term[rank] =
        res->vocab_packed[static_cast<int64_t>(rank) * width] - 'a';
  }
  if (vocab) std::memcpy(res->df, df_src, sizeof(int32_t) * vocab);
  // Emit order (the reducer's per-letter by-df ordering, main.c:55-64):
  // ranks are word-sorted, so first letters are nondecreasing — one
  // stable by-df-descending sort per letter block, ties falling back
  // to rank ascending == word ascending.  Saves the emit path a
  // vocab-scale np.lexsort per run.  The vector and stable_sort can
  // throw bad_alloc AFTER res's arrays exist, so free them on the way
  // out instead of letting the function-level catch leak them.
  try {
    std::vector<int32_t> df_rank(std::max(vocab, 1));
    for (int32_t rank = 0; rank < vocab; ++rank)
      df_rank[rank] = df_src[order[rank]];
    for (int32_t rank = 0; rank < vocab; ++rank) res->emit_order[rank] = rank;
    int32_t b = 0;
    while (b < vocab) {
      const int32_t letter = res->letter_of_term[b];
      int32_t e = b;
      while (e < vocab && res->letter_of_term[e] == letter) ++e;
      std::stable_sort(res->emit_order + b, res->emit_order + e,
                       [&](int32_t a, int32_t c) {
                         return df_rank[a] > df_rank[c];
                       });
      b = e;
    }
  } catch (const std::bad_alloc&) {
    mri_stream_final_free(res);
    return nullptr;
  }
  return res;
} catch (const std::bad_alloc&) {
  return nullptr;
}

void mri_stream_final_free(StreamFinalResult* r) {
  if (!r) return;
  std::free(r->vocab_packed);
  std::free(r->letter_of_term);
  std::free(r->remap);
  std::free(r->df);
  std::free(r->emit_order);
  std::free(r);
}

// Host-exact (token_count, max_cleaned_len) over one byte window — the
// all-device engines' stats guard (ops/device_tokenizer.
// host_token_stats): token boundaries per the device classifier
// (whitespace set main.c:102-104, tokens never span documents), length
// = letters only (main.c:105-111).  Counts EVERY token start including
// letterless tokens ("42"): the count must equal the device program's
// token_start sum.  Returns 0, or -1 on bad args.
int32_t mri_token_stats(const uint8_t* data, int64_t len,
                        const int64_t* doc_ends, int32_t num_docs,
                        int64_t* count_out, int32_t* max_len_out) try {
  if (num_docs < 0 || len < 0) return -1;
  for (int32_t d = 0; d < num_docs; ++d) {  // honor the bad-args contract:
    // a regressing or negative end would double-scan / read out of bounds
    if (doc_ends[d] < 0 || (d && doc_ends[d] < doc_ends[d - 1])) return -1;
  }
  int64_t count = 0;
  int64_t max_len = 0;
  // Token breaks happen at INNER doc ends only; the scan runs to the
  // end of the buffer, exactly like the device classifier (doc_starts
  // uses doc_ends[:-1]) and the numpy mirror — bytes past the last
  // doc's end still tokenize (callers pad with spaces).
  const int32_t spans = std::max(num_docs, 1);
  auto span_end = [&](int32_t d) -> int64_t {
    return d >= num_docs - 1 ? len : std::min<int64_t>(doc_ends[d], len);
  };
#if defined(__x86_64__)
  if (kHaveSimdScan && len > 0) {
    MaskSpan m;
    BuildMasks(data, len, 0, len, m);
    int64_t pos = 0;
    for (int32_t d = 0; d < spans; ++d) {
      const int64_t end = span_end(d);
      while (pos < end) {
        const int64_t a = NextSet(m.T, m.base, pos, end);
        if (a >= end) break;
        const int64_t b = NextSet(m.S, m.base, a, end);
        pos = b;
        ++count;
        int64_t letters = 0;
        for (int64_t p = a; p < b; p += 64) {
          uint64_t bits = ExtractBits(m.L, m.base, p);
          const int64_t take = b - p;
          if (take < 64) bits &= (1ull << take) - 1;
          letters += __builtin_popcountll(bits);
        }
        max_len = std::max(max_len, letters);
      }
      pos = end;
    }
    *count_out = count;
    *max_len_out = static_cast<int32_t>(max_len);
    return 0;
  }
#endif
  int64_t pos = 0;
  for (int32_t d = 0; d < spans; ++d) {
    const int64_t end = span_end(d);
    bool in_tok = false;
    int64_t letters = 0;
    for (; pos < end; ++pos) {
      if (kTab.space[data[pos]]) {
        if (in_tok) max_len = std::max(max_len, letters);
        in_tok = false;
        letters = 0;
        continue;
      }
      if (!in_tok) {
        in_tok = true;
        letters = 0;
        ++count;
      }
      if (kTab.lower[data[pos]]) ++letters;
    }
    if (in_tok) max_len = std::max(max_len, letters);
    pos = end;
  }
  *count_out = count;
  *max_len_out = static_cast<int32_t>(max_len);
  return 0;
} catch (const std::bad_alloc&) {
  return -1;
}

// ---------------------------------------------------------------------------
// Native emit: render the 26 <letter>.txt postings files.
//
// Byte-identical to the reference's fprintf loop (main.c:227-234):
// "word:[id1 id2 ... idN]\n", ids space separated, no trailing space.
// Terms arrive pre-ordered (order[]); letters are contiguous in that
// order because term ids follow sorted-vocab order.
// ---------------------------------------------------------------------------

namespace {

// Two digits per division: doc-id formatting is the emit loop's hot
// op (~12 ns/id with a per-digit division chain, measured; ~half with
// the pair table).
struct DigitPairs {
  char d[200];
  DigitPairs() {
    for (int i = 0; i < 100; ++i) {
      d[2 * i] = static_cast<char>('0' + i / 10);
      d[2 * i + 1] = static_cast<char>('0' + i % 10);
    }
  }
};
const DigitPairs kD2;

inline char* PutU32(char* p, uint32_t v) {
  char tmp[10];
  char* e = tmp + 10;
  while (v >= 100) {
    const uint32_t r = v % 100;
    v /= 100;
    e -= 2;
    std::memcpy(e, kD2.d + 2 * r, 2);
  }
  if (v >= 10) {
    e -= 2;
    std::memcpy(e, kD2.d + 2 * v, 2);
  } else {
    *--e = static_cast<char>('0' + v);
  }
  const size_t n = static_cast<size_t>(tmp + 10 - e);
  std::memcpy(p, e, n);
  return p + n;
}

// One postings run: a flat doc-id array (uint16 or int32 — exactly one
// base non-null) with rank-space offsets/counts.  A term's full postings
// list is the concatenation of its segments across runs in run order —
// the windowed overlap plan's per-window device fetches plus the host
// tail are contiguous ascending doc ranges, so no merge pass is needed
// (the reference re-derives this grouping by re-reading spill text,
// main.c:170-212).
struct EmitRun {
  const uint16_t* p16;
  const int32_t* p32;
  const int64_t* offsets;  // rank space
  const int64_t* counts;   // rank space
};

// Pre-rendered doc-id strings: ids repeat constantly across postings
// lists, and the per-digit division chain in PutU32 is the emit loop's
// hot op — one fixed 8-byte copy per posting halves it.  `s` holds the
// digits left-justified; `len` the digit count (<= 7 under kIdTableMax).
struct IdStr {
  char s[7];
  uint8_t len;
};
// Table ceiling: 1 << 17 entries = 1 MB, still cache/TLB-friendly;
// larger id spaces fall back to PutU32 per posting.
constexpr uint32_t kIdTableMax = 1u << 17;

// Largest doc id across every run segment (full pass — postings are
// ascending per term on every current caller, but a bounds-critical
// table must not trust that).  Returns kIdTableMax early when the ids
// outgrow the table.
uint32_t MaxDocId(const EmitRun* runs, int32_t n_runs, int32_t vocab_size) {
  uint32_t maxid = 0;
  for (int32_t r = 0; r < n_runs; ++r) {
    const EmitRun& run = runs[r];
    for (int32_t t = 0; t < vocab_size; ++t) {
      const int64_t start = run.offsets[t], n = run.counts[t];
      for (int64_t k = 0; k < n; ++k) {
        const uint32_t v = run.p16 ? run.p16[start + k]
                                   : static_cast<uint32_t>(run.p32[start + k]);
        if (v > maxid) {
          maxid = v;
          if (maxid >= kIdTableMax) return kIdTableMax;
        }
      }
    }
  }
  return maxid;
}

// Shared emit core: one letter-file set from rank-space order and
// `n_runs` postings runs, concatenated per term in run order.
//
// Writes are ATOMIC per letter file: each file is rendered fully in
// memory, written to `<letter>.txt.tmp`, then renamed over the final
// name — a crash mid-emit leaves earlier letters complete, the
// in-flight letter only as a `.tmp`, and never a truncated-but-
// plausible `<letter>.txt` (the reference's partial_<letter>.txt spill
// files have the same never-half-a-file property, main.c:332-341).
//
// `letter_lo`/`letter_hi` + the matching `idx_start`/`idx_end` order
// slice restrict the call to a contiguous letter range (the parallel
// reduce's per-reducer partition, main.c:129-130): only files
// `letter_lo..letter_hi-1` are written, and buffer sizing covers the
// slice, not the whole vocab, so M reducers never over-allocate M-fold.
// Defaults preserve the historical whole-alphabet behavior.
int64_t EmitLettersRuns(const uint8_t* vocab_packed, int32_t vocab_size,
                        int32_t width, const int64_t* order,
                        const EmitRun* runs, int32_t n_runs,
                        const char* out_dir,
                        const uint32_t* lens = nullptr,
                        int64_t maxid_hint = -1,
                        int32_t letter_lo = 0, int32_t letter_hi = 26,
                        int64_t idx_start = 0, int64_t idx_end = -1) {
  std::string dir(out_dir);
  if (!dir.empty() && dir.back() != '/') dir += '/';
  if (idx_end < 0) idx_end = vocab_size;
  if (letter_lo >= letter_hi) return 0;  // empty partition: no files owned
  // Vectorized id formatting: render each id once, copy 8 bytes per
  // posting.  The table pays for itself whenever postings outnumber
  // distinct ids (always, past trivial corpora).  Callers that track
  // the max doc id pass it as ``maxid_hint`` and skip the full pass.
  std::vector<IdStr> id_table;
  const uint32_t maxid =
      maxid_hint >= 0 ? static_cast<uint32_t>(std::min<int64_t>(
                            maxid_hint, kIdTableMax))
                      : MaxDocId(runs, n_runs, vocab_size);
  if (idx_end > idx_start && maxid < kIdTableMax) {
    id_table.resize(static_cast<size_t>(maxid) + 1);
    for (uint32_t v = 0; v <= maxid; ++v) {
      char* p = id_table[v].s;
      id_table[v].len = static_cast<uint8_t>(PutU32(p, v) - p);
    }
  }
  const IdStr* tab = id_table.empty() ? nullptr : id_table.data();
  // One upper-bound allocation for the render buffer: per-term resize
  // calls zero-fill their growth, which costs more than the formatting
  // itself.  Bound: word row + ":[]\n" per term, <= 11 bytes per
  // posting (space + 10 digits), + 8 bytes table-copy overhang slack.
  int64_t total_df = 0;
  for (int32_t r = 0; r < n_runs; ++r)
    for (int64_t i = idx_start; i < idx_end; ++i)
      total_df += runs[r].counts[order[i]];
  std::vector<char> buf(static_cast<size_t>(idx_end - idx_start) *
                            (width + 4) +
                        11ull * total_df + 8);
  int64_t total = 0;
  int64_t idx = idx_start;
  for (int letter = letter_lo; letter < letter_hi; ++letter) {
    char* p = buf.data();
    for (; idx < idx_end; ++idx) {
      const int64_t t = order[idx];
      const uint8_t* w = vocab_packed + static_cast<int64_t>(t) * width;
      if (w[0] - 'a' != letter) break;
      // word length: caller-supplied, or walk the NUL-padded row
      int wl;
      if (lens) {
        wl = static_cast<int>(lens[t]);
      } else {
        wl = 0;
        while (wl < width && w[wl]) ++wl;
      }
      std::memcpy(p, w, wl);
      // Branch-free separators: every posting renders as " id" starting
      // one byte past the ':' slot, then ':' and '[' are patched in —
      // the '[' lands exactly on the first posting's leading space.
      char* mark = p + wl;
      p = mark + 1;
      for (int32_t r = 0; r < n_runs; ++r) {
        const EmitRun& run = runs[r];
        const int64_t start = run.offsets[t], n = run.counts[t];
        if (tab) {
          for (int64_t k = 0; k < n; ++k) {
            *p++ = ' ';
            const uint32_t v = run.p16
                ? run.p16[start + k]
                : static_cast<uint32_t>(run.p32[start + k]);
            std::memcpy(p, tab[v].s, 8);  // IdStr is 8 bytes, len <= 7
            p += tab[v].len;
          }
        } else {
          for (int64_t k = 0; k < n; ++k) {
            *p++ = ' ';
            const uint32_t v = run.p16
                ? run.p16[start + k]
                : static_cast<uint32_t>(run.p32[start + k]);
            p = PutU32(p, v);
          }
        }
      }
      mark[0] = ':';
      mark[1] = '[';
      if (p == mark + 1) p = mark + 2;  // df == 0: keep the '[' written
      *p++ = ']';
      *p++ = '\n';
    }
    const size_t nbytes = p - buf.data();
    std::string path = dir;
    path += static_cast<char>('a' + letter);
    path += ".txt";
    const std::string tmp = path + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    if (nbytes && std::fwrite(buf.data(), 1, nbytes, f) != nbytes) {
      std::fclose(f);
      std::remove(tmp.c_str());
      return -1;
    }
    if (std::fclose(f) != 0 || std::rename(tmp.c_str(), path.c_str()) != 0) {
      std::remove(tmp.c_str());
      return -1;
    }
    total += static_cast<int64_t>(nbytes);
    // Crash-injection hook shared with text/formatter.py: after N
    // complete letters, die without unwinding so the durability test
    // observes exactly what a hard crash leaves on disk.
    if (const char* kill_after = std::getenv("MRI_EMIT_KILL_AFTER_LETTERS")) {
      if (letter + 1 == std::atoi(kill_after)) raise(SIGKILL);
    }
  }
  return total;
}

int64_t EmitLetters(const uint8_t* vocab_packed, int32_t vocab_size,
                    int32_t width, const int64_t* order, const int64_t* df,
                    const int64_t* offsets, const uint16_t* postings16,
                    const int32_t* postings32, const char* out_dir,
                    const uint32_t* lens = nullptr,
                    int64_t maxid_hint = -1) {
  const EmitRun run{postings16, postings32, offsets, df};
  return EmitLettersRuns(vocab_packed, vocab_size, width, order, &run, 1,
                         out_dir, lens, maxid_hint);
}

}  // namespace

// postings16/postings32: exactly one is non-null.  order/df/offsets are
// int64 (numpy's native index types).  letter_lo/letter_hi restrict
// emission to that letter range, with idx_start/idx_end the matching
// slice of `order` (full emit: 0/26/0/vocab_size) — the per-owner emit
// of the multi-host "letter" ownership mode and the parallel reduce.
// Returns total bytes written, or -1 on IO error.
int64_t mri_emit(const uint8_t* vocab_packed, int32_t vocab_size, int32_t width,
                 const int64_t* order, const int64_t* df, const int64_t* offsets,
                 const uint16_t* postings16, const int32_t* postings32,
                 const char* out_dir, int32_t letter_lo, int32_t letter_hi,
                 int64_t idx_start, int64_t idx_end) try {
  const EmitRun run{postings16, postings32, offsets, df};
  return EmitLettersRuns(vocab_packed, vocab_size, width, order, &run, 1,
                         out_dir, /*lens=*/nullptr, /*maxid_hint=*/-1,
                         letter_lo, letter_hi, idx_start, idx_end);
} catch (const std::bad_alloc&) {
  return -1;
}

// Multi-run emit for the windowed overlap plan: each term's postings are
// the concatenation of its `n_runs` segments in run order (uint16 doc
// ids; run k's segment for rank t is run_bases[k][run_offsets[k][t] ..
// + run_counts[k][t]]).  Returns total bytes written, or -1 on IO error.
int64_t mri_emit_runs(const uint8_t* vocab_packed, int32_t vocab_size,
                      int32_t width, const int64_t* order, int32_t n_runs,
                      const uint16_t* const* run_bases,
                      const int64_t* const* run_offsets,
                      const int64_t* const* run_counts,
                      const char* out_dir) try {
  std::vector<EmitRun> runs(std::max(n_runs, 1));
  for (int32_t r = 0; r < n_runs; ++r)
    runs[r] = EmitRun{run_bases[r], nullptr, run_offsets[r], run_counts[r]};
  return EmitLettersRuns(vocab_packed, vocab_size, width, order, runs.data(),
                         n_runs, out_dir);
} catch (const std::bad_alloc&) {
  return -1;
}

// ---------------------------------------------------------------------------
// Host backend: the whole pipeline in one native call (no accelerator).
//
// The reference's regime — everything on the host CPU — minus its
// pathologies (per-token stdio locks, O(T*W) reducer dict scan,
// bubble sort).  Documents are scanned once through the incremental
// core — in parallel over contiguous byte-balanced doc ranges when
// num_threads > 1 (the reference's mapper scheduling, main.c:307-328)
// — and each worker's combiner appends first (term, doc) occurrences
// to its local postings vectors, which arrive ascending for free
// because docs are scanned in manifest order (doc ids are 1-based
// manifest positions, main.c:275); ranges are contiguous and merged in
// order, so global postings stay ascending with no token-scale sort.
// ---------------------------------------------------------------------------

struct HostIndexStats {
  int64_t raw_tokens;
  int64_t num_pairs;
  int32_t vocab_size;
  int64_t bytes_written;  // -1 = IO error
};

int32_t mri_host_index(const uint8_t* data, int64_t len,
                       const int64_t* doc_ends, const int32_t* doc_id_values,
                       int32_t num_docs, const char* out_dir,
                       HostIndexStats* stats, int32_t num_threads) try {
  const int32_t T = std::max(num_threads, 1);
  const std::vector<int32_t> cuts = PlanRanges(doc_ends, num_docs, T);

  // Per-worker scan: local vocab + local postings (doc-ascending).
  struct HostWorker {
    StreamState local;
    std::vector<std::vector<int32_t>> postings;  // local prov id -> docs
    std::vector<int32_t> l2g;
  };
  std::vector<HostWorker> workers(T);
  ForkJoin(T, [&](int32_t t) {
    HostWorker& w = workers[t];
    const int32_t lo = cuts[t], hi = cuts[t + 1];
    const int64_t start_pos = lo ? doc_ends[lo - 1] : 0;
    ScanChunk(w.local, data, len, start_pos, doc_ends, doc_id_values, lo, hi,
              /*dedup=*/true, [&](int32_t id, int32_t doc) {
                if (id >= static_cast<int32_t>(w.postings.size()))
                  w.postings.resize(id + 1);
                w.postings[id].push_back(doc);
              });
  });

  // Vocab-scale merge in range order (the join barrier); with one
  // worker its local state is the global vocab (identity l2g).
  StreamState merged;
  int64_t raw_tokens = 0, num_pairs = 0;
  for (HostWorker& w : workers) {
    const uint8_t* base = w.local.arena.data();
    for (int32_t lid = 0; lid < w.local.next_id; ++lid) {
      if (T == 1) {
        w.l2g.push_back(lid);
        continue;
      }
      const uint8_t* word = base + w.local.word_offsets[lid];
      const uint32_t wl = w.local.word_lens[lid];
      w.l2g.push_back(merged.Upsert(word, wl, HashWord(word, wl)));
    }
    raw_tokens += w.local.raw_tokens;
    num_pairs += w.local.num_pairs;
  }
  StreamState& st = (T == 1) ? workers[0].local : merged;

  const int32_t vocab = st.next_id;
  // Global postings by prov id: concatenate the workers' runs in range
  // order — contiguous ranges keep every term's docs ascending.
  std::vector<int64_t> df_prov(std::max(vocab, 1), 0);
  for (const HostWorker& w : workers)
    for (size_t lid = 0; lid < w.postings.size(); ++lid)
      df_prov[w.l2g[lid]] += static_cast<int64_t>(w.postings[lid].size());
  std::vector<int64_t> offsets_prov(std::max(vocab, 1));
  int64_t total_pairs = 0;
  for (int32_t p = 0; p < vocab; ++p) {
    offsets_prov[p] = total_pairs;
    total_pairs += df_prov[p];
  }
  std::vector<int32_t> flat(std::max<int64_t>(total_pairs, 1));
  {
    std::vector<int64_t> cursor(offsets_prov.begin(), offsets_prov.end());
    for (const HostWorker& w : workers)
      for (size_t lid = 0; lid < w.postings.size(); ++lid) {
        const int32_t gid = w.l2g[lid];
        std::copy(w.postings[lid].begin(), w.postings[lid].end(),
                  flat.begin() + cursor[gid]);
        cursor[gid] += static_cast<int64_t>(w.postings[lid].size());
      }
  }

  const std::vector<int32_t> order = SortedOrder(st);
  int32_t width = 1;
  for (int32_t i = 0; i < vocab; ++i)
    width = std::max(width, static_cast<int32_t>(st.word_lens[i]));

  // Rank-space views over prov-space postings (same indirection the
  // device pipeline's host side does in models/inverted_index.py).
  std::vector<uint8_t> vocab_packed(
      std::max<int64_t>(static_cast<int64_t>(vocab) * width, 1), 0);
  std::vector<int32_t> letter_of_rank(std::max(vocab, 1));
  std::vector<int64_t> df_rank(std::max(vocab, 1));
  std::vector<int64_t> offsets_rank(std::max(vocab, 1));
  for (int32_t rank = 0; rank < vocab; ++rank) {
    const int32_t prov = order[rank];
    std::memcpy(vocab_packed.data() + static_cast<int64_t>(rank) * width,
                st.arena.data() + st.word_offsets[prov], st.word_lens[prov]);
    letter_of_rank[rank] = vocab_packed[static_cast<int64_t>(rank) * width] - 'a';
    df_rank[rank] = df_prov[prov];
    offsets_rank[rank] = offsets_prov[prov];
  }

  // Emit order: (letter asc, df desc, rank asc) — stable sort supplies
  // the rank tiebreak == word-ascending (main.c:55-64 semantics).
  std::vector<int64_t> emit_rank(vocab);
  for (int32_t i = 0; i < vocab; ++i) emit_rank[i] = i;
  std::stable_sort(emit_rank.begin(), emit_rank.end(),
                   [&](int64_t a, int64_t b) {
                     if (letter_of_rank[a] != letter_of_rank[b])
                       return letter_of_rank[a] < letter_of_rank[b];
                     return df_rank[a] > df_rank[b];
                   });

  stats->raw_tokens = raw_tokens;
  stats->num_pairs = num_pairs;
  stats->vocab_size = vocab;
  stats->bytes_written = EmitLetters(
      vocab_packed.data(), vocab, width, emit_rank.data(), df_rank.data(),
      offsets_rank.data(), nullptr, flat.data(), out_dir);
  return stats->bytes_written < 0 ? -1 : 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// ---------------------------------------------------------------------------
// Incremental host index: same pipeline as mri_host_index but fed one
// window at a time so the caller can overlap file reads with the scan
// (the ctypes layer releases the GIL for the feed call's duration).
// Single scan state — windows arrive in manifest order, so postings
// stay doc-ascending for free, exactly like the T == 1 one-shot path.
// Stage nanoseconds are accumulated so the Python side can report a
// read/tokenize/emit split without host-side clock instrumentation
// around every call.
// ---------------------------------------------------------------------------

struct HostStreamStats {
  int64_t raw_tokens;
  int64_t num_pairs;
  int32_t vocab_size;
  int32_t reserved;
  int64_t bytes_written;  // -1 = IO error
  int64_t scan_ns;        // cumulative mri_hidx_feed time
  int64_t finalize_ns;    // postings flatten + sorts
  int64_t emit_ns;        // letter-file render + write
};

struct HostStreamState {
  StreamState st;
  // First (term, doc) occurrences in scan order — term ids flat (ONE
  // push in the scan's hot loop), with the doc id recovered from
  // doc_marks: docs are scanned in order, so each mark says "pairs
  // from this index on belong to this doc" (document-count scale).
  // The finalize pass scatters by the combiner's df counts.
  std::vector<int32_t> pair_ids;
  struct DocMark { int64_t start; int32_t doc; };
  std::vector<DocMark> doc_marks;
  int32_t max_doc_id = 0;
  int64_t scan_ns = 0;
  // Within-document term frequencies for the v2 artifact's scoring
  // column: pair_tf[k] counts how often pair_ids[k]'s term occurred in
  // its document (>= 1), bumped via the scan's emit_dup callback;
  // term_last_pair maps a prov id to its latest pair index so the bump
  // is O(1).  doc_tokens records each document's cleaned token count
  // (the BM25 doc-length column) at document scale.
  std::vector<int32_t> pair_tf;
  std::vector<int64_t> term_last_pair;
  std::vector<std::pair<int32_t, int64_t>> doc_tokens;
  // Parallel-reduce partial state (mri_hidx_partial): per-term postings
  // runs, each doc-ascending regardless of window arrival order.  Once
  // built, pair_ids/doc_marks are released — a partial'd handle can no
  // longer be finalize_emit'd, only merged via mri_hidxm_new.
  std::vector<int64_t> local_off;   // local prov id -> run start (+1 end)
  std::vector<int32_t> local_flat;  // concatenated per-term doc runs
  std::vector<int32_t> local_flat_tf;  // tf aligned with local_flat
  bool partial_done = false;
  int64_t partial_ns = 0;
};

namespace {

// Emit order for one vocabulary — (letter asc, df desc, word asc) — via
// a counting pre-partition on the first letter (the bswapped prefix's
// top byte), which turns one big sort into 26 smaller ones whose
// comparator never looks at the letter again.  Ties past the 8-byte
// prefix fall back to the padded tail, which is NUL-filled so prefix
// words sort first (main.c:55-64 semantics).  `letter_off_out[l]` /
// `[l+1]` bound letter `l`'s slice of `emit_order` — the letter
// partition the parallel reduce hands to its reducer workers.
void BuildEmitOrder(const StreamState& st, const int64_t* df,
                    int64_t* emit_order, int32_t letter_off_out[27]) {
  const int32_t vocab = st.next_id;
  struct EmitKey {
    uint64_t prefix;
    int32_t df;
    int32_t id;
  };
  const uint8_t* base = st.arena.data();
  std::vector<EmitKey> keyed(std::max(vocab, 1));
  int32_t letter_count[27] = {0};
  for (int32_t i = 0; i < vocab; ++i) {
    const uint64_t prefix = __builtin_bswap64(Load64(base + st.word_offsets[i]));
    ++letter_count[(prefix >> 56) - 'a' + 1];
    keyed[i] = {prefix, static_cast<int32_t>(df[i]), i};
  }
  letter_off_out[0] = 0;
  for (int i = 1; i < 27; ++i)
    letter_off_out[i] = letter_off_out[i - 1] + letter_count[i];
  std::vector<EmitKey> part(std::max(vocab, 1));
  {
    int32_t cur[26];
    std::memcpy(cur, letter_off_out, sizeof(cur));
    for (int32_t i = 0; i < vocab; ++i)
      part[cur[(keyed[i].prefix >> 56) - 'a']++] = keyed[i];
  }
  const auto by_df_word = [&](const EmitKey& a, const EmitKey& b) {
    if (a.df != b.df) return a.df > b.df;
    if (a.prefix != b.prefix) return a.prefix < b.prefix;
    const uint8_t* pa = base + st.word_offsets[a.id];
    const uint8_t* pb = base + st.word_offsets[b.id];
    const uint32_t pla = (st.word_lens[a.id] + 7) & ~7u;
    const uint32_t plb = (st.word_lens[b.id] + 7) & ~7u;
    const uint32_t lim = pla > plb ? pla : plb;
    for (uint32_t i = 8; i < lim; i += 8) {
      const uint64_t ka = i < pla ? __builtin_bswap64(Load64(pa + i)) : 0;
      const uint64_t kb = i < plb ? __builtin_bswap64(Load64(pb + i)) : 0;
      if (ka != kb) return ka < kb;
    }
    return false;  // identical words cannot occur (unique vocab)
  };
  for (int l = 0; l < 26; ++l)
    std::sort(part.begin() + letter_off_out[l],
              part.begin() + letter_off_out[l + 1], by_df_word);
  for (int32_t i = 0; i < vocab; ++i) emit_order[i] = part[i].id;
}

// Flatten one worker's scan-order pairs into per-term doc runs
// (idempotent; runs in the worker's own thread with the GIL released).
// The steal queue can hand a worker windows in ANY order, so each run
// is sorted ascending here — a no-op is_sorted check in the common
// FIFO case — which lets the merged emit restore globally ascending
// postings with a cheap run merge instead of a token-scale sort.
void PartialFlatten(HostStreamState& h) {
  if (h.partial_done) return;
  const auto t0 = std::chrono::steady_clock::now();
  StreamState& st = h.st;
  const int32_t vocab = st.next_id;
  h.local_off.assign(static_cast<size_t>(std::max(vocab, 1)) + 1, 0);
  int64_t total = 0;
  for (int32_t p = 0; p < vocab; ++p) {
    h.local_off[p] = total;
    total += st.combiner[p].df;
  }
  h.local_off[std::max(vocab, 1)] = total;
  h.local_flat.resize(std::max<int64_t>(total, 1));
  h.local_flat_tf.resize(h.local_flat.size());
  {
    std::vector<int64_t> cursor(h.local_off.begin(), h.local_off.end() - 1);
    const size_t n_marks = h.doc_marks.size();
    for (size_t s = 0; s < n_marks; ++s) {
      const int64_t seg_end = (s + 1 < n_marks) ? h.doc_marks[s + 1].start
                                                : static_cast<int64_t>(
                                                      h.pair_ids.size());
      const int32_t doc = h.doc_marks[s].doc;
      for (int64_t k = h.doc_marks[s].start; k < seg_end; ++k) {
        const int64_t c = cursor[h.pair_ids[k]]++;
        h.local_flat[c] = doc;
        h.local_flat_tf[c] = h.pair_tf[k];
      }
    }
  }
  for (int32_t p = 0; p < vocab; ++p) {
    const int64_t b = h.local_off[p], e = h.local_off[p + 1];
    if (std::is_sorted(h.local_flat.begin() + b, h.local_flat.begin() + e))
      continue;
    // out-of-order window arrival: co-sort the run and its tf column
    // through one packed (doc << 32 | tf) key
    std::vector<uint64_t> packed(static_cast<size_t>(e - b));
    for (int64_t j = b; j < e; ++j)
      packed[j - b] =
          (static_cast<uint64_t>(static_cast<uint32_t>(h.local_flat[j]))
           << 32) |
          static_cast<uint32_t>(h.local_flat_tf[j]);
    std::sort(packed.begin(), packed.end());
    for (int64_t j = b; j < e; ++j) {
      h.local_flat[j] = static_cast<int32_t>(packed[j - b] >> 32);
      h.local_flat_tf[j] =
          static_cast<int32_t>(packed[j - b] & 0xffffffffu);
    }
  }
  // the token-scale scan buffers are spent; release them pre-merge
  // (doc_tokens survives: it is document-scale and feeds the v2
  // artifact's doc-length column)
  std::vector<int32_t>().swap(h.pair_ids);
  std::vector<int32_t>().swap(h.pair_tf);
  std::vector<int64_t>().swap(h.term_last_pair);
  std::vector<HostStreamState::DocMark>().swap(h.doc_marks);
  h.partial_done = true;
  h.partial_ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
}

// Lex order of the vocab by LSD radix sort on the big-endian u64 prefix
// keys — O(V) per pass, 8 passes, no comparator branches; terms sharing
// a full 8-byte prefix land adjacent and their (rare) groups get a tiny
// comparison sort over the padded tails afterwards.  Shared by the v1
// and v2 artifact exporters (on the 1-core bench container this is ~3x
// faster than the comparison sort in SortedOrder, which the pack-time
// budget cannot afford).
std::vector<int32_t> LexOrderRadix(const StreamState& st, int32_t V) {
  const uint8_t* arena = st.arena.data();
  std::vector<std::pair<uint64_t, int32_t>> part(std::max(V, 1));
  for (int32_t i = 0; i < V; ++i)
    part[i] = {__builtin_bswap64(Load64(arena + st.word_offsets[i])), i};
  {
    std::vector<std::pair<uint64_t, int32_t>> tmp(std::max(V, 1));
    for (int pass = 0; pass < 8; ++pass) {
      const int shift = pass * 8;
      int32_t cnt[257] = {0};
      for (int32_t i = 0; i < V; ++i)
        ++cnt[((part[i].first >> shift) & 0xff) + 1];
      for (int b = 1; b <= 256; ++b) cnt[b] += cnt[b - 1];
      for (int32_t i = 0; i < V; ++i)
        tmp[cnt[(part[i].first >> shift) & 0xff]++] = part[i];
      part.swap(tmp);
    }
  }
  const auto tail_cmp = [&](const std::pair<uint64_t, int32_t>& a,
                            const std::pair<uint64_t, int32_t>& b) {
    const int32_t ia = a.second, ib = b.second;
    const uint8_t* pa = arena + st.word_offsets[ia];
    const uint8_t* pb = arena + st.word_offsets[ib];
    const uint32_t pla = (st.word_lens[ia] + 7) & ~7u;
    const uint32_t plb = (st.word_lens[ib] + 7) & ~7u;
    const uint32_t lim = pla > plb ? pla : plb;
    for (uint32_t i = 8; i < lim; i += 8) {
      const uint64_t ka = i < pla ? __builtin_bswap64(Load64(pa + i)) : 0;
      const uint64_t kb = i < plb ? __builtin_bswap64(Load64(pb + i)) : 0;
      if (ka != kb) return ka < kb;
    }
    return false;  // identical words cannot occur (unique vocab)
  };
  for (int32_t i = 0; i < V;) {
    int32_t j = i + 1;
    while (j < V && part[j].first == part[i].first) ++j;
    if (j - i > 1) std::sort(part.begin() + i, part.begin() + j, tail_cmp);
    i = j;
  }
  std::vector<int32_t> lex(std::max(V, 1));
  for (int32_t r = 0; r < V; ++r) lex[r] = part[r].second;
  return lex;
}

// Little-endian bit packer over u32 words (format v2 postings/tf): a
// value's bit k lands at stream bit nbits+k, and stream bit i is bit
// (i & 31) of word (i >> 5) — exactly what np.unpackbits(bitorder=
// 'little') recovers on the serve side.
struct BitPacker {
  std::vector<uint32_t>& out;
  uint64_t acc = 0;
  int nbits = 0;
  explicit BitPacker(std::vector<uint32_t>& o) : out(o) {}
  void Push(uint32_t v, int w) {  // caller guarantees v < 2^w, w <= 31
    acc |= static_cast<uint64_t>(v) << nbits;
    nbits += w;
    while (nbits >= 32) {
      out.push_back(static_cast<uint32_t>(acc));
      acc >>= 32;
      nbits -= 32;
    }
  }
  void Flush() {  // pad to the next word boundary (block alignment)
    if (nbits) {
      out.push_back(static_cast<uint32_t>(acc));
      acc = 0;
      nbits = 0;
    }
  }
};

// Smallest width that can hold v (0 when v == 0: the all-ones delta /
// all-ones tf case packs to zero bytes).
inline int BitWidth(uint32_t v) {
  return v == 0 ? 0 : 32 - __builtin_clz(v);
}

}  // namespace

void* mri_hidx_new() try {
  return new HostStreamState();
} catch (const std::bad_alloc&) {
  return nullptr;
}

void mri_hidx_free(void* handle) {
  delete static_cast<HostStreamState*>(handle);
}

int32_t mri_hidx_feed(void* handle, const uint8_t* data, int64_t len,
                      const int64_t* doc_ends, const int32_t* doc_id_values,
                      int32_t num_docs) try {
  HostStreamState& h = *static_cast<HostStreamState*>(handle);
  const auto t0 = std::chrono::steady_clock::now();
  if (h.pair_ids.capacity() == h.pair_ids.size()) {
    h.pair_ids.reserve(std::max<size_t>(h.pair_ids.size() * 2, 1 << 16));
    h.pair_tf.reserve(h.pair_ids.capacity());
  }
  for (int32_t d = 0; d < num_docs; ++d)
    h.max_doc_id = std::max(h.max_doc_id, doc_id_values[d]);
  int32_t cur_doc = h.doc_marks.empty() ? -1 : h.doc_marks.back().doc;
  ScanChunk(h.st, data, len, 0, doc_ends, doc_id_values, 0, num_docs,
            /*dedup=*/true,
            [&](int32_t id, int32_t doc) {
              if (doc != cur_doc) {
                cur_doc = doc;
                h.doc_marks.push_back(
                    {static_cast<int64_t>(h.pair_ids.size()), doc});
                h.doc_tokens.push_back({doc, 0});
              }
              // a document's first token is always a new pair, so
              // doc_tokens.back() below is this doc in both callbacks
              if (static_cast<size_t>(id) >= h.term_last_pair.size())
                h.term_last_pair.resize(h.st.word_offsets.size(), -1);
              h.term_last_pair[id] =
                  static_cast<int64_t>(h.pair_ids.size());
              h.pair_ids.push_back(id);
              h.pair_tf.push_back(1);
              ++h.doc_tokens.back().second;
            },
            [&](int32_t id) {
              ++h.pair_tf[h.term_last_pair[id]];
              ++h.doc_tokens.back().second;
            });
  h.scan_ns += std::chrono::duration_cast<std::chrono::nanoseconds>(
                   std::chrono::steady_clock::now() - t0)
                   .count();
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

int32_t mri_hidx_finalize_emit(void* handle, const char* out_dir,
                               HostStreamStats* stats) try {
  HostStreamState& h = *static_cast<HostStreamState*>(handle);
  StreamState& st = h.st;
  const auto t0 = std::chrono::steady_clock::now();

  const int32_t vocab = st.next_id;
  // The combiner already holds every term's deduped document frequency;
  // scatter the flat scan-order pairs into per-term runs (scan order is
  // doc-ascending within a term, so the runs arrive sorted).  Doc ids
  // come from the doc_marks segments, not a parallel per-pair array.
  std::vector<int64_t> df_prov(std::max(vocab, 1), 0);
  std::vector<int64_t> offsets_prov(std::max(vocab, 1));
  int64_t total_pairs = 0;
  for (int32_t p = 0; p < vocab; ++p) {
    df_prov[p] = st.combiner[p].df;
    offsets_prov[p] = total_pairs;
    total_pairs += df_prov[p];
  }
  std::vector<int32_t> flat(std::max<int64_t>(total_pairs, 1));
  {
    std::vector<int64_t> cursor(offsets_prov.begin(), offsets_prov.end());
    const size_t n_marks = h.doc_marks.size();
    for (size_t s = 0; s < n_marks; ++s) {
      const int64_t seg_end = (s + 1 < n_marks) ? h.doc_marks[s + 1].start
                                                : static_cast<int64_t>(
                                                      h.pair_ids.size());
      const int32_t doc = h.doc_marks[s].doc;
      for (int64_t k = h.doc_marks[s].start; k < seg_end; ++k)
        flat[cursor[h.pair_ids[k]]++] = doc;
    }
  }

  int32_t width = 1;
  for (int32_t i = 0; i < vocab; ++i)
    width = std::max(width, static_cast<int32_t>(st.word_lens[i]));

  // One sort straight to emit order — (letter asc, df desc, word asc)
  // — instead of SortedOrder + rank views + a second stable sort.
  std::vector<int64_t> emit_order(std::max(vocab, 1));
  int32_t letter_off[27];
  BuildEmitOrder(st, df_prov.data(), emit_order.data(), letter_off);

  // Fixed-width NUL-padded rows for the shared emit core, prov space.
  const uint8_t* base = st.arena.data();
  std::vector<uint8_t> vocab_packed(
      std::max<int64_t>(static_cast<int64_t>(vocab) * width, 1), 0);
  for (int32_t p = 0; p < vocab; ++p)
    std::memcpy(vocab_packed.data() + static_cast<int64_t>(p) * width,
                base + st.word_offsets[p], st.word_lens[p]);
  const auto t1 = std::chrono::steady_clock::now();

  stats->raw_tokens = st.raw_tokens;
  stats->num_pairs = st.num_pairs;
  stats->vocab_size = vocab;
  stats->reserved = 0;
  stats->bytes_written = EmitLetters(
      vocab_packed.data(), vocab, width, emit_order.data(), df_prov.data(),
      offsets_prov.data(), nullptr, flat.data(), out_dir,
      st.word_lens.data(), h.max_doc_id);
  const auto t2 = std::chrono::steady_clock::now();
  stats->scan_ns = h.scan_ns;
  stats->finalize_ns =
      std::chrono::duration_cast<std::chrono::nanoseconds>(t1 - t0).count();
  stats->emit_ns =
      std::chrono::duration_cast<std::chrono::nanoseconds>(t2 - t1).count();
  return stats->bytes_written < 0 ? -1 : 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// ---------------------------------------------------------------------------
// Parallel reduce over K independently-scanned handles: the paper's M
// reducer threads (main.c:129-130) rebuilt on the streaming core.  Each
// of K scan workers owns one HostStreamState; mri_hidx_partial turns
// its scan buffers into per-term doc runs (the per-worker "partial_a..z"
// spill, held in memory); mri_hidxm_new joins the K vocabularies into
// one global vocabulary + emit order; mri_hidxm_emit_range renders a
// contiguous letter range and is READ-ONLY on the merge state, so M
// reducer threads call it concurrently with the GIL released.
//
// Correctness: every document lives in exactly one window and every
// window is consumed by exactly one worker, so a term's per-worker doc
// sets are disjoint — summed df is exact, and an inplace_merge chain
// over the (individually ascending) runs restores the oracle's globally
// ascending postings.
// ---------------------------------------------------------------------------

int32_t mri_hidx_partial(void* handle, int64_t* scan_ns_out,
                         int64_t* partial_ns_out) try {
  HostStreamState& h = *static_cast<HostStreamState*>(handle);
  PartialFlatten(h);
  if (scan_ns_out) *scan_ns_out = h.scan_ns;
  if (partial_ns_out) *partial_ns_out = h.partial_ns;
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// ---------------------------------------------------------------------------
// Out-of-core spill support (build/spill.py): flatten one worker's scan
// state and export it as flat run arrays partitioned by term-hash shard
// — terms in (shard asc, lex asc) order, each term's postings run doc-
// ascending with its tf column, plus the document-scale token counts
// the v2 artifact's doc-length column needs.  The shard of a term is
// HashWord(word) % shards — the same canonical zero-padded hash the
// in-memory vocabulary join uses, so every worker agrees on a term's
// shard without coordination.  The Python side writes the arrays to a
// checksummed run file and replaces the handle with a fresh one; the
// per-shard streaming merge later restores the exact in-memory merge
// semantics (disjoint doc sets, ascending runs) from disk.
// ---------------------------------------------------------------------------

int32_t mri_hidx_runpack_info(void* handle, int32_t* vocab_out,
                              int32_t* width_out, int64_t* pairs_out,
                              int64_t* ndocs_out, int64_t* max_doc_id_out,
                              int64_t* raw_tokens_out) try {
  HostStreamState& h = *static_cast<HostStreamState*>(handle);
  PartialFlatten(h);
  StreamState& st = h.st;
  const int32_t vocab = st.next_id;
  int32_t width = 1;
  for (int32_t g = 0; g < vocab; ++g)
    width = std::max(width, static_cast<int32_t>(st.word_lens[g]));
  if (vocab_out) *vocab_out = vocab;
  if (width_out) *width_out = width;
  if (pairs_out) *pairs_out = h.local_off[std::max(vocab, 1)];
  if (ndocs_out) *ndocs_out = static_cast<int64_t>(h.doc_tokens.size());
  if (max_doc_id_out) *max_doc_id_out = h.max_doc_id;
  if (raw_tokens_out) *raw_tokens_out = st.raw_tokens;
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// Caller sizes every buffer from mri_hidx_runpack_info and zero-fills
// vocab_packed (rows stay NUL-padded past each word's length).
// offsets_out has vocab+1 entries (global cumulative, so shard s's
// pairs live at [shard_pair_off[s], shard_pair_off[s+1])); the shard
// offset arrays have shards+1 entries.
int32_t mri_hidx_runpack(void* handle, int32_t shards, uint8_t* vocab_packed,
                         int32_t* word_lens_out, int64_t* df_out,
                         int64_t* offsets_out, int32_t* postings_out,
                         int32_t* tf_out, int64_t* shard_term_off,
                         int64_t* shard_pair_off, int32_t* doc_ids_out,
                         int64_t* doc_tokens_out) try {
  if (shards < 1) return -1;
  HostStreamState& h = *static_cast<HostStreamState*>(handle);
  PartialFlatten(h);
  StreamState& st = h.st;
  const int32_t vocab = st.next_id;
  int32_t width = 1;
  for (int32_t g = 0; g < vocab; ++g)
    width = std::max(width, static_cast<int32_t>(st.word_lens[g]));
  const uint8_t* base = st.arena.data();
  // (shard asc, lex asc) term order: one stable counting partition over
  // the radix lex order, so each shard's slice stays lex-sorted.
  std::vector<int32_t> lex = LexOrderRadix(st, vocab);
  std::vector<uint32_t> shard_of(std::max(vocab, 1));
  std::vector<int64_t> count(static_cast<size_t>(shards) + 1, 0);
  for (int32_t g = 0; g < vocab; ++g) {
    shard_of[g] = static_cast<uint32_t>(
        HashWord(base + st.word_offsets[g], st.word_lens[g]) %
        static_cast<uint64_t>(shards));
    ++count[shard_of[g] + 1];
  }
  for (int32_t s = 0; s < shards; ++s) count[s + 1] += count[s];
  std::vector<int64_t> cur(count.begin(), count.end() - 1);
  std::vector<int32_t> order(std::max(vocab, 1));
  for (int32_t r = 0; r < vocab; ++r) {
    const int32_t g = lex[r];
    order[cur[shard_of[g]]++] = g;
  }
  for (int32_t s = 0; s <= shards; ++s) shard_term_off[s] = count[s];
  offsets_out[0] = 0;
  for (int32_t r = 0; r < vocab; ++r) {
    const int32_t g = order[r];
    const int64_t lo = h.local_off[g], hi = h.local_off[g + 1];
    std::memcpy(vocab_packed + static_cast<int64_t>(r) * width,
                base + st.word_offsets[g], st.word_lens[g]);
    word_lens_out[r] = static_cast<int32_t>(st.word_lens[g]);
    df_out[r] = hi - lo;
    std::copy(h.local_flat.begin() + lo, h.local_flat.begin() + hi,
              postings_out + offsets_out[r]);
    std::copy(h.local_flat_tf.begin() + lo, h.local_flat_tf.begin() + hi,
              tf_out + offsets_out[r]);
    offsets_out[r + 1] = offsets_out[r] + (hi - lo);
  }
  for (int32_t s = 0; s <= shards; ++s)
    shard_pair_off[s] = offsets_out[shard_term_off[s]];
  // Document section, doc-id ascending for determinism (the steal
  // queue can hand windows to this worker in any order).
  std::vector<std::pair<int32_t, int64_t>> docs(h.doc_tokens);
  std::sort(docs.begin(), docs.end());
  for (size_t d = 0; d < docs.size(); ++d) {
    doc_ids_out[d] = docs[d].first;
    doc_tokens_out[d] = docs[d].second;
  }
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

struct HostMergeState {
  std::vector<HostStreamState*> parts;  // non-owning: caller keeps alive
  StreamState merged;                   // global vocab when K > 1
  StreamState* st = nullptr;            // &merged, or part 0's state (K==1)
  std::vector<int64_t> df_gid;          // global prov id -> merged df
  // Per-term postings segments as (worker, local id) in CSR layout:
  // term g's docs are the union of runs seg_off[g] .. seg_off[g+1].
  std::vector<int64_t> seg_off;
  std::vector<int32_t> seg_worker, seg_lid;
  std::vector<int64_t> emit_order;      // global emit permutation
  int32_t letter_off[27] = {0};         // letter l owns emit_order slice
  std::vector<uint8_t> vocab_packed;    // prov space, NUL-padded rows
  int32_t vocab = 0, width = 1, max_doc_id = 0;
  int64_t raw_tokens = 0, num_pairs = 0;
  // Format-v2 export plan (mri_hidxm_export_v2_prepare fills, _payload
  // consumes and releases): lex permutation, per-block skip entries and
  // bit widths, packed postings/tf words, and the doc-length column.
  std::vector<int32_t> v2_lex;
  std::vector<int32_t> v2_blk_max, v2_blk_first;
  std::vector<uint8_t> v2_blk_width, v2_blk_tf_width;
  std::vector<uint32_t> v2_post_data, v2_tf_data;
  std::vector<int32_t> v2_doc_lens;
  // v2.1 max-score columns as little-endian bytes (score_bits/8 per
  // block): saturated max tf and min doc length — integers, so these
  // bytes match the pure-Python packer bit for bit.
  std::vector<uint8_t> v2_blk_max_tf, v2_blk_min_dl;
  int32_t v2_block_size = 0;
  int32_t v2_score_bits = 0;
};

void* mri_hidxm_new(void* const* handles, int32_t num_handles,
                    HostStreamStats* stats) try {
  if (num_handles < 1) return nullptr;
  const auto t0 = std::chrono::steady_clock::now();
  auto* m = new HostMergeState();
  try {
    const int32_t K = num_handles;
    m->parts.reserve(K);
    for (int32_t i = 0; i < K; ++i) {
      auto* h = static_cast<HostStreamState*>(handles[i]);
      PartialFlatten(*h);  // no-op when the worker already partial'd
      m->parts.push_back(h);
      m->raw_tokens += h->st.raw_tokens;
      m->num_pairs += h->st.num_pairs;
      m->max_doc_id = std::max(m->max_doc_id, h->max_doc_id);
    }
    // Vocab-scale join in worker order (mri_host_index's merge idiom);
    // one worker's local state IS the global vocab (identity l2g).
    std::vector<std::vector<int32_t>> l2g(K);
    for (int32_t w = 0; w < K; ++w) {
      StreamState& local = m->parts[w]->st;
      l2g[w].reserve(local.next_id);
      const uint8_t* base = local.arena.data();
      for (int32_t lid = 0; lid < local.next_id; ++lid) {
        if (K == 1) {
          l2g[w].push_back(lid);
          continue;
        }
        const uint8_t* word = base + local.word_offsets[lid];
        const uint32_t wl = local.word_lens[lid];
        l2g[w].push_back(m->merged.Upsert(word, wl, HashWord(word, wl)));
      }
    }
    m->st = (K == 1) ? &m->parts[0]->st : &m->merged;
    StreamState& st = *m->st;
    const int32_t vocab = m->vocab = st.next_id;

    // Disjoint doc sets sum exactly; count segments per global term.
    m->df_gid.assign(std::max(vocab, 1), 0);
    std::vector<int64_t> nseg(std::max(vocab, 1), 0);
    for (int32_t w = 0; w < K; ++w) {
      StreamState& local = m->parts[w]->st;
      for (int32_t lid = 0; lid < local.next_id; ++lid) {
        const int64_t df = local.combiner[lid].df;
        if (!df) continue;
        m->df_gid[l2g[w][lid]] += df;
        ++nseg[l2g[w][lid]];
      }
    }
    m->seg_off.assign(static_cast<size_t>(std::max(vocab, 1)) + 1, 0);
    for (int32_t g = 0; g < vocab; ++g)
      m->seg_off[g + 1] = m->seg_off[g] + nseg[g];
    m->seg_worker.resize(std::max<int64_t>(m->seg_off[std::max(vocab, 1)], 1));
    m->seg_lid.resize(m->seg_worker.size());
    {
      std::vector<int64_t> cur(m->seg_off.begin(), m->seg_off.end() - 1);
      for (int32_t w = 0; w < K; ++w) {
        StreamState& local = m->parts[w]->st;
        for (int32_t lid = 0; lid < local.next_id; ++lid) {
          if (!local.combiner[lid].df) continue;
          const int64_t s = cur[l2g[w][lid]]++;
          m->seg_worker[s] = w;
          m->seg_lid[s] = lid;
        }
      }
    }

    int32_t width = 1;
    for (int32_t g = 0; g < vocab; ++g)
      width = std::max(width, static_cast<int32_t>(st.word_lens[g]));
    m->width = width;
    m->vocab_packed.assign(
        std::max<int64_t>(static_cast<int64_t>(vocab) * width, 1), 0);
    for (int32_t g = 0; g < vocab; ++g)
      std::memcpy(m->vocab_packed.data() + static_cast<int64_t>(g) * width,
                  st.arena.data() + st.word_offsets[g], st.word_lens[g]);

    m->emit_order.resize(std::max(vocab, 1));
    BuildEmitOrder(st, m->df_gid.data(), m->emit_order.data(), m->letter_off);

    if (stats) {
      stats->raw_tokens = m->raw_tokens;
      stats->num_pairs = m->num_pairs;
      stats->vocab_size = vocab;
      stats->reserved = 0;
      stats->bytes_written = 0;
      stats->scan_ns = 0;
      stats->finalize_ns =
          std::chrono::duration_cast<std::chrono::nanoseconds>(
              std::chrono::steady_clock::now() - t0)
              .count();
      stats->emit_ns = 0;
    }
  } catch (...) {
    delete m;
    throw;
  }
  return m;
} catch (const std::bad_alloc&) {
  return nullptr;
}

void mri_hidxm_free(void* mh) {
  delete static_cast<HostMergeState*>(mh);
}

// Render letter files [letter_lo, letter_hi).  Returns bytes written,
// -1 on IO/range error, -2 on OOM.  Reads only shared merge state plus
// the workers' immutable runs: safe for concurrent reducer threads.
int64_t mri_hidxm_emit_range(void* mh, int32_t letter_lo, int32_t letter_hi,
                             const char* out_dir) try {
  HostMergeState& m = *static_cast<HostMergeState*>(mh);
  if (letter_lo < 0 || letter_hi > 26 || letter_lo > letter_hi) return -1;
  if (letter_lo == letter_hi) return 0;  // empty partition (R > 26)
  const int64_t idx_start = m.letter_off[letter_lo];
  const int64_t idx_end = m.letter_off[letter_hi];

  // Range-scoped postings: gather each in-range term's worker runs and
  // restore global doc-ascending order by chaining inplace_merge over
  // the (ascending, disjoint) runs.
  std::vector<int64_t> off(std::max(m.vocab, 1), 0);
  std::vector<int64_t> cnt(std::max(m.vocab, 1), 0);
  int64_t range_df = 0;
  for (int64_t i = idx_start; i < idx_end; ++i)
    range_df += m.df_gid[m.emit_order[i]];
  std::vector<int32_t> flat(std::max<int64_t>(range_df, 1));
  int64_t cur = 0;
  for (int64_t i = idx_start; i < idx_end; ++i) {
    const int64_t g = m.emit_order[i];
    off[g] = cur;
    cnt[g] = m.df_gid[g];
    const int64_t term_start = cur;
    for (int64_t s = m.seg_off[g]; s < m.seg_off[g + 1]; ++s) {
      const HostStreamState& h = *m.parts[m.seg_worker[s]];
      const int32_t lid = m.seg_lid[s];
      const int64_t lo = h.local_off[lid];
      const int64_t n = h.local_off[lid + 1] - lo;
      std::copy(h.local_flat.begin() + lo, h.local_flat.begin() + lo + n,
                flat.begin() + cur);
      if (cur != term_start)
        std::inplace_merge(flat.begin() + term_start, flat.begin() + cur,
                           flat.begin() + cur + n);
      cur += n;
    }
  }
  const EmitRun run{nullptr, flat.data(), off.data(), cnt.data()};
  return EmitLettersRuns(m.vocab_packed.data(), m.vocab, m.width,
                         m.emit_order.data(), &run, 1, out_dir,
                         m.st->word_lens.data(), m.max_doc_id,
                         letter_lo, letter_hi, idx_start, idx_end);
} catch (const std::bad_alloc&) {
  return -2;
}

// ---------------------------------------------------------------------------
// Integrity probes for the audit layer (audit.py): read-only walks over
// scan/merge state so the Python-side invariant checks never copy
// postings out.  Both are safe concurrently with emit_range (nothing
// here mutates).
// ---------------------------------------------------------------------------

// Per-worker scan totals: vocab (local provisional ids), deduped
// (term, doc) pair count, raw token count.
int32_t mri_hidx_info(void* handle, int32_t* vocab_out, int64_t* pairs_out,
                      int64_t* raw_tokens_out) {
  HostStreamState& h = *static_cast<HostStreamState*>(handle);
  if (vocab_out) *vocab_out = h.st.next_id;
  if (pairs_out) *pairs_out = h.st.num_pairs;
  if (raw_tokens_out) *raw_tokens_out = h.st.raw_tokens;
  return 0;
}

// Merge invariants over every global term's worker runs: summed run
// lengths must equal the merged df (disjoint windows sum exactly), and
// each run must be strictly ascending (each worker's partial restores
// doc order; equal neighbors would mean a doc counted twice).  Returns
// 0 ok, 1 df-sum mismatch, 2 non-monotonic run; the offending global
// term id lands in *bad_term.
int32_t mri_hidxm_audit(void* mh, int32_t* bad_term) {
  HostMergeState& m = *static_cast<HostMergeState*>(mh);
  for (int32_t g = 0; g < m.vocab; ++g) {
    int64_t total = 0;
    for (int64_t s = m.seg_off[g]; s < m.seg_off[g + 1]; ++s) {
      const HostStreamState& h = *m.parts[m.seg_worker[s]];
      const int32_t lid = m.seg_lid[s];
      const int64_t lo = h.local_off[lid];
      const int64_t hi = h.local_off[lid + 1];
      total += hi - lo;
      for (int64_t k = lo + 1; k < hi; ++k)
        if (h.local_flat[k - 1] >= h.local_flat[k]) {
          if (bad_term) *bad_term = g;
          return 2;
        }
    }
    if (total != m.df_gid[g]) {
      if (bad_term) *bad_term = g;
      return 1;
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Columnar export for the serving artifact (serve/artifact.py): flatten
// the merge state into lexicographically-ordered arrays — term rows,
// df, posting offsets, globally-ascending postings, and the emit-order
// permutation re-expressed over lex indices — so the artifact writer
// never round-trips through the letter-file text.  Caller allocates
// (sizes from mri_hidxm_export_info); both calls are read-only on the
// merge state, safe concurrently with emit_range.
// ---------------------------------------------------------------------------

int32_t mri_hidxm_export_info(void* mh, int32_t* vocab_out,
                              int32_t* width_out, int32_t* max_doc_id_out,
                              int64_t* num_pairs_out,
                              int64_t* blob_bytes_out) {
  HostMergeState& m = *static_cast<HostMergeState*>(mh);
  const StreamState& st = *m.st;
  int64_t pairs = 0, blob = 0;
  for (int32_t g = 0; g < m.vocab; ++g) {
    pairs += m.df_gid[g];
    blob += st.word_lens[g];
  }
  if (vocab_out) *vocab_out = m.vocab;
  if (width_out) *width_out = m.width;
  if (max_doc_id_out) *max_doc_id_out = m.max_doc_id;
  if (num_pairs_out) *num_pairs_out = pairs;
  if (blob_bytes_out) *blob_bytes_out = blob;
  return 0;
}

// Fill caller-allocated arrays: vocab_packed (V*width, NUL-padded),
// word_lens (V), df (V), offsets (V+1 exclusive prefix), postings
// (num_pairs, ascending per term via the emit path's inplace_merge
// chain), df_order (V lex indices in (letter asc, df desc, word asc)
// order), letter_off (27 — shared by lex and emit order, both being
// letter-contiguous).  Returns 0, or -2 on OOM.
int32_t mri_hidxm_export(void* mh, uint8_t* vocab_packed, int32_t* word_lens,
                         int64_t* df, int64_t* offsets, int32_t* postings,
                         int64_t* df_order, int64_t* letter_off) try {
  HostMergeState& m = *static_cast<HostMergeState*>(mh);
  const StreamState& st = *m.st;
  const int32_t V = m.vocab;
  const std::vector<int32_t> lex = SortedOrder(st);
  std::vector<int32_t> inv(std::max(V, 1));
  for (int32_t r = 0; r < V; ++r) inv[lex[r]] = r;
  int64_t cur = 0;
  for (int32_t r = 0; r < V; ++r) {
    const int32_t g = lex[r];
    std::memcpy(vocab_packed + static_cast<int64_t>(r) * m.width,
                m.vocab_packed.data() + static_cast<int64_t>(g) * m.width,
                m.width);
    word_lens[r] = static_cast<int32_t>(st.word_lens[g]);
    df[r] = m.df_gid[g];
    offsets[r] = cur;
    const int64_t term_start = cur;
    for (int64_t s = m.seg_off[g]; s < m.seg_off[g + 1]; ++s) {
      const HostStreamState& h = *m.parts[m.seg_worker[s]];
      const int32_t lid = m.seg_lid[s];
      const int64_t lo = h.local_off[lid];
      const int64_t n = h.local_off[lid + 1] - lo;
      std::copy(h.local_flat.begin() + lo, h.local_flat.begin() + lo + n,
                postings + cur);
      if (cur != term_start)
        std::inplace_merge(postings + term_start, postings + cur,
                           postings + cur + n);
      cur += n;
    }
  }
  offsets[V] = cur;
  for (int32_t i = 0; i < V; ++i) df_order[i] = inv[m.emit_order[i]];
  for (int l = 0; l < 27; ++l) letter_off[l] = m.letter_off[l];
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// One-pass artifact payload fill (serve/artifact.py build_from_merge):
// writes every payload section of the index.mri format DIRECTLY into
// the caller's file buffer at the offsets the Python layout computed —
// compact term blob (no fixed-width round-trip), i32 df, and postings
// DELTA-ENCODED in place right after each term's run merge — so the
// Python side is left with just checksum + header + one write().  On
// the 1-core bench container this is the difference between the pack
// fitting the <=10 %-of-e2e budget and tripling it.
int32_t mri_hidxm_export_payload(void* mh, uint8_t* base,
                                 int64_t off_letter_dir,
                                 int64_t off_term_offsets,
                                 int64_t off_term_blob, int64_t off_df,
                                 int64_t off_post_offsets,
                                 int64_t off_postings,
                                 int64_t off_df_order) try {
  HostMergeState& m = *static_cast<HostMergeState*>(mh);
  const StreamState& st = *m.st;
  const int32_t V = m.vocab;
  int64_t* letter_dir = reinterpret_cast<int64_t*>(base + off_letter_dir);
  int64_t* term_offsets = reinterpret_cast<int64_t*>(base + off_term_offsets);
  uint8_t* term_blob = base + off_term_blob;
  int32_t* df = reinterpret_cast<int32_t*>(base + off_df);
  int64_t* post_offsets = reinterpret_cast<int64_t*>(base + off_post_offsets);
  int32_t* postings = reinterpret_cast<int32_t*>(base + off_postings);
  int32_t* df_order = reinterpret_cast<int32_t*>(base + off_df_order);

  for (int l = 0; l < 27; ++l) letter_dir[l] = m.letter_off[l];
  const uint8_t* arena = st.arena.data();
  const std::vector<int32_t> lex = LexOrderRadix(st, V);
  std::vector<int32_t> inv(std::max(V, 1));
  for (int32_t r = 0; r < V; ++r) inv[lex[r]] = r;
  // blob writes may use fixed-width 8-byte stores (the arena pads every
  // word to an 8-byte multiple, so the LOAD is always safe); the store
  // may spill past the word into bytes a later term overwrites, bounded
  // by the section's alignment pad — re-zeroed after the loop.
  const int64_t blob_room = off_df - off_term_blob;
  int64_t blob_cur = 0, cur = 0;
  for (int32_t r = 0; r < V; ++r) {
    // The walk visits gids in lex order — random against every
    // per-gid array — and each term chains 3+ dependent loads (CSR
    // slot -> run bounds -> run data).  Two-distance software
    // prefetch keeps several of those misses in flight: first-level
    // rows far ahead, the second-level values they feed closer in.
    if (r + 16 < V) {
      const int32_t gf = lex[r + 16];
      __builtin_prefetch(&m.seg_off[gf]);
      __builtin_prefetch(&m.df_gid[gf]);
      __builtin_prefetch(&st.word_offsets[gf]);
    }
    if (r + 4 < V) {
      const int32_t gn = lex[r + 4];
      __builtin_prefetch(arena + st.word_offsets[gn]);
      const int64_t sn = m.seg_off[gn];
      __builtin_prefetch(&m.seg_worker[sn]);
      __builtin_prefetch(&m.seg_lid[sn]);
    }
    if (r + 1 < V) {
      const int32_t g1 = lex[r + 1];
      const int64_t s1 = m.seg_off[g1];
      const HostStreamState& h1 = *m.parts[m.seg_worker[s1]];
      __builtin_prefetch(h1.local_flat.data() + h1.local_off[m.seg_lid[s1]]);
    }
    const int32_t g = lex[r];
    term_offsets[r] = blob_cur;
    const uint32_t wl = st.word_lens[g];
    if (wl <= 8 && blob_cur + 8 <= blob_room)
      std::memcpy(term_blob + blob_cur, arena + st.word_offsets[g], 8);
    else
      std::memcpy(term_blob + blob_cur, arena + st.word_offsets[g], wl);
    blob_cur += wl;
    df[r] = static_cast<int32_t>(m.df_gid[g]);
    post_offsets[r] = cur;
    const int64_t term_start = cur;
    const int64_t seg_lo = m.seg_off[g], seg_hi = m.seg_off[g + 1];
    if (seg_hi - seg_lo == 1) {
      // single worker run (the K=1 common case): fused gather + delta,
      // one pass instead of copy-then-encode
      const HostStreamState& h = *m.parts[m.seg_worker[seg_lo]];
      const int32_t lid = m.seg_lid[seg_lo];
      const int64_t lo = h.local_off[lid];
      const int64_t n = h.local_off[lid + 1] - lo;
      const int32_t* src = h.local_flat.data() + lo;
      int32_t prev = 0;  // first id stays absolute
      for (int64_t j = 0; j < n; ++j) {
        postings[cur + j] = src[j] - prev;
        prev = src[j];
      }
      cur += n;
    } else {
      for (int64_t s = seg_lo; s < seg_hi; ++s) {
        const HostStreamState& h = *m.parts[m.seg_worker[s]];
        const int32_t lid = m.seg_lid[s];
        const int64_t lo = h.local_off[lid];
        const int64_t n = h.local_off[lid + 1] - lo;
        std::copy(h.local_flat.begin() + lo, h.local_flat.begin() + lo + n,
                  postings + cur);
        if (cur != term_start)
          std::inplace_merge(postings + term_start, postings + cur,
                             postings + cur + n);
        cur += n;
      }
      // delta-encode the merged run in place, backwards (first id stays
      // absolute) — the format's cumsum-decodable wire form
      for (int64_t j = cur - 1; j > term_start; --j)
        postings[j] -= postings[j - 1];
    }
  }
  if (blob_cur < blob_room)  // fixed-width stores may have scribbled pad
    std::memset(term_blob + blob_cur, 0, blob_room - blob_cur);
  term_offsets[V] = blob_cur;
  post_offsets[V] = cur;
  for (int32_t i = 0; i < V; ++i)
    df_order[i] = inv[m.emit_order[i]];
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// ---------------------------------------------------------------------------
// Format-v2 export (serve/artifact.py build_from_merge with
// MRI_SERVE_FORMAT=2): postings as fixed-size blocks of bitpacked
// (delta - 1) values with per-block skip entries (max doc id, first doc
// id, bit width), a parallel bitpacked (tf - 1) column, and the BM25
// doc-length column.  Two calls: _prepare merges + packs everything
// into the plan vectors (one pass over the runs, block widths chosen on
// the fly) and reports the section sizes the Python layout needs;
// _payload memcpys the plan into the caller's file buffer at the layout
// offsets and releases it.
// ---------------------------------------------------------------------------

int32_t mri_hidxm_export_v2_prepare(void* mh, int32_t block_size,
                                    int32_t score_bits,
                                    int64_t* num_blocks_out,
                                    int64_t* post_bytes_out,
                                    int64_t* tf_bytes_out) try {
  HostMergeState& m = *static_cast<HostMergeState*>(mh);
  const StreamState& st = *m.st;
  const int32_t V = m.vocab;
  const int32_t B = block_size;
  if (B < 2 || B > (1 << 20) || (B & (B - 1)) != 0) return -1;
  if (score_bits != 0 && score_bits != 8 && score_bits != 16) return -1;
  m.v2_block_size = B;
  m.v2_score_bits = score_bits;
  m.v2_lex = LexOrderRadix(st, V);
  m.v2_blk_max.clear();
  m.v2_blk_first.clear();
  m.v2_blk_width.clear();
  m.v2_blk_tf_width.clear();
  m.v2_blk_max_tf.clear();
  m.v2_blk_min_dl.clear();
  m.v2_post_data.clear();
  m.v2_tf_data.clear();

  // doc-length column: each worker's doc_tokens entries are disjoint
  // doc spans, so += sums exactly (a doc split across feeds of one
  // worker contributes multiple entries)
  m.v2_doc_lens.assign(static_cast<size_t>(m.max_doc_id) + 1, 0);
  for (const HostStreamState* p : m.parts)
    for (const auto& dt : p->doc_tokens)
      if (dt.first >= 0 && dt.first <= m.max_doc_id)
        m.v2_doc_lens[dt.first] += static_cast<int32_t>(dt.second);

  BitPacker pp(m.v2_post_data), tp(m.v2_tf_data);
  std::vector<int32_t> docs, tfs;
  std::vector<uint64_t> packed;
  for (int32_t r = 0; r < V; ++r) {
    const int32_t g = m.v2_lex[r];
    const int64_t df = m.df_gid[g];
    if (df == 0) continue;
    const int64_t seg_lo = m.seg_off[g], seg_hi = m.seg_off[g + 1];
    const int32_t* dptr;
    const int32_t* tptr;
    if (seg_hi - seg_lo == 1) {
      // single worker run (the K=1 common case): pack straight from
      // the worker's immutable run, no copy
      const HostStreamState& h = *m.parts[m.seg_worker[seg_lo]];
      const int32_t lid = m.seg_lid[seg_lo];
      const int64_t lo = h.local_off[lid];
      dptr = h.local_flat.data() + lo;
      tptr = h.local_flat_tf.data() + lo;
    } else {
      // multi-run: co-merge docs and tf through packed u64 keys (docs
      // are disjoint across workers, so doc order == key order)
      packed.resize(static_cast<size_t>(df));
      int64_t cur = 0;
      for (int64_t s = seg_lo; s < seg_hi; ++s) {
        const HostStreamState& h = *m.parts[m.seg_worker[s]];
        const int32_t lid = m.seg_lid[s];
        const int64_t lo = h.local_off[lid];
        const int64_t n = h.local_off[lid + 1] - lo;
        for (int64_t j = 0; j < n; ++j)
          packed[cur + j] =
              (static_cast<uint64_t>(
                   static_cast<uint32_t>(h.local_flat[lo + j]))
               << 32) |
              static_cast<uint32_t>(h.local_flat_tf[lo + j]);
        if (cur)
          std::inplace_merge(packed.begin(), packed.begin() + cur,
                             packed.begin() + cur + n);
        cur += n;
      }
      docs.resize(static_cast<size_t>(df));
      tfs.resize(static_cast<size_t>(df));
      for (int64_t j = 0; j < df; ++j) {
        docs[j] = static_cast<int32_t>(packed[j] >> 32);
        tfs[j] = static_cast<int32_t>(packed[j] & 0xffffffffu);
      }
      dptr = docs.data();
      tptr = tfs.data();
    }
    for (int64_t b0 = 0; b0 < df; b0 += B) {
      const int32_t cnt = static_cast<int32_t>(std::min<int64_t>(B, df - b0));
      m.v2_blk_first.push_back(dptr[b0]);
      m.v2_blk_max.push_back(dptr[b0 + cnt - 1]);
      uint32_t maxd = 0, maxt = 0;
      for (int32_t j = 1; j < cnt; ++j)
        maxd = std::max(
            maxd, static_cast<uint32_t>(dptr[b0 + j] - dptr[b0 + j - 1] - 1));
      for (int32_t j = 0; j < cnt; ++j)
        maxt = std::max(maxt, static_cast<uint32_t>(tptr[b0 + j] - 1));
      const int wd = BitWidth(maxd);
      const int wt = BitWidth(maxt);
      m.v2_blk_width.push_back(static_cast<uint8_t>(wd));
      m.v2_blk_tf_width.push_back(static_cast<uint8_t>(wt));
      if (score_bits) {
        // maxt holds max(tf - 1); the columns store saturated max tf
        // and min doc length (same integer saturation as the Python
        // packer — the engines derive the float bound at query time)
        const uint32_t cap = (1u << score_bits) - 1;
        uint32_t mind = UINT32_MAX;
        for (int32_t j = 0; j < cnt; ++j)
          mind = std::min(
              mind, static_cast<uint32_t>(m.v2_doc_lens[dptr[b0 + j]]));
        const uint32_t mt = std::min(maxt + 1, cap);
        const uint32_t md = std::min(mind, cap);
        m.v2_blk_max_tf.push_back(static_cast<uint8_t>(mt & 0xff));
        m.v2_blk_min_dl.push_back(static_cast<uint8_t>(md & 0xff));
        if (score_bits == 16) {
          m.v2_blk_max_tf.push_back(static_cast<uint8_t>(mt >> 8));
          m.v2_blk_min_dl.push_back(static_cast<uint8_t>(md >> 8));
        }
      }
      for (int32_t j = 1; j < cnt; ++j)
        pp.Push(static_cast<uint32_t>(dptr[b0 + j] - dptr[b0 + j - 1] - 1),
                wd);
      pp.Flush();
      for (int32_t j = 0; j < cnt; ++j)
        tp.Push(static_cast<uint32_t>(tptr[b0 + j] - 1), wt);
      tp.Flush();
    }
  }
  if (num_blocks_out)
    *num_blocks_out = static_cast<int64_t>(m.v2_blk_max.size());
  if (post_bytes_out)
    *post_bytes_out = static_cast<int64_t>(m.v2_post_data.size()) * 4;
  if (tf_bytes_out)
    *tf_bytes_out = static_cast<int64_t>(m.v2_tf_data.size()) * 4;
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// Fill the v2/v2.1 payload sections.  `offs` holds byte offsets into
// `base`, in fixed section order: letter_dir, term_offsets, term_blob,
// df, blk_max, blk_first, blk_width, blk_tf_width, [blk_max_tf,
// blk_min_dl,] post_data, tf_data, doc_lens, df_order — 12 offsets for
// a v2 plan (score_bits 0), 14 for a v2.1 plan.  Releases the prepare
// plan on success.
int32_t mri_hidxm_export_v2_payload(void* mh, uint8_t* base,
                                    const int64_t* offs,
                                    int32_t n_offs) try {
  HostMergeState& m = *static_cast<HostMergeState*>(mh);
  const StreamState& st = *m.st;
  const int32_t V = m.vocab;
  if (m.v2_block_size == 0) return -1;
  if (n_offs != (m.v2_score_bits ? 14 : 12)) return -1;
  const int tail = m.v2_score_bits ? 10 : 8;  // post_data's slot
  int64_t* letter_dir = reinterpret_cast<int64_t*>(base + offs[0]);
  int64_t* term_offsets = reinterpret_cast<int64_t*>(base + offs[1]);
  uint8_t* term_blob = base + offs[2];
  int32_t* df = reinterpret_cast<int32_t*>(base + offs[3]);
  int32_t* df_order =
      reinterpret_cast<int32_t*>(base + offs[tail + 3]);

  for (int l = 0; l < 27; ++l) letter_dir[l] = m.letter_off[l];
  const uint8_t* arena = st.arena.data();
  int64_t blob_cur = 0;
  for (int32_t r = 0; r < V; ++r) {
    const int32_t g = m.v2_lex[r];
    term_offsets[r] = blob_cur;
    std::memcpy(term_blob + blob_cur, arena + st.word_offsets[g],
                st.word_lens[g]);
    blob_cur += st.word_lens[g];
    df[r] = static_cast<int32_t>(m.df_gid[g]);
  }
  term_offsets[V] = blob_cur;
  const auto copy_bytes = [&](int idx, const void* src, size_t n) {
    if (n) std::memcpy(base + offs[idx], src, n);
  };
  copy_bytes(4, m.v2_blk_max.data(), m.v2_blk_max.size() * 4);
  copy_bytes(5, m.v2_blk_first.data(), m.v2_blk_first.size() * 4);
  copy_bytes(6, m.v2_blk_width.data(), m.v2_blk_width.size());
  copy_bytes(7, m.v2_blk_tf_width.data(), m.v2_blk_tf_width.size());
  if (m.v2_score_bits) {
    copy_bytes(8, m.v2_blk_max_tf.data(), m.v2_blk_max_tf.size());
    copy_bytes(9, m.v2_blk_min_dl.data(), m.v2_blk_min_dl.size());
  }
  copy_bytes(tail, m.v2_post_data.data(), m.v2_post_data.size() * 4);
  copy_bytes(tail + 1, m.v2_tf_data.data(), m.v2_tf_data.size() * 4);
  copy_bytes(tail + 2, m.v2_doc_lens.data(), m.v2_doc_lens.size() * 4);
  std::vector<int32_t> inv(std::max(V, 1));
  for (int32_t r = 0; r < V; ++r) inv[m.v2_lex[r]] = r;
  for (int32_t i = 0; i < V; ++i) df_order[i] = inv[m.emit_order[i]];

  std::vector<int32_t>().swap(m.v2_lex);
  std::vector<int32_t>().swap(m.v2_blk_max);
  std::vector<int32_t>().swap(m.v2_blk_first);
  std::vector<uint8_t>().swap(m.v2_blk_width);
  std::vector<uint8_t>().swap(m.v2_blk_tf_width);
  std::vector<uint8_t>().swap(m.v2_blk_max_tf);
  std::vector<uint8_t>().swap(m.v2_blk_min_dl);
  std::vector<uint32_t>().swap(m.v2_post_data);
  std::vector<uint32_t>().swap(m.v2_tf_data);
  std::vector<int32_t>().swap(m.v2_doc_lens);
  m.v2_block_size = 0;
  m.v2_score_bits = 0;
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// =====================================================================
// Serve-path kernels (mri_serve_*): width-specialized block decode,
// skip+gallop intersect, and the BM25 exhaustive/BMW/MaxScore top-k.
//
// The numpy Engine stays the conformance oracle: every kernel here
// reproduces its answers byte-for-byte.  The float contract is the
// tight part — per-element BM25 contributions use the numpy scorer's
// exact expression and association order,
//     denom   = tf + k1 * ((1.0 - b) + (b * dl) / avgdl)
//     contrib = ((idf * tf) * (k1 + 1.0)) / denom
// and final scores are re-accumulated in query OCCURRENCE order (the
// exhaustive path's addition order).  idf is computed caller-side (in
// Python, with np.log) and passed in as float64 so a libm-vs-numpy ulp
// can never split the backends.  The build uses baseline x86-64 (no
// -march / -mfma in native/__init__.py), so the compiler cannot
// contract the mul+adds above into FMAs — contraction would break the
// byte-identity contract.
//
// Handles are NOT thread-safe; the engine serializes calls (GIL +
// daemon reload lock), same as the mri_hidx_* streams.

}  // extern "C" (reopened after the templated serve helpers below)

namespace {

//: mirror of serve.planner.THETA_MARGIN — relative slack on every
//: theta comparison so float associativity never prunes a true top-k
//: doc (1.0 - 1e-9 in IEEE double, bit-identical to the Python value).
const double kServeThetaMargin = 1.0 - 1e-9;
//: largest k the ranked fast path selects on the stack (bounded
//: insertion); larger cutoffs fall back to nth_element over a heap
//: vector
const int32_t kServeStackK = 128;

// ---- width-specialized bitpacked decode -----------------------------
//
// Values are LSB-first in u32 words (BitPacker's layout): value j of a
// w-bit run occupies stream bits [j*w, (j+1)*w).  A value spans at
// most two words, so a branchless 64-bit two-word window + shift +
// mask recovers it.  The window unconditionally reads words[wi + 1];
// the caller guarantees one readable word past the run (in a mmapped
// artifact the next file section provides it — see
// serve.artifact.serve_columns).

template <int W>
void ServeUnpackW(const uint32_t* words, int n, uint32_t* out) {
  constexpr uint64_t mask = (1ull << W) - 1;
  int bp = 0;
  for (int j = 0; j < n; ++j, bp += W) {
    const int wi = bp >> 5;
    const uint64_t win = words[wi]
        | (static_cast<uint64_t>(words[wi + 1]) << 32);
    out[j] = static_cast<uint32_t>((win >> (bp & 31)) & mask);
  }
}

using ServeUnpackFn = void (*)(const uint32_t*, int, uint32_t*);

//: one specialization per width 1..31 (width 0 never unpacks; the
//: exporter's BitPacker caps widths at 31)
const ServeUnpackFn kServeUnpack[32] = {
    nullptr,           ServeUnpackW<1>,  ServeUnpackW<2>,
    ServeUnpackW<3>,   ServeUnpackW<4>,  ServeUnpackW<5>,
    ServeUnpackW<6>,   ServeUnpackW<7>,  ServeUnpackW<8>,
    ServeUnpackW<9>,   ServeUnpackW<10>, ServeUnpackW<11>,
    ServeUnpackW<12>,  ServeUnpackW<13>, ServeUnpackW<14>,
    ServeUnpackW<15>,  ServeUnpackW<16>, ServeUnpackW<17>,
    ServeUnpackW<18>,  ServeUnpackW<19>, ServeUnpackW<20>,
    ServeUnpackW<21>,  ServeUnpackW<22>, ServeUnpackW<23>,
    ServeUnpackW<24>,  ServeUnpackW<25>, ServeUnpackW<26>,
    ServeUnpackW<27>,  ServeUnpackW<28>, ServeUnpackW<29>,
    ServeUnpackW<30>,  ServeUnpackW<31>,
};

// ---- in-register delta prefix sum -----------------------------------
//
// ids[0] = first; ids[j + 1] = ids[j] + (vals[j] + 1) — the stored
// values are (delta - 1).  Integer adds are exact, so the SIMD and
// scalar forms agree bit-for-bit.

void ServePrefixIdsScalar(const uint32_t* vals, int m, int32_t first,
                          int32_t* out) {
  out[0] = first;
  int32_t run = first;
  for (int j = 0; j < m; ++j) {
    run += static_cast<int32_t>(vals[j]) + 1;
    out[j + 1] = run;
  }
}

#if defined(__x86_64__) || defined(_M_X64)
__attribute__((target("avx2")))
void ServePrefixIdsAvx2(const uint32_t* vals, int m, int32_t first,
                        int32_t* out) {
  out[0] = first;
  __m256i run = _mm256_set1_epi32(first);
  const __m256i one = _mm256_set1_epi32(1);
  const __m256i bcast7 = _mm256_set1_epi32(7);
  int j = 0;
  for (; j + 8 <= m; j += 8) {
    __m256i d = _mm256_add_epi32(
        _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(vals + j)), one);
    // in-lane inclusive scan (shift-and-add), then carry the low
    // lane's total into the high lane
    d = _mm256_add_epi32(d, _mm256_slli_si256(d, 4));
    d = _mm256_add_epi32(d, _mm256_slli_si256(d, 8));
    const __m256i tot = _mm256_shuffle_epi32(d, 0xff);
    d = _mm256_add_epi32(d, _mm256_permute2x128_si256(tot, tot, 0x08));
    d = _mm256_add_epi32(d, run);
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + 1 + j), d);
    run = _mm256_permutevar8x32_epi32(d, bcast7);
  }
  int32_t r = (j == 0) ? first : out[j];
  for (; j < m; ++j) {
    r += static_cast<int32_t>(vals[j]) + 1;
    out[j + 1] = r;
  }
}

const bool kHaveServeAvx2 = __builtin_cpu_supports("avx2");
#endif

inline void ServePrefixIds(const uint32_t* vals, int m, int32_t first,
                           int32_t* out) {
#if defined(__x86_64__) || defined(_M_X64)
  if (kHaveServeAvx2 && m >= 8) {
    ServePrefixIdsAvx2(vals, m, first, out);
    return;
  }
#endif
  ServePrefixIdsScalar(vals, m, first, out);
}

// ---- serve handle ----------------------------------------------------

struct ServeTermEntry {
  std::vector<int32_t> docs;        // ascending absolute doc ids
  std::vector<double> contrib;      // per-doc BM25 contribution
  std::vector<double> sorted_desc;  // contrib sorted descending
  double idf = 0.0;
};

//: one frozen ranked query (mri_serve_topk_prep): the occ/idf argument
//: arrays copied into the handle so the per-call entry point takes only
//: scalar arguments
struct ServePrep {
  std::vector<int32_t> occ;
  std::vector<double> idf;
};

struct ServeState {
  // borrowed artifact columns — the Python wrapper keeps the backing
  // buffers (mmap views + derived arrays) alive for the handle's life
  const int32_t* blk_max = nullptr;
  const int32_t* blk_first = nullptr;
  const uint8_t* blk_width = nullptr;
  const uint8_t* blk_tf_width = nullptr;
  const uint8_t* blk_max_tf = nullptr;  // u8 / u16-LE per score_bits
  const uint8_t* blk_min_dl = nullptr;  // (null on plain v2)
  const uint32_t* post_words = nullptr;
  const uint32_t* tf_words = nullptr;
  const double* doc_lens = nullptr;
  const int64_t* term_block_off = nullptr;  // vocab + 1
  const int32_t* blk_cnt = nullptr;
  const int64_t* blk_woff = nullptr;        // num_blocks + 1
  const int64_t* blk_tf_woff = nullptr;     // num_blocks + 1
  int32_t vocab = 0;
  int64_t num_blocks = 0;
  int32_t block_size = 0;
  int32_t score_bits = 0;
  int64_t num_docs = 0;
  double avgdl = 1.0, k1 = 1.2, b = 0.75;
  int32_t cache_cap = 4096;
  // per-term score memo (mirror of Engine._score_memo: cleared
  // wholesale at the cap, node-based so held pointers stay valid
  // across inserts)
  std::unordered_map<int32_t, ServeTermEntry> cache;
  // dense accumulator + epoch marks: touch-only reset between queries
  std::vector<double> acc;
  std::vector<uint32_t> mark;
  uint32_t epoch = 0;
  // scratch
  std::vector<uint32_t> vals;     // one block of raw unpacked values
  std::vector<int32_t> blk_ids;   // one decoded block (ids)
  std::vector<int32_t> blk_tf;    // one decoded block (tf)
  std::vector<int32_t> cand;      // candidate docs
  std::vector<double> partial;    // theta-maintenance scratch
  // registered ranked-path output buffers (mri_serve_set_topk_out)
  // plus the prepared-query registry (mri_serve_topk_prep) — borrowed
  // pointers, owned by the Python wrapper
  int32_t* out_docs = nullptr;
  double* out_scores = nullptr;
  int64_t* out_stats = nullptr;
  std::unordered_map<int64_t, ServePrep> preps;
  int64_t next_prep = 1;
};

inline uint32_t ServeNextEpoch(ServeState* st) {
  if (st->epoch > UINT32_MAX - 8) {
    std::fill(st->mark.begin(), st->mark.end(), 0u);
    st->epoch = 0;
  }
  return ++st->epoch;
}

// decode one block's doc ids into out (>= blk_cnt[b] slots); returns cnt
inline int ServeDecodeIds(const ServeState& st, int64_t b, int32_t* out) {
  const int cnt = st.blk_cnt[b];
  const int32_t first = st.blk_first[b];
  const int w = st.blk_width[b];
  if (cnt <= 1) {
    out[0] = first;
    return cnt;
  }
  if (w == 0) {  // all stored deltas are 0 -> consecutive ids
    for (int j = 0; j < cnt; ++j) out[j] = first + j;
    return cnt;
  }
  const uint32_t* words = st.post_words + st.blk_woff[b];
  uint32_t* scratch = const_cast<ServeState&>(st).vals.data();
  kServeUnpack[w](words, cnt - 1, scratch);
  ServePrefixIds(scratch, cnt - 1, first, out);
  return cnt;
}

// decode one block's term frequencies into out (>= cnt slots)
inline void ServeDecodeTf(const ServeState& st, int64_t b, int cnt,
                          int32_t* out) {
  const int w = st.blk_tf_width[b];
  if (w == 0) {  // stored (tf - 1) all zero -> tf 1 everywhere
    for (int j = 0; j < cnt; ++j) out[j] = 1;
    return;
  }
  const uint32_t* words = st.tf_words + st.blk_tf_woff[b];
  uint32_t* scratch = const_cast<ServeState&>(st).vals.data();
  kServeUnpack[w](words, cnt, scratch);
  for (int j = 0; j < cnt; ++j)
    out[j] = static_cast<int32_t>(scratch[j]) + 1;
}

// 3-distance prefetch for a forward walk over a term's blocks: run
// geometry far ahead, the posting payload those offsets feed closer
// in, the tf payload (touched right after the ids) last.
inline void ServePrefetchBlocks(const ServeState& st, int64_t bb,
                                int64_t b1) {
  if (bb + 8 < b1) {
    __builtin_prefetch(&st.blk_woff[bb + 8]);
    __builtin_prefetch(&st.blk_tf_woff[bb + 8]);
    __builtin_prefetch(&st.blk_first[bb + 8]);
  }
  if (bb + 2 < b1)
    __builtin_prefetch(st.post_words + st.blk_woff[bb + 2]);
  if (bb + 1 < b1)
    __builtin_prefetch(st.tf_words + st.blk_tf_woff[bb + 1]);
}

// BM25 contribution with the numpy scorer's exact expression and
// association order (see the header comment).
inline double ServeContrib(double idf, double tf, double dl, double om,
                           double k1, double b, double avgdl,
                           double k1p1) {
  const double denom = tf + k1 * (om + (b * dl) / avgdl);
  return ((idf * tf) * k1p1) / denom;
}

// decode + score one whole term into a cache entry; false on a doc id
// outside [0, num_docs) (corrupt artifact — never index doc_lens with
// it)
bool ServeFillEntry(ServeState* st, int32_t term, double idf,
                    ServeTermEntry* e) {
  const int64_t b0 = st->term_block_off[term];
  const int64_t b1 = st->term_block_off[term + 1];
  const int64_t nb = b1 - b0;
  const int64_t df = nb <= 0 ? 0
      : (nb - 1) * st->block_size + st->blk_cnt[b1 - 1];
  e->docs.resize(df);
  e->contrib.resize(df);
  e->idf = idf;
  const double om = 1.0 - st->b;
  const double k1p1 = st->k1 + 1.0;
  int64_t o = 0;
  for (int64_t bb = b0; bb < b1; ++bb) {
    ServePrefetchBlocks(*st, bb, b1);
    const int cnt = ServeDecodeIds(*st, bb, e->docs.data() + o);
    if (e->docs[o] < 0 || e->docs[o + cnt - 1] >= st->num_docs)
      return false;
    ServeDecodeTf(*st, bb, cnt, st->blk_tf.data());
    for (int j = 0; j < cnt; ++j) {
      e->contrib[o + j] = ServeContrib(
          idf, static_cast<double>(st->blk_tf[j]),
          st->doc_lens[e->docs[o + j]], om, st->k1, st->b, st->avgdl,
          k1p1);
    }
    o += cnt;
  }
  e->sorted_desc = e->contrib;
  std::sort(e->sorted_desc.begin(), e->sorted_desc.end(),
            std::greater<double>());
  return true;
}

// cached entry for a term, decoding + scoring on miss.  The cap sweep
// happens ONLY between queries (callers resolve all entries up front),
// so pointers into the node-based map never dangle mid-query.
ServeTermEntry* ServeGetEntry(ServeState* st, int32_t term, double idf) {
  auto it = st->cache.find(term);
  if (it != st->cache.end()) {
    if (it->second.idf == idf) return &it->second;
    st->cache.erase(it);  // idf changed (corpus override): rescore
  }
  // fill a local entry first: a bad_alloc mid-fill must never leave a
  // half-built entry behind for the next query to trust
  ServeTermEntry tmp;
  if (!ServeFillEntry(st, term, idf, &tmp)) return nullptr;
  ServeTermEntry* e = &st->cache[term];
  *e = std::move(tmp);
  return e;
}

// first index in a[lo, hi) with a[i] >= key (galloping from lo: the
// serve walks probe ascending keys, so lo is monotone)
template <typename T>
inline int64_t ServeGallopLower(const T* a, int64_t lo, int64_t hi,
                                T key) {
  if (lo >= hi || a[lo] >= key) return lo;
  int64_t prev = lo, step = 1;
  while (lo + step < hi && a[lo + step] < key) {
    prev = lo + step;
    step <<= 1;
  }
  int64_t l = prev + 1, h = std::min(lo + step, hi);
  while (l < h) {
    const int64_t mid = (l + h) >> 1;
    if (a[mid] < key) l = mid + 1; else h = mid;
  }
  return l;
}

// quantized per-block score column (u8 or u16-LE per score_bits)
inline uint32_t ServeScoreCol(const uint8_t* p, int score_bits,
                              int64_t i) {
  if (score_bits == 8) return p[i];
  return static_cast<uint32_t>(p[2 * i])
      | (static_cast<uint32_t>(p[2 * i + 1]) << 8);
}

// per-block BM25 upper bound — mirror of planner.block_upper_bounds:
// evaluate the contribution at (max tf, min dl); a saturated max-tf
// cell takes the tf->inf limit idf*(k1+1)
inline double ServeBlockUb(const ServeState& st, int64_t b, double idf) {
  const uint32_t cap = (1u << st.score_bits) - 1;
  const uint32_t mtf = ServeScoreCol(st.blk_max_tf, st.score_bits, b);
  if (mtf >= cap) return idf * (st.k1 + 1.0);
  const uint32_t mdl = ServeScoreCol(st.blk_min_dl, st.score_bits, b);
  return ServeContrib(idf, static_cast<double>(mtf),
                      static_cast<double>(mdl), 1.0 - st.b, st.k1,
                      st.b, st.avgdl, st.k1 + 1.0);
}

struct ServeHit {
  double score;
  int32_t doc;
};

inline bool ServeHitBetter(const ServeHit& a, const ServeHit& b) {
  if (a.score != b.score) return a.score > b.score;
  return a.doc < b.doc;
}

// top-k selection with the oracle's order: score descending, ties by
// ascending doc id (np.lexsort((cand, -scores)) semantics)
inline int64_t ServeSelectTopK(std::vector<ServeHit>* hits, int32_t k,
                               int32_t* out_docs, double* out_scores) {
  if (static_cast<int64_t>(hits->size()) > k) {
    std::nth_element(hits->begin(), hits->begin() + k, hits->end(),
                     ServeHitBetter);
    hits->resize(k);
  }
  std::sort(hits->begin(), hits->end(), ServeHitBetter);
  const int64_t n = static_cast<int64_t>(hits->size());
  for (int64_t j = 0; j < n; ++j) {
    out_docs[j] = (*hits)[j].doc;
    out_scores[j] = (*hits)[j].score;
  }
  return n;
}

//: per-query term view for the ranked evaluator
struct ServeQTerm {
  int32_t term = 0;
  int32_t w = 0;             // occurrence count in the query
  double idf = 0.0;
  ServeTermEntry* e = nullptr;  // null: not decoded (bound-only)
  double u = 0.0;            // w * (max contribution upper bound)
  int64_t b0 = 0, b1 = 0;
};

// stream the union of one or two doc-ascending contribution lists,
// calling f(score, doc) once per doc in ascending doc order.  Shared
// docs sum list-0-then-list-1 (the oracle's occurrence order); with
// ``dbl`` list 0 is a duplicated query term and every emit doubles
// (c + c — exactly w * c for w == 2).  Sequential scans only: the
// 1-2 term fast path runs through here with no dense accumulator,
// no epoch marks, and no candidate vector.
template <typename F>
inline void ServeScan2(const int32_t* d0, const double* c0, int64_t n0,
                       const int32_t* d1, const double* c1, int64_t n1,
                       bool dbl, F&& f) {
  int64_t i = 0, j = 0;
  while (i < n0 && j < n1) {
    const int32_t a = d0[i], b = d1[j];
    if (a < b) {
      f(c0[i], a);
      ++i;
    } else if (b < a) {
      f(c1[j], b);
      ++j;
    } else {
      f(c0[i] + c1[j], a);
      ++i;
      ++j;
    }
  }
  if (dbl) {
    for (; i < n0; ++i) f(c0[i] + c0[i], d0[i]);
  } else {
    for (; i < n0; ++i) f(c0[i], d0[i]);
  }
  for (; j < n1; ++j) f(c1[j], d1[j]);
}

}  // namespace

extern "C" {

void* mri_serve_new(
    const int32_t* blk_max, const int32_t* blk_first,
    const uint8_t* blk_width, const uint8_t* blk_tf_width,
    const uint8_t* blk_max_tf, const uint8_t* blk_min_dl,
    const uint32_t* post_words, const uint32_t* tf_words,
    const double* doc_lens, const int64_t* term_block_off,
    const int32_t* blk_cnt, const int64_t* blk_woff,
    const int64_t* blk_tf_woff, int32_t vocab, int64_t num_blocks,
    int32_t block_size, int32_t score_bits, int64_t num_docs,
    double avgdl, double k1, double b, int32_t cache_cap) try {
  if (vocab < 0 || num_blocks < 0 || num_docs < 0 || block_size < 2 ||
      (block_size & (block_size - 1)) != 0 || avgdl <= 0.0)
    return nullptr;
  if (!blk_max || !blk_first || !blk_width || !blk_tf_width ||
      !post_words || !tf_words || !doc_lens || !term_block_off ||
      !blk_cnt || !blk_woff || !blk_tf_woff)
    return nullptr;
  if (score_bits != 0 && score_bits != 8 && score_bits != 16)
    return nullptr;
  ServeState* st = new ServeState();
  st->blk_max = blk_max;
  st->blk_first = blk_first;
  st->blk_width = blk_width;
  st->blk_tf_width = blk_tf_width;
  st->blk_max_tf = blk_max_tf;
  st->blk_min_dl = blk_min_dl;
  st->post_words = post_words;
  st->tf_words = tf_words;
  st->doc_lens = doc_lens;
  st->term_block_off = term_block_off;
  st->blk_cnt = blk_cnt;
  st->blk_woff = blk_woff;
  st->blk_tf_woff = blk_tf_woff;
  st->vocab = vocab;
  st->num_blocks = num_blocks;
  st->block_size = block_size;
  st->score_bits = score_bits;
  st->num_docs = num_docs;
  st->avgdl = avgdl;
  st->k1 = k1;
  st->b = b;
  st->cache_cap = std::max(cache_cap, 1);
  st->acc.resize(num_docs, 0.0);
  st->mark.resize(num_docs, 0u);
  st->vals.resize(block_size);
  st->blk_ids.resize(block_size);
  st->blk_tf.resize(block_size);
  return st;
} catch (const std::bad_alloc&) {
  return nullptr;
}

void mri_serve_free(void* h) {
  delete static_cast<ServeState*>(h);
}

// decode the selected global block indices: out_ids is (n, block_size)
// int32 row-major, entries past a block's count repeating its last
// real doc id; out_tf (optional) likewise with 1s past the count —
// both exactly the numpy Artifact.decode_blocks /decode_tf_blocks
// padding so callers can swap backends per call.
int32_t mri_serve_decode_blocks(void* h, const int64_t* sel, int64_t n,
                                int32_t* out_ids, int32_t* out_tf,
                                int32_t* out_cnt) try {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || !sel || n < 0 || !out_ids || !out_cnt) return -1;
  const int B = st->block_size;
  for (int64_t r = 0; r < n; ++r) {
    if (sel[r] < 0 || sel[r] >= st->num_blocks) return -1;
    // 3-distance prefetch on the random block walk: geometry rows far
    // ahead, posting payloads nearer, tf payloads last
    if (r + 8 < n) {
      __builtin_prefetch(&st->blk_woff[sel[r + 8]]);
      __builtin_prefetch(&st->blk_first[sel[r + 8]]);
    }
    if (r + 2 < n)
      __builtin_prefetch(st->post_words + st->blk_woff[sel[r + 2]]);
    if (out_tf && r + 1 < n)
      __builtin_prefetch(st->tf_words + st->blk_tf_woff[sel[r + 1]]);
    int32_t* row = out_ids + r * B;
    const int cnt = ServeDecodeIds(*st, sel[r], row);
    const int32_t last = row[cnt - 1];
    for (int j = cnt; j < B; ++j) row[j] = last;
    if (out_tf) {
      int32_t* trow = out_tf + r * B;
      ServeDecodeTf(*st, sel[r], cnt, trow);
      for (int j = cnt; j < B; ++j) trow[j] = 1;
    }
    out_cnt[r] = cnt;
  }
  return 0;
} catch (const std::bad_alloc&) {
  return -2;
}

// decode one whole term: ascending doc ids (+ aligned tfs when out_tf
// is non-null); returns df, or a negative error
int64_t mri_serve_decode_postings(void* h, int32_t term,
                                  int32_t* out_docs, int32_t* out_tf) try {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || !out_docs || term < 0 || term >= st->vocab) return -1;
  const int64_t b0 = st->term_block_off[term];
  const int64_t b1 = st->term_block_off[term + 1];
  int64_t o = 0;
  for (int64_t bb = b0; bb < b1; ++bb) {
    ServePrefetchBlocks(*st, bb, b1);
    const int cnt = ServeDecodeIds(*st, bb, out_docs + o);
    if (out_tf) ServeDecodeTf(*st, bb, cnt, out_tf + o);
    o += cnt;
  }
  return o;
} catch (const std::bad_alloc&) {
  return -2;
}

// intersect the ascending candidate list against one term: blk_max
// routes each candidate to the single block that could hold it
// (galloping, monotone), only those blocks are ever bit-unpacked, and
// the in-block probe gallops too.  Returns the surviving count;
// stats2 = {blocks decoded, blocks skipped}.
int64_t mri_serve_and(void* h, const int32_t* cand, int64_t n,
                      int32_t term, int32_t* out, int64_t* stats2) try {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || (!cand && n > 0) || n < 0 || !out || !stats2 ||
      term < 0 || term >= st->vocab)
    return -1;
  const int64_t b0 = st->term_block_off[term];
  const int64_t b1 = st->term_block_off[term + 1];
  int64_t lo_blk = b0, cur_blk = -1, decoded = 0, m = 0, pos = 0;
  int cur_cnt = 0;
  for (int64_t t = 0; t < n; ++t) {
    const int32_t c = cand[t];
    lo_blk = ServeGallopLower(st->blk_max, lo_blk, b1, c);
    if (lo_blk >= b1) break;
    if (lo_blk != cur_blk) {
      if (lo_blk + 1 < b1)
        __builtin_prefetch(st->post_words + st->blk_woff[lo_blk + 1]);
      cur_cnt = ServeDecodeIds(*st, lo_blk, st->blk_ids.data());
      cur_blk = lo_blk;
      ++decoded;
      pos = 0;
    }
    pos = ServeGallopLower(st->blk_ids.data(), pos,
                           static_cast<int64_t>(cur_cnt), c);
    if (pos < cur_cnt && st->blk_ids[pos] == c) out[m++] = c;
  }
  stats2[0] = decoded;
  stats2[1] = (b1 - b0) - decoded;
  return m;
} catch (const std::bad_alloc&) {
  return -2;
}

// BM25 top-k over the query's occurrence list (occ[i] = lex index of
// the i-th scoring occurrence, absent terms already dropped; idf_occ
// aligned).  mode: 0 exhaustive, 1 block-max WAND, 2 MaxScore.
// Returns the result count (<= k), writing (doc, score) best-first
// with ties doc-ascending — byte-identical to the numpy Engine's
// top_k_scored.  stats3 = {blocks scored, blocks skipped, candidates}.
int64_t mri_serve_topk_bm25(void* h, const int32_t* occ, int32_t n_occ,
                            const double* idf_occ, int32_t k,
                            int32_t mode, int32_t* out_docs,
                            double* out_scores, int64_t* stats3) try {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || !occ || !idf_occ || !out_docs || !out_scores || !stats3 ||
      n_occ < 0 || mode < 0 || mode > 2)
    return -1;
  stats3[0] = stats3[1] = stats3[2] = 0;
  if (n_occ == 0 || k <= 0) return 0;
  for (int32_t i = 0; i < n_occ; ++i)
    if (occ[i] < 0 || occ[i] >= st->vocab) return -1;
  if (mode != 0 && (!st->blk_max_tf || !st->blk_min_dl ||
                    st->score_bits == 0))
    mode = 0;  // no bound columns: prune nothing, score everything
  // cap sweep BEFORE any entry pointer is taken (mirrors the numpy
  // memo's clear-at-cap; unordered_map nodes are stable under insert,
  // so held pointers survive the fills below)
  if (static_cast<int64_t>(st->cache.size()) + n_occ >
      static_cast<int64_t>(st->cache_cap))
    st->cache.clear();

  const double margin = kServeThetaMargin;

  // ---- fast path: <= 2 scoring occurrences ---------------------------
  // sums of one or two floats are order-independent, and w*c == c+c
  // exactly for w == 2, so a single dense accumulate in occurrence
  // order already carries the exhaustive bits.  The Zipf-head query mix
  // lives here, so the path is allocation-free: entries resolve into
  // the node-stable cache and the selection runs as a bounded insertion
  // into a stack array (same strict (score desc, doc asc) order as
  // ServeSelectTopK) whenever k fits.
  if (n_occ <= 2) {
    ServeTermEntry* e0 = ServeGetEntry(st, occ[0], idf_occ[0]);
    if (!e0) return -3;
    const bool dup = n_occ == 2 && occ[1] == occ[0];
    ServeTermEntry* e1 = nullptr;
    if (n_occ == 2) {
      e1 = dup ? e0 : ServeGetEntry(st, occ[1], idf_occ[1]);
      if (!e1) return -3;
    }
    // theta seed: the best k-th single-term contribution is a floor on
    // the k-th best final score (contributions are positive)
    double theta = 0.0;
    if (static_cast<int64_t>(e0->sorted_desc.size()) >= k) {
      theta = e0->sorted_desc[k - 1];
      if (dup) theta = 2.0 * theta;
    }
    if (e1 && !dup &&
        static_cast<int64_t>(e1->sorted_desc.size()) >= k) {
      const double t = e1->sorted_desc[k - 1];
      if (t > theta) theta = t;
    }
    const double thr = theta * margin;
    // union scores stream out of a sequential two-pointer merge (the
    // lists are doc-ascending) — see ServeScan2.  Emission order is
    // doc-ascending, so the bounded insertion below lands the same
    // strict (score desc, doc asc) order as ServeSelectTopK.
    const int32_t* d0 = e0->docs.data();
    const double* c0 = e0->contrib.data();
    const int64_t n0 = static_cast<int64_t>(e0->docs.size());
    const bool two = e1 != nullptr && !dup;
    const int32_t* d1 = two ? e1->docs.data() : nullptr;
    const double* c1 = two ? e1->contrib.data() : nullptr;
    const int64_t n1 = two ? static_cast<int64_t>(e1->docs.size()) : 0;
    int64_t npass = 0;
    if (k <= kServeStackK) {
      ServeHit top[kServeStackK];
      int32_t nk = 0;
      ServeScan2(d0, c0, n0, d1, c1, n1, dup, [&](double s, int32_t d) {
        if (theta > 0.0 && s < thr) return;
        ++npass;
        if (nk == k && !(s > top[k - 1].score ||
                         (s == top[k - 1].score && d < top[k - 1].doc)))
          return;
        int32_t p = nk < k ? nk : k - 1;
        while (p > 0 && (top[p - 1].score < s ||
                         (top[p - 1].score == s && top[p - 1].doc > d))) {
          top[p] = top[p - 1];
          --p;
        }
        top[p] = ServeHit{s, d};
        if (nk < k) ++nk;
      });
      stats3[2] = npass;
      for (int32_t j = 0; j < nk; ++j) {
        out_docs[j] = top[j].doc;
        out_scores[j] = top[j].score;
      }
      return nk;
    }
    std::vector<ServeHit> big;
    big.reserve(static_cast<size_t>(n0 + n1));
    ServeScan2(d0, c0, n0, d1, c1, n1, dup, [&](double s, int32_t d) {
      if (theta <= 0.0 || s >= thr) big.push_back(ServeHit{s, d});
    });
    stats3[2] = static_cast<int64_t>(big.size());
    return ServeSelectTopK(&big, k, out_docs, out_scores);
  }

  // unique terms in first-occurrence order
  std::vector<ServeQTerm> qt;
  qt.reserve(n_occ);
  for (int32_t i = 0; i < n_occ; ++i) {
    bool seen = false;
    for (ServeQTerm& q : qt)
      if (q.term == occ[i]) {
        ++q.w;
        seen = true;
        break;
      }
    if (seen) continue;
    ServeQTerm q;
    q.term = occ[i];
    q.w = 1;
    q.idf = idf_occ[i];
    q.b0 = st->term_block_off[q.term];
    q.b1 = st->term_block_off[q.term + 1];
    qt.push_back(q);
  }
  std::vector<ServeHit> hits;

  // ---- exhaustive (3+ occurrences) -----------------------------------
  if (mode == 0) {
    for (ServeQTerm& q : qt) {
      q.e = ServeGetEntry(st, q.term, q.idf);
      if (!q.e) return -3;
    }
    const uint32_t ep = ServeNextEpoch(st);
    st->cand.clear();
    // dense accumulate per OCCURRENCE in occurrence order — the
    // oracle's exact float addition order (duplicate terms add their
    // contribution once per occurrence, not w-multiplied)
    for (int32_t i = 0; i < n_occ; ++i) {
      const ServeTermEntry* e = nullptr;
      for (const ServeQTerm& q : qt)
        if (q.term == occ[i]) {
          e = q.e;
          break;
        }
      const int64_t df = static_cast<int64_t>(e->docs.size());
      for (int64_t j = 0; j < df; ++j) {
        const int32_t d = e->docs[j];
        if (st->mark[d] != ep) {
          st->mark[d] = ep;
          st->acc[d] = e->contrib[j];
          st->cand.push_back(d);
        } else {
          st->acc[d] += e->contrib[j];
        }
      }
    }
    hits.reserve(st->cand.size());
    for (const int32_t d : st->cand)
      hits.push_back(ServeHit{st->acc[d], d});
    const int64_t n = ServeSelectTopK(&hits, k, out_docs, out_scores);
    stats3[2] = n;
    return n;
  }

  // ---- BMW / MaxScore (3+ occurrences) -------------------------------
  // Terms sort by descending weighted upper bound; while the remaining
  // bounds can still reach theta a term is essential (every posting
  // admitted), past that point no new candidate can enter the top k.
  // Survivor scores are then re-accumulated in occurrence order, so
  // the output carries the exhaustive bits.
  int64_t scored_blocks = 0, skipped_blocks = 0;
  double theta = 0.0;
  for (ServeQTerm& q : qt) {
    auto it = st->cache.find(q.term);
    if (it != st->cache.end() && it->second.idf == q.idf) {
      q.e = &it->second;
      const std::vector<double>& srt = q.e->sorted_desc;
      q.u = srt.empty() ? 0.0
          : static_cast<double>(q.w) * srt[0];
      if (static_cast<int64_t>(srt.size()) >= k) {
        const double t = static_cast<double>(q.w) * srt[k - 1];
        if (t > theta) theta = t;
      }
    } else {
      double umax = 0.0;
      for (int64_t bb = q.b0; bb < q.b1; ++bb) {
        const double ub = ServeBlockUb(*st, bb, q.idf);
        if (ub > umax) umax = ub;
      }
      q.u = static_cast<double>(q.w) * umax;
    }
  }
  std::vector<int32_t> order(qt.size());
  for (size_t p = 0; p < qt.size(); ++p)
    order[p] = static_cast<int32_t>(p);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t bq) {
    if (qt[a].u != qt[bq].u) return qt[a].u > qt[bq].u;
    return qt[a].term < qt[bq].term;
  });
  const size_t nt = qt.size();
  std::vector<double> suffix(nt + 1, 0.0);
  for (size_t p = nt; p-- > 0;)
    suffix[p] = suffix[p + 1] + qt[order[p]].u;

  const uint32_t ep = ServeNextEpoch(st);
  st->cand.clear();
  size_t boundary = nt;
  for (size_t p = 0; p < nt; ++p) {
    if (theta > 0.0 && suffix[p] < theta * margin) {
      boundary = p;
      break;
    }
    ServeQTerm& q = qt[order[p]];
    if (!q.e) {
      q.e = ServeGetEntry(st, q.term, q.idf);
      if (!q.e) return -3;
    }
    scored_blocks += q.b1 - q.b0;
    const double w = static_cast<double>(q.w);
    const int64_t df = static_cast<int64_t>(q.e->docs.size());
    for (int64_t j = 0; j < df; ++j) {
      const int32_t d = q.e->docs[j];
      const double add = q.w == 1 ? q.e->contrib[j]
                                  : w * q.e->contrib[j];
      if (st->mark[d] != ep) {
        st->mark[d] = ep;
        st->acc[d] = add;
        st->cand.push_back(d);
      } else {
        st->acc[d] += add;
      }
    }
    // dynamic theta: the k-th best partial is a floor on the k-th
    // best final score (remaining contributions only add)
    if (static_cast<int64_t>(st->cand.size()) >= k) {
      st->partial.clear();
      st->partial.reserve(st->cand.size());
      for (const int32_t d : st->cand)
        st->partial.push_back(st->acc[d]);
      std::nth_element(st->partial.begin(), st->partial.begin() + (k - 1),
                       st->partial.end(), std::greater<double>());
      const double kth = st->partial[k - 1];
      if (kth > theta) theta = kth;
    }
  }
  // drop candidates that provably cannot reach theta even with every
  // remaining (non-essential) term's full bound
  const double tail = suffix[boundary];
  const double thr = theta * margin;
  std::vector<int32_t>& cands = st->cand;
  if (theta > 0.0) {
    size_t m = 0;
    for (const int32_t d : cands)
      if (st->acc[d] + tail >= thr) cands[m++] = d;
    cands.resize(m);
  }
  std::sort(cands.begin(), cands.end());
  stats3[2] = static_cast<int64_t>(cands.size());

  // exact rescore in occurrence order = the exhaustive addition order
  std::vector<double> scores(cands.size(), 0.0);
  const double om = 1.0 - st->b;
  const double k1p1 = st->k1 + 1.0;
  std::vector<bool> counted(nt, false);
  for (int32_t i = 0; i < n_occ && !cands.empty(); ++i) {
    ServeQTerm* q = nullptr;
    size_t qpos = 0;
    for (size_t p = 0; p < nt; ++p)
      if (qt[p].term == occ[i]) {
        q = &qt[p];
        qpos = p;
        break;
      }
    if (q->e) {
      // gallop-probe the term's decoded run at each candidate
      const int32_t* docs = q->e->docs.data();
      const int64_t df = static_cast<int64_t>(q->e->docs.size());
      int64_t pos = 0;
      int64_t touched = 0, last_blk = -1;
      const int shift = __builtin_ctz(st->block_size);
      for (size_t j = 0; j < cands.size(); ++j) {
        pos = ServeGallopLower(docs, pos, df, cands[j]);
        if (pos >= df) break;
        if (docs[pos] == cands[j]) {
          scores[j] += q->e->contrib[pos];
          const int64_t blk = pos >> shift;
          if (blk != last_blk) {
            ++touched;
            last_blk = blk;
          }
        }
      }
      if (!counted[qpos]) {
        counted[qpos] = true;
        bool essential = false;
        for (size_t p = 0; p < boundary; ++p)
          if (order[p] == static_cast<int32_t>(qpos)) {
            essential = true;
            break;
          }
        if (!essential) {
          // probe economy of a memoized non-essential term
          scored_blocks += touched;
          skipped_blocks += (q->b1 - q->b0) - touched;
        }
      }
    } else {
      // never decoded: route candidates through blk_max, decode only
      // the blocks they land in, score those postings on the fly with
      // the same expression (elementwise bit-equal to a full decode)
      int64_t lo_blk = q->b0, cur_blk = -1, decoded = 0, pos = 0;
      int cur_cnt = 0;
      for (size_t j = 0; j < cands.size(); ++j) {
        const int32_t c = cands[j];
        lo_blk = ServeGallopLower(st->blk_max, lo_blk, q->b1, c);
        if (lo_blk >= q->b1) break;
        if (lo_blk != cur_blk) {
          cur_cnt = ServeDecodeIds(*st, lo_blk, st->blk_ids.data());
          if (st->blk_ids[0] < 0 ||
              st->blk_ids[cur_cnt - 1] >= st->num_docs)
            return -3;
          ServeDecodeTf(*st, lo_blk, cur_cnt, st->blk_tf.data());
          cur_blk = lo_blk;
          ++decoded;
          pos = 0;
        }
        pos = ServeGallopLower(st->blk_ids.data(), pos,
                               static_cast<int64_t>(cur_cnt), c);
        if (pos < cur_cnt && st->blk_ids[pos] == c) {
          scores[j] += ServeContrib(
              q->idf, static_cast<double>(st->blk_tf[pos]),
              st->doc_lens[c], om, st->k1, st->b, st->avgdl, k1p1);
        }
      }
      if (!counted[qpos]) {
        counted[qpos] = true;
        scored_blocks += decoded;
        skipped_blocks += (q->b1 - q->b0) - decoded;
      }
    }
  }
  hits.reserve(cands.size());
  for (size_t j = 0; j < cands.size(); ++j)
    if (scores[j] > 0.0)
      hits.push_back(ServeHit{scores[j], cands[j]});
  stats3[0] = scored_blocks;
  stats3[1] = skipped_blocks;
  return ServeSelectTopK(&hits, k, out_docs, out_scores);
} catch (const std::bad_alloc&) {
  return -2;
}

// register reusable ranked-path output buffers on the handle — the
// warm-query entry points below then take only scalar arguments, so
// ctypes marshals 4 integers instead of 9 mixed pointers per call
// (argument conversion is a measurable share of a warm ranked query)
int64_t mri_serve_set_topk_out(void* h, int32_t* out_docs,
                               double* out_scores, int64_t* stats3) {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || !out_docs || !out_scores || !stats3) return -1;
  st->out_docs = out_docs;
  st->out_scores = out_scores;
  st->out_stats = stats3;
  return 0;
}

// freeze one query's (occ, idf) argument arrays into the handle;
// returns a prep id (>= 1) for mri_serve_topk_run, < 0 on error
int64_t mri_serve_topk_prep(void* h, const int32_t* occ, int32_t n_occ,
                            const double* idf_occ) try {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || !occ || !idf_occ || n_occ <= 0) return -1;
  for (int32_t i = 0; i < n_occ; ++i)
    if (occ[i] < 0 || occ[i] >= st->vocab) return -1;
  const int64_t id = st->next_prep++;
  ServePrep& p = st->preps[id];
  p.occ.assign(occ, occ + n_occ);
  p.idf.assign(idf_occ, idf_occ + n_occ);
  return id;
} catch (const std::bad_alloc&) {
  return -2;
}

// drop every prepared query (the engine clears its prep memo at the
// same cap as its other per-query memos)
int64_t mri_serve_topk_prep_clear(void* h) {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st) return -1;
  st->preps.clear();
  return 0;
}

// drop one prepared query (un-memoizable one-shot callers)
int64_t mri_serve_topk_prep_free(void* h, int64_t prep) {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st) return -1;
  st->preps.erase(prep);
  return 0;
}

// ranked query over a prepared id, writing into the buffers registered
// by mri_serve_set_topk_out
int64_t mri_serve_topk_run(void* h, int64_t prep, int32_t k,
                           int32_t mode) try {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || !st->out_docs) return -1;
  auto it = st->preps.find(prep);
  if (it == st->preps.end()) return -1;
  const ServePrep& p = it->second;
  return mri_serve_topk_bm25(h, p.occ.data(),
                             static_cast<int32_t>(p.occ.size()),
                             p.idf.data(), k, mode, st->out_docs,
                             st->out_scores, st->out_stats);
} catch (const std::bad_alloc&) {
  return -2;
}

// coalesced ranked batch: answer nq prepared queries in ONE library
// crossing.  Query i writes its hits at out_docs/out_scores[i * k]
// and its hit count into out_n[i]; stats3 accumulates the batch's
// block economy (blocks scored, blocks skipped, candidates) across
// all queries.  Every query must resolve to a valid prep id — any
// failure returns < 0 and the caller re-runs the batch per query.
int64_t mri_serve_topk_batch(void* h, const int64_t* preps,
                             const int32_t* modes, int32_t nq,
                             int32_t k, int32_t* out_docs,
                             double* out_scores, int32_t* out_n,
                             int64_t* stats3) try {
  ServeState* st = static_cast<ServeState*>(h);
  if (!st || !preps || !modes || !out_docs || !out_scores || !out_n ||
      !stats3 || nq <= 0 || k <= 0)
    return -1;
  stats3[0] = stats3[1] = stats3[2] = 0;
  int64_t q_stats[3];
  for (int32_t i = 0; i < nq; ++i) {
    auto it = st->preps.find(preps[i]);
    if (it == st->preps.end()) return -1;
    const ServePrep& p = it->second;
    const int64_t n = mri_serve_topk_bm25(
        h, p.occ.data(), static_cast<int32_t>(p.occ.size()),
        p.idf.data(), k, modes[i], out_docs + int64_t{i} * k,
        out_scores + int64_t{i} * k, q_stats);
    if (n < 0) return n;
    out_n[i] = static_cast<int32_t>(n);
    stats3[0] += q_stats[0];
    stats3[1] += q_stats[1];
    stats3[2] += q_stats[2];
  }
  return nq;
} catch (const std::bad_alloc&) {
  return -2;
}

}  // extern "C"
