// Native host tokenizer: the map phase's hot loop, one pass in C++.
//
// Re-implements (TPU-framework-style, not a translation) what the
// reference mapper does per token — fscanf whitespace split, delete
// non-letters, lowercase, cap at 299 letters (main.c:102-117) — plus
// what its reducer re-derives later: the term dictionary.  Output is
// the integer corpus the device engine consumes: per-token sorted-vocab
// term ids + doc ids, the packed sorted vocab, and first-letter ids.
//
// Single allocation arena for cleaned words, open-addressing FNV-1a
// hash table with power-of-two growth; final std::sort over unique
// words only (vocab-scale, not token-scale).
//
// Build: g++ -O3 -shared -fPIC -o libmri_tokenizer.so tokenizer.cc

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <string>
#include <vector>

namespace {

constexpr int kMaxWordLetters = 299;  // reference MAX_WORD - 1 (main.c:7,105)

struct Entry {
  uint32_t offset;  // into arena
  uint32_t len;
  int32_t id;       // provisional (first-occurrence) id; -1 = empty slot
};

inline bool IsSpace(uint8_t b) {
  // C-locale isspace set, what fscanf %s splits on (main.c:102).
  return b == ' ' || b == '\t' || b == '\n' || b == '\v' || b == '\f' || b == '\r';
}

inline uint64_t Fnv1a(const uint8_t* p, uint32_t len) {
  uint64_t h = 1469598103934665603ull;
  for (uint32_t i = 0; i < len; ++i) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

}  // namespace

extern "C" {

struct TokenizeResult {
  int64_t num_tokens;   // emitted pairs (== raw tokens unless dedup_pairs)
  int64_t raw_tokens;   // tokens scanned before the combiner
  int32_t vocab_size;
  int32_t vocab_width;
  int32_t* term_ids;        // [num_tokens], sorted-vocab ids
  int32_t* doc_ids;         // [num_tokens]
  uint8_t* vocab_packed;    // [vocab_size * vocab_width], NUL padded, sorted
  int32_t* letter_of_term;  // [vocab_size]
};

// data: concatenated document bytes; doc_ends[i] = exclusive end offset of
// doc i; doc_id_values[i] = its (1-based) doc id.  dedup_pairs != 0
// enables the combiner: each (term, doc) pair is emitted once (the
// reference reducer's dedup, main.c:176-184, pulled forward into the map
// phase — output-invariant, shrinks the device feed ~4x on real text).
// Returns NULL on OOM.
TokenizeResult* mri_tokenize(const uint8_t* data, int64_t len,
                             const int64_t* doc_ends,
                             const int32_t* doc_id_values, int32_t num_docs,
                             int32_t dedup_pairs) {
  std::vector<uint8_t> arena;
  arena.reserve(1 << 20);
  std::vector<Entry> table(1 << 16);
  for (auto& e : table) e.id = -1;
  uint64_t mask = table.size() - 1;
  int32_t next_id = 0;

  std::vector<int32_t> tok_terms;
  std::vector<int32_t> tok_docs;
  tok_terms.reserve(len / 6 + 16);
  tok_docs.reserve(len / 6 + 16);

  std::vector<uint32_t> word_offsets;  // provisional id -> arena offset
  std::vector<uint32_t> word_lens;
  std::vector<int32_t> last_doc;       // provisional id -> last doc ordinal seen

  int64_t raw_tokens = 0;
  uint8_t word[kMaxWordLetters];
  int64_t pos = 0;
  for (int32_t d = 0; d < num_docs; ++d) {
    const int64_t end = doc_ends[d];
    const int32_t doc_id = doc_id_values[d];
    while (pos < end) {
      // skip to next token start (whitespace run)
      int wlen = 0;
      bool in_token = false;
      for (; pos < end; ++pos) {
        const uint8_t b = data[pos];
        if (IsSpace(b)) {
          if (in_token) break;  // token finished
          continue;
        }
        in_token = true;
        // clean: keep letters only, lowercase, cap at 299
        if (b >= 'A' && b <= 'Z') {
          if (wlen < kMaxWordLetters) word[wlen++] = b + 32;
        } else if (b >= 'a' && b <= 'z') {
          if (wlen < kMaxWordLetters) word[wlen++] = b;
        }
      }
      if (!in_token) break;  // trailing whitespace
      if (wlen == 0) continue;  // token cleaned to nothing (main.c:113)

      // hash-table upsert
      const uint64_t h = Fnv1a(word, wlen);
      uint64_t slot = h & mask;
      int32_t id = -1;
      for (;;) {
        Entry& e = table[slot];
        if (e.id < 0) {
          // insert
          const uint32_t off = static_cast<uint32_t>(arena.size());
          arena.insert(arena.end(), word, word + wlen);
          e.offset = off;
          e.len = wlen;
          e.id = next_id;
          word_offsets.push_back(off);
          word_lens.push_back(wlen);
          last_doc.push_back(-1);
          id = next_id++;
          break;
        }
        if (e.len == static_cast<uint32_t>(wlen) &&
            std::memcmp(arena.data() + e.offset, word, wlen) == 0) {
          id = e.id;
          break;
        }
        slot = (slot + 1) & mask;
      }
      ++raw_tokens;
      if (dedup_pairs) {
        if (last_doc[id] == d) continue;  // (term, doc) already emitted
        last_doc[id] = d;
      }
      tok_terms.push_back(id);
      tok_docs.push_back(doc_id);

      // grow at 0.7 load
      if (static_cast<uint64_t>(next_id) * 10 > table.size() * 7) {
        std::vector<Entry> bigger(table.size() * 2);
        for (auto& e : bigger) e.id = -1;
        const uint64_t bmask = bigger.size() - 1;
        for (const Entry& e : table) {
          if (e.id < 0) continue;
          uint64_t s = Fnv1a(arena.data() + e.offset, e.len) & bmask;
          while (bigger[s].id >= 0) s = (s + 1) & bmask;
          bigger[s] = e;
        }
        table.swap(bigger);
        mask = bmask;
      }
    }
    pos = end;
  }

  const int32_t vocab = next_id;
  // sort unique words lexicographically (== strcmp order: letters only)
  std::vector<int32_t> order(vocab);
  for (int32_t i = 0; i < vocab; ++i) order[i] = i;
  const uint8_t* base = arena.data();
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    const uint32_t la = word_lens[a], lb = word_lens[b];
    const int c = std::memcmp(base + word_offsets[a], base + word_offsets[b],
                              la < lb ? la : lb);
    if (c != 0) return c < 0;
    return la < lb;
  });

  int32_t width = 1;
  for (int32_t i = 0; i < vocab; ++i)
    width = std::max(width, static_cast<int32_t>(word_lens[i]));

  auto* res = static_cast<TokenizeResult*>(std::malloc(sizeof(TokenizeResult)));
  if (!res) return nullptr;
  const int64_t n = static_cast<int64_t>(tok_terms.size());
  res->num_tokens = n;
  res->raw_tokens = raw_tokens;
  res->vocab_size = vocab;
  res->vocab_width = width;
  res->term_ids = static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max<int64_t>(n, 1)));
  res->doc_ids = static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max<int64_t>(n, 1)));
  res->vocab_packed = static_cast<uint8_t*>(
      std::calloc(std::max<int64_t>(static_cast<int64_t>(vocab) * width, 1), 1));
  res->letter_of_term = static_cast<int32_t*>(std::malloc(sizeof(int32_t) * std::max(vocab, 1)));
  if (!res->term_ids || !res->doc_ids || !res->vocab_packed || !res->letter_of_term) {
    std::free(res->term_ids); std::free(res->doc_ids);
    std::free(res->vocab_packed); std::free(res->letter_of_term); std::free(res);
    return nullptr;
  }

  // provisional id -> sorted id remap; pack vocab rows
  std::vector<int32_t> remap(vocab);
  for (int32_t rank = 0; rank < vocab; ++rank) {
    const int32_t prov = order[rank];
    remap[prov] = rank;
    std::memcpy(res->vocab_packed + static_cast<int64_t>(rank) * width,
                base + word_offsets[prov], word_lens[prov]);
    res->letter_of_term[rank] = res->vocab_packed[static_cast<int64_t>(rank) * width] - 'a';
  }
  for (int64_t i = 0; i < n; ++i) {
    res->term_ids[i] = remap[tok_terms[i]];
    res->doc_ids[i] = tok_docs[i];
  }
  return res;
}

void mri_free_result(TokenizeResult* r) {
  if (!r) return;
  std::free(r->term_ids);
  std::free(r->doc_ids);
  std::free(r->vocab_packed);
  std::free(r->letter_of_term);
  std::free(r);
}

// ---------------------------------------------------------------------------
// Native emit: render the 26 <letter>.txt postings files.
//
// Byte-identical to the reference's fprintf loop (main.c:227-234):
// "word:[id1 id2 ... idN]\n", ids space separated, no trailing space.
// Terms arrive pre-ordered (order[]); letters are contiguous in that
// order because term ids follow sorted-vocab order.
// ---------------------------------------------------------------------------

namespace {

inline char* PutU32(char* p, uint32_t v) {
  char tmp[10];
  int n = 0;
  do {
    tmp[n++] = '0' + (v % 10);
    v /= 10;
  } while (v);
  while (n) *p++ = tmp[--n];
  return p;
}

}  // namespace

// postings16/postings32: exactly one is non-null.  order/df/offsets are
// int64 (numpy's native index types).  Returns total bytes written, or
// -1 on IO error.
int64_t mri_emit(const uint8_t* vocab_packed, int32_t vocab_size, int32_t width,
                 const int64_t* order, const int64_t* df, const int64_t* offsets,
                 const uint16_t* postings16, const int32_t* postings32,
                 const char* out_dir) {
  std::vector<char> buf;
  buf.reserve(1 << 22);
  std::string dir(out_dir);
  if (!dir.empty() && dir.back() != '/') dir += '/';
  int64_t total = 0;
  int32_t idx = 0;
  for (int letter = 0; letter < 26; ++letter) {
    buf.clear();
    for (; idx < vocab_size; ++idx) {
      const int64_t t = order[idx];
      const uint8_t* w = vocab_packed + static_cast<int64_t>(t) * width;
      if (w[0] - 'a' != letter) break;
      // word (NUL-padded row)
      int wl = 0;
      while (wl < width && w[wl]) ++wl;
      const size_t need = buf.size() + wl + 2 + 11ull * df[t] + 2;
      if (buf.capacity() < need) buf.reserve(need * 2);
      const size_t old = buf.size();
      buf.resize(old + wl + 2);
      std::memcpy(buf.data() + old, w, wl);
      buf[old + wl] = ':';
      buf[old + wl + 1] = '[';
      const int64_t start = offsets[t], n = df[t];
      // ids
      char* p;
      buf.resize(buf.size() + 11ull * n + 2);
      p = buf.data() + old + wl + 2;
      for (int64_t k = 0; k < n; ++k) {
        if (k) *p++ = ' ';
        const uint32_t v = postings16 ? postings16[start + k]
                                      : static_cast<uint32_t>(postings32[start + k]);
        p = PutU32(p, v);
      }
      *p++ = ']';
      *p++ = '\n';
      buf.resize(p - buf.data());
    }
    std::string path = dir;
    path += static_cast<char>('a' + letter);
    path += ".txt";
    FILE* f = std::fopen(path.c_str(), "wb");
    if (!f) return -1;
    if (!buf.empty() && std::fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
      std::fclose(f);
      return -1;
    }
    std::fclose(f);
    total += static_cast<int64_t>(buf.size());
  }
  return total;
}

}  // extern "C"
