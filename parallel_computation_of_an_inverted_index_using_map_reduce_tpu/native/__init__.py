"""ctypes loader for the native host runtime (C++ tokenizer).

The reference's performance-critical host code is C (the whole program);
here the host hot path — tokenize + vocab build, the analogue of
main.c:102-117 plus the reducer's dictionary — is a C++ library compiled
on first use with the system toolchain and loaded via ctypes (no
pybind11 in this image).  Everything degrades gracefully to the
vectorized numpy path if no compiler is available.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import re
import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

from ..utils import envknobs

_SRC = Path(__file__).parent / "tokenizer.cc"
_lib = None
_lib_error: str | None = None
_lib_variant: str | None = None


class _TokenizeResult(ctypes.Structure):
    _fields_ = [
        ("num_tokens", ctypes.c_int64),
        ("raw_tokens", ctypes.c_int64),
        ("vocab_size", ctypes.c_int32),
        ("vocab_width", ctypes.c_int32),
        ("term_ids", ctypes.POINTER(ctypes.c_int32)),
        ("doc_ids", ctypes.POINTER(ctypes.c_int32)),
        ("vocab_packed", ctypes.POINTER(ctypes.c_uint8)),
        ("letter_of_term", ctypes.POINTER(ctypes.c_int32)),
    ]


class _StreamChunkResult(ctypes.Structure):
    _fields_ = [
        ("num_pairs", ctypes.c_int64),
        ("raw_tokens", ctypes.c_int64),
        ("keys", ctypes.POINTER(ctypes.c_int32)),
    ]


class _StreamChunkU16Result(ctypes.Structure):
    _fields_ = [
        ("num_pairs", ctypes.c_int64),
        ("raw_tokens", ctypes.c_int64),
        ("padded", ctypes.c_int64),
        ("feed_u16", ctypes.POINTER(ctypes.c_uint16)),
        ("keys", ctypes.POINTER(ctypes.c_int32)),
    ]


class _HostIndexStats(ctypes.Structure):
    _fields_ = [
        ("raw_tokens", ctypes.c_int64),
        ("num_pairs", ctypes.c_int64),
        ("vocab_size", ctypes.c_int32),
        ("bytes_written", ctypes.c_int64),
    ]


class _HostStreamStats(ctypes.Structure):
    _fields_ = [
        ("raw_tokens", ctypes.c_int64),
        ("num_pairs", ctypes.c_int64),
        ("vocab_size", ctypes.c_int32),
        ("reserved", ctypes.c_int32),
        ("bytes_written", ctypes.c_int64),
        ("scan_ns", ctypes.c_int64),
        ("finalize_ns", ctypes.c_int64),
        ("emit_ns", ctypes.c_int64),
    ]


class _StreamFinalResult(ctypes.Structure):
    _fields_ = [
        ("vocab_size", ctypes.c_int32),
        ("vocab_width", ctypes.c_int32),
        ("raw_tokens", ctypes.c_int64),
        ("num_pairs", ctypes.c_int64),
        ("vocab_packed", ctypes.POINTER(ctypes.c_uint8)),
        ("letter_of_term", ctypes.POINTER(ctypes.c_int32)),
        ("remap", ctypes.POINTER(ctypes.c_int32)),
        ("df", ctypes.POINTER(ctypes.c_int32)),
        ("emit_order", ctypes.POINTER(ctypes.c_int32)),
    ]


def _build_dirs():
    yield Path(__file__).parent / "_build"
    yield Path(tempfile.gettempdir()) / f"mri_tpu_native_{os.getuid()}"


# -march=native would SIGILL if a prebuilt .so ever moved across machines;
# plain -O3 is within noise for this workload.
_CXX_FLAGS = ["-O3", "-shared", "-fPIC"]

#: MRI_NATIVE_SANITIZE selects a hardened build variant; sanitized .so
#: names carry the variant in their stem so an ASan build can never
#: shadow (or be pruned by) the production library.  Loading the asan
#: variant into CPython needs LD_PRELOAD=libasan.so (see Makefile
#: test-native-asan); ubsan links its runtime via DT_NEEDED.
_SANITIZE_FLAGS = {
    "": [],
    "asan": ["-fsanitize=address", "-fno-omit-frame-pointer", "-g"],
    "ubsan": ["-fsanitize=undefined", "-fno-sanitize-recover=undefined",
              "-g"],
}

#: exact-name pattern per variant: the production glob
#: ``libmri_tokenizer_*`` would otherwise match (and prune) the
#: sanitizer-suffixed builds too
_SO_NAME_RE = {
    "": re.compile(r"libmri_tokenizer_[0-9a-f]{12}\.so\Z"),
    "asan": re.compile(r"libmri_tokenizer_asan_[0-9a-f]{12}\.so\Z"),
    "ubsan": re.compile(r"libmri_tokenizer_ubsan_[0-9a-f]{12}\.so\Z"),
}


def _prune_stale(d: Path, keep: str, variant: str = "") -> None:
    """Drop hashed builds of ``variant`` other than ``keep`` (and
    orphaned .tmp files of any variant) from a build dir — every source
    edit otherwise leaves a dead ~100 KB artifact behind forever.
    Other variants' current builds are left alone.  Best-effort: a
    concurrent process may hold an old .so open; unlink still works on
    POSIX, and failures are ignored."""
    name_re = _SO_NAME_RE[variant]
    try:
        stale = [p for p in d.glob("libmri_tokenizer_*.so")
                 if p.name != keep and name_re.match(p.name)]
        stale += list(d.glob("libmri_tokenizer_*.tmp"))
    except OSError:
        return
    for p in stale:
        try:
            p.unlink()
        except OSError:
            pass


def _compile(variant: str = "") -> Path:
    flags = _CXX_FLAGS + _SANITIZE_FLAGS[variant]
    src = _SRC.read_bytes()
    tag = hashlib.md5(src + " ".join(flags).encode()).hexdigest()[:12]
    stem = "libmri_tokenizer" + (f"_{variant}" if variant else "")
    name = f"{stem}_{tag}.so"
    last_err: Exception | None = None
    for d in _build_dirs():
        so = d / name
        if so.exists():
            _prune_stale(d, name, variant)
            return so
        try:
            d.mkdir(parents=True, exist_ok=True)
            tmp = so.with_suffix(f".{os.getpid()}.tmp")
            subprocess.run(
                ["g++", *flags, "-o", str(tmp), str(_SRC)],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(tmp, so)
            _prune_stale(d, name, variant)
            return so
        except (OSError, subprocess.SubprocessError) as e:
            last_err = e
    raise RuntimeError(f"native build failed: {last_err}")


def load_error() -> str | None:
    """Why :func:`load` returned None, if it did."""
    return _lib_error


def load():
    """The compiled library, or None (with the reason cached).

    The MRI_NATIVE_SANITIZE variant is re-read on every call; flipping
    it invalidates the cached handle so a test process can opt into
    the sanitized build it was launched for."""
    global _lib, _lib_error, _lib_variant
    variant = envknobs.get("MRI_NATIVE_SANITIZE")
    if variant != _lib_variant:
        _lib, _lib_error, _lib_variant = None, None, variant
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        lib = ctypes.CDLL(str(_compile(variant)))
        lib.mri_tokenize.restype = ctypes.POINTER(_TokenizeResult)
        lib.mri_tokenize.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ]
        lib.mri_free_result.restype = None
        lib.mri_free_result.argtypes = [ctypes.POINTER(_TokenizeResult)]
        lib.mri_stream_new_mt.restype = ctypes.c_void_p
        lib.mri_stream_new_mt.argtypes = [ctypes.c_int64, ctypes.c_int32]
        lib.mri_stream_free.restype = None
        lib.mri_stream_free.argtypes = [ctypes.c_void_p]
        lib.mri_stream_feed.restype = ctypes.POINTER(_StreamChunkResult)
        lib.mri_stream_feed.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.mri_stream_chunk_free.restype = None
        lib.mri_stream_chunk_free.argtypes = [ctypes.POINTER(_StreamChunkResult)]
        lib.mri_stream_feed_u16.restype = ctypes.POINTER(_StreamChunkU16Result)
        lib.mri_stream_feed_u16.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int64,
        ]
        lib.mri_stream_chunk_u16_free.restype = None
        lib.mri_stream_chunk_u16_free.argtypes = [
            ctypes.POINTER(_StreamChunkU16Result)]
        lib.mri_stream_df_snapshot.restype = ctypes.c_int32
        lib.mri_stream_df_snapshot.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.mri_stream_finalize.restype = ctypes.POINTER(_StreamFinalResult)
        lib.mri_stream_finalize.argtypes = [ctypes.c_void_p]
        lib.mri_stream_final_free.restype = None
        lib.mri_stream_final_free.argtypes = [ctypes.POINTER(_StreamFinalResult)]
        lib.mri_host_index.restype = ctypes.c_int32
        lib.mri_host_index.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_char_p, ctypes.POINTER(_HostIndexStats),
            ctypes.c_int32,
        ]
        lib.mri_hidx_new.restype = ctypes.c_void_p
        lib.mri_hidx_new.argtypes = []
        lib.mri_hidx_free.restype = None
        lib.mri_hidx_free.argtypes = [ctypes.c_void_p]
        lib.mri_hidx_feed.restype = ctypes.c_int32
        lib.mri_hidx_feed.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.mri_hidx_finalize_emit.restype = ctypes.c_int32
        lib.mri_hidx_finalize_emit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(_HostStreamStats),
        ]
        lib.mri_hidx_partial.restype = ctypes.c_int32
        lib.mri_hidx_partial.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_hidx_info.restype = ctypes.c_int32
        lib.mri_hidx_info.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_hidx_runpack_info.restype = ctypes.c_int32
        lib.mri_hidx_runpack_info.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_hidx_runpack.restype = ctypes.c_int32
        lib.mri_hidx_runpack.argtypes = [
            ctypes.c_void_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_hidxm_audit.restype = ctypes.c_int32
        lib.mri_hidxm_audit.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ]
        lib.mri_hidxm_new.restype = ctypes.c_void_p
        lib.mri_hidxm_new.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32,
            ctypes.POINTER(_HostStreamStats),
        ]
        lib.mri_hidxm_free.restype = None
        lib.mri_hidxm_free.argtypes = [ctypes.c_void_p]
        lib.mri_hidxm_emit_range.restype = ctypes.c_int64
        lib.mri_hidxm_emit_range.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p,
        ]
        lib.mri_hidxm_export_info.restype = ctypes.c_int32
        lib.mri_hidxm_export_info.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_hidxm_export_payload.restype = ctypes.c_int32
        lib.mri_hidxm_export_payload.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.mri_hidxm_export_v2_prepare.restype = ctypes.c_int32
        lib.mri_hidxm_export_v2_prepare.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_hidxm_export_v2_payload.restype = ctypes.c_int32
        lib.mri_hidxm_export_v2_payload.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ]
        lib.mri_hidxm_export.restype = ctypes.c_int32
        lib.mri_hidxm_export.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_token_stats.restype = ctypes.c_int32
        lib.mri_token_stats.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.mri_emit.restype = ctypes.c_int64
        lib.mri_emit.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint16), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.mri_emit_runs.restype = ctypes.c_int64
        lib.mri_emit_runs.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint16)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_char_p,
        ]
        lib.mri_serve_new.restype = ctypes.c_void_p
        lib.mri_serve_new.argtypes = [
            ctypes.POINTER(ctypes.c_int32),   # blk_max
            ctypes.POINTER(ctypes.c_int32),   # blk_first
            ctypes.POINTER(ctypes.c_uint8),   # blk_width
            ctypes.POINTER(ctypes.c_uint8),   # blk_tf_width
            ctypes.POINTER(ctypes.c_uint8),   # blk_max_tf (raw bytes|NULL)
            ctypes.POINTER(ctypes.c_uint8),   # blk_min_dl (raw bytes|NULL)
            ctypes.POINTER(ctypes.c_uint32),  # post_words
            ctypes.POINTER(ctypes.c_uint32),  # tf_words
            ctypes.POINTER(ctypes.c_double),  # doc_lens
            ctypes.POINTER(ctypes.c_int64),   # term_block_off
            ctypes.POINTER(ctypes.c_int32),   # blk_cnt
            ctypes.POINTER(ctypes.c_int64),   # blk_woff
            ctypes.POINTER(ctypes.c_int64),   # blk_tf_woff
            ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_int32,
        ]
        lib.mri_serve_free.restype = None
        lib.mri_serve_free.argtypes = [ctypes.c_void_p]
        lib.mri_serve_decode_blocks.restype = ctypes.c_int32
        lib.mri_serve_decode_blocks.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.mri_serve_decode_postings.restype = ctypes.c_int64
        lib.mri_serve_decode_postings.argtypes = [
            ctypes.c_void_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.mri_serve_and.restype = ctypes.c_int64
        lib.mri_serve_and.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_serve_topk_bm25.restype = ctypes.c_int64
        lib.mri_serve_topk_bm25.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_serve_set_topk_out.restype = ctypes.c_int64
        lib.mri_serve_set_topk_out.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mri_serve_topk_prep.restype = ctypes.c_int64
        lib.mri_serve_topk_prep.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
        ]
        lib.mri_serve_topk_prep_clear.restype = ctypes.c_int64
        lib.mri_serve_topk_prep_clear.argtypes = [ctypes.c_void_p]
        lib.mri_serve_topk_prep_free.restype = ctypes.c_int64
        lib.mri_serve_topk_prep_free.argtypes = [
            ctypes.c_void_p, ctypes.c_int64]
        lib.mri_serve_topk_run.restype = ctypes.c_int64
        lib.mri_serve_topk_run.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32,
        ]
        # raw-address argtypes: the coalesced hot path passes
        # array.array/ndarray buffer addresses as plain ints, skipping
        # per-call ctypes pointer casts
        lib.mri_serve_topk_batch.restype = ctypes.c_int64
        lib.mri_serve_topk_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        _lib = lib
    except (OSError, RuntimeError) as e:
        _lib_error = str(e)
        print(f"warning: native tokenizer unavailable ({e}); using numpy path",
              file=sys.stderr)
    return _lib


def available() -> bool:
    return load() is not None


def token_stats(buf: np.ndarray, ends: np.ndarray):
    """Native ``(token_count, max_cleaned_len)`` over one byte window
    (``mri_token_stats``, SIMD masks) — the fast path behind
    ops/device_tokenizer.host_token_stats, byte-for-byte the same
    contract as its numpy mirror.  ``None`` when the library is
    unavailable."""
    lib = load()
    if lib is None:
        return None
    b = np.ascontiguousarray(buf, dtype=np.uint8)
    e = np.ascontiguousarray(ends, dtype=np.int64)
    count = ctypes.c_int64()
    max_len = ctypes.c_int32()
    rc = lib.mri_token_stats(
        b.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(b.shape[0]),
        e.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int32(e.shape[0]),
        ctypes.byref(count), ctypes.byref(max_len))
    if rc != 0:
        return None
    return int(count.value), int(max_len.value)


def _marshal_docs(contents: list[bytes], doc_ids: list[int]):
    """ctypes arguments for the document-window C entry points:
    ``(data_ptr, data_len, ends_ptr, ids_ptr, n_docs), keepalive`` —
    NULL pointers for empty input.  Hold ``keepalive`` across the call
    so the backing numpy arrays outlive the native read."""
    buf = b"".join(contents)
    data = np.frombuffer(buf, dtype=np.uint8)
    ends = np.cumsum(np.array([len(c) for c in contents], dtype=np.int64))
    ids = np.asarray(doc_ids, dtype=np.int32)
    n_docs = len(contents)

    def ptr(arr, ctype, nonempty):
        if not nonempty:
            return ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctype))
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    args = (
        ptr(data, ctypes.c_uint8, data.size),
        ctypes.c_int64(data.size),
        ptr(ends, ctypes.c_int64, n_docs),
        ptr(ids, ctypes.c_int32, n_docs),
        ctypes.c_int32(n_docs),
    )
    return args, (buf, data, ends, ids)


def default_threads() -> int:
    """Auto map-phase thread count: the cores we have, capped — the scan
    saturates memory bandwidth long before high core counts pay off."""
    return max(1, min(os.cpu_count() or 1, 8))


def tokenize_native(contents: list[bytes], doc_ids: list[int],
                    dedup_pairs: bool = False, num_threads: int = 1):
    """Native equivalent of text.tokenizer.tokenize_documents.

    ``dedup_pairs`` applies the map-side combiner: each (term, doc) pair
    is emitted once (output-invariant; see tokenizer.cc).
    ``num_threads`` scans contiguous byte-balanced doc ranges in
    parallel (the reference's mapper threads, main.c:348-365); output
    arrays are identical for every thread count.
    """
    from ..text.tokenizer import TokenizedCorpus

    lib = load()
    if lib is None:
        raise RuntimeError(f"native tokenizer unavailable: {_lib_error}")

    args, keepalive = _marshal_docs(contents, doc_ids)
    res = lib.mri_tokenize(*args, ctypes.c_int32(1 if dedup_pairs else 0),
                           ctypes.c_int32(max(1, num_threads)))
    del keepalive
    if not res:
        raise MemoryError("native tokenizer allocation failure")
    try:
        r = res.contents
        n, v, w = int(r.num_tokens), int(r.vocab_size), int(r.vocab_width)
        term = np.ctypeslib.as_array(r.term_ids, shape=(max(n, 1),))[:n].copy()
        doc = np.ctypeslib.as_array(r.doc_ids, shape=(max(n, 1),))[:n].copy()
        packed = np.ctypeslib.as_array(r.vocab_packed, shape=(max(v * w, 1),))[: v * w].copy()
        letters = np.ctypeslib.as_array(r.letter_of_term, shape=(max(v, 1),))[:v].copy()
        vocab = packed.view(f"S{w}") if v else np.empty(0, "S1")
        return TokenizedCorpus(
            term_ids=term, doc_ids=doc, vocab=vocab, letter_of_term=letters,
            pairs_deduped=bool(dedup_pairs), raw_tokens=int(r.raw_tokens))
    finally:
        lib.mri_free_result(res)


class KeyOverflow(Exception):
    """A packed provisional key would exceed int32 — the caller must fall
    back to the one-shot (unpacked / remapped) engine path."""


class NativeKeyStream:
    """Incremental native tokenizer emitting packed provisional keys.

    Feeds the pipelined engine path (models/inverted_index.py): each
    :meth:`feed` scans one window of whole documents and returns packed
    ``prov_id * stride + doc_id`` int32 keys, combiner-deduped, ready
    for an immediate async ``jax.device_put`` — the device program
    (ops/engine.sort_prov_chunks) never needs the final vocab, so
    uploads overlap the tokenizer's remaining work.  :meth:`finalize`
    resolves the sorted vocab, the prov->rank remap, letters and the
    per-term document frequencies the emit phase needs.
    """

    def __init__(self, stride: int, num_threads: int = 1):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native tokenizer unavailable: {_lib_error}")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.mri_stream_new_mt(
            ctypes.c_int64(stride), ctypes.c_int32(max(1, num_threads))))
        if not self._handle:
            raise MemoryError("native stream allocation failure")

    def feed(self, contents: list[bytes], doc_ids: list[int]):
        """Tokenize one whole-document window.

        Returns ``(keys, raw_tokens)`` — packed int32 keys (a copy,
        safe past the next feed).  Raises :class:`KeyOverflow` when
        ``prov_id * stride + doc_id`` no longer fits int32.
        """
        args, keepalive = _marshal_docs(contents, doc_ids)
        res = self._lib.mri_stream_feed(self._handle, *args)
        del keepalive
        if not res:
            raise MemoryError("native stream feed allocation failure")
        try:
            r = res.contents
            n, raw = int(r.num_pairs), int(r.raw_tokens)
            if n < 0:
                raise KeyOverflow()
            keys = np.ctypeslib.as_array(r.keys, shape=(max(n, 1),))[:n].copy()
            return keys, raw
        finally:
            self._lib.mri_stream_chunk_free(res)

    def feed_u16(self, contents: list[bytes], doc_ids: list[int],
                 granule: int = 1 << 14):
        """Tokenize one window, returning the device-ready uint16 feed.

        Returns ``("u16", buf, num_pairs, raw_tokens)`` where ``buf`` is
        the ``[terms | docs]`` uint16 upload buffer (each half padded to
        ``granule``, 0xFFFF padding) — or ``("keys", keys, num_pairs,
        raw_tokens)`` when provisional ids outgrow uint16.  Raises
        :class:`KeyOverflow` when even packed int32 keys overflow.
        """
        args, keepalive = _marshal_docs(contents, doc_ids)
        res = self._lib.mri_stream_feed_u16(
            self._handle, *args, ctypes.c_int64(granule))
        del keepalive
        if not res:
            raise MemoryError("native stream feed allocation failure")
        try:
            r = res.contents
            n, raw = int(r.num_pairs), int(r.raw_tokens)
            if n < 0:
                raise KeyOverflow()
            if r.feed_u16:
                padded = int(r.padded)
                buf = np.ctypeslib.as_array(
                    r.feed_u16, shape=(2 * padded,)).copy()
                return "u16", buf, n, raw
            if n == 0:
                return "u16", np.empty(0, np.uint16), 0, raw
            keys = np.ctypeslib.as_array(r.keys, shape=(max(n, 1),))[:n].copy()
            return "keys", keys, n, raw
        finally:
            self._lib.mri_stream_chunk_u16_free(res)

    def df_snapshot(self, hint: int = 1 << 16) -> np.ndarray:
        """Current per-term deduped (term, doc) counts, GLOBAL prov-id
        space (int32, one slot per provisional id seen so far).  Cheap
        (vocab-scale copy; in MT mode a vocab-scale fold per worker) —
        the overlap plan diffs consecutive snapshots for per-window
        per-term pair counts instead of token-scale bincounts."""
        buf = np.empty(max(hint, 1), np.int32)
        n = self._lib.mri_stream_df_snapshot(
            self._handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(buf.shape[0]))
        if n < 0:
            buf = np.empty(-n, np.int32)
            n = self._lib.mri_stream_df_snapshot(
                self._handle,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.c_int32(buf.shape[0]))
        return buf[:n].copy()

    def finalize(self):
        """``(vocab, letter_of_term, remap, df_prov, raw_tokens,
        num_pairs, emit_order)``.

        ``vocab`` is the sorted 'S'-dtype array; ``letter_of_term`` is in
        rank space; ``remap`` maps prov id -> rank; ``df_prov`` holds the
        combiner's per-term document frequencies in prov space;
        ``emit_order`` lists ranks in the reducer's emit order
        (letter, -df, word — main.c:55-64), computed in C++ so the emit
        path skips its vocab-scale ``np.lexsort``.
        """
        res = self._lib.mri_stream_finalize(self._handle)
        if not res:
            raise MemoryError("native stream finalize allocation failure")
        try:
            r = res.contents
            v, w = int(r.vocab_size), int(r.vocab_width)
            packed = np.ctypeslib.as_array(
                r.vocab_packed, shape=(max(v * w, 1),))[: v * w].copy()
            vocab = packed.view(f"S{w}") if v else np.empty(0, "S1")
            letters = np.ctypeslib.as_array(r.letter_of_term, shape=(max(v, 1),))[:v].copy()
            remap = np.ctypeslib.as_array(r.remap, shape=(max(v, 1),))[:v].copy()
            df = np.ctypeslib.as_array(r.df, shape=(max(v, 1),))[:v].copy()
            order = np.ctypeslib.as_array(
                r.emit_order, shape=(max(v, 1),))[:v].astype(np.int64)
            return (vocab, letters, remap, df, int(r.raw_tokens),
                    int(r.num_pairs), order)
        finally:
            self._lib.mri_stream_final_free(res)

    def close(self):
        if self._handle:
            self._lib.mri_stream_free(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def host_index_native(contents: list[bytes], doc_ids: list[int],
                      out_dir, num_threads: int = 1) -> dict:
    """Whole pipeline in one native call: tokenize + postings + emit.

    The ``backend="cpu"`` engine (models/inverted_index.py): the
    reference's all-on-host regime without its pathologies — no spill
    files, no stdio locks, no token-scale sorts (docs arrive ascending
    per term by construction).  ``num_threads`` forks the map scan over
    contiguous byte-balanced doc ranges.  Returns the stats dict.
    """
    lib = load()
    if lib is None:
        raise RuntimeError(f"native host index unavailable: {_lib_error}")
    os.makedirs(out_dir, exist_ok=True)
    stats = _HostIndexStats()
    args, keepalive = _marshal_docs(contents, doc_ids)
    rc = lib.mri_host_index(*args, str(out_dir).encode(), ctypes.byref(stats),
                            ctypes.c_int32(max(1, num_threads)))
    del keepalive
    if rc == -2:
        raise MemoryError("native host index allocation failure")
    if rc != 0:
        raise OSError(f"native host index failed writing to {out_dir!r}")
    return {
        "documents": len(contents),
        "tokens": int(stats.raw_tokens),
        "unique_terms": int(stats.vocab_size),
        "unique_pairs": int(stats.num_pairs),
        "lines_written": int(stats.vocab_size),
        "bytes_written": int(stats.bytes_written),
    }


class HostIndexStream:
    """Incremental ``backend="cpu"`` pipeline: feed windows, emit once.

    The zero-copy counterpart of :func:`host_index_native` — each
    :meth:`feed_arrays` call hands the scan a window straight out of a
    reusable io.arena buffer (no ``b"".join``, no marshalling copies),
    and ctypes releases the GIL for the call's duration, so a Python
    reader thread can fill the next arena while C++ scans this one.
    :meth:`finalize_emit` flattens postings, sorts, and writes the 26
    letter files, returning a stats dict that includes the native-side
    ``scan_ms`` / ``finalize_ms`` / ``emit_ms`` stage split.
    """

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native host index unavailable: {_lib_error}")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.mri_hidx_new())
        if not self._handle:
            raise MemoryError("native host index allocation failure")
        self._documents = 0

    def feed_arrays(self, buf: np.ndarray, ends: np.ndarray,
                    ids: np.ndarray, num_docs: int | None = None,
                    used_bytes: int | None = None) -> None:
        """Scan one window of whole documents, zero-copy.

        ``buf`` is the concatenated uint8 document bytes, ``ends`` the
        int64 cumulative end offsets, ``ids`` the int32 doc ids.  Pass
        ``num_docs`` / ``used_bytes`` to scan a prefix of oversized
        arena arrays without slicing (slices of C-contiguous prefixes
        are fine too — the pointers are taken as-is).
        """
        n = int(num_docs if num_docs is not None else ends.shape[0])
        if n == 0:
            return
        nbytes = int(used_bytes if used_bytes is not None else buf.shape[0])
        if buf.dtype != np.uint8 or ends.dtype != np.int64 \
                or ids.dtype != np.int32:
            raise TypeError("feed_arrays requires uint8/int64/int32 arrays")
        rc = self._lib.mri_hidx_feed(
            self._handle,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(nbytes),
            ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(n))
        if rc != 0:
            raise MemoryError("native host index feed allocation failure")
        self._documents += n

    def feed(self, contents: list[bytes], doc_ids: list[int]) -> None:
        """Convenience wrapper for list-of-bytes callers (tests)."""
        args, keepalive = _marshal_docs(contents, doc_ids)
        rc = self._lib.mri_hidx_feed(self._handle, *args)
        del keepalive
        if rc != 0:
            raise MemoryError("native host index feed allocation failure")
        self._documents += len(contents)

    def finalize_emit(self, out_dir) -> dict:
        """Flatten + sort + write the 26 letter files; the stats dict."""
        os.makedirs(out_dir, exist_ok=True)
        stats = _HostStreamStats()
        rc = self._lib.mri_hidx_finalize_emit(
            self._handle, str(out_dir).encode(), ctypes.byref(stats))
        if rc == -2:
            raise MemoryError("native host index allocation failure")
        if rc != 0:
            raise OSError(f"native host index failed writing to {out_dir!r}")
        return {
            "documents": self._documents,
            "tokens": int(stats.raw_tokens),
            "unique_terms": int(stats.vocab_size),
            "unique_pairs": int(stats.num_pairs),
            "lines_written": int(stats.vocab_size),
            "bytes_written": int(stats.bytes_written),
            "scan_ms": stats.scan_ns / 1e6,
            "finalize_ms": stats.finalize_ns / 1e6,
            "emit_ms": stats.emit_ns / 1e6,
        }

    def partial(self) -> dict:
        """Flatten this worker's scan into per-term doc runs (the paper's
        per-worker ``partial_a..z`` spill, kept in memory).

        Runs in the calling worker thread with the GIL released, so K
        workers' partial passes overlap.  Each term's run is sorted
        ascending even when the steal queue delivered windows out of
        order.  After this call the handle can only be merged via
        :class:`HostIndexMerge` — ``finalize_emit`` is no longer valid
        (the scan buffers are released).  Idempotent.
        """
        scan_ns = ctypes.c_int64(0)
        partial_ns = ctypes.c_int64(0)
        rc = self._lib.mri_hidx_partial(
            self._handle, ctypes.byref(scan_ns), ctypes.byref(partial_ns))
        if rc != 0:
            raise MemoryError("native host index partial allocation failure")
        return {
            "scan_ms": scan_ns.value / 1e6,
            "partial_ms": partial_ns.value / 1e6,
        }

    def info(self) -> dict:
        """Scan-state probe for the audit layer: this worker's local
        vocab size, deduped pair count, and raw token count (read-only,
        vocab-free — O(1))."""
        vocab = ctypes.c_int32(0)
        pairs = ctypes.c_int64(0)
        raw = ctypes.c_int64(0)
        self._lib.mri_hidx_info(
            self._handle, ctypes.byref(vocab), ctypes.byref(pairs),
            ctypes.byref(raw))
        return {"vocab": int(vocab.value), "pairs": int(pairs.value),
                "raw_tokens": int(raw.value)}

    def runpack(self, shards: int) -> dict:
        """Flatten + export this worker's scan state as term-hash-sharded
        run arrays (the out-of-core spill tier's unit of work).

        Terms come back in (shard asc, lex asc) order with NUL-padded
        fixed-width rows; each term's postings run is doc-ascending with
        a parallel tf column; ``shard_term_off`` / ``shard_pair_off``
        (``shards + 1`` entries each) delimit every shard's slice.  The
        ``doc_ids`` / ``doc_tokens`` columns carry per-document cleaned
        token counts (doc-id ascending) for the artifact's doc-length
        table.  After this call the handle is spent — close it and feed
        a fresh stream.
        """
        if shards < 1:
            raise ValueError("shards must be >= 1")
        vocab = ctypes.c_int32(0)
        width = ctypes.c_int32(0)
        pairs = ctypes.c_int64(0)
        ndocs = ctypes.c_int64(0)
        max_doc = ctypes.c_int64(0)
        raw = ctypes.c_int64(0)
        rc = self._lib.mri_hidx_runpack_info(
            self._handle, ctypes.byref(vocab), ctypes.byref(width),
            ctypes.byref(pairs), ctypes.byref(ndocs), ctypes.byref(max_doc),
            ctypes.byref(raw))
        if rc != 0:
            raise MemoryError("native host index runpack allocation failure")
        v, w = int(vocab.value), max(int(width.value), 1)
        p, d = int(pairs.value), int(ndocs.value)
        vocab_packed = np.zeros((max(v, 1), w), dtype=np.uint8)
        word_lens = np.zeros(max(v, 1), dtype=np.int32)
        df = np.zeros(max(v, 1), dtype=np.int64)
        offsets = np.zeros(v + 1, dtype=np.int64)
        postings = np.zeros(max(p, 1), dtype=np.int32)
        tf = np.zeros(max(p, 1), dtype=np.int32)
        shard_term_off = np.zeros(shards + 1, dtype=np.int64)
        shard_pair_off = np.zeros(shards + 1, dtype=np.int64)
        doc_ids = np.zeros(max(d, 1), dtype=np.int32)
        doc_tokens = np.zeros(max(d, 1), dtype=np.int64)
        rc = self._lib.mri_hidx_runpack(
            self._handle, ctypes.c_int32(shards),
            vocab_packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            word_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            df.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            postings.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            tf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            shard_term_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            shard_pair_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            doc_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            doc_tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc != 0:
            raise MemoryError("native host index runpack allocation failure")
        return {
            "vocab": v, "width": w, "pairs": p,
            "max_doc_id": int(max_doc.value),
            "raw_tokens": int(raw.value),
            "vocab_packed": vocab_packed[:v],
            "word_lens": word_lens[:v], "df": df[:v],
            "offsets": offsets,
            "postings": postings[:p], "tf": tf[:p],
            "shard_term_off": shard_term_off,
            "shard_pair_off": shard_pair_off,
            "doc_ids": doc_ids[:d], "doc_tokens": doc_tokens[:d],
        }

    def close(self):
        if self._handle:
            self._lib.mri_hidx_free(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class HostIndexMerge:
    """Letter-partitioned parallel reduce over K scanned streams.

    Joins the workers' vocabularies into one global vocabulary + emit
    order (the fork-join barrier), then :meth:`emit_range` renders any
    contiguous first-letter range — it is read-only on the merge state
    and releases the GIL, so M reducer threads (``num_reducers``) call
    it concurrently.  The union of ``plan_letter_ranges`` calls is
    byte-identical to a single-stream ``finalize_emit``.

    Keeps references to the source streams: their native runs back the
    merge until :meth:`close`.
    """

    def __init__(self, streams):
        if not streams:
            raise ValueError("HostIndexMerge needs at least one stream")
        lib = load()
        if lib is None:
            raise RuntimeError(f"native host merge unavailable: {_lib_error}")
        self._lib = lib
        self._streams = list(streams)  # keep worker runs alive
        handles = (ctypes.c_void_p * len(self._streams))(
            *[s._handle for s in self._streams])
        stats = _HostStreamStats()
        self._handle = ctypes.c_void_p(
            lib.mri_hidxm_new(handles, len(self._streams),
                              ctypes.byref(stats)))
        if not self._handle:
            raise MemoryError("native host merge allocation failure")
        self._documents = sum(s._documents for s in self._streams)
        self._stats = stats

    def stats(self) -> dict:
        return {
            "documents": self._documents,
            "tokens": int(self._stats.raw_tokens),
            "unique_terms": int(self._stats.vocab_size),
            "unique_pairs": int(self._stats.num_pairs),
            "lines_written": int(self._stats.vocab_size),
            "merge_ms": self._stats.finalize_ns / 1e6,
        }

    def emit_range(self, letter_lo: int, letter_hi: int, out_dir) -> int:
        """Write letter files ``[letter_lo, letter_hi)``; bytes written.

        An empty range (``lo == hi``, from ``plan_letter_ranges`` with
        more reducers than letters) writes nothing and returns 0.
        """
        os.makedirs(out_dir, exist_ok=True)
        n = self._lib.mri_hidxm_emit_range(
            self._handle, ctypes.c_int32(letter_lo),
            ctypes.c_int32(letter_hi), str(out_dir).encode())
        if n == -2:
            raise MemoryError("native host merge emit allocation failure")
        if n < 0:
            raise OSError(
                f"native host merge failed writing letters "
                f"[{letter_lo}, {letter_hi}) to {out_dir!r}")
        return int(n)

    def export_arrays(self) -> dict:
        """Columnar lex-order export of the merged index — the serving
        artifact's source arrays, no letter-file text round-trip.

        Returns ``vocab_packed`` ((V, width) uint8 NUL-padded rows),
        ``word_lens`` (V int32), ``df`` (V int64), ``offsets`` (V+1
        int64 exclusive prefix), ``postings`` (P int32, globally
        ascending per term), ``df_order`` (V int64 — emit-order
        permutation over lex indices), ``letter_off`` (27 int64), plus
        ``vocab``/``width``/``max_doc_id``/``num_pairs`` scalars.
        Read-only on the merge state.
        """
        V, width, P, _, mdi = self.export_info()
        vocab_packed = np.zeros((max(V, 1), width), dtype=np.uint8)
        word_lens = np.zeros(max(V, 1), dtype=np.int32)
        df = np.zeros(max(V, 1), dtype=np.int64)
        offsets = np.zeros(V + 1, dtype=np.int64)
        postings = np.zeros(max(P, 1), dtype=np.int32)
        df_order = np.zeros(max(V, 1), dtype=np.int64)
        letter_off = np.zeros(27, dtype=np.int64)

        def ptr(a, ctype):
            return a.ctypes.data_as(ctypes.POINTER(ctype))

        rc = self._lib.mri_hidxm_export(
            self._handle, ptr(vocab_packed, ctypes.c_uint8),
            ptr(word_lens, ctypes.c_int32), ptr(df, ctypes.c_int64),
            ptr(offsets, ctypes.c_int64), ptr(postings, ctypes.c_int32),
            ptr(df_order, ctypes.c_int64), ptr(letter_off, ctypes.c_int64))
        if rc == -2:
            raise MemoryError("native merge export allocation failure")
        if rc != 0:
            raise RuntimeError(f"native merge export failed (rc={rc})")
        return {
            "vocab_packed": vocab_packed[:V], "word_lens": word_lens[:V],
            "df": df[:V], "offsets": offsets, "postings": postings[:P],
            "df_order": df_order[:V], "letter_off": letter_off,
            "vocab": V, "width": width, "max_doc_id": mdi,
            "num_pairs": P,
        }

    def export_info(self) -> tuple[int, int, int, int, int]:
        """``(vocab, width, num_pairs, blob_bytes, max_doc_id)`` of the
        merged index — the artifact layout's scalars, O(V)."""
        v = ctypes.c_int32(0)
        w = ctypes.c_int32(0)
        mdi = ctypes.c_int32(0)
        pairs = ctypes.c_int64(0)
        blob = ctypes.c_int64(0)
        self._lib.mri_hidxm_export_info(
            self._handle, ctypes.byref(v), ctypes.byref(w),
            ctypes.byref(mdi), ctypes.byref(pairs), ctypes.byref(blob))
        return (int(v.value), int(w.value), int(pairs.value),
                int(blob.value), int(mdi.value))

    def export_payload(self, buf: np.ndarray, offsets: dict) -> None:
        """One-pass fill of an ``index.mri`` file buffer: every payload
        section written at ``offsets[section]`` (absolute byte offsets
        into ``buf``), postings already delta-encoded.  Read-only on the
        merge state; ``buf`` must be C-contiguous uint8."""
        rc = self._lib.mri_hidxm_export_payload(
            self._handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            *(ctypes.c_int64(offsets[name]) for name in (
                "letter_dir", "term_offsets", "term_blob", "df",
                "post_offsets", "postings", "df_order")))
        if rc == -2:
            raise MemoryError("native artifact export allocation failure")
        if rc != 0:
            raise RuntimeError(f"native artifact export failed (rc={rc})")

    def export_v2_prepare(self, block_size: int,
                          score_bits: int = 0) -> tuple[int, int, int]:
        """Build the format-v2/v2.1 export plan (block skip entries,
        packed postings/tf words, doc lengths, and — when ``score_bits``
        is 8 or 16 — the saturated max-tf / min-doc-length columns) and
        return the section sizes the layout needs: ``(num_blocks,
        post_data_bytes, tf_data_bytes)``.  ``block_size`` must be a
        power of two >= 2."""
        nb = ctypes.c_int64(0)
        pb = ctypes.c_int64(0)
        tb = ctypes.c_int64(0)
        rc = self._lib.mri_hidxm_export_v2_prepare(
            self._handle, ctypes.c_int32(block_size),
            ctypes.c_int32(score_bits),
            ctypes.byref(nb), ctypes.byref(pb), ctypes.byref(tb))
        if rc == -2:
            raise MemoryError("native v2 export allocation failure")
        if rc != 0:
            raise RuntimeError(f"native v2 export prepare failed (rc={rc})")
        return int(nb.value), int(pb.value), int(tb.value)

    def export_v2_payload(self, buf: np.ndarray, offsets: dict) -> None:
        """Fill a format-v2/v2.1 ``index.mri`` file buffer from the
        prepared plan (:meth:`export_v2_prepare` first) and release the
        plan.  ``offsets`` maps every payload section name to its
        absolute byte offset in ``buf``; the v2.1 max-score sections
        ride between ``blk_tf_width`` and ``post_data`` when present."""
        names = ["letter_dir", "term_offsets", "term_blob", "df",
                 "blk_max", "blk_first", "blk_width", "blk_tf_width",
                 "post_data", "tf_data", "doc_lens", "df_order"]
        if "blk_max_tf" in offsets:
            names[8:8] = ["blk_max_tf", "blk_min_dl"]
        offs = np.array([offsets[name] for name in names], dtype=np.int64)
        rc = self._lib.mri_hidxm_export_v2_payload(
            self._handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int32(len(offs)))
        if rc == -2:
            raise MemoryError("native v2 artifact export allocation failure")
        if rc != 0:
            raise RuntimeError(f"native v2 artifact export failed (rc={rc})")

    def audit(self) -> tuple[int, int]:
        """Walk every global term's worker runs checking the merge
        invariants (df sums, per-run monotonicity) in C++.  Returns
        ``(rc, bad_term)`` — rc 0 ok, 1 df-sum mismatch, 2 non-monotonic
        run; interpretation (and the raised :class:`~..audit.AuditError`)
        lives in audit.py, keeping this layer exception-vocabulary-free.
        """
        bad = ctypes.c_int32(-1)
        rc = self._lib.mri_hidxm_audit(self._handle, ctypes.byref(bad))
        return int(rc), int(bad.value)

    def close(self):
        if self._handle:
            self._lib.mri_hidxm_free(self._handle)
            self._handle = None
        self._streams = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def emit_native_runs(out_dir, vocab: np.ndarray, order, runs) -> int:
    """Multi-run native emit: each term's postings list is the
    concatenation of its per-run segments in run order.

    ``runs`` is a sequence of ``(postings_u16, offsets, counts)`` —
    postings a uint16 array, offsets/counts rank-space int64 arrays.
    Used by the windowed overlap plan, whose device-window fetches and
    host tail are contiguous ascending doc ranges (so concatenation in
    run order IS the merge).  Byte-identical to a single merged
    :func:`emit_native` call.  Returns total bytes written.
    """
    lib = load()
    if lib is None:
        raise RuntimeError(f"native emit unavailable: {_lib_error}")
    os.makedirs(out_dir, exist_ok=True)
    vocab_size = int(vocab.shape[0])
    width = vocab.dtype.itemsize if vocab_size else 1
    vbuf = np.ascontiguousarray(vocab).view(np.uint8)
    order64 = np.ascontiguousarray(order, dtype=np.int64)
    n = len(runs)
    keep = []  # contiguous arrays outliving the call
    bases = (ctypes.POINTER(ctypes.c_uint16) * max(n, 1))()
    offs = (ctypes.POINTER(ctypes.c_int64) * max(n, 1))()
    cnts = (ctypes.POINTER(ctypes.c_int64) * max(n, 1))()
    for i, (postings, offsets, counts) in enumerate(runs):
        p = np.ascontiguousarray(postings, dtype=np.uint16)
        o = np.ascontiguousarray(offsets, dtype=np.int64)
        c = np.ascontiguousarray(counts, dtype=np.int64)
        keep.extend((p, o, c))
        bases[i] = p.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))
        offs[i] = o.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        cnts[i] = c.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    null8 = ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_uint8))
    null64 = ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_int64))
    rc = lib.mri_emit_runs(
        vbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if vocab_size else null8,
        ctypes.c_int32(vocab_size), ctypes.c_int32(width),
        order64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) if vocab_size else null64,
        ctypes.c_int32(n), bases, offs, cnts,
        str(out_dir).encode(),
    )
    del keep
    if rc < 0:
        raise OSError(f"native emit failed writing to {out_dir!r}")
    return int(rc)


def emit_native(out_dir, vocab: np.ndarray, order, df, offsets, postings,
                letter_range: tuple[int, int] = (0, 26),
                idx_bounds: tuple[int, int] | None = None) -> int:
    """Native letter-file emit; byte-identical to text.formatter.emit_index.

    ``vocab`` is the sorted numpy 'S' array; postings may be uint16 or
    int32.  ``letter_range`` restricts emission to letters ``[lo, hi)``
    with ``idx_bounds`` the matching slice of ``order`` (required for a
    partial range; defaults to the whole permutation) — the per-owner
    emit the multi-host letter-ownership mode and the parallel reduce
    share.  Returns total bytes written.
    """
    lib = load()
    if lib is None:
        raise RuntimeError(f"native emit unavailable: {_lib_error}")
    os.makedirs(out_dir, exist_ok=True)
    vocab_size = int(vocab.shape[0])
    width = vocab.dtype.itemsize if vocab_size else 1
    vbuf = np.ascontiguousarray(vocab).view(np.uint8)
    order64 = np.ascontiguousarray(order, dtype=np.int64)
    df64 = np.ascontiguousarray(df, dtype=np.int64)
    off64 = np.ascontiguousarray(offsets, dtype=np.int64)
    postings = np.ascontiguousarray(postings)
    null16 = ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_uint16))
    null32 = ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_int32))
    if postings.dtype == np.uint16:
        p16 = postings.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))
        p32 = null32
    else:
        postings = postings.astype(np.int32, copy=False)
        p16 = null16
        p32 = postings.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    rc = lib.mri_emit(
        vbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if vocab_size else
        ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int32(vocab_size), ctypes.c_int32(width),
        order64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) if vocab_size else
        ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_int64)),
        df64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) if vocab_size else
        ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_int64)),
        off64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)) if vocab_size else
        ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctypes.c_int64)),
        p16, p32,
        str(out_dir).encode(),
        ctypes.c_int32(letter_range[0]), ctypes.c_int32(letter_range[1]),
        ctypes.c_int64(idx_bounds[0] if idx_bounds is not None else 0),
        ctypes.c_int64(idx_bounds[1] if idx_bounds is not None
                       else vocab_size),
    )
    if rc < 0:
        raise OSError(f"native emit failed writing to {out_dir!r}")
    return int(rc)


# -- serve-path kernels (mri_serve_*) ----------------------------------

#: planner mode -> mri_serve_topk_bm25 mode argument
_SERVE_MODES = {"exhaustive": 0, "bmw": 1, "maxscore": 2}
_SERVE_MODE_NAMES = ("exhaustive", "bmw", "maxscore")


def _serve_ptr(arr, ctype):
    if arr is None:
        return ctypes.cast(ctypes.c_void_p(), ctypes.POINTER(ctype))
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


class NativeServe:
    """One ``mri_serve_*`` handle over a v2/v2.1 artifact's columns.

    The handle borrows every pointer it is given, so this wrapper pins
    the backing buffers (the artifact's mmap views plus the engine's
    float64 doc-length column) for its lifetime — close the wrapper
    before closing the artifact.  Calls are NOT thread-safe; the engine
    serializes them (CPython GIL, daemon reload lock), the same
    contract as the ``mri_hidx_*`` build streams.
    """

    # planner-mode → C mode code (and the inverse), exposed so the
    # engine can memoize the translated code next to the prep id and
    # account coalesced batches without re-deriving mode strings
    MODES = _SERVE_MODES
    MODE_NAMES = _SERVE_MODE_NAMES

    def __init__(self, cols: dict, doc_lens: np.ndarray, avgdl: float,
                 k1: float, b: float, cache_cap: int = 4096):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native serve unavailable: {_lib_error}")
        self._lib = lib
        self._cols = cols  # keeps the mmap views alive
        self._doc_lens = np.ascontiguousarray(doc_lens, dtype=np.float64)
        self.block_size = int(cols["block_size"])
        self.score_bits = int(cols["score_bits"])
        self._h = lib.mri_serve_new(
            _serve_ptr(cols["blk_max"], ctypes.c_int32),
            _serve_ptr(cols["blk_first"], ctypes.c_int32),
            _serve_ptr(cols["blk_width"], ctypes.c_uint8),
            _serve_ptr(cols["blk_tf_width"], ctypes.c_uint8),
            _serve_ptr(cols["blk_max_tf"], ctypes.c_uint8),
            _serve_ptr(cols["blk_min_dl"], ctypes.c_uint8),
            _serve_ptr(cols["post_words"], ctypes.c_uint32),
            _serve_ptr(cols["tf_words"], ctypes.c_uint32),
            _serve_ptr(self._doc_lens, ctypes.c_double),
            _serve_ptr(cols["term_block_off"], ctypes.c_int64),
            _serve_ptr(cols["blk_cnt"], ctypes.c_int32),
            _serve_ptr(cols["blk_woff"], ctypes.c_int64),
            _serve_ptr(cols["blk_tf_woff"], ctypes.c_int64),
            ctypes.c_int32(int(cols["vocab"])),
            ctypes.c_int64(int(cols["num_blocks"])),
            ctypes.c_int32(self.block_size),
            ctypes.c_int32(self.score_bits),
            ctypes.c_int64(len(self._doc_lens)),
            ctypes.c_double(float(avgdl)), ctypes.c_double(float(k1)),
            ctypes.c_double(float(b)), ctypes.c_int32(int(cache_cap)),
        )
        if not self._h:
            raise RuntimeError(
                "mri_serve_new rejected the artifact columns")
        # reusable ranked-path output buffers (grown on demand),
        # registered on the handle once: the per-query fast call then
        # marshals 4 scalars instead of 9 mixed pointers
        self._f_run = lib.mri_serve_topk_run
        self._f_batch = lib.mri_serve_topk_batch
        self._stats = np.zeros(3, dtype=np.int64)
        self._p_stats = _serve_ptr(self._stats, ctypes.c_int64)
        self._batch_bufs = None
        self._grow_topk(256)

    def _grow_topk(self, cap: int) -> None:
        self._topk_cap = cap
        self._out_d = np.empty(cap, dtype=np.int32)
        self._out_s = np.empty(cap, dtype=np.float64)
        self._p_out_d = _serve_ptr(self._out_d, ctypes.c_int32)
        self._p_out_s = _serve_ptr(self._out_s, ctypes.c_double)
        self._lib.mri_serve_set_topk_out(
            self._h, self._p_out_d, self._p_out_s, self._p_stats)

    def close(self) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.mri_serve_free(h)
        self._cols = None
        self._doc_lens = None

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.mri_serve_free(self._h)
                self._h = None
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- ops ------------------------------------------------------------

    def decode_blocks(self, sel, want_tf: bool = True):
        """``(ids, tf|None, cnt)`` for the selected global blocks —
        the exact matrices (padding included) of the numpy
        ``Artifact.decode_blocks`` / ``decode_tf_blocks`` pair.
        ``None`` on a rejected call (caller falls back to numpy)."""
        sel = np.ascontiguousarray(sel, dtype=np.int64)
        n = len(sel)
        B = self.block_size
        ids = np.empty((max(n, 1), B), dtype=np.int32)
        tfm = np.empty((max(n, 1), B), dtype=np.int32) if want_tf \
            else None
        cnt = np.empty(max(n, 1), dtype=np.int32)
        rc = self._lib.mri_serve_decode_blocks(
            self._h, _serve_ptr(sel, ctypes.c_int64), ctypes.c_int64(n),
            _serve_ptr(ids, ctypes.c_int32),
            _serve_ptr(tfm, ctypes.c_int32),
            _serve_ptr(cnt, ctypes.c_int32))
        if rc != 0:
            return None
        return ids[:n], (tfm[:n] if want_tf else None), cnt[:n]

    def decode_postings(self, idx: int, df: int, want_tf: bool = True):
        """``(docs, tf|None)`` of one term, or ``None`` on error."""
        docs = np.empty(max(df, 1), dtype=np.int32)
        tf = np.empty(max(df, 1), dtype=np.int32) if want_tf else None
        got = self._lib.mri_serve_decode_postings(
            self._h, ctypes.c_int32(int(idx)),
            _serve_ptr(docs, ctypes.c_int32),
            _serve_ptr(tf, ctypes.c_int32))
        if got != df:
            return None
        return docs[:df], (tf[:df] if want_tf else None)

    def query_and(self, acc, idx: int):
        """``(survivors, blocks_decoded, blocks_skipped)`` of the
        ascending candidate list intersected against term ``idx``, or
        ``None`` on error."""
        acc = np.ascontiguousarray(acc, dtype=np.int32)
        out = np.empty(max(len(acc), 1), dtype=np.int32)
        stats = np.zeros(2, dtype=np.int64)
        m = self._lib.mri_serve_and(
            self._h, _serve_ptr(acc, ctypes.c_int32),
            ctypes.c_int64(len(acc)), ctypes.c_int32(int(idx)),
            _serve_ptr(out, ctypes.c_int32),
            _serve_ptr(stats, ctypes.c_int64))
        if m < 0:
            return None
        return out[:m], int(stats[0]), int(stats[1])

    def top_k_bm25(self, occ, idfs, k: int, mode: str):
        """``(docs, scores, blocks_scored, blocks_skipped, candidates)``
        for the occurrence list, byte-identical to the numpy oracle's
        ``top_k_scored``; ``None`` on error (caller falls back)."""
        occ_a = np.ascontiguousarray(occ, dtype=np.int32)
        idf_a = np.ascontiguousarray(idfs, dtype=np.float64)
        kk = max(int(k), 0)
        out_d = np.empty(max(kk, 1), dtype=np.int32)
        out_s = np.empty(max(kk, 1), dtype=np.float64)
        stats = np.zeros(3, dtype=np.int64)
        n = self._lib.mri_serve_topk_bm25(
            self._h, _serve_ptr(occ_a, ctypes.c_int32),
            ctypes.c_int32(len(occ_a)),
            _serve_ptr(idf_a, ctypes.c_double),
            ctypes.c_int32(kk), ctypes.c_int32(_SERVE_MODES[mode]),
            _serve_ptr(out_d, ctypes.c_int32),
            _serve_ptr(out_s, ctypes.c_double),
            _serve_ptr(stats, ctypes.c_int64))
        if n < 0:
            return None
        return (out_d[:n], out_s[:n], int(stats[0]), int(stats[1]),
                int(stats[2]))

    def prep_query(self, occ, idfs):
        """Freeze one query's (occ, idf) argument arrays into the
        handle, returning the prep id :meth:`top_k_bm25_fast` executes
        (``None`` on rejection) — argument marshalling dominates a warm
        ranked query, so the engine memoizes this per query key."""
        occ_a = np.ascontiguousarray(occ, dtype=np.int32)
        idf_a = np.ascontiguousarray(idfs, dtype=np.float64)
        pid = self._lib.mri_serve_topk_prep(
            self._h, _serve_ptr(occ_a, ctypes.c_int32), len(occ_a),
            _serve_ptr(idf_a, ctypes.c_double))
        return int(pid) if pid > 0 else None

    def clear_preps(self) -> None:
        """Drop every prepared query (engine prep-memo sweep)."""
        if self._h:
            self._lib.mri_serve_topk_prep_clear(self._h)

    def free_prep(self, pid: int) -> None:
        """Drop one prepared query (un-memoizable one-shot query)."""
        if self._h:
            self._lib.mri_serve_topk_prep_free(self._h, pid)

    def top_k_bm25_fast(self, pid: int, k: int, mode: str):
        """Ranked query over a :meth:`prep_query` id reusing the
        handle's registered output buffers: ``(pairs, scored, skipped,
        candidates)`` with ``pairs`` the engine's final
        ``[(doc, score), ...]``; ``None`` on error."""
        if k > self._topk_cap:
            self._grow_topk(max(k, 2 * self._topk_cap))
        n = self._f_run(self._h, pid, k, _SERVE_MODES[mode])
        if n < 0:
            return None
        stats = self._stats
        return (list(zip(self._out_d[:n].tolist(),
                         self._out_s[:n].tolist())),
                int(stats[0]), int(stats[1]), int(stats[2]))

    def top_k_bm25_batch(self, pids, modes, nq: int, k: int):
        """Coalesced ranked batch — ``nq`` prepared queries in ONE
        library crossing (the router/daemon micro-batch regime, where
        per-call dispatch would otherwise dominate the kernels).
        ``pids`` is an ``array.array('q')`` of prep ids and ``modes``
        an ``array.array('i')`` of ``MODES`` codes — the engine builds
        them append-by-append, and their buffer addresses go straight
        into the call.  Returns ``(pairs_list, scored, skipped,
        candidates)`` with ``pairs_list[i]`` the i-th query's
        ``[(doc, score), ...]`` and the stats summed across the batch;
        ``None`` on any error (the caller re-runs per query)."""
        need = nq * k
        bb = self._batch_bufs
        if bb is None or bb[8] < need or bb[9] < nq:
            docs = np.empty(max(need, 256), dtype=np.int32)
            scores = np.empty(max(need, 256), dtype=np.float64)
            nhits = np.empty(max(nq, 64), dtype=np.int32)
            stats = np.zeros(3, dtype=np.int64)
            bb = (docs, scores, nhits, stats,
                  docs.ctypes.data, scores.ctypes.data,
                  nhits.ctypes.data, stats.ctypes.data,
                  len(docs), len(nhits))
            self._batch_bufs = bb
        rc = self._f_batch(
            self._h, pids.buffer_info()[0], modes.buffer_info()[0],
            nq, k, bb[4], bb[5], bb[6], bb[7])
        if rc < 0:
            return None
        dl = bb[0][:need].tolist()
        sl = bb[1][:need].tolist()
        nl = bb[2][:nq].tolist()
        pairs_list = [list(zip(dl[lo:lo + n], sl[lo:lo + n]))
                      for lo, n in zip(range(0, need, k), nl)]
        s0, s1, s2 = bb[3].tolist()
        return (pairs_list, s0, s1, s2)
